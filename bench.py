"""Benchmark: ResNet-50 ImageNet training throughput, single TPU chip.

North-star metric (BASELINE.json): samples/sec/chip, ResNet-50, BS=256.
Baseline (BASELINE.md): the reference's best published ResNet-50
training number is 84.08 img/s (BS=256, 2x Xeon 6148 + MKL-DNN,
benchmark/IntelOptimizedPaddle.md:38-45).  ``vs_baseline`` is the ratio
of our samples/sec to that.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np


def build(batch, image, class_dim, dtype="float32"):
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet_imagenet

    fluid.framework.reset_default_programs()
    img = fluid.layers.data(name="img", shape=list(image), dtype=dtype)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    pred = resnet_imagenet(img, class_dim=class_dim)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(input=pred, label=label))
    fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    return fluid, loss


def run(batch=256, image=(3, 224, 224), class_dim=1000, steps=20, warmup=3):
    import jax
    from paddle_tpu import amp

    if os.environ.get("BENCH_AMP", "1") == "1":
        amp.enable()  # bf16 matmul/conv with fp32 master weights
    fluid, loss = build(batch, image, class_dim)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    xs = rng.randn(batch, *image).astype("float32")
    ys = rng.randint(0, class_dim, (batch, 1)).astype("int64")
    import jax.numpy as jnp

    pipeline = os.environ.get("BENCH_PIPELINE", "0") == "1"
    if os.environ.get("BENCH_CHAIN", "1") == "1" and not pipeline:
        # jitted training loop: lax.scan over K steps in ONE program,
        # the standard JAX shape for a training loop.  Per-step
        # dispatch through this harness's network tunnel costs a fixed
        # ~6-9 ms of RPC per program that a locally attached chip does
        # not pay; the scanned loop measures the device step itself
        # (measured r4: 97.2 ms/step scanned vs 103-106 ms dispatched,
        # same program, loss trajectory identical).
        from jax import lax

        fn, state, feeds, _ = exe.build_callable(
            fluid.default_main_program(), {"img": xs, "label": ys},
            [loss.name])
        K = 10

        def multi(state, feeds):
            def body(s, _):
                fetches, s2 = fn(s, feeds)
                return s2, fetches[0]

            s, losses = lax.scan(body, state, None, length=K)
            return losses[-1], s

        jm = jax.jit(multi, donate_argnums=(0,))
        dev_feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        # one warm call compiles and runs K steps — `warmup` and
        # `steps` are interpreted in units of K-step chains here
        # (timed steps round up to >= 2 chains)
        out, state = jm(state, dev_feeds)
        float(np.asarray(out))
        for _ in range(max(warmup // K - 1, 0)):
            out, state = jm(state, dev_feeds)
        float(np.asarray(out))
        reps = max(steps // K, 2)
        # chains dispatch asynchronously inside a block (the tunnel RTT
        # overlaps device work); the best of 5 blocks drops inter-block
        # jitter without putting a host sync inside the pipeline
        best, loss_val = float("inf"), 0.0
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(reps):
                out, state = jm(state, dev_feeds)
            loss_val = float(np.asarray(out))  # sync once per block
            best = min(best, time.perf_counter() - t0)
        return batch * reps * K / best, loss_val

    if pipeline:
        # double-buffered host feed: decode-free here (synthetic), but
        # every step pays a fresh host->device transfer that the next
        # step's dispatch overlaps — the trainer's prefetch=True shape
        feeds = [{"img": xs + np.float32(i % 2),
                  "label": ys} for i in range(2)]
        staged = {k: jax.device_put(v) for k, v in feeds[0].items()}
        for _ in range(warmup):
            (l,) = exe.run(feed=staged, fetch_list=[loss],
                           return_numpy=False)
        np.asarray(l)
        t0 = time.perf_counter()
        for i in range(steps):
            (l,) = exe.run(feed=staged, fetch_list=[loss],
                           return_numpy=False)
            staged = {k: jax.device_put(v)
                      for k, v in feeds[(i + 1) % 2].items()}
        loss_val = float(np.asarray(l))
        dt = time.perf_counter() - t0
        return batch * steps / dt, loss_val

    # Device-resident feed: on real hardware the input pipeline streams
    # batches to HBM asynchronously; this harness's TPU sits behind a
    # slow network tunnel, so we pre-stage one batch to measure the
    # training step itself rather than tunnel bandwidth
    # (BENCH_PIPELINE=1 measures the double-buffered loader shape).
    feed = {"img": jnp.asarray(xs), "label": jnp.asarray(ys)}

    for _ in range(warmup):
        (l,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    np.asarray(l)  # sync

    # async dispatch: materialize the loss once at the end (a real loop
    # logs every N steps; per-step host sync would measure tunnel RTT)
    t0 = time.perf_counter()
    for _ in range(steps):
        (l,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    loss_val = float(np.asarray(l))  # sync
    dt = time.perf_counter() - t0
    return batch * steps / dt, loss_val


# Nominal bf16 peak TFLOPS by device kind.  MFU here is the honest
# model-FLOPs utilization vs the marketing peak; note the *achievable*
# matmul roofline is lower (benchmark/peak_matmul.py measures ~132
# TFLOPS sustained on this tunnel's v5e chip, i.e. ~67% of nominal —
# see PERF.md for the step-time decomposition).
_PEAK_TFLOPS = {  # longest-prefix entries first: "TPU v5e" before "TPU v5"
    "TPU v5 lite": 197, "TPU v5e": 197, "TPU v5p": 459,
    "TPU v6 lite": 918, "TPU v6e": 918,
    "TPU v2": 45, "TPU v3": 123, "TPU v4": 275, "TPU v5": 459,
}

_RESNET50_TRAIN_GFLOP_PER_IMG = 12.3  # ~3x the 4.1 GFLOP fwd at 224x224


def _mfu(ips: float) -> float:
    import jax

    kind = jax.devices()[0].device_kind
    peak = next((v for k, v in _PEAK_TFLOPS.items() if kind.startswith(k)), None)
    if peak is None:
        return -1.0
    if os.environ.get("BENCH_AMP", "1") != "1":
        peak /= 2  # f32 run: the MXU's f32 rate is half the bf16 peak
    return ips * _RESNET50_TRAIN_GFLOP_PER_IMG * 1e9 / (peak * 1e12)


def write_telemetry_artifact(path, headline):
    """Per-run telemetry artifact (schema paddle_tpu.bench_telemetry.v1):
    the headline record plus the observability registry snapshot
    (compile/step/feed/fetch metrics the run accumulated), the host
    event trace, and a measured per-step telemetry overhead with its
    fraction of the mean cached step — the checked-in-baseline shape
    BENCH_TELEMETRY_BASELINE.json pins (see BENCHMARKS.md).
    """
    import jax
    from paddle_tpu import observability as obs

    snap = obs.snapshot()
    overhead = obs.measure_step_overhead()
    art = {
        "schema": "paddle_tpu.bench_telemetry.v1",
        "headline": headline,
        "device": {
            "backend": jax.default_backend(),
            "kind": jax.devices()[0].device_kind,
            "count": jax.device_count(),
        },
        "telemetry_overhead_sec_per_step": overhead,
        "metrics": snap,
        "events": obs.GLOBAL_EVENTS.to_chrome_trace(),
    }
    # overhead as a fraction of the mean cached (hot-path) step, when
    # the run produced one — the <=2% budget, measured per run
    step = snap.get("executor_step_seconds", {}).get("values", [])
    hot = [v for v in step
           if v["labels"].get("cached") == "hit" and v["count"]]
    if hot:
        mean = sum(v["sum"] for v in hot) / sum(v["count"] for v in hot)
        if mean > 0:
            art["telemetry_overhead_fraction_of_step"] = overhead / mean
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
    return path


def main():
    baseline = 84.08  # img/s, reference ResNet-50 BS=256 train (see header)
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    try:
        ips, loss_val = run(batch=batch, steps=steps)
    except Exception as e:  # OOM etc: retry with half batch
        print(f"bench: batch={batch} failed ({type(e).__name__}); retrying 128",
              file=sys.stderr)
        batch = 128
        ips, loss_val = run(batch=batch, steps=steps)
    headline = {
        "metric": f"resnet50_train_samples_per_sec_per_chip_bs{batch}",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / baseline, 2),
        "mfu": round(_mfu(ips), 4),
    }
    print(json.dumps(headline))
    telemetry_path = os.environ.get("BENCH_TELEMETRY",
                                    "bench_telemetry.json")
    if telemetry_path not in ("", "0", "off"):
        try:
            write_telemetry_artifact(telemetry_path, headline)
            print(f"bench: telemetry artifact -> {telemetry_path}",
                  file=sys.stderr)
        except Exception as e:  # telemetry must never sink the bench
            print(f"bench: telemetry artifact failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
