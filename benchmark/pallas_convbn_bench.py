"""Epilogue-fused conv+BN-stats kernel vs XLA's in-model fusion
(the one unexplored ResNet-MFU lever VERDICT r4 names).

Compares, at the ResNet c4/c5 shapes where the plain Pallas conv came
closest (0.83-0.96x), the COMPOSITE forward op the model actually runs:
conv -> batch-statistics (mean/var over N,H,W).  The XLA side is the
jit-fused conv + stats reduction (what the in-model step executes);
the Pallas side accumulates the statistics in the conv's flush epilogue
while the f32 output block is still in VMEM, saving the stats pass's
full-tensor HBM read.

Methodology: R=64 value-chains inside one jit (benchmark/conv_probe.py
— the tunnel adds ~20 ms fixed overhead per program, so short chains
measure the harness, not the chip); a chained iteration feeds the conv
output back as input (Cin == Cout at these shapes) and folds mean/var
into the carried value so neither side can dead-code the statistics.

Prints one JSON line per (shape, variant).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.pallas.conv import conv2d_bn_stats_nhwc

SHAPES = [
    # (tag, N, H, W, C==O, K)
    ("c4.3x3", 256, 14, 14, 256, 3),
    ("c5.3x3", 256, 7, 7, 512, 3),
]
R = 64


def xla_conv_bn(x, w):
    out = lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    mean = jnp.mean(out, axis=(0, 1, 2))
    var = jnp.mean(out * out, axis=(0, 1, 2)) - mean * mean
    return out.astype(x.dtype), mean, var


def pallas_conv_bn(x, w, k):
    return conv2d_bn_stats_nhwc(x, w, k // 2)


def chain(fn):
    """Feed conv output back as input; fold the stats into the carry so
    they cannot be dead-coded."""

    def run(x0):
        def body(_, y):
            out, mean, var = fn(y)
            # rank-1 correction keeps stats live at negligible cost
            return out + (mean * 0 + var * 0).astype(out.dtype)

        y = lax.fori_loop(0, R, body, x0)
        return jnp.sum(y.astype(jnp.float32))

    return jax.jit(run)


def timed(jf, arg, steps=3):
    # same discipline as benchmark/pallas_conv_bench.py::timed (R-chain
    # amortization; kept in step with that file's methodology)
    out = float(jf(arg))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = jf(arg)
    float(out)
    return (time.perf_counter() - t0) / steps / R


def main():
    rows = []
    for tag, n, h, w, c, k in SHAPES:
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(n, h, w, c).astype(np.float32),
                        dtype=jnp.bfloat16)
        wt = jnp.asarray(rng.randn(k, k, c, c).astype(np.float32) * 0.05,
                        dtype=jnp.bfloat16)
        flops = 2.0 * n * h * w * c * c * k * k

        t_xla = timed(chain(lambda v: xla_conv_bn(v, wt)), x)
        t_pal = timed(chain(lambda v: pallas_conv_bn(v, wt, k)), x)
        row = {
            "shape": tag, "n": n, "hw": h, "c": c, "k": k,
            "xla_fused_ms": round(t_xla * 1e3, 3),
            "pallas_fused_ms": round(t_pal * 1e3, 3),
            "xla_tf_s": round(flops / t_xla / 1e12, 1),
            "pallas_tf_s": round(flops / t_pal / 1e12, 1),
            "pallas_speedup_vs_xla": round(t_xla / t_pal, 3),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    main()
