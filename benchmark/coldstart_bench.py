#!/usr/bin/env python3
"""Cold-start benchmark (ISSUE 20): artifact-booted serving vs cold JIT.

What it measures
----------------
A bundled MLP export (--depth x --hidden, buckets up to --max_batch) is
compiled once with ``paddle compile``; then two fresh server processes
are booted via ``paddle serve --warmup``:

- **jit boot** — no artifacts: every bucket-ladder program is
  traced + compiled before the listening line prints;
- **aot boot** — ``--artifacts=DIR``: every program is deserialized
  from the artifact store (donation restored).

The reported number is **time-to-first-successful-response**: process
spawn -> first 200 from POST /predict, the interval a rolling restart
actually spends dark.  Both boots answer the same request body and the
response bytes must be identical (the artifact path is a cache, never
an approximation).  The aot boot's /health must report a pure
``boot=aot`` store with zero rejected lookups.

A separate in-process probe asserts donation is ACTIVE on the AOT
path: a stateful two-op program is exported, re-loaded from the store
in a fresh executor, stepped twice, and the step-2 donated input
buffer must come back deleted (donated to XLA), not merely unused.

Artifact
--------
``--out`` (default COLDSTART_r01.json) gets a
``paddle_tpu.coldstart_bench.v1`` document; BENCHMARKS.md records the
acceptance row (aot boot >= --min-speedup x faster, default 3.0).

Usage
-----
    python benchmark/coldstart_bench.py [--depth=64] [--hidden=128]
        [--max_batch=64] [--reps=1] [--min-speedup=3.0]
        [--out=COLDSTART_r01.json] [--smoke]

The default model is deep and narrow on purpose: cold-start pain is
compile time, so the bench wants many XLA programs (7 buckets) each
with a long op chain (64 fc layers), while keeping the parameter set
small enough that loading params — paid identically by both boots —
does not drown the compile-time difference being measured.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCHEMA = "paddle_tpu.coldstart_bench.v1"


def build_model(dirname: str, depth: int, hidden: int, in_dim: int,
                classes: int) -> str:
    import paddle_tpu as fluid

    fluid.framework.reset_default_programs()
    x = fluid.layers.data(name="x", shape=[in_dim], dtype="float32")
    h = x
    for _ in range(depth):
        h = fluid.layers.fc(input=h, size=hidden, act="relu")
    pred = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(dirname, ["x"], [pred], exe)
    return dirname


# ---------------------------------------------------------------------------
# subprocess boot: spawn `paddle serve --warmup`, time to first 200
# ---------------------------------------------------------------------------


def boot_once(model_dir: str, max_batch: int, body: bytes,
              artifacts: str = None, timeout: float = 900.0) -> dict:
    """One cold boot in a fresh process.  Returns wall times (spawn ->
    listening, spawn -> first 200), the /predict response bytes, and
    the server's /health aot block."""
    cmd = [sys.executable, "-m", "paddle_tpu.cli", "serve",
           f"--model_dir={model_dir}", "--port=0",
           f"--max_batch={max_batch}", "--warmup"]
    if artifacts:
        cmd.append(f"--artifacts={artifacts}")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    t0 = time.perf_counter()
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env,
                            cwd=REPO)
    address = None
    try:
        deadline = t0 + timeout
        for line in proc.stdout:
            if "listening on" in line:
                address = line.rsplit("listening on", 1)[1].strip()
                break
            if time.perf_counter() > deadline:
                raise SystemExit("boot timed out before listening line")
        if address is None:
            raise SystemExit(
                f"server exited before listening (rc={proc.wait()})")
        listening_s = time.perf_counter() - t0
        base = f"http://{address}"
        req = urllib.request.Request(
            base + "/predict", data=body,
            headers={"Content-Type": "application/json"})
        while True:
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    resp = r.read()
                break
            except (urllib.error.URLError, ConnectionError):
                if time.perf_counter() > deadline:
                    raise SystemExit("no 200 before boot timeout")
                time.sleep(0.02)
        first_response_s = time.perf_counter() - t0
        with urllib.request.urlopen(base + "/health", timeout=30) as r:
            health = json.loads(r.read())
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
    return {"listening_s": round(listening_s, 3),
            "first_response_s": round(first_response_s, 3),
            "response": resp, "aot": health.get("aot")}


# ---------------------------------------------------------------------------
# donation probe: AOT-loaded executables must still alias state buffers
# ---------------------------------------------------------------------------


def donation_probe(tmp: str) -> dict:
    """Export a stateful program, reload it from the store in a fresh
    executor, step twice: step 2's donated input (step 1's own output)
    must come back deleted — donation active, asserted not assumed."""
    import jax.numpy as jnp

    from paddle_tpu import aot, framework
    from paddle_tpu.aot.artifact import ArtifactStore, ArtifactWriter
    from paddle_tpu.executor import Executor, Scope

    def _program():
        prog = framework.Program()
        block = prog.global_block()
        block.create_var(name="W", shape=(8, 8), dtype="float32",
                         persistable=True)
        block.create_var(name="Y", shape=(8, 8), dtype="float32")
        block.append_op(type="scale", inputs={"X": ["W"]},
                        outputs={"Out": ["Y"]}, attrs={"scale": 2.0})
        block.append_op(type="scale", inputs={"X": ["W"]},
                        outputs={"Out": ["W"]}, attrs={"scale": 1.5})
        return prog

    art = os.path.join(tmp, "donation_artifacts")
    w0 = np.arange(64, dtype=np.float32).reshape(8, 8)
    exe = Executor()
    scope = Scope()
    scope.set("W", jnp.asarray(w0))
    writer = ArtifactWriter(art)
    with aot.capture(writer):
        (y_ref,) = exe.run(_program(), feed={}, fetch_list=["Y"],
                           scope=scope)
    writer.finish()

    exe2 = Executor()
    exe2.aot_store = ArtifactStore(art)
    scope2 = Scope()
    scope2.set("W", jnp.asarray(w0))
    prog2 = _program()
    (y_aot,) = exe2.run(prog2, feed={}, fetch_list=["Y"], scope=scope2)
    w_step1 = scope2.get("W")
    exe2.run(prog2, feed={}, fetch_list=["Y"], scope=scope2)
    return {
        "loaded_from_store": exe2.aot_store.results.get("loaded", 0) > 0,
        "bit_identical": bool(np.array_equal(np.asarray(y_ref),
                                             np.asarray(y_aot))),
        "donation_active": bool(w_step1.is_deleted()),
    }


# ---------------------------------------------------------------------------


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--depth", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--in-dim", type=int, default=64)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--max_batch", type=int, default=64)
    ap.add_argument("--reps", type=int, default=1,
                    help="boots per mode; the best (min) time is scored")
    ap.add_argument("--min-speedup", type=float, default=3.0)
    ap.add_argument("--out", default="COLDSTART_r01.json")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model, no speedup gate (CI wiring check)")
    args = ap.parse_args()
    if args.smoke:
        args.depth, args.hidden, args.max_batch = 2, 16, 2
        args.min_speedup = 0.0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.aot.export import export_model

    with tempfile.TemporaryDirectory(prefix="paddle_coldstart_") as tmp:
        model_dir = build_model(os.path.join(tmp, "model"), args.depth,
                                args.hidden, args.in_dim, args.classes)
        art_dir = os.path.join(tmp, "artifacts")
        t0 = time.perf_counter()
        writer = export_model(model_dir, art_dir, max_batch=args.max_batch)
        export_s = time.perf_counter() - t0
        print(f"paddle compile: {len(writer.entries)} executables "
              f"in {export_s:.1f}s", flush=True)

        rng = np.random.RandomState(0)
        body = json.dumps({
            "x": rng.randn(2, args.in_dim).astype("float32").tolist()
        }).encode()

        boots = {"jit": [], "aot": []}
        for rep in range(max(1, args.reps)):
            for mode in ("jit", "aot"):
                b = boot_once(model_dir, args.max_batch, body,
                              artifacts=art_dir if mode == "aot" else None)
                boots[mode].append(b)
                print(f"{mode} boot #{rep}: listening "
                      f"{b['listening_s']}s, first response "
                      f"{b['first_response_s']}s", flush=True)

        parity = all(b["response"] == boots["jit"][0]["response"]
                     for m in boots for b in boots[m])
        aot_health = boots["aot"][-1]["aot"] or {}
        rejected = {k: v for k, v in
                    (aot_health.get("results") or {}).items()
                    if k != "loaded"}
        probe = donation_probe(tmp)

    jit_s = min(b["first_response_s"] for b in boots["jit"])
    aot_s = min(b["first_response_s"] for b in boots["aot"])
    speedup = jit_s / aot_s if aot_s else float("inf")
    doc = {
        "schema": SCHEMA,
        "config": {"depth": args.depth, "hidden": args.hidden,
                   "in_dim": args.in_dim, "classes": args.classes,
                   "max_batch": args.max_batch, "reps": args.reps,
                   "smoke": args.smoke},
        "export": {"executables": len(writer.entries),
                   "bytes": sum(e["nbytes"]
                                for e in writer.entries.values()),
                   "seconds": round(export_s, 3)},
        "boots": {m: [{k: b[k] for k in
                       ("listening_s", "first_response_s")}
                      for b in boots[m]] for m in boots},
        "jit_first_response_s": jit_s,
        "aot_first_response_s": aot_s,
        "speedup": round(speedup, 2),
        "parity_bit_identical": parity,
        "aot_boot": aot_health.get("boot"),
        "aot_store_results": aot_health.get("results"),
        "donation": probe,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"jit {jit_s:.2f}s -> aot {aot_s:.2f}s "
          f"({speedup:.1f}x); parity={parity} "
          f"donation_active={probe['donation_active']} -> {args.out}")

    ok = (parity and probe["donation_active"] and probe["bit_identical"]
          and probe["loaded_from_store"] and not rejected
          and aot_health.get("boot") == "aot"
          and speedup >= args.min_speedup)
    if not ok:
        print(f"FAIL: speedup={speedup:.2f} (need >= "
              f"{args.min_speedup}), parity={parity}, "
              f"aot_boot={aot_health.get('boot')!r}, "
              f"rejected={rejected}, donation={probe}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
