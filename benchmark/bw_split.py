"""Bandwidth attribution over an xprof trace: for every device XLA op,
estimate HBM bytes moved from the tensor shapes in its HLO result type
and report effective GB/s, so "is this step bandwidth-bound?" has a
number instead of a vibe.

Usage: python benchmark/bw_split.py /tmp/rn50_trace [n_steps]

Byte model per op (conservative):
  - the op writes its result tensors once, and reads at least the
    same volume of operands (factor 2 total) — multi-operand fusions
    read MORE, so the derived GB/s is a LOWER bound on achieved
    bandwidth;
  - convolution/dot ops are flagged [MXU] and excluded from the
    bandwidth bound (their time is compute).
"""

import re
import sys

import os

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from xprof import find_trace, load_xspace  # noqa: E402

_DTYPE_BYTES = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4,
                "s8": 1, "u8": 1, "pred": 1, "s64": 8}
_OPCODE = re.compile(r"\s+[a-z][a-z\-.0-9]*\(")


def result_bytes(name):
    """Tensor bytes of the op's RESULT type(s) only (the text right of
    " = " up to the opcode word)."""
    if " = " not in name:
        return 0
    rhs = name.split(" = ", 1)[1]
    head = _OPCODE.split(rhs)[0]
    total = 0
    for m in re.finditer(
            r"(bf16|f16|f32|s32|u32|s8|u8|pred|s64)\[([\d,]*)\]", head):
        dt, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def main():
    path = find_trace(sys.argv[1])
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    xs = load_xspace(path)
    for plane in xs.planes:
        if not plane.name.startswith("/device:"):
            continue
        meta = plane.event_metadata
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            agg = {}
            for ev in line.events:
                name = meta[ev.metadata_id].name
                t, n = agg.get(name, (0.0, 0))
                agg[name] = (t + ev.duration_ps / 1e12, n + 1)
            rows = sorted(((t, n, name) for name, (t, n) in agg.items()),
                          reverse=True)
            if not rows:
                print(f"== {plane.name}: no XLA op events")
                continue
            total = sum(t for t, _, _ in rows)
            print(f"== {plane.name}: busy {total/steps*1e3:.2f} ms/step")
            print(f"{'ms/step':>8} {'share':>6} {'GB/step':>8} "
                  f"{'>=GB/s':>7}  op")
            bw_time = mxu_time = bw_bytes = 0.0
            for t, n, name in rows:
                per = t / steps
                rb = result_bytes(name) * n / steps
                is_mxu = ("convolution" in name.split(" = ")[0]
                          or re.search(r"%(dot|conv)", name.split(" = ")[0]))
                traffic = rb * 2
                if is_mxu:
                    mxu_time += per
                else:
                    bw_time += per
                    bw_bytes += traffic
                if per * steps >= rows[min(29, len(rows) - 1)][0]:
                    gbs = traffic / per / 1e9 if per else 0
                    label = name.split(" = ")[0]
                    print(f"{per*1e3:8.3f} {t/total:6.1%} {traffic/1e9:8.3f} "
                          f"{gbs:7.0f}  {label[:55]}"
                          f"{' [MXU]' if is_mxu else ''}")
            print(f"\nMXU (conv/dot standalone) time: {mxu_time*1e3:.1f} "
                  f"ms/step")
            if bw_time:
                print(f"non-MXU time: {bw_time*1e3:.1f} ms/step moving "
                      f">= {bw_bytes/1e9:.1f} GB/step "
                      f"=> >= {bw_bytes/bw_time/1e9:.0f} GB/s average")


if __name__ == "__main__":
    main()
