"""Per-shape XLA conv emitter probe at the ResNet-50 BS=256 hot shapes.

Methodology (round-4 correction): this chip's tunnel adds ~20 ms of
FIXED per-program overhead on top of the 2.4-5.7 ms dispatch floor —
a 4096^3 bf16 matmul chain measures 38 TF/s at R=8 chained
applications but 126 TF/s at R=64.  Every measurement here therefore
value-chains R=64 applications inside one jit and reads a single
scalar:

- square stride-1 convs (Cin == Cout) chain directly: y = conv(y, w);
- expand/reduce 1x1 pairs chain as alternating pairs (C -> 4C -> C),
  reporting the pair average.

The stride-2 downsample/stem shapes are not probed here (no
shape-preserving chain exists for them); they stay on the XLA emitter
unconditionally.

The earlier revision of this file dep-chained with R=8 and read
5-16 TF/s for every shape; those numbers were fixed-overhead
artifacts, not emitter efficiency (PERF.md "Round-4 conv kernel
verdict").

Usage: python benchmark/conv_probe.py [--steps N] [--only c2,c4]
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

R = 64

# (name, N, H, W, Cin, Cout, k, stride)
SQUARE = [
    ("c2.3x3", 256, 56, 56, 64, 64, 3, 1),
    ("c3.3x3", 256, 28, 28, 128, 128, 3, 1),
    ("c4.3x3", 256, 14, 14, 256, 256, 3, 1),
    ("c5.3x3", 256, 7, 7, 512, 512, 3, 1),
]
PAIRS = [  # 1x1 expand/reduce bottleneck pairs
    ("c2.1x1", 256, 56, 56, 64, 256),
    ("c3.1x1", 256, 28, 28, 128, 512),
    ("c4.1x1", 256, 14, 14, 256, 1024),
]


def conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def timed(jf, arg, steps, napps):
    out = float(jf(arg))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = jf(arg)
    float(out)
    return (time.perf_counter() - t0) / steps / napps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--only", type=str, default="")
    args = ap.parse_args()
    only = [t for t in args.only.split(",") if t]
    rng = np.random.RandomState(0)
    print(f"{'shape':10} {'ms':>8} {'TF/s':>7}", flush=True)

    for name, n, h, w, ci, co, k, s in SQUARE:
        if only and not any(t in name for t in only):
            continue
        x = jnp.asarray(rng.randn(n, h, w, ci), jnp.bfloat16)
        wt = jnp.asarray(rng.randn(k, k, ci, co) * 0.03, jnp.bfloat16)
        flops = 2 * n * h * w * ci * co * k * k

        def run(x0, wt=wt, s=s):
            def body(_, y):
                return conv(y, wt, s)

            return jnp.sum(lax.fori_loop(0, R, body, x0).astype(
                jnp.float32))

        dt = timed(jax.jit(run), x, args.steps, R)
        print(f"{name:10} {dt*1e3:8.3f} {flops/dt/1e12:7.1f}", flush=True)

    for name, n, h, w, ci, co in PAIRS:
        if only and not any(t in name for t in only):
            continue
        x = jnp.asarray(rng.randn(n, h, w, ci), jnp.bfloat16)
        w1 = jnp.asarray(rng.randn(1, 1, ci, co) * 0.05, jnp.bfloat16)
        w2 = jnp.asarray(rng.randn(1, 1, co, ci) * 0.05, jnp.bfloat16)
        flops = 2 * n * h * w * ci * co  # per application (avg of pair)

        def run(x0, w1=w1, w2=w2):
            def body(_, y):
                return conv(conv(y, w1), w2)

            return jnp.sum(lax.fori_loop(0, R // 2, body, x0).astype(
                jnp.float32))

        dt = timed(jax.jit(run), x, args.steps, R)
        print(f"{name:10} {dt*1e3:8.3f} {flops/dt/1e12:7.1f}", flush=True)


if __name__ == "__main__":
    main()
