"""Per-shape XLA conv emitter probe at the ResNet-50 BS=256 hot shapes.

PERF.md's trace decomposition shows the framework ResNet step is bound
by the conv emitters (fwd ~48 TF, bwd-input ~31 TF, bwd-filter ~45 TF
of a measured 132 TF matmul roofline).  This probe times each dominant
conv shape in isolation — forward, backward-input, backward-filter —
so a Pallas implicit-GEMM kernel has a per-shape target to beat.

Tunnel-aware methodology (PERF.md): the per-dispatch floor is
2.4-5.7 ms and D2H runs ~30 MB/s, so each measurement runs R
dependency-chained iterations inside ONE jitted program and transfers
only a scalar.  The chain dependency is data-dependent
(where(isnan(s), s, 0)) so XLA can neither fold it away nor CSE the
iterations.  bf16 IO, f32 accumulation, NHWC (the amp model layout).

Usage: python benchmark/conv_probe.py [--steps N] [--inner R]
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# (name, N, H, W, Cin, Cout, k, stride) — the shapes carrying ResNet-50
# BS=256's conv FLOPs (each 3x3 row repeats 3-6x per step, fwd + 2 bwd)
SHAPES = [
    ("c2.3x3", 256, 56, 56, 64, 64, 3, 1),
    ("c3.3x3", 256, 28, 28, 128, 128, 3, 1),
    ("c4.3x3", 256, 14, 14, 256, 256, 3, 1),
    ("c5.3x3", 256, 7, 7, 512, 512, 3, 1),
    ("c2.1x1x4", 256, 56, 56, 64, 256, 1, 1),
    ("c4.1x1x4", 256, 14, 14, 256, 1024, 1, 1),
    ("c3.down", 256, 56, 56, 256, 512, 1, 2),
    ("stem.7x7", 256, 224, 224, 3, 64, 7, 2),
]


def conv(x, w, stride):
    # plain bf16 conv (the MXU accumulates f32 internally); grad
    # through preferred_element_type=f32 trips a dtype check in the
    # conv transpose rule, and the model path convolves bf16->bf16
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def chain(fn, x, r):
    """Run fn(x_i) R times with an unfoldable data dependency between
    iterations; returns a scalar."""

    def body(_, carry):
        x_c, acc = carry
        s = jnp.sum(fn(x_c).astype(jnp.float32))
        dep = jnp.where(jnp.isnan(s), s, 0.0).astype(x.dtype)
        return x + dep, acc + s

    _, acc = lax.fori_loop(0, r, body, (x, jnp.float32(0)))
    return acc


def time_scalar(fn, steps):
    out = float(fn())  # compile + warm
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn()
    float(out)
    return (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--inner", type=int, default=8)
    ap.add_argument("--only", type=str, default="",
                    help="comma-separated shape-name substrings")
    args = ap.parse_args()
    R = args.inner
    rng = np.random.RandomState(0)
    only = [t for t in args.only.split(",") if t]
    print(f"{'shape':10} {'dir':6} {'ms':>8} {'TF/s':>7}", flush=True)
    for name, n, h, w, ci, co, k, s in SHAPES:
        if only and not any(t in name for t in only):
            continue
        x = jnp.asarray(rng.randn(n, h, w, ci), jnp.bfloat16)
        wt = jnp.asarray(rng.randn(k, k, ci, co) * 0.05, jnp.bfloat16)
        oh, ow = -(-h // s), -(-w // s)
        flops = 2 * n * oh * ow * ci * co * k * k
        g = jnp.asarray(rng.randn(n, oh, ow, co) * 0.05, jnp.bfloat16)

        def loss_x(xx, ww, gg):
            return jnp.sum(conv(xx, ww, s).astype(jnp.float32) *
                           gg.astype(jnp.float32))

        # each direction chains on an operand its output DEPENDS on
        # (dx is linear: independent of x; dw independent of w) so the
        # loop body cannot be hoisted as loop-invariant
        fwd = jax.jit(lambda xx: chain(lambda v: conv(v, wt, s), xx, R))
        bwd_x = jax.jit(lambda gg: chain(
            lambda v: jax.grad(loss_x, argnums=0)(x, wt, v), gg, R))
        bwd_w = jax.jit(lambda xx: chain(
            lambda v: jax.grad(loss_x, argnums=1)(v, wt, g), xx, R))
        for tag, fn, arg in (("fwd", fwd, x), ("bwd_x", bwd_x, g),
                             ("bwd_w", bwd_w, x)):
            # the harness itself costs a sum + a dep-add pass per
            # iteration (measured: it caps a 132TF 4096^3 matmul at
            # ~38TF) — subtract an identity-chain baseline on the same
            # argument so the reported net time is the op alone
            ov_fn = jax.jit(lambda aa: chain(lambda v: v, aa, R))
            dt_ov = time_scalar(functools.partial(ov_fn, arg),
                                args.steps) / R
            dt = time_scalar(functools.partial(fn, arg), args.steps) / R
            net = max(dt - dt_ov, 1e-9)
            print(f"{name:10} {tag:6} {net*1e3:8.2f} "
                  f"{flops/net/1e12:7.1f}  (raw {dt*1e3:.2f} "
                  f"ov {dt_ov*1e3:.2f})", flush=True)


if __name__ == "__main__":
    main()
