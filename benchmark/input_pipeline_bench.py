"""Input-pipeline benchmark: the native prefetching recordio loader in
the training path (round-1 VERDICT weak item 9 — the loader must appear
in a measured path, not sit as dead code).

Writes CIFAR-sized sample batches into recordio shards, then measures:
  1. raw loader throughput (records/s, MB/s) vs prefetch thread count,
  2. a short training loop fed from the loader (decode + host->device
     transfer overlapped with the previous step's compute) vs the same
     loop on a pre-staged device batch — the delta is the pipeline cost.

Run on CPU (default) or against the real chip (JAX_PLATFORMS unset).
Prints one JSON line per measurement.
"""

import json
import os
import struct
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# honor JAX_PLATFORMS before first backend use (the axon TPU plugin
# otherwise overrides it and "CPU" runs silently hit the tunnel)
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass


def make_shards(tmp, n_shards=2, records_per_shard=200, batch=64):
    from paddle_tpu.native import RecordIOWriter

    rng = np.random.RandomState(0)
    paths = []
    for s in range(n_shards):
        path = os.path.join(tmp, f"train-{s:03d}.recordio")
        with RecordIOWriter(path) as w:
            for _ in range(records_per_shard):
                xs = (rng.rand(batch, 3, 32, 32) * 255).astype(np.uint8)
                ys = rng.randint(0, 10, (batch,)).astype(np.int32)
                w.write(struct.pack("<I", batch) + xs.tobytes() + ys.tobytes())
        paths.append(path)
    return paths


def decode(rec, batch):
    n = struct.unpack("<I", rec[:4])[0]
    assert n == batch
    img_bytes = batch * 3 * 32 * 32
    xs = np.frombuffer(rec[4:4 + img_bytes], np.uint8).reshape(
        batch, 3, 32, 32).astype(np.float32) / 255.0
    ys = np.frombuffer(rec[4 + img_bytes:], np.int32).astype(np.int64)
    return xs, ys.reshape(-1, 1)


def bench_loader(paths, batch):
    from paddle_tpu.native import DataLoader

    rec_bytes = 4 + batch * 3 * 32 * 32 + batch * 4
    for threads in (1, 2, 4):
        t0 = time.perf_counter()
        n = 0
        dl = DataLoader(paths, num_threads=threads, capacity=64)
        for rec in dl:
            n += 1
        dl.close()
        dt = time.perf_counter() - t0
        print(json.dumps({
            "bench": f"recordio_loader_threads{threads}",
            "records_per_sec": round(n / dt, 1),
            "mb_per_sec": round(n * rec_bytes / dt / 1e6, 1),
            "samples_per_sec": round(n * batch / dt, 1)}))


def bench_train_from_loader(paths, batch, steps=60):
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet_cifar10
    from paddle_tpu.native import DataLoader

    fluid.framework.reset_default_programs()
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    pred = resnet_cifar10(img, depth=8, class_dim=10)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(input=pred,
                                                        label=label))
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())

    # warm the compile with one staged batch
    dl = DataLoader(paths, num_threads=2, capacity=64)
    it = iter(dl)
    xs, ys = decode(next(it), batch)
    for _ in range(2):
        (l,) = exe.run(feed={"img": xs, "label": ys},
                       fetch_list=[loss], return_numpy=False)
    float(np.asarray(l))

    # loader-fed loop: decode + H2D every step, async dispatch
    t0 = time.perf_counter()
    done = 0
    for rec in it:
        if done >= steps:
            break
        xs, ys = decode(rec, batch)
        (l,) = exe.run(feed={"img": xs, "label": ys},
                       fetch_list=[loss], return_numpy=False)
        done += 1
    float(np.asarray(l))
    dt_loader = (time.perf_counter() - t0) / max(done, 1)
    dl.close()

    # pre-staged loop: same batch resident on device
    feed = {"img": jnp.asarray(xs), "label": jnp.asarray(ys)}
    (l,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(l))
    t0 = time.perf_counter()
    for _ in range(steps):
        (l,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(l))
    dt_staged = (time.perf_counter() - t0) / steps

    # double-buffered loop: decode + device_put of batch N+1 issued
    # while step N executes (the trainer's prefetch=True path)
    import jax

    dl2 = DataLoader(paths, num_threads=2, capacity=64)
    it2 = iter(dl2)
    xs, ys = decode(next(it2), batch)
    staged = {"img": jax.device_put(xs), "label": jax.device_put(ys)}
    (l,) = exe.run(feed=staged, fetch_list=[loss], return_numpy=False)
    float(np.asarray(l))
    t0 = time.perf_counter()
    done = 0
    # step 1 consumes the pre-staged buffer (no decode cost in-loop) and
    # the final iteration stages a buffer that is never run; the two
    # biases cancel to first order over the 60-step window
    for rec in it2:
        if done >= steps:
            break
        (l,) = exe.run(feed=staged, fetch_list=[loss], return_numpy=False)
        xs, ys = decode(rec, batch)
        staged = {"img": jax.device_put(xs), "label": jax.device_put(ys)}
        done += 1
    float(np.asarray(l))
    dt_prefetch = (time.perf_counter() - t0) / max(done, 1)
    dl2.close()

    print(json.dumps({
        "bench": "train_smallnet_bs%d" % batch,
        "ms_per_step_loader_fed": round(dt_loader * 1e3, 2),
        "ms_per_step_loader_prefetch": round(dt_prefetch * 1e3, 2),
        "ms_per_step_prestaged": round(dt_staged * 1e3, 2),
        "pipeline_overhead_ms": round((dt_loader - dt_staged) * 1e3, 2),
        "prefetch_overhead_ms": round((dt_prefetch - dt_staged) * 1e3, 2)}))


def main():
    batch = int(os.environ.get("IPB_BATCH", "64"))
    with tempfile.TemporaryDirectory() as tmp:
        paths = make_shards(tmp, batch=batch)
        bench_loader(paths, batch)
        bench_train_from_loader(paths, batch)


if __name__ == "__main__":
    main()
