#!/usr/bin/env python3
"""Paged-KV decode benchmark (ISSUE 15): concurrent ragged-batch
generation through the DecodeSession vs the serving engine's
solo-execution fallback — the throughput claim as a number.

What it runs
------------
The bundled NMT demo network (demos/seq2seq) with seed-initialized
parameters — identical weights for both paths, so both decode identical
tokens and the comparison is pure scheduling:

- **solo**  — the PR-13 serving shape for ragged workloads: W worker
  threads, each a dense ``SequenceGenerator`` (one sequence per step
  dispatch, encoder re-run every step), draining one request queue.
  This is exactly what the bucketer's ragged fallback does per request.
- **paged** — ``GenerationEngine``: one prefill per admission writes
  the encoder states into KV pages; every decode step advances ALL
  active slots through one fixed-shape compiled program (continuous
  batching at token granularity).

Both paths serve the same burst of ragged-length requests; we record
generated tokens/s, per-request p50/p99 latency, and the executor
compile-cache hit rate over the measured window (after warmup the paged
path must be 1.0 — batch churn never re-traces).

Artifact
--------
``--out`` (default decode_bench.json) gets a
``paddle_tpu.decode_bench.v1`` document; BENCHMARKS.md documents the
schema and records the acceptance row (>= 3x tokens/s at equal or
lower p99, cache hit rate 1.0).

Sharing modes (ISSUE 18)
------------------------
``--mode=prefix`` serves a prefix-heavy burst (N requests drawn from a
handful of long shared prompt prefixes) through a ``TinyDecoderLM``
engine twice — prefix cache off, then on — and records tokens/s and
**peak page-pool occupancy** for both.  The cached run must decode
token-identical ids; the win is skipped prefill work plus aliased
(copy-on-write) prefix pages.  ``--mode=spec`` decodes the same burst
greedily and speculatively (n-gram prompt-lookup draft, one ragged
verify step per chunk) and hard-fails unless the speculative ids are
token-identical; the acceptance ratio comes from the
``decode_spec_*`` counters.  ``--mode=sharing`` runs both and writes
one ``paddle_tpu.decode_bench.v2`` artifact
(benchmark/DECODE_BENCH_r02.json is such a run).

Usage
-----
    python benchmark/decode_bench.py [--mode=compare|prefix|spec|sharing]
        [--requests=64] [--slots=8]
        [--solo-workers=2] [--max-new-tokens=16] [--pages=96]
        [--page-size=8] [--pages-per-seq=8] [--prefix-pages=4]
        [--spec-k=4] [--out=decode_bench.json] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# honor JAX_PLATFORMS before first backend use (the axon TPU plugin
# otherwise overrides it and "CPU" runs silently hit the tunnel)
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

SCHEMA = "paddle_tpu.decode_bench.v1"
SCHEMA_V2 = "paddle_tpu.decode_bench.v2"


class _Params:
    def __init__(self):
        from paddle_tpu.executor import Scope

        self.scope = Scope()


def make_beam_gen(max_length: int):
    # the ONE shared spec builder — bench, serving config, and parity
    # tests must construct the identical network
    from demos.seq2seq.gen_config import make_beam_gen as _mk

    return _mk(beam_size=1, max_length=max_length)


def make_requests(n: int, seed: int = 7):
    from demos.seq2seq.network import VOCAB

    rng = np.random.RandomState(seed)
    return [list(rng.randint(2, VOCAB, rng.randint(2, 9)))
            for _ in range(n)]


def _cache_counts():
    from paddle_tpu.observability import metrics as M

    snap = M.snapshot()
    out = {}
    for k, name in (("miss", "executor_compile_cache_miss_total"),
                    ("hit", "executor_compile_cache_hit_total")):
        out[k] = sum(r["value"] for r in
                     snap.get(name, {"values": []})["values"])
    return out


def _percentiles(lat_s):
    lat = sorted(lat_s)
    pick = lambda p: lat[min(len(lat) - 1, int(p * len(lat)))]  # noqa: E731
    return {"p50_ms": round(pick(0.50) * 1e3, 3),
            "p99_ms": round(pick(0.99) * 1e3, 3)}


# ---------------------------------------------------------------------------
# solo baseline: the serving engine's ragged fallback, W workers
# ---------------------------------------------------------------------------


def clone_params(params):
    """Deep-copy the parameter scope (the ``pd_machine_clone`` shape the
    serving replicas use): the executor donates state buffers per run,
    so concurrent workers must not share device buffers."""
    p = _Params()
    for name in list(params.scope.keys()):
        p.scope.set(name, np.array(np.asarray(params.scope.get(name))))
    return p


def run_solo(params, requests, max_new, workers: int):
    from paddle_tpu.generation import SequenceGenerator

    gens = [SequenceGenerator(make_beam_gen(max_new), clone_params(params))
            for _ in range(workers)]
    for g in gens:                      # warmup: compile each replica
        g.generate_greedy([requests[0]])
    c0 = _cache_counts()

    work: queue.Queue = queue.Queue()
    results = [None] * len(requests)
    t0 = time.perf_counter()
    for i, r in enumerate(requests):
        work.put((i, r))

    errors = []

    def worker(g):
        while True:
            try:
                i, src = work.get_nowait()
            except queue.Empty:
                return
            try:
                ids = g.generate_greedy([src])
            except BaseException as e:  # surface, don't silently drop
                errors.append(e)
                return
            results[i] = (ids, time.perf_counter() - t0)

    threads = [threading.Thread(target=worker, args=(g,)) for g in gens]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    wall = time.perf_counter() - t0
    c1 = _cache_counts()
    tokens = sum(len(ids) for ids, _ in results)
    lat = [dt for _, dt in results]
    misses = c1["miss"] - c0["miss"]
    hits = c1["hit"] - c0["hit"]
    return {
        "workers": workers,
        "tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        **_percentiles(lat),
        "cache": {"miss": misses, "hit": hits,
                  "hit_rate": round(hits / max(1, hits + misses), 4)},
    }, [ids for ids, _ in results]


# ---------------------------------------------------------------------------
# paged: the decode engine
# ---------------------------------------------------------------------------


def run_paged(params, requests, max_new, slots, pages, page_size):
    from paddle_tpu.decode import GenerationEngine

    engine = GenerationEngine.for_seq2seq(
        make_beam_gen(max_new), clone_params(params), num_pages=pages,
        page_size=page_size, pages_per_seq=2, max_slots=slots,
        max_waiting=len(requests) + 1, max_new_tokens=max_new)
    engine.submit(requests[0]).wait(600)      # warmup: prefill + step
    c0 = _cache_counts()

    t0 = time.perf_counter()
    reqs = [engine.submit(r) for r in requests]
    done_at = []
    for r in reqs:
        r.wait(600)
        done_at.append(time.perf_counter() - t0)
    wall = time.perf_counter() - t0
    c1 = _cache_counts()
    engine.stop()
    tokens = sum(len(r.tokens) for r in reqs)
    misses = c1["miss"] - c0["miss"]
    hits = c1["hit"] - c0["hit"]
    return {
        "slots": slots,
        "pages": pages,
        "page_size": page_size,
        "tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        **_percentiles(done_at),
        "cache": {"miss": misses, "hit": hits,
                  "hit_rate": round(hits / max(1, hits + misses), 4)},
    }, [list(r.tokens) for r in reqs]


# ---------------------------------------------------------------------------
# sharing modes (ISSUE 18): prefix cache + speculative decoding
# ---------------------------------------------------------------------------


class _PeakSampler:
    """Polls ``allocator.pages_in_use`` on a side thread and keeps the
    max — the pool-occupancy number CoW prefix sharing is supposed to
    shrink.  Polling can miss a one-tick spike; at decode-step
    timescales (ms) a 0.5 ms sample period is dense enough."""

    def __init__(self, alloc):
        self.alloc, self.peak = alloc, 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            v = self.alloc.pages_in_use
            if v > self.peak:
                self.peak = v
            time.sleep(0.0005)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *a):
        self._stop.set()
        self._t.join()


def _make_lm(args, seed: int = 11):
    from paddle_tpu.decode.model import TinyDecoderLM

    return TinyDecoderLM(num_pages=args.pages, page_size=args.page_size,
                         pages_per_seq=args.pages_per_seq, seed=seed)


def make_prefix_requests(n: int, page_size: int, prefix_pages: int,
                         n_prefixes: int = 4, seed: int = 13):
    """A prefix-heavy burst: every request is one of ``n_prefixes``
    long shared prefixes (full pages of tokens) plus a short random
    suffix — the workload prefix caching exists for."""
    rng = np.random.RandomState(seed)
    bases = [list(rng.randint(2, 64, prefix_pages * page_size))
             for _ in range(n_prefixes)]
    return [bases[rng.randint(n_prefixes)]
            + list(rng.randint(2, 64, 1 + rng.randint(4)))
            for _ in range(n)]


def make_lm_requests(n: int, seed: int = 17):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(2, 64, rng.randint(4, 13))) for _ in range(n)]


def _run_lm_burst(engine, requests, sample_alloc=None):
    engine.submit(requests[0]).wait(600)      # warmup: compile the step
    peak = 0
    sampler = (_PeakSampler(sample_alloc) if sample_alloc is not None
               else None)
    t0 = time.perf_counter()
    if sampler:
        sampler.__enter__()
    try:
        reqs = [engine.submit(r) for r in requests]
        done_at = []
        for r in reqs:
            r.wait(600)
            done_at.append(time.perf_counter() - t0)
    finally:
        if sampler:
            sampler.__exit__()
            peak = sampler.peak
    wall = time.perf_counter() - t0
    tokens = sum(len(r.tokens) for r in reqs)
    out = {
        "tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        **_percentiles(done_at),
    }
    if sampler:
        out["peak_pages_in_use"] = peak
    return out, [list(r.tokens) for r in reqs]


def run_prefix(cache_on: bool, requests, args):
    from paddle_tpu.decode import GenerationEngine

    lm = _make_lm(args)
    engine = GenerationEngine(lm, max_slots=args.slots,
                              max_waiting=len(requests) + 1,
                              max_new_tokens=args.max_new_tokens,
                              prefix_cache=cache_on)
    try:
        out, ids = _run_lm_burst(engine, requests, sample_alloc=lm.allocator)
        out["prefix_cache"] = bool(cache_on)
        if cache_on:
            out["cache_stats"] = engine.session.prefix_cache.stats()
    finally:
        engine.stop()
    return out, ids


def mode_prefix(args):
    requests = make_prefix_requests(args.requests, args.page_size,
                                    args.prefix_pages)
    print(f"== prefix-heavy load, cache OFF ({args.requests} requests, "
          f"{args.prefix_pages * args.page_size}-token shared prefixes)",
          file=sys.stderr)
    off, off_ids = run_prefix(False, requests, args)
    print(f"   {off['tokens_per_s']} tok/s  "
          f"peak {off['peak_pages_in_use']} pages", file=sys.stderr)
    print("== prefix-heavy load, cache ON", file=sys.stderr)
    on, on_ids = run_prefix(True, requests, args)
    print(f"   {on['tokens_per_s']} tok/s  "
          f"peak {on['peak_pages_in_use']} pages  "
          f"hits {on['cache_stats']['hits']}", file=sys.stderr)
    if on_ids != off_ids:
        raise SystemExit("prefix-cached decode diverged from the uncached "
                         "run — page sharing corrupted the KV")
    return {
        "workload": {
            "requests": args.requests,
            "shared_prefixes": 4,
            "prefix_tokens": args.prefix_pages * args.page_size,
            "max_new_tokens": args.max_new_tokens,
        },
        "cache_off": off,
        "cache_on": on,
        "tokens_identical": True,
        "speedup_tokens_per_s": round(
            on["tokens_per_s"] / max(1e-9, off["tokens_per_s"]), 2),
        "peak_pages_ratio": round(
            on["peak_pages_in_use"] / max(1, off["peak_pages_in_use"]), 3),
    }


def _spec_counts():
    from paddle_tpu.observability import metrics as M

    snap = M.snapshot()
    out = {}
    for key, name in (("proposed", "decode_spec_proposed_total"),
                      ("accepted", "decode_spec_accepted_total")):
        out[key] = sum(r["value"] for r in
                       snap.get(name, {"values": []})["values"])
    return out


def mode_spec(args):
    from paddle_tpu.decode import GenerationEngine
    from paddle_tpu.decode.spec import NgramDraft

    requests = make_lm_requests(args.requests)

    print(f"== greedy baseline ({args.requests} requests)", file=sys.stderr)
    base_engine = GenerationEngine(_make_lm(args), max_slots=args.slots,
                                   max_waiting=len(requests) + 1,
                                   max_new_tokens=args.max_new_tokens)
    try:
        base, base_ids = _run_lm_burst(base_engine, requests)
    finally:
        base_engine.stop()
    print(f"   {base['tokens_per_s']} tok/s", file=sys.stderr)

    print(f"== speculative (ngram draft, k={args.spec_k})", file=sys.stderr)
    spec_engine = GenerationEngine(_make_lm(args), max_slots=args.slots,
                                   max_waiting=len(requests) + 1,
                                   max_new_tokens=args.max_new_tokens,
                                   spec_draft=NgramDraft(),
                                   spec_k=args.spec_k)
    s0 = _spec_counts()
    try:
        spec, spec_ids = _run_lm_burst(spec_engine, requests)
    finally:
        spec_engine.stop()
    s1 = _spec_counts()
    proposed = s1["proposed"] - s0["proposed"]
    accepted = s1["accepted"] - s0["accepted"]
    spec["draft"] = f"ngram(k={args.spec_k})"
    spec["proposed"] = proposed
    spec["accepted"] = accepted
    spec["accept_ratio"] = round(accepted / max(1, proposed), 4)
    print(f"   {spec['tokens_per_s']} tok/s  "
          f"accept {spec['accept_ratio']}", file=sys.stderr)

    if spec_ids != base_ids:
        raise SystemExit("speculative decode is not token-identical to "
                         "greedy — the acceptance rule is broken")
    return {
        "workload": {
            "requests": args.requests,
            "max_new_tokens": args.max_new_tokens,
            "spec_k": args.spec_k,
        },
        "greedy": base,
        "speculative": spec,
        "tokens_identical": True,
        "speedup_tokens_per_s": round(
            spec["tokens_per_s"] / max(1e-9, base["tokens_per_s"]), 2),
    }


def main_sharing(args):
    doc = {
        "schema": SCHEMA_V2,
        "model": "paddle_tpu/decode TinyDecoderLM (seed-initialized)",
        "config": {
            "slots": args.slots,
            "pages": args.pages,
            "page_size": args.page_size,
            "pages_per_seq": args.pages_per_seq,
            "backend": os.environ.get("JAX_PLATFORMS", "default"),
        },
    }
    summary = {}
    if args.mode in ("prefix", "sharing"):
        doc["prefix"] = mode_prefix(args)
        summary["prefix_speedup"] = doc["prefix"]["speedup_tokens_per_s"]
        summary["peak_pages_ratio"] = doc["prefix"]["peak_pages_ratio"]
    if args.mode in ("spec", "sharing"):
        doc["spec"] = mode_spec(args)
        summary["spec_accept_ratio"] = \
            doc["spec"]["speculative"]["accept_ratio"]
        summary["spec_speedup"] = doc["spec"]["speedup_tokens_per_s"]
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps(summary))
    print(f"artifact written to {args.out}", file=sys.stderr)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", default="compare",
                    choices=("compare", "prefix", "spec", "sharing"))
    ap.add_argument("--requests", type=int, default=128)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--solo-workers", type=int, default=2)
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--pages", type=int, default=96)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--prefix-pages", type=int, default=4,
                    help="shared-prefix length in pages (prefix mode)")
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--out", default="decode_bench.json")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config: exercise the harness, not the claim")
    args = ap.parse_args(argv)
    if args.smoke:
        args.requests, args.slots = 6, 3
        args.max_new_tokens, args.solo_workers = 5, 1
        args.pages = 24
        if args.mode != "compare":
            args.requests, args.pages = 8, 48
            args.prefix_pages = 2

    import jax

    # the persistent XLA compile cache must not shape a throughput
    # measurement — and on jax 0.4.37 a cache-loaded executable for a
    # structurally-identical second program mis-applies the donated
    # state aliasing and corrupts the weights (two clone generators is
    # exactly that shape), so the bench runs with it off
    try:
        jax.config.update("jax_enable_compilation_cache", False)
    except Exception:
        pass

    import paddle_tpu  # noqa: F401  (register ops before anything else)

    if args.mode != "compare":
        return main_sharing(args)

    params = _Params()
    # materialize the parameters once (fixed startup seeds) so every
    # clone serves byte-identical weights
    from paddle_tpu.generation import SequenceGenerator

    SequenceGenerator(make_beam_gen(args.max_new_tokens), params)
    requests = make_requests(args.requests)

    print(f"== solo fallback ({args.solo_workers} workers, "
          f"{args.requests} requests)", file=sys.stderr)
    solo, solo_ids = run_solo(params, requests, args.max_new_tokens,
                              args.solo_workers)
    print(f"   {solo['tokens_per_s']} tok/s  p99 {solo['p99_ms']} ms",
          file=sys.stderr)

    print(f"== paged decode ({args.slots} slots)", file=sys.stderr)
    paged, paged_ids = run_paged(params, requests, args.max_new_tokens,
                                 args.slots, args.pages, args.page_size)
    print(f"   {paged['tokens_per_s']} tok/s  p99 {paged['p99_ms']} ms",
          file=sys.stderr)

    if paged_ids != solo_ids:
        raise SystemExit("paged decode diverged from the solo oracle — "
                         "the speedup would be meaningless")

    doc = {
        "schema": SCHEMA,
        "model": "demos/seq2seq (NMT, seed-initialized)",
        "config": {
            "requests": args.requests,
            "max_new_tokens": args.max_new_tokens,
            "backend": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "solo": solo,
        "paged": paged,
        "speedup_tokens_per_s": round(
            paged["tokens_per_s"] / max(1e-9, solo["tokens_per_s"]), 2),
        "p99_ratio": round(paged["p99_ms"] / max(1e-9, solo["p99_ms"]), 3),
        "tokens_identical": True,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({k: doc[k] for k in
                      ("speedup_tokens_per_s", "p99_ratio")}))
    print(f"artifact written to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
