#!/usr/bin/env python3
"""Serving chaos/fairness harness (ISSUE 19): prove the self-healing
serving claims with load, not adjectives.

Modes (``--mode``, default ``chaos``; ``--smoke`` runs the CI gate):

- **chaos** — open-loop HTTP load (fixed arrival schedule, measured
  from the *scheduled* arrival, same coordinated-omission rules as
  serving_bench) against a ``--replicas`` pool; a third of the way into
  the window a ``FaultInjector`` hard-kills one replica mid-dispatch
  (in-process stand-in for SIGKILL: the dispatch never returns, the
  worker dies with its batch in flight).  The supervisor requeues the
  in-flight batch and respawns the replica.  Asserted outcome: **zero
  failed (non-rejected) requests** — every request either completes
  (possibly after requeue) or is a counted, reasoned rejection — with
  availability >= --availability (default 0.99) and
  ``serving_replica_restarts_total >= 1``.
- **fairness** — tenants A (weight 1) and B (weight 4) saturate the
  queue with closed-loop clients; B's completed RPS must be >= 3x A's
  while A still completes requests (no starvation).  A second A/B pass
  measures fair-queue overhead: the same server shape without a tenant
  registry vs with one, single-tenant traffic — the delta must be
  noise (~<3%), matching the SERVING_BENCH_r01.json claim that fair
  queuing is free when there is no contention.
- **--smoke** — the lint_self.sh gate: 2 replicas, a 20-request burst,
  one replica killed mid-burst; exits nonzero unless every request
  completed and the pool restarted a replica.

Artifact: ``--out`` (default serving_chaos_bench.json) gets a
``paddle_tpu.serving_chaos.v1`` document; the checked-in run is
``SERVING_CHAOS_r01.json`` (schema documented in BENCHMARKS.md).

Usage:
    python benchmark/serving_chaos_bench.py [--mode=chaos|fairness|all]
        [--replicas=2] [--max_batch=8] [--rate=200] [--duration=6]
        [--depth=4] [--hidden=256] [--clients=12] [--out=FILE] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from serving_bench import (  # noqa: E402 - sibling harness, shared pieces
    Client,
    _percentile,
    build_model,
)

SCHEMA = "paddle_tpu.serving_chaos.v1"

#: Statuses that are *reasoned rejections* (counted shedding), not
#: failures: tenant quota (429), shed/quarantine/overload (503),
#: deadline (504).
REJECT_CODES = frozenset({429, 503, 504})


def _pool_counters():
    from paddle_tpu.serving import replica as R

    return {
        "replica_restarts_total": R._M_RESTARTS.value(),
        "replica_deaths_total": sum(
            R._M_DEATHS.value(**ls) for ls in R._M_DEATHS.label_sets()),
        "requeued_total": R._M_REQUEUED.value(),
    }


# ---------------------------------------------------------------------------
# load loops that classify outcomes (complete / rejected / failed)
# ---------------------------------------------------------------------------


def open_loop_outcomes(address: str, body: bytes, rate: float,
                       duration: float, senders: int):
    """serving_bench's open loop, but every request lands in one of
    three buckets: ok (200), rejected (REJECT_CODES), failed (anything
    else, including transport errors)."""
    n = max(1, int(rate * duration))
    next_idx = [0]
    latencies: list = []
    counts = {"ok": 0, "rejected": 0, "failed": 0}
    reject_by_code: dict = {}
    lock = threading.Lock()
    start_gate = threading.Barrier(senders + 1)
    t0_box = [0.0]

    def worker():
        c = Client(address)
        c.conn.connect()
        mine = []
        local = {"ok": 0, "rejected": 0, "failed": 0}
        local_codes: dict = {}
        start_gate.wait()
        t0 = t0_box[0]
        while True:
            with lock:
                i = next_idx[0]
                if i >= n:
                    break
                next_idx[0] += 1
            sched = t0 + i / rate
            now = time.perf_counter()
            if sched > now:
                time.sleep(sched - now)
            try:
                code = c.predict(body)
            except OSError:
                local["failed"] += 1
                continue
            if code == 200:
                local["ok"] += 1
                mine.append((time.perf_counter() - sched) * 1e3)
            elif code in REJECT_CODES:
                local["rejected"] += 1
                local_codes[code] = local_codes.get(code, 0) + 1
            else:
                local["failed"] += 1
        c.close()
        with lock:
            latencies.extend(mine)
            for k in counts:
                counts[k] += local[k]
            for k, v in local_codes.items():
                reject_by_code[k] = reject_by_code.get(k, 0) + v

    threads = [threading.Thread(target=worker) for _ in range(senders)]
    for t in threads:
        t.start()
    t0_box[0] = time.perf_counter() + 0.05
    start_gate.wait()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0_box[0]
    latencies.sort()
    sent = sum(counts.values())
    return {
        "loop": "open", "offered_rps": round(rate, 1),
        "duration_s": round(elapsed, 3), "sent": sent,
        "completed": counts["ok"], "rejected": counts["rejected"],
        "rejected_by_code": {str(k): v
                             for k, v in sorted(reject_by_code.items())},
        "failed": counts["failed"],
        "availability": round(counts["ok"] / max(1, sent), 6),
        "achieved_rps": round(counts["ok"] / max(elapsed, 1e-9), 1),
        "p50_ms": round(_percentile(latencies, 0.50), 3),
        "p99_ms": round(_percentile(latencies, 0.99), 3),
    }


def closed_loop_tenants(address: str, body_of, tenants, clients_each: int,
                        duration: float):
    """Closed-loop load per tenant (X-Tenant header), counted per
    tenant — the fairness measurement."""
    per = {t: {"ok": 0, "rejected": 0, "failed": 0, "lat": [],
               "failed_codes": {}} for t in tenants}
    lock = threading.Lock()
    total = len(tenants) * clients_each
    start_gate = threading.Barrier(total + 1)
    stop_box = [0.0]

    def worker(tenant):
        c = Client(address)
        c.headers = dict(c.headers, **{"X-Tenant": tenant})
        c.conn.connect()
        body = body_of(tenant)
        mine = {"ok": 0, "rejected": 0, "failed": 0, "lat": []}
        codes: dict = {}
        start_gate.wait()
        while time.perf_counter() < stop_box[0]:
            t0 = time.perf_counter()
            try:
                code = c.predict(body)
            except OSError as exc:
                mine["failed"] += 1
                codes[type(exc).__name__] = \
                    codes.get(type(exc).__name__, 0) + 1
                c.close()                 # keep-alive conn is poisoned
                c = Client(address)
                c.headers = dict(c.headers, **{"X-Tenant": tenant})
                continue
            if code == 200:
                mine["ok"] += 1
                mine["lat"].append((time.perf_counter() - t0) * 1e3)
            elif code in REJECT_CODES:
                mine["rejected"] += 1
            else:
                mine["failed"] += 1
                codes[str(code)] = codes.get(str(code), 0) + 1
        c.close()
        with lock:
            for k in ("ok", "rejected", "failed"):
                per[tenant][k] += mine[k]
            per[tenant]["lat"].extend(mine["lat"])
            for k, v in codes.items():
                per[tenant]["failed_codes"][k] = \
                    per[tenant]["failed_codes"].get(k, 0) + v

    threads = [threading.Thread(target=worker, args=(t,))
               for t in tenants for _ in range(clients_each)]
    for t in threads:
        t.start()
    stop_box[0] = time.perf_counter() + duration + 0.05
    start_gate.wait()
    t_start = time.perf_counter()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_start
    out = {}
    for tenant, d in per.items():
        lat = sorted(d["lat"])
        out[tenant] = {
            "completed": d["ok"], "rejected": d["rejected"],
            "failed": d["failed"], "failed_codes": d["failed_codes"],
            "rps": round(d["ok"] / max(elapsed, 1e-9), 1),
            "p50_ms": round(_percentile(lat, 0.50), 3),
            "p99_ms": round(_percentile(lat, 0.99), 3),
        }
    return out, elapsed


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _make_server(model_dir, **kw):
    from paddle_tpu.serving import InferenceServer

    srv = InferenceServer(model_dir, warmup=True, **kw)
    from serving_bench import _request_body

    return srv, _request_body(srv)


def _wait_for(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


def run_chaos(model_dir, *, replicas, max_batch, rate, duration, senders,
              availability_target):
    from paddle_tpu.serving import FaultInjector

    fault = FaultInjector("die", nth=1)
    srv, body = _make_server(model_dir, replicas=replicas,
                             max_batch=max_batch,
                             replica_heartbeat_ms=50, chaos=fault)
    before = _pool_counters()
    try:
        # arm a third of the way into the window: the next dispatch dies
        # with its batch in flight, mid-burst
        killer = threading.Timer(duration / 3.0, fault.arm)
        killer.start()
        run = open_loop_outcomes(srv.address, body, rate, duration, senders)
        killer.cancel()
        healed = _wait_for(
            lambda: len(srv._pool.replicas) == replicas)
        after = _pool_counters()
        pool = srv._pool.info()
    finally:
        srv.stop()
    counters = {k: after[k] - before[k] for k in after}
    run["replica_killed"] = fault.fired >= 1
    run["counters"] = counters
    run["pool"] = pool
    run["healed_to_full_strength"] = bool(healed)
    run["checks"] = {
        "zero_failed": run["failed"] == 0,
        "availability_ok": run["availability"] >= availability_target,
        "availability_target": availability_target,
        "restarted": counters["replica_restarts_total"] >= 1,
    }
    run["passed"] = all(v for k, v in run["checks"].items()
                        if isinstance(v, bool))
    return run


def run_fairness(model_dir, *, replicas, max_batch, clients, duration):
    # weighted fairness only shows under contention: the pool must be
    # the bottleneck (persistent backlog for both tenants), so this mode
    # defaults to a deliberately small pool (1 replica, max_batch 4)
    # saturation pass: A (weight 1) vs B (weight 4), both greedy
    srv, body = _make_server(model_dir, replicas=replicas,
                             max_batch=max_batch, tenants="A:::1,B:::4")
    try:
        per, _ = closed_loop_tenants(srv.address, lambda t: body,
                                     ("A", "B"), clients, duration)
    finally:
        srv.stop()
    ratio = per["B"]["rps"] / max(per["A"]["rps"], 1e-9)

    # overhead pass: single-tenant traffic, registry off vs on — the
    # fair queue must be free when there is no contention.  Windows are
    # interleaved across two live servers (plain, tenanted, plain, ...)
    # and each side keeps its best: a single 6 s window on a busy
    # shared host swings +-10%, far more than the effect under test, so
    # back-to-back sampling of the same noise is the only fair compare.
    srv_p, body = _make_server(model_dir, replicas=replicas,
                               max_batch=max_batch)
    srv_t, _ = _make_server(model_dir, replicas=replicas,
                            max_batch=max_batch, tenants="A:::1,B:::4")
    plain_rps = single_rps = 0.0
    try:
        def window(srv, tenant):
            per1, _ = closed_loop_tenants(srv.address, lambda t: body,
                                          (tenant,), clients, duration)
            return per1[tenant]["rps"]

        # throwaway warm window each (throughput climbs a few percent
        # over the first windows as everything warms), then alternate
        # who goes first so neither side always gets the warmer slot
        window(srv_p, "default")
        window(srv_t, "B")
        for i in range(3):
            order = [("p", srv_p, "default"), ("t", srv_t, "B")]
            if i % 2:
                order.reverse()
            for tag, srv1, tenant in order:
                rps = window(srv1, tenant)
                if tag == "p":
                    plain_rps = max(plain_rps, rps)
                else:
                    single_rps = max(single_rps, rps)
    finally:
        srv_p.stop()
        srv_t.stop()
    overhead_pct = round(100.0 * (1.0 - single_rps /
                                  max(plain_rps, 1e-9)), 2)
    return {
        "saturated": per,
        "weight_ratio_B_over_A": round(ratio, 2),
        "single_tenant": {"plain_rps": plain_rps,
                          "tenanted_rps": single_rps,
                          "overhead_pct": overhead_pct},
        "checks": {
            "ratio_ge_3": ratio >= 3.0,
            "no_starvation": per["A"]["completed"] > 0,
            "overhead_within_3pct": overhead_pct <= 3.0,
        },
    }


def run_smoke(model_dir):
    """The lint_self.sh gate: 2 replicas, 20-request burst, one replica
    killed mid-burst -> zero lost requests + >= 1 restart."""
    from paddle_tpu.serving import FaultInjector

    fault = FaultInjector("die", nth=1)
    srv, body = _make_server(model_dir, replicas=2, max_batch=4,
                             replica_heartbeat_ms=50, chaos=fault)
    before = _pool_counters()
    results = []
    lock = threading.Lock()
    try:
        c = Client(srv.address)
        assert c.predict(body) == 200     # traffic warm (past compiles)
        c.close()
        fault.arm()

        def one():
            cc = Client(srv.address)
            try:
                code = cc.predict(body)
            except OSError:
                code = -1
            cc.close()
            with lock:
                results.append(code)

        threads = [threading.Thread(target=one) for _ in range(20)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        restarted = _wait_for(
            lambda: _pool_counters()["replica_restarts_total"]
            - before["replica_restarts_total"] >= 1)
        after = _pool_counters()
    finally:
        srv.stop()
    lost = [code for code in results if code != 200]
    run = {
        "burst": 20, "completed": results.count(200),
        "lost": len(lost), "replica_killed": fault.fired >= 1,
        "restarts": after["replica_restarts_total"]
        - before["replica_restarts_total"],
        "passed": (not lost and len(results) == 20
                   and fault.fired >= 1 and restarted),
    }
    return run


# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", default="all",
                    choices=("chaos", "fairness", "all"))
    ap.add_argument("--model_dir")
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--in_dim", type=int, default=64)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--max_batch", type=int, default=8)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="chaos open-loop offered RPS")
    ap.add_argument("--duration", type=float, default=6.0)
    ap.add_argument("--senders", type=int, default=32)
    ap.add_argument("--clients", type=int, default=12,
                    help="fairness closed-loop clients per tenant")
    ap.add_argument("--fair_replicas", type=int, default=1,
                    help="pool size for the fairness pass (small, so the "
                    "queue is the bottleneck and weights can bite)")
    ap.add_argument("--fair_max_batch", type=int, default=4)
    ap.add_argument("--fair_depth", type=int, default=12,
                    help="fairness-pass model depth (serving_bench's "
                    "shape, so the pool — not HTTP — is the bottleneck)")
    ap.add_argument("--fair_hidden", type=int, default=2048)
    ap.add_argument("--availability", type=float, default=0.99)
    ap.add_argument("--out", default="serving_chaos_bench.json")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: 20-request burst, one replica killed, "
                    "exit nonzero on any lost request / missing restart")
    args = ap.parse_args(argv)

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_multi_thread_eigen=false").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.smoke:
        args.depth, args.hidden, args.in_dim, args.classes = 1, 32, 8, 4

    model_dir = args.model_dir
    tmp = None
    if not model_dir:
        tmp = tempfile.TemporaryDirectory(prefix="serving_chaos_")
        model_dir = build_model(os.path.join(tmp.name, "model"), args.depth,
                                args.hidden, args.in_dim, args.classes)

    doc = {
        "schema": SCHEMA,
        "host": {"cpus": os.cpu_count(),
                 "jax_platforms": os.environ.get("JAX_PLATFORMS", "")},
        "model": ({"model_dir": args.model_dir} if args.model_dir else
                  {"depth": args.depth, "hidden": args.hidden,
                   "in_dim": args.in_dim, "classes": args.classes}),
    }
    ok = True
    if args.smoke:
        doc["smoke"] = run_smoke(model_dir)
        print("smoke:", json.dumps(doc["smoke"]), flush=True)
        ok = doc["smoke"]["passed"]
    else:
        if args.mode in ("chaos", "all"):
            print(f"== chaos: replicas={args.replicas} rate={args.rate} "
                  f"duration={args.duration}s", flush=True)
            doc["chaos"] = run_chaos(
                model_dir, replicas=args.replicas,
                max_batch=args.max_batch, rate=args.rate,
                duration=args.duration, senders=args.senders,
                availability_target=args.availability)
            print("  ", json.dumps(doc["chaos"]), flush=True)
            ok = ok and doc["chaos"]["passed"]
        if args.mode in ("fairness", "all"):
            print(f"== fairness: A(w1) vs B(w4), {args.clients} clients "
                  "each", flush=True)
            fair_dir = model_dir
            if not args.model_dir and tmp is not None:
                fair_dir = build_model(
                    os.path.join(tmp.name, "fair_model"), args.fair_depth,
                    args.fair_hidden, args.in_dim, args.classes)
                doc["fairness_model"] = {"depth": args.fair_depth,
                                         "hidden": args.fair_hidden,
                                         "in_dim": args.in_dim,
                                         "classes": args.classes}
            doc["fairness"] = run_fairness(
                fair_dir, replicas=args.fair_replicas,
                max_batch=args.fair_max_batch, clients=args.clients,
                duration=args.duration)
            print("  ", json.dumps(doc["fairness"]), flush=True)
            ok = ok and all(doc["fairness"]["checks"].values())
    doc["passed"] = bool(ok)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"artifact written to {args.out} (passed={ok})")
    if tmp:
        tmp.cleanup()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
