"""Transformer-LM training throughput + MFU on one chip.

The matmul-dominated counterpart to the ResNet headline bench: shows
the framework sustaining high MXU utilization where the model shape
allows it (PERF.md documents why ResNet-50's convs+BN cannot).  Runs
the framework's own transformer (models/transformer.py) through the
compiling Executor under bf16 AMP.

Prints one JSON line: tokens/sec, step ms, model TFLOP/step, MFU vs
nominal peak and vs the measured matmul roofline.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# honor JAX_PLATFORMS before first backend use (the axon TPU plugin
# otherwise overrides it and "CPU" runs silently hit the tunnel)
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

NOMINAL_PEAK = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
                "TPU v5p": 459e12, "TPU v3": 123e12}
MEASURED_ROOFLINE = 132e12  # benchmark/peak_matmul.py on this chip


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import amp
    from paddle_tpu.models import transformer_lm_loss

    B = int(os.environ.get("TB_BATCH", "8"))
    S = int(os.environ.get("TB_SEQ", "1024"))
    D = int(os.environ.get("TB_DMODEL", "2048"))
    L = int(os.environ.get("TB_LAYERS", "4"))
    V = int(os.environ.get("TB_VOCAB", "32768"))
    steps = int(os.environ.get("TB_STEPS", "10"))
    recompute = os.environ.get("TB_RECOMPUTE", "0") == "1"
    if os.environ.get("BENCH_AMP", "1") == "1":
        amp.enable()

    fluid.framework.reset_default_programs()
    tokens = fluid.layers.data(name="tokens", shape=[S, 1], dtype="int64")
    labels = fluid.layers.data(name="labels", shape=[S, 1], dtype="int64")
    loss = transformer_lm_loss(tokens, labels=labels, vocab_size=V,
                               d_model=D, num_heads=D // 128, num_layers=L,
                               recompute=recompute)
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    feed = {"tokens": jnp.asarray(rng.randint(0, V, (B, S, 1)).astype(np.int64)),
            "labels": jnp.asarray(rng.randint(0, V, (B, S, 1)).astype(np.int64))}
    if os.environ.get("BENCH_CHAIN", "1") == "1":
        # scanned K-step training loop in one jitted program — the
        # same methodology as bench.py (PERF.md "scanned training
        # loop"): the tunnel's fixed per-dispatch RPC is not device
        # time.  BENCH_CHAIN=0 restores per-dispatch timing.
        from jax import lax

        fn, state, feeds, uses_rng = exe.build_callable(
            fluid.default_main_program(),
            {k: np.asarray(v) for k, v in feed.items()}, [loss.name])
        K = 5

        def multi(state, feeds, base_seed):
            def body(s, i):
                fetches, s2 = (fn(s, feeds, base_seed + i) if uses_rng
                               else fn(s, feeds))
                return s2, fetches[0]

            s, losses = lax.scan(body, state, jnp.arange(K))
            return losses[-1], s

        jm = jax.jit(multi, donate_argnums=(0,))
        dev_feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        # base_seed advances per macro-step so random ops never replay
        # the same mask across reps
        out, state = jm(state, dev_feeds, jnp.int32(0))
        float(np.asarray(out))
        reps = max(steps // K, 2)
        t0 = time.perf_counter()
        for r in range(reps):
            out, state = jm(state, dev_feeds, jnp.int32((r + 1) * K))
        lv = float(np.asarray(out))
        dt = (time.perf_counter() - t0) / (reps * K)
    else:
        for _ in range(3):
            (l,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
        float(np.asarray(l))  # host-read sync (block_until_ready is a
        t0 = time.perf_counter()  # no-op through the tunnel)
        for _ in range(steps):
            (l,) = exe.run(feed=feed, fetch_list=[loss],
                           return_numpy=False)
        lv = float(np.asarray(l))
        dt = (time.perf_counter() - t0) / steps

    # model FLOPs per step: 6 * non-embedding params * tokens for the
    # blocks, + 6 * D * V * tokens for the logits matmul
    block_params = L * 12 * D * D
    tokens_per_step = B * S
    flops = 6 * block_params * tokens_per_step \
        + 6 * D * V * tokens_per_step
    kind = jax.devices()[0].device_kind
    peak = next((v for k, v in NOMINAL_PEAK.items() if kind.startswith(k)),
                197e12)
    print(json.dumps({
        "metric": f"transformer_lm_train_B{B}_S{S}_D{D}_L{L}"
                  + ("_remat" if recompute else ""),
        "tokens_per_sec": round(tokens_per_step / dt, 1),
        "ms_per_step": round(dt * 1e3, 2),
        "model_tflop_per_step": round(flops / 1e12, 2),
        "mfu_vs_nominal": round(flops / dt / peak, 3),
        "mfu_vs_measured_roofline": round(flops / dt / MEASURED_ROOFLINE, 3),
        "loss": round(lv, 3),
    }))


if __name__ == "__main__":
    main()
