"""Static FLOPs accounting over a built Program (matmul/conv terms).

Guard rail demanded by the round-4 GoogLeNet incident: a missing stem
stride made the model do 4x the work for three rounds of benchmarking
without anything noticing — throughput numbers alone can't tell
"slower" from "doing more work".  ``program_flops`` counts the
forward matmul/conv FLOPs straight from the program's static shapes,
and ``assert_model_flops`` pins each bench model to its published
per-image cost so a work regression fails the bench run loudly.
"""

from __future__ import annotations


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def program_flops(prog, batch_hint: int = 1) -> float:
    """Forward matmul/conv FLOPs of a program from static var shapes
    (2*M*N*K per matmul; elementwise/norm traffic excluded — those are
    bandwidth, not MXU work).  Backward is not counted: callers compare
    forward-only architecture cost.  ``batch_hint`` substitutes for
    symbolic (-1/None) leading batch dims."""
    block = prog.global_block()
    total = 0.0

    def dims(shape, hint):
        return [int(d) if d and d > 0 else hint for d in shape]

    for op in block.ops:
        t = op.type
        try:
            if t in ("conv2d", "conv2d_cudnn", "conv2d_transpose"):
                w = block.var(op.input("Filter")[0])
                out = block.var(op.output("Output")[0])
                ow = dims(out.shape, batch_hint)
                # out (N, K, OH, OW); filter (K, C/g, kh, kw)
                n = ow[0] if len(ow) == 4 else 1
                oh_ow = _prod(ow[-2:])
                k, cpg, kh, kw = [int(d) for d in w.shape]
                total += 2.0 * n * k * cpg * kh * kw * oh_ow
            elif t == "conv3d":
                w = block.var(op.input("Filter")[0])
                out = block.var(op.output("Output")[0])
                ow = dims(out.shape, batch_hint)
                n = ow[0] if len(ow) == 5 else 1
                od_oh_ow = _prod(ow[-3:])
                k, cpg, kd, kh, kw = [int(d) for d in w.shape]
                total += 2.0 * n * k * cpg * kd * kh * kw * od_oh_ow
            elif t in ("mul", "matmul"):
                x = block.var(op.input("X")[0])
                y = block.var(op.input("Y")[0])
                xs = dims(x.shape, batch_hint)
                ys = [int(d) for d in y.shape]  # weights: static
                if t == "mul":
                    ncol = int(op.attr("x_num_col_dims") or 1)
                    m = _prod(xs[:ncol]) or 1
                    kdim = _prod(xs[ncol:])
                    ndim = _prod(d for d in ys[1:] if d > 0)
                    total += 2.0 * m * kdim * ndim
                else:
                    # batched (..., M, K) x (..., K, N)
                    b = _prod(xs[:-2]) or 1
                    m, kdim = xs[-2], xs[-1]
                    ndim = ys[-1] if ys[-1] > 0 else batch_hint
                    total += 2.0 * b * m * kdim * ndim
        except Exception:
            # unknown/dynamic shapes: skip the op rather than guess
            continue
    return total


# forward cost per image at 224x224 (3x32x32 for smallnet) in true
# FLOPs = 2x the papers' published multiply-accumulate counts (He et
# al. count a MAC as one "FLOP"; the MFU convention here and in
# bench.py is 2 FLOPs/MAC).  Tolerance is wide enough for head
# variants but far tighter than the 4x-class regressions this guards.
EXPECTED_FWD_GFLOPS_PER_IMG = {
    "resnet50": 7.7,     # He et al. 2015: 3.8-4.1 GMAC incl. fc
    "googlenet": 3.2,    # Szegedy et al. 2014: ~1.5 GMAC + aux heads
    "alexnet": 1.43,     # single-tower variant, ~0.7 GMAC
    "vgg16": 31.0,       # 15.5 GMAC
    "smallnet": 0.082,   # resnet-20 cifar10, 41 MMAC
}


def assert_model_flops(model_name, prog, batch, rtol=0.35):
    """Fail loudly when the built program's conv/matmul work diverges
    from the architecture's published per-image FLOPs."""
    want = EXPECTED_FWD_GFLOPS_PER_IMG.get(model_name)
    if want is None:
        return None
    got = program_flops(prog, batch_hint=batch) / batch / 1e9
    if not (want * (1 - rtol) <= got <= want * (1 + rtol)):
        raise AssertionError(
            f"{model_name}: program does {got:.2f} GFLOP/img forward vs "
            f"the architecture's ~{want} GFLOP/img (tolerance "
            f"{rtol:.0%}) — the model graph is doing the wrong amount "
            f"of WORK (cf. the round-4 GoogLeNet missing-stem-stride "
            f"4x bug); fix the graph before trusting any throughput "
            f"number")
    return got
