"""Pallas-vs-XLA microbenchmarks on the real TPU.

Each kernel in paddle_tpu/pallas must earn its place (VERDICT round 1):
this prints a per-kernel table of Pallas time vs the XLA lowering it
shadows.  Results are recorded in PALLAS_BENCH.md; the defaults in
paddle_tpu/pallas/__init__.py follow the winners.

All timings force a host read (block_until_ready does not block through
the axon tunnel).
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# repo root importable without PYTHONPATH (setting PYTHONPATH breaks the
# axon TPU plugin registration in this image)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


CHAIN = 8  # sequential in-jit applications: amortizes the ~2-6ms tunnel
           # dispatch floor that would otherwise make the loop host-bound

# --tuned: let kernel dispatch consult the checked-in tuning database
# (paddle_tpu/pallas/tuning).  Without it the DB is disabled so the
# pallas column measures the hard-coded defaults — run both to get the
# tuned-vs-default A/B rows BENCHMARKS.md records.
TUNED = False


def timeit(fn, *args, reps=10, warmup=2):
    """fn must be a jitted callable that runs its op CHAIN times with a
    data dependency; returns seconds per single application."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / (reps * CHAIN)


def _sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def row(name, xla_ms, pal_ms):
    speedup = xla_ms / pal_ms
    verdict = "pallas" if speedup > 1.05 else ("tie" if speedup > 0.95 else "xla")
    print(json.dumps({"bench": name, "xla_ms": round(xla_ms, 3),
                      "pallas_ms": round(pal_ms, 3),
                      "speedup": round(speedup, 2), "winner": verdict}))


def bench_matmul():
    from paddle_tpu.pallas.matmul import matmul

    for n in (1024, 2048, 4096):
        x = jax.random.normal(jax.random.key(0), (n, n), jnp.bfloat16)
        y = jax.random.normal(jax.random.key(1), (n, n), jnp.bfloat16)

        def chain(mm):
            def run(a, b):
                for _ in range(CHAIN):
                    a = mm(a, b) * jnp.bfloat16(1e-3)
                return a
            return jax.jit(run)

        xla = chain(lambda a, b: jnp.dot(a, b))
        # unset blocks resolve via the tuning DB (disabled = defaults)
        pal = chain(lambda a, b: matmul(a, b))
        row(f"matmul_{n}x{n}_bf16", timeit(xla, x, y) * 1e3,
            timeit(pal, x, y) * 1e3)


def bench_softmax():
    from paddle_tpu.pallas.softmax import softmax

    for rows, cols in ((8192, 512), (16384, 128), (4096, 1024)):
        x = jax.random.normal(jax.random.key(0), (rows, cols), jnp.float32)

        def chain(sm):
            def run(a):
                for _ in range(CHAIN):
                    a = sm(a) + a
                return a
            return jax.jit(run)

        xla = chain(lambda a: jax.nn.softmax(a, axis=-1))
        pal = chain(lambda a: softmax(a))
        row(f"softmax_{rows}x{cols}", timeit(xla, x) * 1e3,
            timeit(pal, x) * 1e3)


def bench_gather():
    from paddle_tpu.pallas.embedding import gather_rows

    v, d, n = 50304, 512, 8192
    w = jax.random.normal(jax.random.key(0), (v, d), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (n,), 0, v, jnp.int32)

    def chain(g):
        def run(w, ids):
            acc = jnp.zeros((), jnp.int32)
            for _ in range(CHAIN):
                out = g(w, (ids + acc) % v)
                acc = out[0, 0].astype(jnp.int32) % 2
            return acc
        return jax.jit(run)

    xla = chain(lambda w, i: jnp.take(w, i, axis=0))
    pal = chain(lambda w, i: gather_rows(w, i))
    row(f"gather_{v}x{d}_n{n}", timeit(xla, w, ids) * 1e3,
        timeit(pal, w, ids) * 1e3)


def _lstm_ref(xp, w, b, h0, c0):
    from jax import lax

    def step(carry, xt):
        h, c = carry
        gates = xt + jnp.dot(h, w, preferred_element_type=jnp.float32
                             ).astype(xt.dtype) + b
        i, f, g, o = jnp.split(gates, 4, -1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), (h, c)

    _, (hs, cs) = lax.scan(step, (h0, c0), xp)
    return hs, cs


def bench_lstm():
    from paddle_tpu.pallas.lstm import lstm_seq

    for t, b, h in ((100, 64, 256), (128, 32, 512), (256, 128, 128)):
        dt = jnp.float32
        xp = jax.random.normal(jax.random.key(0), (t, b, 4 * h), dt) * 0.1
        w = jax.random.normal(jax.random.key(1), (h, 4 * h), dt) * 0.05
        bias = jnp.zeros((4 * h,), dt)
        h0 = jnp.zeros((b, h), dt)
        c0 = jnp.zeros((b, h), dt)

        def chain_f(f):
            def run(xp, w, bias, h0, c0):
                for _ in range(CHAIN):
                    hs = f(xp, w, bias, h0, c0)[0]
                    h0 = hs[-1]
                return h0
            return jax.jit(run)

        row(f"lstm_fwd_T{t}_B{b}_H{h}",
            timeit(chain_f(_lstm_ref), xp, w, bias, h0, c0) * 1e3,
            timeit(chain_f(lstm_seq), xp, w, bias, h0, c0) * 1e3)

        def chain_g(f):
            def loss(xp, w, bias, h0, c0):
                hs, _ = f(xp, w, bias, h0, c0)
                return jnp.sum(hs ** 2)

            g = jax.grad(loss, argnums=(0, 4))

            def run(xp, w, bias, h0, c0):
                for _ in range(CHAIN):
                    dxp, dh0 = g(xp, w, bias, h0, c0)
                    h0 = h0 + dh0 * 1e-6
                return h0
            return jax.jit(run)

        row(f"lstm_grad_T{t}_B{b}_H{h}",
            timeit(chain_g(_lstm_ref), xp, w, bias, h0, c0) * 1e3,
            timeit(chain_g(lstm_seq), xp, w, bias, h0, c0) * 1e3)


def bench_batch_norm():
    """ResNet-50 BS=256 BN shapes, channel-minor (R=N*H*W, C) view."""
    from paddle_tpu.pallas.batch_norm import batch_norm_train, _bn_fwd_impl
    from jax import lax

    eps = 1e-5

    def xla_bn(x, g, b):
        m = jnp.mean(x, 0, dtype=jnp.float32)
        v = jnp.mean(jnp.square(x.astype(jnp.float32)), 0) - m * m
        inv = lax.rsqrt(v + eps)
        a = g.astype(jnp.float32) * inv
        bb = b.astype(jnp.float32) - m * a
        return (x * a.astype(x.dtype)[None] + bb.astype(x.dtype)[None],
                m, v)

    for R, C in ((256 * 56 * 56, 256), (256 * 28 * 28, 512),
                 (256 * 14 * 14, 1024)):
        x = jax.random.normal(jax.random.key(0), (R, C), jnp.bfloat16)
        g = jnp.ones((C,), jnp.float32)
        b = jnp.zeros((C,), jnp.float32)

        def chain_f(bn):
            def run(x, g, b):
                for _ in range(CHAIN):
                    y, m, v = bn(x, g, b)
                    x = y + jnp.asarray(1e-6, y.dtype)
                return x
            return jax.jit(run)

        row(f"batch_norm_fwd_R{R}_C{C}",
            timeit(chain_f(xla_bn), x, g, b) * 1e3,
            timeit(chain_f(lambda x, g, b: _bn_fwd_impl(x, g, b, eps)),
                   x, g, b) * 1e3)

        def chain_t(bn):
            def loss(x, g, b):
                acc = x
                for _ in range(CHAIN):
                    y, m, v = bn(acc, g, b)
                    acc = y + jnp.asarray(1e-6, y.dtype)
                return jnp.sum(acc.astype(jnp.float32))

            def run(x, g, b):
                return jax.grad(loss)(x, g, b)
            return jax.jit(run)

        row(f"batch_norm_train_R{R}_C{C}",
            timeit(chain_t(xla_bn), x, g, b) * 1e3,
            timeit(chain_t(batch_norm_train), x, g, b) * 1e3)


def bench_flash_attention():
    """Transformer-flagship shapes (B=8 H=16 D=128) + long-context."""
    from paddle_tpu.pallas.flash_attention import flash_attention
    from paddle_tpu.parallel.ring_attention import local_attention

    for BH, S, D in ((128, 1024, 128), (128, 2048, 128), (16, 8192, 128)):
        q, k, v = (jax.random.normal(jax.random.key(i), (BH, S, D),
                                     jnp.bfloat16) for i in range(3))

        def jnp_attn(q, k, v):
            o = local_attention(q[:, None], k[:, None], v[:, None],
                                causal=True)
            return o[:, 0]

        def fl_attn(q, k, v):
            return flash_attention(q, k, v, True)

        def chain_f(f):
            def run(q, k, v):
                for _ in range(CHAIN):
                    o = f(q, k, v)
                    q = o + jnp.asarray(1e-3, o.dtype)
                return o
            return jax.jit(run)

        row(f"flash_attn_fwd_BH{BH}_S{S}_D{D}",
            timeit(chain_f(jnp_attn), q, k, v) * 1e3,
            timeit(chain_f(fl_attn), q, k, v) * 1e3)

        def chain_t(f):
            def loss(q, k, v):
                acc = q
                for _ in range(CHAIN):
                    acc = f(acc, k, v) + jnp.asarray(1e-3, q.dtype)
                return jnp.sum(acc.astype(jnp.float32))

            def run(q, k, v):
                return jax.grad(loss)(q, k, v)
            return jax.jit(run)

        row(f"flash_attn_train_BH{BH}_S{S}_D{D}",
            timeit(chain_t(jnp_attn), q, k, v) * 1e3,
            timeit(chain_t(fl_attn), q, k, v) * 1e3)


if __name__ == "__main__":
    import sys

    from paddle_tpu.pallas import tuning

    args = [a for a in sys.argv[1:] if a != "--tuned"]
    TUNED = len(args) != len(sys.argv) - 1
    if not TUNED:
        tuning.disable()
    which = args[0] if args else "all"
    if which in ("all", "matmul"):
        bench_matmul()
    if which in ("all", "softmax"):
        bench_softmax()
    if which in ("all", "gather"):
        bench_gather()
    if which in ("all", "lstm"):
        bench_lstm()
    if which in ("all", "batch_norm"):
        bench_batch_norm()
    if which in ("all", "flash"):
        bench_flash_attention()
