"""Raw-JAX ResNet-50 training-step ceiling probe.

Hand-rolled NHWC bf16 ResNet-50 (no framework) to measure the best
throughput XLA gives this chip; the framework bench is then tuned
toward this number.  Variants toggled by env:

  CEIL_LAYOUT=NHWC|NCHW   conv data layout (default NHWC)
  CEIL_DTYPE=bf16|f32     activation/param compute dtype (default bf16)
  CEIL_BN=f32|compute     batch-norm statistics dtype (default f32)

Prints one JSON line per run with img/s and MFU.
"""

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

LAYOUT = os.environ.get("CEIL_LAYOUT", "NHWC")
DTYPE = jnp.bfloat16 if os.environ.get("CEIL_DTYPE", "bf16") == "bf16" else jnp.float32
BN_F32 = os.environ.get("CEIL_BN", "f32") == "f32"

DN = (("NHWC", "HWIO", "NHWC") if LAYOUT == "NHWC" else ("NCHW", "OIHW", "NCHW"))
C_AXIS = 3 if LAYOUT == "NHWC" else 1


DOT1X1 = os.environ.get("CEIL_DOT1X1", "0") == "1"


def conv(x, w, stride, pad):
    if (DOT1X1 and LAYOUT == "NHWC" and w.shape[0] == 1 and w.shape[1] == 1
            and pad == 0):
        # 1x1 conv as an explicit matmul: XLA's dot emitter sustains a
        # higher fraction of the MXU roofline than the conv emitter at
        # these shapes (measured).  stride-2 = subsample then dot.
        if stride != 1:
            x = x[:, ::stride, ::stride, :]
        return jnp.dot(x, w[0, 0])
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=[(pad, pad), (pad, pad)],
        dimension_numbers=DN)


NO_BN = os.environ.get("CEIL_NOBN", "0") == "1"


def bn(x, scale, bias, eps=1e-5):
    shp = [1, 1, 1, 1]
    shp[C_AXIS] = x.shape[C_AXIS]
    if NO_BN:  # scale+shift only: isolates the cost of the statistics
        return x * scale.reshape(shp) + bias.reshape(shp)
    red = tuple(i for i in range(4) if i != C_AXIS)
    if os.environ.get("CEIL_BN") == "mixed":
        # f32-accumulated stats (fused convert+reduce), bf16 normalize
        m = jnp.mean(x, axis=red, keepdims=True, dtype=jnp.float32)
        v = (jnp.mean(jnp.square(x.astype(jnp.float32)), axis=red,
                      keepdims=True) - jnp.square(m))
        inv = lax.rsqrt(v + eps).astype(x.dtype)
        y = (x - m.astype(x.dtype)) * inv
        return y * scale.reshape(shp) + bias.reshape(shp)
    xf = x.astype(jnp.float32) if BN_F32 else x
    m = jnp.mean(xf, axis=red, keepdims=True)
    v = jnp.mean(jnp.square(xf), axis=red, keepdims=True) - jnp.square(m)
    y = (xf - m) * lax.rsqrt(v + eps)
    return (y * scale.reshape(shp) + bias.reshape(shp)).astype(x.dtype)


def make_params(rng):
    params = []

    def add_conv(cin, cout, k):
        nonlocal rng
        rng, sub = jax.random.split(rng)
        fan = k * k * cin
        shape = (k, k, cin, cout) if LAYOUT == "NHWC" else (cout, cin, k, k)
        w = (jax.random.normal(sub, shape, DTYPE) / float(np.sqrt(fan))).astype(DTYPE)
        params.append(w)
        params.append(jnp.ones((cout,), DTYPE))   # bn scale
        params.append(jnp.zeros((cout,), DTYPE))  # bn bias
        return len(params) - 3

    cfg = {50: (3, 4, 6, 3)}[50]
    idx = {}
    idx["stem"] = add_conv(3, 64, 7)
    cin = 64
    for gi, (count, cmid) in enumerate(zip(cfg, (64, 128, 256, 512))):
        for bi in range(count):
            stride = 2 if (bi == 0 and gi > 0) else 1
            if bi == 0:
                idx[f"g{gi}b{bi}s"] = add_conv(cin, cmid * 4, 1)
            idx[f"g{gi}b{bi}c1"] = add_conv(cin, cmid, 1)
            idx[f"g{gi}b{bi}c2"] = add_conv(cmid, cmid, 3)
            idx[f"g{gi}b{bi}c3"] = add_conv(cmid, cmid * 4, 1)
            cin = cmid * 4
    rng, sub = jax.random.split(rng)
    params.append(jax.random.normal(sub, (2048, 1000), DTYPE) * 0.01)
    params.append(jnp.zeros((1000,), DTYPE))
    idx["fc"] = len(params) - 2
    return params, idx, cfg


def forward(params, idx, cfg, x):
    def cbr(tag, x, stride, pad, relu=True):
        i = idx[tag]
        y = bn(conv(x, params[i], stride, pad), params[i + 1], params[i + 2])
        return jax.nn.relu(y) if relu else y

    x = cbr("stem", x, 2, 3)
    window = [1, 3, 3, 1] if LAYOUT == "NHWC" else [1, 1, 3, 3]
    strides = [1, 2, 2, 1] if LAYOUT == "NHWC" else [1, 1, 2, 2]
    pads = [(0, 0), (1, 1), (1, 1), (0, 0)] if LAYOUT == "NHWC" else [(0, 0), (0, 0), (1, 1), (1, 1)]
    x = lax.reduce_window(x, np.array(-np.inf, x.dtype), lax.max, window,
                          strides, pads)
    for gi, count in enumerate(cfg):
        for bi in range(count):
            stride = 2 if (bi == 0 and gi > 0) else 1
            short = cbr(f"g{gi}b{bi}s", x, stride, 0, relu=False) if f"g{gi}b{bi}s" in idx else x
            y = cbr(f"g{gi}b{bi}c1", x, stride, 0)
            y = cbr(f"g{gi}b{bi}c2", y, 1, 1)
            y = cbr(f"g{gi}b{bi}c3", y, 1, 0, relu=False)
            x = jax.nn.relu(short + y)
    x = jnp.mean(x, axis=(1, 2) if LAYOUT == "NHWC" else (2, 3))
    i = idx["fc"]
    return x.astype(jnp.float32) @ params[i].astype(jnp.float32) + params[i + 1].astype(jnp.float32)


def main():
    batch = int(os.environ.get("CEIL_BATCH", "256"))
    steps = int(os.environ.get("CEIL_STEPS", "20"))
    rng = jax.random.key(0)
    params, idx, cfg = make_params(rng)

    shape = (batch, 224, 224, 3) if LAYOUT == "NHWC" else (batch, 3, 224, 224)
    x = jax.random.normal(jax.random.key(1), shape, DTYPE)
    labels = jax.random.randint(jax.random.key(2), (batch,), 0, 1000)

    def loss_fn(params):
        logits = forward(params, idx, cfg, x)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - ll)

    flat_update = os.environ.get("CEIL_FLATOPT", "1") == "1"
    if flat_update:
        # One fused SGD-momentum kernel over a single flat master buffer:
        # 157 per-tensor updates cost ~140us each in dispatch/fixup alone
        # (measured); one flat kernel is pure bandwidth.
        sizes = [int(np.prod(p.shape)) for p in params]
        offs = np.cumsum([0] + sizes)
        master = jnp.concatenate([p.astype(jnp.float32).ravel() for p in params])
        mom_flat = jnp.zeros_like(master)

        def unflatten(flat):
            return [lax.dynamic_slice(flat, (int(offs[i]),), (sizes[i],))
                    .reshape(params[i].shape).astype(params[i].dtype)
                    for i in range(len(params))]

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(master, mom_flat):
            ps = unflatten(master)
            loss, grads = jax.value_and_grad(loss_fn)(ps)
            gflat = jnp.concatenate(
                [g.astype(jnp.float32).ravel() for g in grads])
            mom_flat = 0.9 * mom_flat + gflat
            master = master - 0.1 * mom_flat
            return loss, master, mom_flat

        for _ in range(3):
            loss, master, mom_flat = step(master, mom_flat)
        float(np.asarray(loss))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, master, mom_flat = step(master, mom_flat)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        ips = batch * steps / dt
        tflops = ips * 12.3e9 / 1e12
        print(json.dumps({
            "layout": LAYOUT, "dtype": str(DTYPE.__name__), "bn_f32": BN_F32,
            "flat_opt": True, "img_per_sec": round(ips, 1),
            "est_tflops": round(tflops, 1),
            "mfu_vs_197tflops": round(tflops / 197, 3), "loss": float(loss),
        }))
        return

    moms = [jnp.zeros_like(p, dtype=jnp.float32) for p in params]

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, moms):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_p, new_m = [], []
        for p, m, g in zip(params, moms, grads):
            m = 0.9 * m + g.astype(jnp.float32)
            new_m.append(m)
            new_p.append((p.astype(jnp.float32) - 0.1 * m).astype(p.dtype))
        return loss, new_p, new_m

    mode = os.environ.get("CEIL_MODE", "step")
    if mode == "fwd":
        fwd = jax.jit(lambda p: jnp.sum(forward(p, idx, cfg, x)))
        for _ in range(3):
            out = fwd(params)
        float(np.asarray(out))  # block_until_ready does not block over the
        t0 = time.perf_counter()  # axon tunnel; force a host read to sync
        for _ in range(steps):
            out = fwd(params)
        float(np.asarray(out))
        dt = time.perf_counter() - t0
        loss = out
    else:
        for _ in range(3):
            loss, params, moms = step(params, moms)
        float(np.asarray(loss))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, moms = step(params, moms)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
    ips = batch * steps / dt
    tflops = ips * 12.3e9 / 1e12  # ~3x fwd FLOPs, 4.1 GFLOP/img fwd
    print(json.dumps({
        "layout": LAYOUT, "dtype": str(DTYPE.__name__), "bn_f32": BN_F32,
        "img_per_sec": round(ips, 1), "est_tflops": round(tflops, 1),
        "mfu_vs_197tflops": round(tflops / 197, 3), "loss": float(loss),
    }))


if __name__ == "__main__":
    main()
