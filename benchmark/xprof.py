"""Parse a jax.profiler xplane trace into a per-op time table.

Usage:
    python benchmark/xprof.py /tmp/jaxtrace            # newest trace under dir
    python benchmark/xprof.py path/to/*.xplane.pb

Groups XLA op events by fusion/op category so the output answers "where
does the step time go" without TensorBoard (which this image's
tensorboard-plugin-profile build cannot serve).
"""

import collections
import glob
import os
import re
import sys


def load_xspace(path):
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def find_trace(arg):
    if arg.endswith(".pb"):
        return arg
    hits = sorted(glob.glob(arg + "/**/*.xplane.pb", recursive=True))
    if not hits:
        raise SystemExit(f"no .xplane.pb under {arg}")
    return hits[-1]


_CATEGORY_RULES = [
    (re.compile(r"convolution|conv(\.|$|\d)"), "conv"),
    (re.compile(r"dot(\.|$|\d)|matmul"), "matmul"),
    (re.compile(r"all-reduce|all-gather|reduce-scatter|collective|permute"), "collective"),
    (re.compile(r"copy|transpose|bitcast"), "copy/transpose"),
    (re.compile(r"reduce-window|select-and-scatter"), "pooling"),
    (re.compile(r"reduce"), "reduce"),
    (re.compile(r"fusion|fused"), "fusion(elementwise)"),
    (re.compile(r"infeed|outfeed|send|recv"), "io"),
]


def categorize(name):
    # only the instruction name left of " = " — the full text includes
    # operand names, which would mis-categorize (e.g. any fusion fed by
    # a copy-done would count as "copy")
    low = name.split(" = ")[0].lower()
    for rx, cat in _CATEGORY_RULES:
        if rx.search(low):
            return cat
    return "other"


def main():
    path = find_trace(sys.argv[1] if len(sys.argv) > 1 else "/tmp/jaxtrace")
    xs = load_xspace(path)
    for plane in xs.planes:
        if not plane.name.startswith("/device:"):
            continue
        meta = plane.event_metadata
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            by_name = collections.Counter()
            by_cat = collections.Counter()
            total = 0
            for ev in line.events:
                name = meta[ev.metadata_id].name
                dur = ev.duration_ps / 1e6  # -> us
                by_name[name] += dur
                by_cat[categorize(name)] += dur
                total += dur
            print(f"== {plane.name}  total busy {total/1e3:.2f} ms "
                  f"({len(line.events)} events)")
            print("-- by category")
            for cat, t in by_cat.most_common():
                print(f"  {t/total*100:6.2f}%  {t/1e3:9.3f} ms  {cat}")
            print("-- top ops")
            for name, t in by_name.most_common(28):
                print(f"  {t/total*100:6.2f}%  {t/1e3:9.3f} ms  {name[:76]}")


if __name__ == "__main__":
    main()
