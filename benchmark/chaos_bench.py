#!/usr/bin/env python3
"""Fault-injection harness for the elastic training supervisor.

Runs the DemoRegression workload (paddle_tpu/distributed/elastic.py)
against a real coord store + master, SIGKILLs a worker mid-epoch, and
measures the recovery:

  replace  (default)  one worker at a time, the pod-rescheduling shape:
                      kill worker A after its first few checkpoint
                      commits, wait for the lease to lapse, launch a
                      replacement, and check the final loss is
                      bit-identical to an unkilled in-process oracle.
  survivor            two concurrent workers; kill one and verify the
                      survivor finishes the pass (the master's TTL
                      requeues the dead worker's in-flight task).

Reports kill-to-resume latency, redone-task count, and the recovery
counters (`elastic_*`, `rpc_*`) rendered the same way `paddle stats
--file` does.  Writes a JSON artifact with --out.

Usage:
  python benchmark/chaos_bench.py [--mode=replace|survivor]
      [--tasks=8] [--passes=4] [--task-sleep=0.15] [--kill-after-steps=2]
      [--out=chaos.json]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

from paddle_tpu.distributed import CoordClient, CoordServer, MasterServer  # noqa: E402
from paddle_tpu.distributed.elastic import DemoRegression  # noqa: E402
from paddle_tpu import io as io_mod  # noqa: E402
from paddle_tpu.observability import format_snapshot  # noqa: E402


def _spawn(coord, master, ckpt, wid, args, stats_out=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.elastic",
           f"--coord={coord}", f"--master={master}", "--job=chaos",
           f"--checkpoint-dir={ckpt}", f"--tasks={args.tasks}",
           f"--passes={args.passes}", f"--task-sleep={args.task_sleep}",
           "--lease-ttl=2", "--checkpoint-period=1", f"--worker-id={wid}",
           f"--seed={args.seed}", f"--dim={args.dim}"]
    if stats_out:
        cmd.append(f"--stats-out={stats_out}")
    return subprocess.Popen(cmd, env=env, cwd=REPO_ROOT,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)


def _wait_step(probe, key, min_step, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = probe.get(key)
        if got is not None:
            step = json.loads(got[1].decode())["step"]
            if step >= min_step:
                return step
        time.sleep(0.05)
    raise RuntimeError(f"no checkpoint reached step {min_step}")


def _wait_lease_gone(probe, key, timeout=30):
    t0 = time.time()
    while probe.get(key) is not None:
        if time.time() - t0 > timeout:
            raise RuntimeError("worker lease never expired")
        time.sleep(0.05)
    return time.time() - t0


def run_replace(args):
    demo = DemoRegression(dim=args.dim, seed=args.seed)
    oracle = demo.oracle(args.tasks, args.passes)
    result = {"mode": "replace", "tasks": args.tasks, "passes": args.passes}
    with CoordServer() as cs, MasterServer(lease_sec=2) as ms, \
            tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "ck")
        stats_json = os.path.join(tmp, "stats.json")
        probe = CoordClient(cs.address)
        a = _spawn(cs.address, ms.address, ck, "w-a", args)
        killed_at = _wait_step(probe, "/elastic/chaos/manifest",
                               args.kill_after_steps)
        a.send_signal(signal.SIGKILL)
        a.wait(timeout=30)
        t_kill = time.time()
        result["killed_at_step"] = killed_at
        result["lease_lapse_seconds"] = _wait_lease_gone(
            probe, "/elastic/chaos/workers/w-a")

        b = _spawn(cs.address, ms.address, ck, "w-b", args,
                   stats_out=stats_json)
        out, err = b.communicate(timeout=600)
        if b.returncode != 0:
            raise RuntimeError(f"replacement worker failed:\n{out}\n{err}")
        result["kill_to_finish_seconds"] = round(time.time() - t_kill, 3)
        man = json.loads(probe.get("/elastic/chaos/manifest")[1].decode())
        probe.close()
        final = io_mod.load_state_tree(os.path.join(ck, "params"),
                                       man["step"])
        snap = json.load(open(stats_json))

    loss_chaos = demo.loss(final)
    loss_oracle = demo.loss(oracle)
    result.update(
        final_step=man["step"],
        loss_chaos=loss_chaos, loss_oracle=loss_oracle,
        loss_identical=bool(np.allclose(final["w"], oracle["w"],
                                        rtol=0, atol=0)),
        replacement_tasks=_snap_value(snap, "elastic_tasks_finished_total"),
        recovered_tasks=_snap_value(snap, "elastic_recovered_tasks_total"),
        counters={k: v for k, v in snap.items()
                  if k.startswith(("elastic_", "rpc_"))},
    )
    print(f"killed w-a at step {killed_at}/{args.tasks * args.passes}; "
          f"lease lapsed in {result['lease_lapse_seconds']:.2f}s; "
          f"replacement finished in {result['kill_to_finish_seconds']:.2f}s")
    print(f"loss chaos={loss_chaos:.9g} oracle={loss_oracle:.9g} "
          f"identical={result['loss_identical']}")
    print()
    print(format_snapshot(result["counters"]))
    assert result["loss_identical"], "recovery diverged from the oracle"
    return result


def run_survivor(args):
    result = {"mode": "survivor", "tasks": args.tasks, "passes": 1}
    with CoordServer() as cs, MasterServer(lease_sec=2) as ms, \
            tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "ck")
        stats_json = os.path.join(tmp, "stats.json")
        probe = CoordClient(cs.address)
        sargs = argparse.Namespace(**vars(args))
        sargs.passes = 1
        a = _spawn(cs.address, ms.address, os.path.join(ck, "a"), "w-a",
                   sargs)
        b = _spawn(cs.address, ms.address, os.path.join(ck, "b"), "w-b",
                   sargs, stats_out=stats_json)
        # kill A only once it is registered and has had time to lease a
        # task, so the requeue path is actually exercised
        deadline = time.time() + 60
        while probe.get("/elastic/chaos/workers/w-a") is None:
            if time.time() > deadline or a.poll() is not None:
                break
            time.sleep(0.05)
        time.sleep(max(args.task_sleep * 3, 0.5))
        a.send_signal(signal.SIGKILL)
        a.wait(timeout=30)
        t_kill = time.time()
        out, err = b.communicate(timeout=600)
        if b.returncode != 0:
            raise RuntimeError(f"survivor failed:\n{out}\n{err}")
        result["kill_to_finish_seconds"] = round(time.time() - t_kill, 3)
        snap = json.load(open(stats_json))
        probe.close()
    survivor_tasks = _snap_value(snap, "elastic_tasks_finished_total")
    result["survivor_tasks"] = survivor_tasks
    result["counters"] = {k: v for k, v in snap.items()
                          if k.startswith(("elastic_", "rpc_"))}
    print(f"survivor finished the pass {result['kill_to_finish_seconds']:.2f}s "
          f"after the kill, completing {survivor_tasks:g} of "
          f"{args.tasks} tasks itself")
    print()
    print(format_snapshot(result["counters"]))
    assert survivor_tasks >= 1
    return result


def _snap_value(snap, name):
    fam = snap.get(name, {})
    return sum(v["value"] for v in fam.get("values", []))


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--mode", choices=("replace", "survivor"),
                    default="replace")
    ap.add_argument("--tasks", type=int, default=8)
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--task-sleep", type=float, default=0.15)
    ap.add_argument("--kill-after-steps", type=int, default=2)
    ap.add_argument("--out", default=None, help="write a JSON artifact")
    args = ap.parse_args()

    result = (run_replace if args.mode == "replace" else run_survivor)(args)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
        print(f"\nartifact written to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
