"""Pallas implicit-GEMM conv vs the XLA conv emitter, per ResNet-50
hot shape and direction.

Methodology (supersedes the first conv_probe harness): this chip's
tunnel adds ~20 ms of fixed per-program overhead (measured: a 4096^3
matmul chain reads 38 TF/s at R=8 but 126 TF/s at R=64), so every
measurement value-chains R=64 applications inside one jit and reads
one scalar at the end.  fwd and bwd-input chain directly (Cin == Cout
at the 3x3 shapes); bwd-filter uses a data-dependent perturbation
chain whose per-iteration cost (~one sum pass) is identical for both
implementations.

Usage: python benchmark/pallas_conv_bench.py [--only c2,c4] [--dirs fwd]
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.pallas.conv import _conv_dw_impl, _conv_fwd_impl

SHAPES = [
    ("c2.3x3", 256, 56, 56, 64, 3),
    ("c3.3x3", 256, 28, 28, 128, 3),
    ("c4.3x3", 256, 14, 14, 256, 3),
    ("c5.3x3", 256, 7, 7, 512, 3),
]

R = 64


def xla_conv(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def timed(jf, arg, steps=3):
    out = float(jf(arg))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = jf(arg)
    float(out)
    return (time.perf_counter() - t0) / steps / R


def value_chain(fn):
    def run(x0):
        def body(_, y):
            return fn(y)

        y = lax.fori_loop(0, R, body, x0)
        return jnp.sum(y.astype(jnp.float32))

    return jax.jit(run)


def dep_chain(fn):
    def run(x0):
        def body(_, carry):
            x_c, acc = carry
            s = jnp.sum(fn(x_c).astype(jnp.float32))
            dep = jnp.where(jnp.isnan(s), s, 0.0).astype(x0.dtype)
            return x0 + dep, acc + s

        _, acc = lax.fori_loop(0, R, body, (x0, jnp.float32(0)))
        return acc

    return jax.jit(run)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", type=str, default="")
    ap.add_argument("--dirs", type=str, default="fwd,bwd_x,bwd_w")
    args = ap.parse_args()
    only = [t for t in args.only.split(",") if t]
    dirs = args.dirs.split(",")
    rng = np.random.RandomState(0)
    print(f"{'shape':8} {'dir':6} {'xla ms':>8} {'pallas ms':>9} "
          f"{'xla TF':>7} {'pallas TF':>9} {'speedup':>8}", flush=True)
    for name, n, h, w, c, k in SHAPES:
        if only and not any(t in name for t in only):
            continue
        x = jnp.asarray(rng.randn(n, h, w, c), jnp.bfloat16)
        wt = jnp.asarray(rng.randn(k, k, c, c) * 0.03, jnp.bfloat16)
        g = jnp.asarray(rng.randn(n, h, w, c) * 0.03, jnp.bfloat16)
        flops = 2 * n * h * w * c * c * k * k
        w_flip = jnp.flip(wt, (0, 1)).swapaxes(2, 3)

        cases = {}
        if "fwd" in dirs:
            cases["fwd"] = (
                value_chain(lambda v: xla_conv(v, wt).astype(v.dtype)),
                value_chain(lambda v: _conv_fwd_impl(v, wt, k // 2)), x)
        if "bwd_x" in dirs:
            # backward-input == forward conv with flipped/transposed w
            cases["bwd_x"] = (
                value_chain(lambda v: lax.conv_general_dilated(
                    v, w_flip, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC")).astype(
                        v.dtype)),
                value_chain(lambda v: _conv_fwd_impl(v, w_flip, k // 2)), g)
        if "bwd_w" in dirs:
            def xla_dw(v):
                return jax.grad(
                    lambda ww: jnp.sum(xla_conv(v, ww).astype(jnp.float32)
                                       * g.astype(jnp.float32)))(wt)

            cases["bwd_w"] = (
                dep_chain(xla_dw),
                dep_chain(lambda v: _conv_dw_impl(v, g, k, k // 2)), x)

        for tag, (jx, jp, arg) in cases.items():
            tx = timed(jx, arg)
            tp = timed(jp, arg)
            print(f"{name:8} {tag:6} {tx*1e3:8.3f} {tp*1e3:9.3f} "
                  f"{flops/tx/1e12:7.1f} {flops/tp/1e12:9.1f} "
                  f"{tx/tp:8.2f}x", flush=True)


if __name__ == "__main__":
    main()
