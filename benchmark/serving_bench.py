#!/usr/bin/env python3
"""Serving load-test harness (ISSUE 13): measure the continuous-batching
replica-pool engine against the single-lock-equivalent baseline, so the
throughput claim is a number, not an adjective.

What it runs
------------
A bundled MLP inference model (fc stack, --depth x --hidden) is exported
once; then for each engine config:

- **baseline**  — replicas=1, max_batch=1: every request dispatches
  alone at its exact shape, one worker.  Functionally identical to the
  pre-ISSUE-13 server (one executor behind a lock).
- **batched**   — --replicas N, --max_batch B: bucketed coalescing
  across a replica pool.

two load loops are driven over plain HTTP (keep-alive connections):

- **closed loop** — C clients issue requests back-to-back for D
  seconds: sustained RPS + p50/p99 service latency.
- **open loop**   — requests arrive on a fixed schedule at a target
  rate (sweeping fractions of the closed-loop RPS): the saturation
  curve.  Latency is measured from the *scheduled* arrival, so
  coordinated omission cannot hide queueing.

Compile-cache behavior is scraped from /metrics before and after each
measured window: after warmup the miss delta must be 0 (one compiled
XLA program per bucket, hit rate ~1.0).

Artifact
--------
``--out`` (default serving_bench.json) gets a
``paddle_tpu.serving_bench.v1`` document; BENCHMARKS.md documents the
schema and records the acceptance row.

Usage
-----
    python benchmark/serving_bench.py [--replicas=4] [--max_batch=16]
        [--clients=16] [--duration=10] [--depth=4] [--hidden=256]
        [--open-points=0.5,0.75,1.0,1.25] [--out=serving_bench.json]
        [--model_dir=DIR] [--smoke]
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCHEMA = "paddle_tpu.serving_bench.v1"


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------


def build_model(dirname: str, depth: int, hidden: int, in_dim: int,
                classes: int) -> str:
    import paddle_tpu as fluid

    fluid.framework.reset_default_programs()
    x = fluid.layers.data(name="x", shape=[in_dim], dtype="float32")
    h = x
    for _ in range(depth):
        h = fluid.layers.fc(input=h, size=hidden, act="relu")
    pred = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(dirname, ["x"], [pred], exe)
    return dirname


# ---------------------------------------------------------------------------
# HTTP client (keep-alive; one connection per worker thread)
# ---------------------------------------------------------------------------


class Client:
    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self.conn = http.client.HTTPConnection(host, int(port), timeout=60)
        self.headers = {"Content-Type": "application/json"}

    def predict(self, body: bytes) -> int:
        self.conn.request("POST", "/predict", body=body,
                          headers=self.headers)
        resp = self.conn.getresponse()
        resp.read()
        return resp.status

    def get(self, path: str) -> str:
        self.conn.request("GET", path)
        resp = self.conn.getresponse()
        return resp.read().decode()

    def close(self):
        self.conn.close()


def _percentile(sorted_ms, q):
    if not sorted_ms:
        return float("nan")
    i = min(len(sorted_ms) - 1, int(round(q * (len(sorted_ms) - 1))))
    return sorted_ms[i]


def _cache_counts(address: str):
    text = Client(address).get("/metrics")
    hits = misses = 0.0
    for line in text.splitlines():
        if line.startswith("executor_compile_cache_hit_total"):
            hits += float(line.rsplit(" ", 1)[1])
        elif line.startswith("executor_compile_cache_miss_total"):
            misses += float(line.rsplit(" ", 1)[1])
    return hits, misses


# ---------------------------------------------------------------------------
# load loops
# ---------------------------------------------------------------------------


def closed_loop(address: str, body: bytes, clients: int, duration: float):
    """C clients, back-to-back requests: sustained RPS + service latency."""
    latencies: list = []
    errors = [0]
    lock = threading.Lock()
    stop_at = time.perf_counter() + duration
    start_gate = threading.Barrier(clients + 1)

    def worker():
        c = Client(address)
        # connect before the gate: accepting a connection needs the
        # server's (GIL-scheduled) accept loop, and under full load an
        # unlucky client can sit in the backlog for the whole window —
        # that would measure the accept loop, not the engine
        c.conn.connect()
        mine, bad = [], 0
        start_gate.wait()
        while True:
            t0 = time.perf_counter()
            if t0 >= stop_at:
                break
            try:
                code = c.predict(body)
                if code != 200:
                    bad += 1
                    continue
            except OSError:
                bad += 1
                continue
            mine.append((time.perf_counter() - t0) * 1e3)
        c.close()
        with lock:
            latencies.extend(mine)
            errors[0] += bad

    threads = [threading.Thread(target=worker) for _ in range(clients)]
    for t in threads:
        t.start()
    start_gate.wait()
    t_start = time.perf_counter()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_start
    latencies.sort()
    return {
        "loop": "closed", "clients": clients,
        "duration_s": round(elapsed, 3),
        "requests": len(latencies), "errors": errors[0],
        "achieved_rps": round(len(latencies) / elapsed, 1),
        "p50_ms": round(_percentile(latencies, 0.50), 3),
        "p99_ms": round(_percentile(latencies, 0.99), 3),
        "max_ms": round(latencies[-1], 3) if latencies else float("nan"),
    }


def open_loop(address: str, body: bytes, rate: float, duration: float,
              senders: int):
    """Fixed-rate arrivals; latency measured from the *scheduled*
    arrival time (coordinated-omission-proof)."""
    n = max(1, int(rate * duration))
    next_idx = [0]
    latencies: list = []
    errors = [0]
    lock = threading.Lock()
    start_gate = threading.Barrier(senders + 1)
    t0_box = [0.0]

    def worker():
        c = Client(address)
        c.conn.connect()   # see closed_loop: keep accept out of the window
        mine, bad = [], 0
        start_gate.wait()
        t0 = t0_box[0]
        while True:
            with lock:
                i = next_idx[0]
                if i >= n:
                    break
                next_idx[0] += 1
            sched = t0 + i / rate
            now = time.perf_counter()
            if sched > now:
                time.sleep(sched - now)
            try:
                code = c.predict(body)
                if code != 200:
                    bad += 1
                    continue
            except OSError:
                bad += 1
                continue
            mine.append((time.perf_counter() - sched) * 1e3)
        c.close()
        with lock:
            latencies.extend(mine)
            errors[0] += bad

    threads = [threading.Thread(target=worker) for _ in range(senders)]
    for t in threads:
        t.start()
    t0_box[0] = time.perf_counter() + 0.05
    start_gate.wait()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0_box[0]
    latencies.sort()
    return {
        "loop": "open", "offered_rps": round(rate, 1),
        "duration_s": round(elapsed, 3),
        "requests": len(latencies), "errors": errors[0],
        "achieved_rps": round(len(latencies) / max(elapsed, 1e-9), 1),
        "p50_ms": round(_percentile(latencies, 0.50), 3),
        "p99_ms": round(_percentile(latencies, 0.99), 3),
        "max_ms": round(latencies[-1], 3) if latencies else float("nan"),
    }


# ---------------------------------------------------------------------------
# one engine config = server + warmup + closed + open sweep
# ---------------------------------------------------------------------------


def _request_body(srv) -> bytes:
    """One single-row request synthesized from the served model's own
    BatchSpec (feed names, row shapes, dtypes) — so --model_dir exports
    bench the same way the bundled MLP does instead of 400ing on a
    hardcoded feed name."""
    from paddle_tpu.serving.batching import BatchSpec

    spec = srv._spec
    if not spec.batchable:
        # a no-coalescing config (baseline max_batch=1) disables the
        # spec; rebuild it just to synthesize feeds
        spec = BatchSpec.from_program(srv._bundle.program,
                                      srv._bundle.feed_names,
                                      srv._bundle.fetch_names)
    if not spec.batchable:
        raise SystemExit(
            f"cannot synthesize load for this export: {spec.reason}; "
            "serving_bench needs a batch-major model (ragged/LoD models "
            "serve, but the harness cannot invent their feeds)")
    rng = np.random.RandomState(0)
    payload = {}
    for name in spec.feed_names:
        shape = (1,) + spec.row_shapes[name]
        dt = np.dtype(spec.dtypes[name])
        if dt.kind == "f":
            payload[name] = rng.standard_normal(shape).astype(dt).tolist()
        else:
            payload[name] = np.zeros(shape, dt).tolist()
    return json.dumps(payload).encode()


def bench_config(model_dir: str, *, mode: str, replicas: int, max_batch: int,
                 batch_timeout_ms: float, clients: int, duration: float,
                 open_points, senders: int):
    from paddle_tpu.serving import InferenceServer

    srv = InferenceServer(model_dir, replicas=replicas, max_batch=max_batch,
                          batch_timeout_ms=batch_timeout_ms, warmup=True)
    body = _request_body(srv)
    try:
        # traffic warmup: exercise the HTTP path + any solo shapes
        closed_loop(srv.address, body, clients=min(4, clients),
                    duration=min(1.0, duration / 4))
        h0, m0 = _cache_counts(srv.address)
        closed = closed_loop(srv.address, body, clients, duration)
        h1, m1 = _cache_counts(srv.address)
        closed["cache"] = {
            "hits": h1 - h0, "misses": m1 - m0,
            "hit_rate": round((h1 - h0) / max(1.0, (h1 - h0) + (m1 - m0)), 6),
        }
        runs = [closed]
        for frac in open_points:
            rate = max(1.0, closed["achieved_rps"] * frac)
            runs.append(open_loop(srv.address, body, rate, duration,
                                  senders))
        info = srv.batching_info()
    finally:
        srv.stop()
    return {"mode": mode, "replicas": replicas, "max_batch": max_batch,
            "batch_timeout_ms": batch_timeout_ms, "batching": info,
            "runs": runs}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model_dir", help="serve an existing export instead "
                    "of building the bundled MLP")
    ap.add_argument("--depth", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--in_dim", type=int, default=64)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--replicas", type=int, default=4)
    ap.add_argument("--max_batch", type=int, default=16)
    ap.add_argument("--batch_timeout_ms", type=float, default=0.0)
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--senders", type=int, default=64,
                    help="open-loop sender pool size")
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--open-points", default="0.5,0.75,1.0,1.25",
                    help="open-loop rates as fractions of closed-loop RPS"
                    " ('' to skip)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the single-lock baseline config")
    ap.add_argument("--out", default="serving_bench.json")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale sanity run (lint_self.sh)")
    ap.add_argument("--multi-thread-eigen", action="store_true",
                    help="let XLA CPU's eigen pool use every core per op. "
                    "Off by default: the spinning pool starves the Python "
                    "HTTP/client threads (seconds-long GIL convoys, wild "
                    "run-to-run variance) and no serving deployment gives "
                    "one request every core anyway — per-replica "
                    "single-thread steps measure the engine, not the "
                    "scheduler fight")
    args = ap.parse_args(argv)

    if not args.multi_thread_eigen:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_cpu_multi_thread_eigen=false").strip()

    if args.smoke:
        args.depth, args.hidden, args.in_dim, args.classes = 1, 32, 8, 4
        args.replicas, args.max_batch = 2, 4
        args.clients, args.senders, args.duration = 4, 8, 0.5
        args.open_points = "1.0"

    model_dir = args.model_dir
    tmp = None
    if not model_dir:
        tmp = tempfile.TemporaryDirectory(prefix="serving_bench_")
        model_dir = build_model(os.path.join(tmp.name, "model"), args.depth,
                                args.hidden, args.in_dim, args.classes)
    open_points = [float(p) for p in args.open_points.split(",") if p]

    configs = []
    if not args.no_baseline:
        configs.append(dict(mode="baseline", replicas=1, max_batch=1,
                            batch_timeout_ms=0.0))
    configs.append(dict(mode="batched", replicas=args.replicas,
                        max_batch=args.max_batch,
                        batch_timeout_ms=args.batch_timeout_ms))

    results = []
    for cfg in configs:
        print(f"== {cfg['mode']}: replicas={cfg['replicas']} "
              f"max_batch={cfg['max_batch']}", flush=True)
        r = bench_config(model_dir, clients=args.clients,
                         duration=args.duration, open_points=open_points,
                         senders=args.senders, **cfg)
        for run in r["runs"]:
            print("  ", json.dumps(run), flush=True)
        results.append(r)

    doc = {
        "schema": SCHEMA,
        "host": {"cpus": os.cpu_count(),
                 "jax_platforms": os.environ.get("JAX_PLATFORMS", "")},
        "model": ({"model_dir": args.model_dir} if args.model_dir else
                  {"depth": args.depth, "hidden": args.hidden,
                   "in_dim": args.in_dim, "classes": args.classes}),
        "load": {"clients": args.clients, "duration_s": args.duration,
                 "senders": args.senders, "open_points": open_points},
        "configs": results,
    }
    if len(results) == 2:
        base = results[0]["runs"][0]
        batt = results[1]["runs"][0]
        doc["headline"] = {
            "baseline_rps": base["achieved_rps"],
            "batched_rps": batt["achieved_rps"],
            "speedup": round(batt["achieved_rps"]
                             / max(base["achieved_rps"], 1e-9), 2),
            "baseline_p99_ms": base["p99_ms"],
            "batched_p99_ms": batt["p99_ms"],
            "batched_cache_hit_rate": batt["cache"]["hit_rate"],
        }
        print("headline:", json.dumps(doc["headline"]))
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"artifact written to {args.out}")
    if tmp:
        tmp.cleanup()
    return 0


if __name__ == "__main__":
    sys.exit(main())
