"""Model benchmark runner (reference: benchmark/paddle/image/*.py —
AlexNet/GoogLeNet/VGG/ResNet/smallnet configs timed by run.sh — and
benchmark/paddle/rnn/rnn.py for the 2-layer LSTM IMDB model; published
numbers in benchmark/README.md + IntelOptimizedPaddle.md, mirrored in
BASELINE.md).

Usage:
  python benchmark/run.py                      # all models, default sizes
  python benchmark/run.py resnet50 lstm        # a subset
  BENCH_STEPS=20 python benchmark/run.py smallnet

Feeds are staged on device once and reused (the harness TPU sits behind
a ~30MB/s tunnel; per-step host feeds would time the tunnel, not the
training step — same policy as bench.py).  bf16 AMP is on by default
(BENCH_AMP=0 for f32).

Prints one table row + one JSON line per model with the reference
baseline ratio where BASELINE.md publishes a comparable config.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# honor JAX_PLATFORMS before first backend use (the axon TPU plugin
# otherwise overrides it and "CPU" runs silently hit the tunnel)
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

# model -> (default batch, baseline ms/batch, baseline source)
BASELINES = {
    "alexnet":    (128, 334.0,   "K40m GPU, benchmark/README.md:33-37"),
    "googlenet":  (128, 1149.0,  "K40m GPU, benchmark/README.md:46-50"),
    "smallnet":   (256, 33.113,  "K40m GPU, benchmark/README.md:53-58"),
    "vgg16":      (256, 8410.0,  "VGG-19 2xXeon6148 MKL-DNN 30.44 img/s, IntelOptimizedPaddle.md:29-36"),
    "resnet50":   (256, 3045.0,  "2xXeon6148 MKL-DNN 84.08 img/s, IntelOptimizedPaddle.md:38-45"),
    "lstm":       (64,  83.0,    "h=256 K40m GPU, benchmark/README.md:113-119"),
    "lstm_h512":  (64,  184.0,   "h=512 K40m GPU, benchmark/README.md:113-119"),
    "lstm_h1280": (64,  641.0,   "h=1280 K40m GPU, benchmark/README.md:113-119"),
}

LSTM_HIDDEN = {"lstm": 256, "lstm_h512": 512, "lstm_h1280": 1280}


def _train_step_fn(model_name, batch):
    import paddle_tpu as fluid
    from paddle_tpu import models

    fluid.framework.reset_default_programs()
    if model_name in LSTM_HIDDEN:
        T, emb, hid = 100, 512, LSTM_HIDDEN[model_name]
        ids = fluid.layers.data(name="ids", shape=[T, 1], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        pred = models.lstm_text_classifier(ids, class_dim=2, emb_dim=emb,
                                           hidden=hid)
        feed = lambda rng: {  # noqa: E731
            "ids": rng.randint(0, 10000, (batch, T, 1)).astype(np.int64),
            "label": rng.randint(0, 2, (batch, 1)).astype(np.int64)}
    else:
        smoke = os.environ.get("BENCH_SMOKE", "0") == "1"  # CI smoke: tiny
        image = {"smallnet": (3, 16, 16) if smoke else (3, 32, 32)}.get(
            model_name, (3, 224, 224))
        classes = {"smallnet": 10}.get(model_name, 1000)
        img = fluid.layers.data(name="img", shape=list(image),
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        net = {
            "alexnet": models.alexnet,
            "googlenet": models.googlenet,
            "vgg16": models.vgg16,
            "resnet50": models.resnet_imagenet,
            "smallnet": lambda x, class_dim: models.resnet_cifar10(
                x, depth=8 if smoke else 20, class_dim=class_dim),
        }[model_name]
        pred = net(img, class_dim=classes)
        feed = lambda rng: {  # noqa: E731
            "img": rng.rand(batch, *image).astype(np.float32),
            "label": rng.randint(0, classes, (batch, 1)).astype(np.int64)}
    loss = fluid.layers.mean(fluid.layers.cross_entropy(input=pred,
                                                        label=label))
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, fluid.default_main_program(), loss, feed


def bench_model(model_name, batch=None, steps=None, warmup=3):
    from paddle_tpu import amp
    import jax.numpy as jnp

    if os.environ.get("BENCH_AMP", "1") == "1":
        amp.enable()
    batch = batch or int(os.environ.get("BENCH_BATCH", 0)) \
        or BASELINES[model_name][0]
    steps = steps or int(os.environ.get("BENCH_STEPS", 10))
    rng = np.random.RandomState(0)
    exe, prog, loss, feed = _train_step_fn(model_name, batch)
    # work guard: a graph doing the wrong amount of FLOPs (round-4
    # GoogLeNet stem-stride 4x bug) must fail here, not ship a number
    from flops import assert_model_flops

    if os.environ.get("BENCH_SMOKE", "0") != "1":
        fwd_gflop = assert_model_flops(model_name, prog, batch)
    else:
        fwd_gflop = None
    dev_feed = {k: jnp.asarray(v) for k, v in feed(rng).items()}
    for _ in range(warmup):
        (l,) = exe.run(prog, feed=dev_feed, fetch_list=[loss],
                       return_numpy=False)
    float(np.asarray(l).ravel()[0])  # sync (block_until_ready does not
    t0 = time.perf_counter()         # block through the tunnel)
    for _ in range(steps):
        (l,) = exe.run(prog, feed=dev_feed, fetch_list=[loss],
                       return_numpy=False)
    float(np.asarray(l).ravel()[0])
    dt = (time.perf_counter() - t0) / steps
    base_batch, base_ms, base_src = BASELINES[model_name]
    # compare on throughput so a BENCH_BATCH override stays meaningful
    # (the baseline ms/batch is only valid at its own batch size)
    vs = (batch / dt) / (base_batch / (base_ms / 1e3))
    return {"model": model_name, "batch": batch,
            "img_per_sec": round(batch / dt, 2),
            "ms_per_batch": round(dt * 1e3, 2),
            "fwd_gflop_per_img": (round(fwd_gflop, 3)
                                  if fwd_gflop is not None else None),
            "baseline_ms_per_batch": base_ms,
            "baseline_batch": base_batch,
            "vs_baseline": round(vs, 2),
            "baseline_source": base_src}


def _device_peak():
    import jax

    kind = jax.devices()[0].device_kind
    nominal = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
               "TPU v6": 918e12}
    return next((v for k, v in nominal.items() if kind.startswith(k)), None)


def bench_seq2seq(batch=None, steps=None, warmup=3):
    """Attention NMT training throughput (BASELINE.json acceptance
    config #3 at bench scale): GRU encoder + recurrent_group decoder
    with simple_attention, the demos/seq2seq architecture scaled to
    VOCAB=30k, EMB=HID=512, S=32.  Reports tokens/s + MFU; the
    reference publishes no NMT number (benchmark/paddle/rnn covers the
    LSTM classifier only), so vs_baseline is null."""
    import jax.numpy as jnp

    from paddle_tpu import amp

    if os.environ.get("BENCH_AMP", "1") == "1":
        amp.enable()
    VOCAB, EMB, HID, S = 30000, 512, 512, 32
    B = batch or int(os.environ.get("BENCH_BATCH", 0)) or 64
    steps = steps or int(os.environ.get("BENCH_STEPS", 10))

    import paddle_tpu as fluid
    import paddle_tpu.executor as executor_mod
    from paddle_tpu.trainer.config_parser import parse_config
    from paddle_tpu.trainer.trainer import Trainer

    fluid.framework.reset_default_programs()

    def config():
        from paddle_tpu.trainer_config_helpers import (
            AdamOptimizer, LinearActivation, ParamAttr, SoftmaxActivation,
            StaticInput, classification_cost, data_layer,
            embedding_layer, fc_layer, grumemory, memory, outputs,
            recurrent_group, settings)
        from paddle_tpu.trainer_config_helpers.networks import \
            simple_attention

        settings(batch_size=B, learning_rate=1e-3,
                 learning_method=AdamOptimizer())
        src = data_layer(name="src", size=VOCAB)
        src_emb = embedding_layer(input=src, size=EMB,
                                  param_attr=ParamAttr(name="src_emb"))
        enc_proj = fc_layer(input=src_emb, size=3 * HID,
                            act=LinearActivation(), bias_attr=False)
        enc = grumemory(input=enc_proj, size=HID, name="enc_seq")
        trg_in = data_layer(name="trg_in", size=VOCAB)
        trg_out = data_layer(name="trg_out", size=VOCAB)
        trg_emb = embedding_layer(input=trg_in, size=EMB,
                                  param_attr=ParamAttr(name="trg_emb"))

        def step(word, enc_states):
            from paddle_tpu.trainer_config_helpers.layers_extra import \
                gru_step_layer

            dec_mem = memory(name="dec_state", size=HID)
            ctx = simple_attention(encoded_sequence=enc_states,
                                   encoded_proj=enc_states,
                                   decoder_state=dec_mem)
            inp = fc_layer(input=[word, ctx], size=3 * HID,
                           act=LinearActivation(), bias_attr=False)
            dec = gru_step_layer(input=inp, output_mem=dec_mem, size=HID,
                                 name="dec_state")
            return fc_layer(input=dec, size=VOCAB,
                            act=SoftmaxActivation())

        probs = recurrent_group(step=step,
                                input=[trg_emb,
                                       StaticInput(enc, is_seq=True,
                                                   size=HID)])
        outputs(classification_cost(input=probs, label=trg_out))

    conf = parse_config(config)
    from paddle_tpu.v2.data_type import integer_value_sequence

    for name in ("src", "trg_in", "trg_out"):
        conf.data_layers[name].input_type = integer_value_sequence(VOCAB)
    t = Trainer(conf)
    topo = t._sgd.topology
    prog = topo.main_program
    rng = np.random.RandomState(0)
    lens = np.full((B,), S, np.int32)
    feed = {
        "src": jnp.asarray(rng.randint(2, VOCAB, (B, S)).astype(np.int64)),
        "src@len": jnp.asarray(lens),
        "trg_in": jnp.asarray(rng.randint(2, VOCAB, (B, S)).astype(np.int64)),
        "trg_in@len": jnp.asarray(lens),
        "trg_out": jnp.asarray(
            rng.randint(2, VOCAB, (B, S)).astype(np.int64)),
        "trg_out@len": jnp.asarray(lens),
    }
    from paddle_tpu.executor import Executor
    from paddle_tpu.framework import TPUPlace

    exe = Executor(TPUPlace())
    if os.environ.get("BENCH_CHAIN", "1") == "1":
        # scanned K-step training loop, best-of-5 chain blocks — the
        # bench.py ResNet methodology: per-step dispatch through the
        # harness tunnel pays a fixed ~6-9 ms RPC per program that a
        # locally attached chip does not, so the chain times the device
        # step itself, and the best block drops inter-block jitter
        # without putting a host sync inside the pipeline.
        # BENCH_CHAIN=0 restores per-dispatch timing.
        import jax
        from jax import lax

        fn, state, feeds, uses_rng = exe.build_callable(
            prog, {k: np.asarray(v) for k, v in feed.items()},
            [topo.cost_var.name], scope=t.parameters.scope)
        K = 5

        def multi(state, feeds, base_seed):
            def body(s, i):
                fetches, s2 = (fn(s, feeds, base_seed + i) if uses_rng
                               else fn(s, feeds))
                return s2, fetches[0]

            s, losses = lax.scan(body, state, jnp.arange(K))
            return losses[-1], s

        jm = jax.jit(multi, donate_argnums=(0,))
        dev_feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        out, state = jm(state, dev_feeds, jnp.int32(0))
        float(np.asarray(out))            # compile + warm chain
        for _ in range(max(warmup // K - 1, 0)):
            out, state = jm(state, dev_feeds, jnp.int32(0))
        float(np.asarray(out))
        reps = max(steps // K, 2)
        best, seed = float("inf"), K
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(reps):
                out, state = jm(state, dev_feeds, jnp.int32(seed))
                seed += K
            float(np.asarray(out))        # sync once per block
            best = min(best, time.perf_counter() - t0)
        dt = best / (reps * K)
    else:
        with executor_mod.scope_guard(t.parameters.scope):
            for _ in range(warmup):
                (l,) = exe.run(prog, feed=feed,
                               fetch_list=[topo.cost_var.name],
                               return_numpy=False)
            float(np.asarray(l).ravel()[0])
            t0 = time.perf_counter()
            for _ in range(steps):
                (l,) = exe.run(prog, feed=feed,
                               fetch_list=[topo.cost_var.name],
                               return_numpy=False)
            float(np.asarray(l).ravel()[0])
            dt = (time.perf_counter() - t0) / steps
    tokens = B * S
    # model FLOPs per step (matmul terms only, x3 for fwd+bwd):
    # encoder: emb->3H proj + GRU recurrent 3H*H; decoder per target
    # token: attention (2 H*H projections + 2*S H-dots + S scores),
    # input proj (EMB+H)->3H, GRU 3H*H, output fc H*VOCAB (dominant)
    per_tok = (EMB * 3 * HID + 3 * HID * HID            # encoder
               + 2 * HID * HID + 2 * S * HID            # attention
               + (EMB + HID) * 3 * HID + 3 * HID * HID  # decoder gru
               + HID * VOCAB)                           # softmax fc
    flops = 3 * 2 * per_tok * tokens
    peak = _device_peak()
    return {"model": "seq2seq_nmt_attention", "batch": B, "seq_len": S,
            "vocab": VOCAB, "emb": EMB, "hidden": HID,
            "tokens_per_sec": round(tokens / dt, 1),
            "ms_per_batch": round(dt * 1e3, 2),
            "model_tflop_per_step": round(flops / 1e12, 4),
            "mfu_vs_nominal": (round(flops / dt / peak, 4)
                               if peak else None),
            "vs_baseline": None,
            "baseline_source": "no published reference NMT number "
                               "(benchmark/paddle/rnn is the LSTM "
                               "classifier); acceptance config tracked "
                               "for trend"}


def bench_wide_deep(batch=None, steps=None, warmup=3):
    """Wide&Deep CTR with the sparse lookup_table path on
    (BASELINE.json acceptance config #4 at bench scale): 1e5-row wide
    table, 26 deep fields.  Reports examples/s; the reference publishes
    no CTR throughput number, so vs_baseline is null."""
    import jax.numpy as jnp

    from paddle_tpu import amp

    if os.environ.get("BENCH_AMP", "1") == "1":
        amp.enable()
    Wv, Dv, F, W = 100_000, 10_000, 26, 26
    B = batch or int(os.environ.get("BENCH_BATCH", 0)) or 1024
    steps = steps or int(os.environ.get("BENCH_STEPS", 10))

    import paddle_tpu as fluid
    from paddle_tpu import models

    fluid.framework.reset_default_programs()
    wide = fluid.layers.data(name="wide", shape=[W, 1], dtype="int64")
    deep = fluid.layers.data(name="deep", shape=[F, 1], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")
    prob = models.wide_deep(wide, deep, wide_vocab=Wv, deep_vocab=Dv,
                            num_fields=F, emb_dim=16, hidden=(256, 128),
                            is_sparse=True)
    loss = fluid.layers.mean(fluid.layers.log_loss(prob, label))
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"wide": jnp.asarray(
                rng.randint(0, Wv, (B, W, 1)).astype(np.int64)),
            "deep": jnp.asarray(
                rng.randint(0, Dv, (B, F, 1)).astype(np.int64)),
            "label": jnp.asarray(
                (rng.rand(B, 1) < 0.3).astype(np.float32))}
    for _ in range(warmup):
        (l,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(l).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        (l,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(l).ravel()[0])
    dt = (time.perf_counter() - t0) / steps
    return {"model": "wide_deep_ctr_sparse", "batch": B,
            "wide_vocab": Wv, "deep_vocab": Dv, "fields": F,
            "examples_per_sec": round(B / dt, 1),
            "ms_per_batch": round(dt * 1e3, 3),
            "vs_baseline": None,
            "baseline_source": "no published reference CTR throughput; "
                               "sparse-path acceptance config tracked "
                               "for trend"}


EXTRA_BENCHES = {"seq2seq": bench_seq2seq, "wide_deep": bench_wide_deep}


def main(argv=None):
    names = (argv or sys.argv[1:]) or (list(BASELINES)
                                       + list(EXTRA_BENCHES))
    rows = []
    for n in names:
        try:
            r = EXTRA_BENCHES[n]() if n in EXTRA_BENCHES else bench_model(n)
        except Exception as e:  # keep sweeping; record the failure
            r = {"model": n, "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(r), flush=True)
            rows.append(r)
            continue
        rows.append(r)
        if "img_per_sec" in r:
            print(f"{r['model']:<10} bs={r['batch']:<4} "
                  f"{r['img_per_sec']:>10.2f} img/s  "
                  f"{r['ms_per_batch']:>8.2f} ms/batch  "
                  f"{r['vs_baseline']:>7.2f}x baseline", flush=True)
        else:
            rate = r.get("tokens_per_sec") or r.get("examples_per_sec")
            unit = "tok/s" if "tokens_per_sec" in r else "ex/s"
            mfu = r.get("mfu_vs_nominal")
            print(f"{r['model']:<24} bs={r['batch']:<5} "
                  f"{rate:>10.1f} {unit}  {r['ms_per_batch']:>8.2f} ms/batch"
                  + (f"  MFU {mfu:.1%}" if mfu else ""), flush=True)
        print(json.dumps(r), flush=True)
    return rows


if __name__ == "__main__":
    main()
