"""Model benchmark runner (reference: benchmark/paddle/image/*.py —
AlexNet/GoogLeNet/VGG/ResNet/smallnet configs timed by run.sh — and
benchmark/paddle/rnn/rnn.py for the LSTM text model; published numbers
in benchmark/README.md + IntelOptimizedPaddle.md, mirrored in
BASELINE.md).

Usage:
  python benchmark/run.py                    # all models, default sizes
  python benchmark/run.py resnet50 alexnet   # a subset
  BENCH_STEPS=20 BENCH_BATCH=64 python benchmark/run.py smallnet

Prints one table row + one JSON line per model:
  {"model": ..., "batch": ..., "img_per_sec": ..., "ms_per_batch": ...}
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _train_step_fn(model_name, batch):
    import paddle_tpu as fluid
    from paddle_tpu import models

    fluid.framework.reset_default_programs()
    if model_name == "lstm":
        T, emb, hid = 100, 512, 512
        ids = fluid.layers.data(name="ids", shape=[T, 1], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        pred = models.lstm_text_classifier(ids, class_dim=2, emb_dim=emb,
                                           hidden=hid)
        feed = lambda rng: {  # noqa: E731
            "ids": rng.randint(0, 10000, (batch, T, 1)).astype(np.int64),
            "label": rng.randint(0, 2, (batch, 1)).astype(np.int64)}
    else:
        image = {"smallnet": (3, 32, 32)}.get(model_name, (3, 224, 224))
        classes = {"smallnet": 10}.get(model_name, 1000)
        img = fluid.layers.data(name="img", shape=list(image),
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        net = {
            "alexnet": models.alexnet,
            "googlenet": models.googlenet,
            "vgg16": models.vgg16,
            "resnet50": models.resnet_imagenet,
            "smallnet": lambda x, class_dim: models.resnet_cifar10(
                x, depth=20, class_dim=class_dim),
        }[model_name]
        pred = net(img, class_dim=classes)
        feed = lambda rng: {  # noqa: E731
            "img": rng.rand(batch, *image).astype(np.float32),
            "label": rng.randint(0, classes, (batch, 1)).astype(np.int64)}
    loss = fluid.layers.mean(fluid.layers.cross_entropy(input=pred,
                                                        label=label))
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, fluid.default_main_program(), loss, feed


DEFAULT_BATCH = {"alexnet": 128, "googlenet": 128, "vgg16": 64,
                 "resnet50": 64, "smallnet": 256, "lstm": 64}


def bench_model(model_name, batch=None, steps=None, warmup=2):
    batch = batch or int(os.environ.get("BENCH_BATCH", 0)) \
        or DEFAULT_BATCH[model_name]
    steps = steps or int(os.environ.get("BENCH_STEPS", 10))
    rng = np.random.RandomState(0)
    exe, prog, loss, feed = _train_step_fn(model_name, batch)
    for _ in range(warmup):
        exe.run(prog, feed=feed(rng), fetch_list=[loss])
    t0 = time.perf_counter()
    for _ in range(steps):
        (l,) = exe.run(prog, feed=feed(rng), fetch_list=[loss])
    _ = float(np.asarray(l).ravel()[0])  # sync
    dt = (time.perf_counter() - t0) / steps
    return {"model": model_name, "batch": batch,
            "img_per_sec": round(batch / dt, 2),
            "ms_per_batch": round(dt * 1e3, 2)}


def main(argv=None):
    names = (argv or sys.argv[1:]) or list(DEFAULT_BATCH)
    rows = []
    for n in names:
        r = bench_model(n)
        rows.append(r)
        print(f"{r['model']:<10} bs={r['batch']:<4} "
              f"{r['img_per_sec']:>10.2f} img/s  "
              f"{r['ms_per_batch']:>8.2f} ms/batch", flush=True)
        print(json.dumps(r), flush=True)
    return rows


if __name__ == "__main__":
    main()
