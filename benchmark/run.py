"""Model benchmark runner (reference: benchmark/paddle/image/*.py —
AlexNet/GoogLeNet/VGG/ResNet/smallnet configs timed by run.sh — and
benchmark/paddle/rnn/rnn.py for the 2-layer LSTM IMDB model; published
numbers in benchmark/README.md + IntelOptimizedPaddle.md, mirrored in
BASELINE.md).

Usage:
  python benchmark/run.py                      # all models, default sizes
  python benchmark/run.py resnet50 lstm        # a subset
  BENCH_STEPS=20 python benchmark/run.py smallnet

Feeds are staged on device once and reused (the harness TPU sits behind
a ~30MB/s tunnel; per-step host feeds would time the tunnel, not the
training step — same policy as bench.py).  bf16 AMP is on by default
(BENCH_AMP=0 for f32).

Prints one table row + one JSON line per model with the reference
baseline ratio where BASELINE.md publishes a comparable config.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# honor JAX_PLATFORMS before first backend use (the axon TPU plugin
# otherwise overrides it and "CPU" runs silently hit the tunnel)
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

# model -> (default batch, baseline ms/batch, baseline source)
BASELINES = {
    "alexnet":    (128, 334.0,   "K40m GPU, benchmark/README.md:33-37"),
    "googlenet":  (128, 1149.0,  "K40m GPU, benchmark/README.md:46-50"),
    "smallnet":   (256, 33.113,  "K40m GPU, benchmark/README.md:53-58"),
    "vgg16":      (256, 8410.0,  "VGG-19 2xXeon6148 MKL-DNN 30.44 img/s, IntelOptimizedPaddle.md:29-36"),
    "resnet50":   (256, 3045.0,  "2xXeon6148 MKL-DNN 84.08 img/s, IntelOptimizedPaddle.md:38-45"),
    "lstm":       (64,  83.0,    "h=256 K40m GPU, benchmark/README.md:113-119"),
    "lstm_h512":  (64,  184.0,   "h=512 K40m GPU, benchmark/README.md:113-119"),
    "lstm_h1280": (64,  641.0,   "h=1280 K40m GPU, benchmark/README.md:113-119"),
}

LSTM_HIDDEN = {"lstm": 256, "lstm_h512": 512, "lstm_h1280": 1280}


def _train_step_fn(model_name, batch):
    import paddle_tpu as fluid
    from paddle_tpu import models

    fluid.framework.reset_default_programs()
    if model_name in LSTM_HIDDEN:
        T, emb, hid = 100, 512, LSTM_HIDDEN[model_name]
        ids = fluid.layers.data(name="ids", shape=[T, 1], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        pred = models.lstm_text_classifier(ids, class_dim=2, emb_dim=emb,
                                           hidden=hid)
        feed = lambda rng: {  # noqa: E731
            "ids": rng.randint(0, 10000, (batch, T, 1)).astype(np.int64),
            "label": rng.randint(0, 2, (batch, 1)).astype(np.int64)}
    else:
        smoke = os.environ.get("BENCH_SMOKE", "0") == "1"  # CI smoke: tiny
        image = {"smallnet": (3, 16, 16) if smoke else (3, 32, 32)}.get(
            model_name, (3, 224, 224))
        classes = {"smallnet": 10}.get(model_name, 1000)
        img = fluid.layers.data(name="img", shape=list(image),
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        net = {
            "alexnet": models.alexnet,
            "googlenet": models.googlenet,
            "vgg16": models.vgg16,
            "resnet50": models.resnet_imagenet,
            "smallnet": lambda x, class_dim: models.resnet_cifar10(
                x, depth=8 if smoke else 20, class_dim=class_dim),
        }[model_name]
        pred = net(img, class_dim=classes)
        feed = lambda rng: {  # noqa: E731
            "img": rng.rand(batch, *image).astype(np.float32),
            "label": rng.randint(0, classes, (batch, 1)).astype(np.int64)}
    loss = fluid.layers.mean(fluid.layers.cross_entropy(input=pred,
                                                        label=label))
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, fluid.default_main_program(), loss, feed


def bench_model(model_name, batch=None, steps=None, warmup=3):
    from paddle_tpu import amp
    import jax.numpy as jnp

    if os.environ.get("BENCH_AMP", "1") == "1":
        amp.enable()
    batch = batch or int(os.environ.get("BENCH_BATCH", 0)) \
        or BASELINES[model_name][0]
    steps = steps or int(os.environ.get("BENCH_STEPS", 10))
    rng = np.random.RandomState(0)
    exe, prog, loss, feed = _train_step_fn(model_name, batch)
    dev_feed = {k: jnp.asarray(v) for k, v in feed(rng).items()}
    for _ in range(warmup):
        (l,) = exe.run(prog, feed=dev_feed, fetch_list=[loss],
                       return_numpy=False)
    float(np.asarray(l).ravel()[0])  # sync (block_until_ready does not
    t0 = time.perf_counter()         # block through the tunnel)
    for _ in range(steps):
        (l,) = exe.run(prog, feed=dev_feed, fetch_list=[loss],
                       return_numpy=False)
    float(np.asarray(l).ravel()[0])
    dt = (time.perf_counter() - t0) / steps
    base_batch, base_ms, base_src = BASELINES[model_name]
    # compare on throughput so a BENCH_BATCH override stays meaningful
    # (the baseline ms/batch is only valid at its own batch size)
    vs = (batch / dt) / (base_batch / (base_ms / 1e3))
    return {"model": model_name, "batch": batch,
            "img_per_sec": round(batch / dt, 2),
            "ms_per_batch": round(dt * 1e3, 2),
            "baseline_ms_per_batch": base_ms,
            "baseline_batch": base_batch,
            "vs_baseline": round(vs, 2),
            "baseline_source": base_src}


def main(argv=None):
    names = (argv or sys.argv[1:]) or list(BASELINES)
    rows = []
    for n in names:
        try:
            r = bench_model(n)
        except Exception as e:  # keep sweeping; record the failure
            r = {"model": n, "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(r), flush=True)
            rows.append(r)
            continue
        rows.append(r)
        print(f"{r['model']:<10} bs={r['batch']:<4} "
              f"{r['img_per_sec']:>10.2f} img/s  "
              f"{r['ms_per_batch']:>8.2f} ms/batch  "
              f"{r['vs_baseline']:>7.2f}x baseline", flush=True)
        print(json.dumps(r), flush=True)
    return rows


if __name__ == "__main__":
    main()
