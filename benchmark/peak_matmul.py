"""Measure achievable bf16 matmul TFLOPS on this chip (roofline probe)."""
import json
import time

import jax
import jax.numpy as jnp

N = 8192
a = jax.random.normal(jax.random.key(0), (N, N), jnp.bfloat16)
b = jax.random.normal(jax.random.key(1), (N, N), jnp.bfloat16)


@jax.jit
def f(a, b):
    c = a
    for _ in range(8):
        c = c @ b
    return c


c = f(a, b)
jax.block_until_ready(c)
t0 = time.perf_counter()
reps = 5
for _ in range(reps):
    c = f(a, b)
jax.block_until_ready(c)
dt = time.perf_counter() - t0
flops = 2 * N**3 * 8 * reps
print(json.dumps({"tflops": round(flops / dt / 1e12, 1),
                  "device": jax.devices()[0].device_kind}))
