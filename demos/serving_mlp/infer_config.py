"""Serving MLP inference config (fluid script form).

The model the serving stack benchmarks (benchmark/serving_bench.py
build_model): a relu fc stack ending in a softmax head.  Shipped as a
lint/optimize target so `paddle lint --optimize` exercises the rewrite
pipeline + donation-safety analyzer over the exact program shape the
replica pool serves — see scripts/lint_self.sh.

Feed: x (batch, 32).  Fetch: prediction (batch, 10).
"""

import paddle_tpu as fluid

DEPTH = 3
HIDDEN = 64
IN_DIM = 32
CLASSES = 10

x = fluid.layers.data(name="x", shape=[IN_DIM], dtype="float32")
h = x
for _ in range(DEPTH):
    h = fluid.layers.fc(input=h, size=HIDDEN, act="relu")
pred = fluid.layers.fc(input=h, size=CLASSES, act="softmax")

# stable fetch name for the lint harness (fc tmp names are positional)
_out = fluid.default_main_program().global_block().create_var(
    name="prediction", shape=pred.shape, dtype=pred.dtype)
fluid.layers.assign(pred, output=_out)
