"""v1 quick-start text classification config (reference:
demo quick_start — sequence_conv_pool backbone, trainer_config_helpers
networks.py)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

dict_dim = get_config_arg("dict_dim", int, 200)

define_py_data_sources2(
    train_list="256", test_list="64",
    module="demos.quick_start.text_provider", obj="process",
    args={"dict_dim": dict_dim})

settings(batch_size=32, learning_rate=1e-3,
         learning_method=AdamOptimizer())

words = data_layer(name="word", size=dict_dim)
emb = embedding_layer(input=words, size=32)
conv = sequence_conv_pool(input=emb, context_len=3, hidden_size=64)
prob = fc_layer(input=conv, size=2, act=SoftmaxActivation())

label = data_layer(name="label", size=2)
cost = classification_cost(input=prob, label=label)

outputs(cost)
