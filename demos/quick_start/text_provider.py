"""Synthetic sentiment provider for the quick-start demo: class-1
sequences are drawn from the top half of the vocab, class-0 from the
bottom half — linearly separable through the embedding."""

import numpy as np

from paddle_tpu.trainer.PyDataProvider2 import (integer_value,
                                                integer_value_sequence,
                                                provider)


@provider(input_types={"word": integer_value_sequence(200),
                       "label": integer_value(2)})
def process(settings, filename, dict_dim=200):
    rng = np.random.RandomState(11)
    n = int(filename) if filename and str(filename).isdigit() else 256
    half = dict_dim // 2
    for _ in range(n):
        y = int(rng.randint(0, 2))
        length = int(rng.randint(4, 12))
        lo, hi = (half, dict_dim) if y else (1, half)
        words = rng.randint(lo, hi, length).tolist()
        yield {"word": words, "label": y}
