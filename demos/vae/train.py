"""VAE demo (reference: v1_api_demo/vae/vae_conf.py + vae_train.py —
encoder/decoder MLPs with the reparameterization trick on MNIST).

The reparameterization noise comes from the in-graph gaussian_random op
(deterministically seeded per step by the executor's RNG plumbing), so
the whole ELBO step compiles to one XLA program.

Run: python -m demos.vae.train [steps]
"""

import numpy as np

import paddle_tpu as fluid


def build(xdim=64, hdim=32, zdim=4, batch=64):
    x = fluid.layers.data(name="x", shape=[xdim], dtype="float32")
    h = fluid.layers.fc(input=x, size=hdim, act="tanh")
    mu = fluid.layers.fc(input=h, size=zdim)
    logvar = fluid.layers.fc(input=h, size=zdim)

    eps = fluid.layers.gaussian_random(shape=[batch, zdim], mean=0.0, std=1.0)
    half_logvar = fluid.layers.scale(logvar, scale=0.5)
    std = fluid.layers.exp(half_logvar)
    z = fluid.layers.elementwise_add(mu,
                                     fluid.layers.elementwise_mul(eps, std))

    dh = fluid.layers.fc(input=z, size=hdim, act="tanh")
    recon = fluid.layers.fc(input=dh, size=xdim)

    rec_loss = fluid.layers.mean(
        fluid.layers.square_error_cost(input=recon, label=x))
    # KL(q||N(0,1)) = -0.5 * sum(1 + logvar - mu^2 - exp(logvar))
    kl_terms = fluid.layers.elementwise_sub(
        fluid.layers.elementwise_add(
            fluid.layers.scale(logvar, scale=1.0, bias=1.0),   # 1 + logvar
            fluid.layers.scale(fluid.layers.square(mu), scale=-1.0)),
        fluid.layers.exp(logvar))
    kl = fluid.layers.scale(
        fluid.layers.mean(fluid.layers.reduce_sum(kl_terms, dim=1)),
        scale=-0.5)
    loss = fluid.layers.elementwise_add(rec_loss,
                                        fluid.layers.scale(kl, scale=0.1))
    return x.name, recon, rec_loss, kl, loss


def data_batch(rng, n, xdim=64):
    """Two-factor synthetic images: each sample is a mix of two fixed
    patterns with random weights (a true 2-D latent)."""
    basis = np.stack([np.sin(np.linspace(0, 6, xdim)),
                      np.cos(np.linspace(0, 9, xdim))]).astype(np.float32)
    w = rng.randn(n, 2).astype(np.float32)
    return w @ basis + 0.05 * rng.randn(n, xdim).astype(np.float32)


def main(steps=400, batch=64, seed=0, verbose=True):
    fluid.framework.reset_default_programs()
    rng = np.random.RandomState(seed)
    xname, recon, rec_loss, kl, loss = build(batch=batch)
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    first = last = None
    for step in range(steps):
        rl, k = exe.run(feed={xname: data_batch(rng, batch)},
                        fetch_list=[rec_loss, kl])
        first = first if first is not None else float(rl)
        last = float(rl)
        if verbose and step % 100 == 0:
            print(f"step {step}: recon={float(rl):.4f} kl={float(k):.4f}")
    return first, last


if __name__ == "__main__":
    import sys

    main(int(sys.argv[1]) if len(sys.argv) > 1 else 400)
