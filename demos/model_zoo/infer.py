"""Model-zoo style inference demo (reference: v1_api_demo/model_zoo —
download a released model, run prediction; also capi's merged-model
flow).  Here: train a small ResNet briefly, export with
save_inference_model (the merged-model equivalent: program + params in
one directory), reload into a fresh scope, and classify a batch.

Run: python -m demos.model_zoo.infer
"""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import resnet_cifar10


def export(model_dir, steps=10, seed=0, verbose=True):
    """Train a few steps, then export the pruned inference slice."""
    fluid.framework.reset_default_programs()
    rng = np.random.RandomState(seed)
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    pred = resnet_cifar10(img, depth=20, class_dim=10)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(input=pred,
                                                        label=label))
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    protos = rng.randn(10, 3, 32, 32).astype("float32")
    for step in range(steps):
        ys = rng.randint(0, 10, (32,)).astype("int64")
        xs = protos[ys] + 0.1 * rng.randn(32, 3, 32, 32).astype("float32")
        (l,) = exe.run(feed={"img": xs, "label": ys.reshape(-1, 1)},
                       fetch_list=[loss])
        if verbose and step % 5 == 0:
            print(f"train step {step}: loss={float(l):.4f}")
    fluid.io.save_inference_model(model_dir, ["img"], [pred], exe)
    return protos


def infer(model_dir, images):
    """Fresh-scope reload + forward (what a deployment process does)."""
    fluid.framework.reset_default_programs()
    scope = fluid.executor.Scope()
    exe = fluid.Executor(fluid.TPUPlace())
    with fluid.executor.scope_guard(scope):
        program, feeds, fetches = fluid.io.load_inference_model(model_dir, exe)
        (probs,) = exe.run(program, feed={feeds[0]: images},
                           fetch_list=fetches)
    return np.asarray(probs)


def main(verbose=True):
    with tempfile.TemporaryDirectory() as d:
        model_dir = os.path.join(d, "resnet20")
        protos = export(model_dir, verbose=verbose)
        probs = infer(model_dir, protos)  # the 10 class prototypes
        top1 = probs.argmax(1)
        if verbose:
            print("prototype top-1:", top1.tolist())
        return probs


if __name__ == "__main__":
    main()
