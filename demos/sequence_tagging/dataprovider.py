"""PyDataProvider2 for the sequence-tagging demo (reference:
v1_api_demo/sequence_tagging/dataprovider.py — CoNLL-format
word/tag sequences; synthetic tag-from-word-bucket corpus here)."""

import numpy as np

from paddle_tpu.trainer.PyDataProvider2 import (integer_value_sequence,
                                                provider)

VOCAB = 20
NUM_TAGS = 4


@provider(input_types={"word": integer_value_sequence(VOCAB),
                       "tag": integer_value_sequence(NUM_TAGS)})
def process(settings, filename):
    rng = np.random.RandomState(11)
    n = int(filename) if filename and str(filename).isdigit() else 512
    for _ in range(n):
        T = int(rng.randint(5, 12))
        words = rng.randint(0, VOCAB, T)
        tags = (words // 5).astype(np.int64)  # tag = word bucket
        yield {"word": words.tolist(), "tag": tags.tolist()}
