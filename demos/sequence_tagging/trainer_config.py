"""v1 sequence-tagging config with a CRF cost (reference:
v1_api_demo/sequence_tagging/linear_crf.py — data_layer → embedding →
mixed/fc emission → crf_layer)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

define_py_data_sources2(
    train_list="512", test_list="128",
    module="demos.sequence_tagging.dataprovider", obj="process")

settings(batch_size=32, learning_rate=0.05,
         learning_method=AdamOptimizer())

NUM_TAGS = 4
VOCAB = 20

word = data_layer(name="word", size=VOCAB)
emb = embedding_layer(input=word, size=16)
emission = fc_layer(input=emb, size=NUM_TAGS, act=LinearActivation())

tag = data_layer(name="tag", size=NUM_TAGS)
crf = crf_layer(input=emission, label=tag,
                param_attr=ParamAttr(name="crf_transition"))

outputs(crf)
