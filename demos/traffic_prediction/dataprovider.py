"""Data provider for traffic prediction (reference:
v1_api_demo/traffic_prediction/dataprovider.py): sliding windows over a
periodic-with-noise sensor series; predict the next reading."""

import numpy as np

from paddle_tpu.trainer.PyDataProvider2 import dense_vector, provider

HIST = 12


@provider(input_types={"series": dense_vector(HIST),
                       "next": dense_vector(1)})
def process(settings, filename):
    rng = np.random.RandomState(13)
    n = int(filename) if filename and str(filename).isdigit() else 512
    t0 = rng.rand(n) * 100
    for i in range(n):
        t = t0[i] + np.arange(HIST + 1)
        # daily + weekly periodicity, like road-sensor flow curves
        y = (np.sin(2 * np.pi * t / 24) + 0.3 * np.sin(2 * np.pi * t / 168)
             + 0.05 * rng.randn(HIST + 1)).astype(np.float32)
        yield {"series": y[:HIST].tolist(), "next": [float(y[HIST])]}
