"""v1 traffic-prediction config (reference:
v1_api_demo/traffic_prediction/trainer_config.py — embedding + GRU/LSTM
sequence regression over road-sensor time series)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

define_py_data_sources2(
    train_list="512", test_list="128",
    module="demos.traffic_prediction.dataprovider", obj="process")

settings(batch_size=32, learning_rate=1e-3,
         learning_method=AdamOptimizer())

HIST = 12  # past readings per sample

series = data_layer(name="series", size=HIST)
h1 = fc_layer(input=series, size=32, act=TanhActivation())
h2 = fc_layer(input=h1, size=16, act=TanhActivation())
pred = fc_layer(input=h2, size=1, act=LinearActivation())

nxt = data_layer(name="next", size=1)
cost = regression_cost(input=pred, label=nxt)

outputs(cost)
