"""GAN demo (reference: v1_api_demo/gan/gan_conf.py + gan_trainer.py —
generator/discriminator configs trained alternately on a 2-D synthetic
distribution).

TPU-native formulation: one program holds G and D; the two optimizers
restrict their updates via ``parameter_list`` (the fluid analog of the
reference's two separate trainer configs), and the whole alternating
step stays compiled — no per-step graph rebuilds.

Run: python -m demos.gan.train [steps]
"""

import numpy as np

import paddle_tpu as fluid


def generator(z, name="g"):
    h = fluid.layers.fc(input=z, size=32, act="relu",
                        param_attr=fluid.ParamAttr(name=f"{name}_w1"),
                        bias_attr=fluid.ParamAttr(name=f"{name}_b1"))
    return fluid.layers.fc(input=h, size=2,
                           param_attr=fluid.ParamAttr(name=f"{name}_w2"),
                           bias_attr=fluid.ParamAttr(name=f"{name}_b2"))


def discriminator(x, name="d"):
    h = fluid.layers.fc(input=x, size=32, act="relu",
                        param_attr=fluid.ParamAttr(name=f"{name}_w1"),
                        bias_attr=fluid.ParamAttr(name=f"{name}_b1"))
    return fluid.layers.fc(input=h, size=1,
                           param_attr=fluid.ParamAttr(name=f"{name}_w2"),
                           bias_attr=fluid.ParamAttr(name=f"{name}_b2"))


def build(batch=64, zdim=8):
    z = fluid.layers.data(name="z", shape=[zdim], dtype="float32")
    real = fluid.layers.data(name="real", shape=[2], dtype="float32")
    fake = generator(z)
    d_real = discriminator(real)
    d_fake = discriminator(fake)  # shared d_* params

    ones = fluid.layers.fill_constant([batch, 1], "float32", 1.0)
    zeros = fluid.layers.fill_constant([batch, 1], "float32", 0.0)
    bce = fluid.layers.sigmoid_cross_entropy_with_logits
    d_loss = fluid.layers.elementwise_add(
        fluid.layers.mean(bce(d_real, ones)),
        fluid.layers.mean(bce(d_fake, zeros)))
    g_loss = fluid.layers.mean(bce(d_fake, ones))

    d_params = [p.name for p in fluid.default_main_program().all_parameters()
                if p.name.startswith("d_")]
    g_params = [p.name for p in fluid.default_main_program().all_parameters()
                if p.name.startswith("g_")]
    # BOTH backward passes are appended before EITHER update so the G
    # gradient flows through the same D weights the forward pass used
    # (minimize() would interleave D's update before G's backward)
    d_pg = fluid.backward.append_backward(d_loss, parameter_list=d_params)
    g_pg = fluid.backward.append_backward(g_loss, parameter_list=g_params)
    opt_d = fluid.optimizer.Adam(learning_rate=2e-3)
    opt_g = fluid.optimizer.Adam(learning_rate=1e-3)
    opt_d._create_optimization_pass(d_pg, d_loss)
    opt_g._create_optimization_pass(g_pg, g_loss)
    return z.name, real.name, fake, d_loss, g_loss


def real_batch(rng, n):
    """Target distribution: ring of 4 Gaussians (gan_conf's 2-D toy)."""
    centers = np.array([[2, 0], [-2, 0], [0, 2], [0, -2]], np.float32)
    c = centers[rng.randint(0, 4, n)]
    return (c + 0.1 * rng.randn(n, 2)).astype(np.float32)


def main(steps=400, batch=64, zdim=8, seed=0, verbose=True):
    fluid.framework.reset_default_programs()
    rng = np.random.RandomState(seed)
    zname, rname, fake, d_loss, g_loss = build(batch, zdim)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    dl = gl = None
    for step in range(steps):
        feed = {zname: rng.randn(batch, zdim).astype(np.float32),
                rname: real_batch(rng, batch)}
        dl, gl = exe.run(feed=feed, fetch_list=[d_loss, g_loss])
        if verbose and step % 100 == 0:
            print(f"step {step}: d_loss={float(dl):.4f} g_loss={float(gl):.4f}")
    # sample G on a test-mode clone (keeps batch-size-bound fills happy
    # and, crucially, doesn't keep training)
    test_prog = fluid.default_main_program().clone(for_test=True)
    chunks = []
    for _ in range(4):
        s, = exe.run(test_prog,
                     feed={zname: rng.randn(batch, zdim).astype(np.float32),
                           rname: real_batch(rng, batch)},
                     fetch_list=[fake])
        chunks.append(np.asarray(s))
    return float(dl), float(gl), np.concatenate(chunks, 0)


if __name__ == "__main__":
    import sys

    main(int(sys.argv[1]) if len(sys.argv) > 1 else 400)
