"""v1 MNIST LeNet-ish config (reference: v1_api_demo/mnist/
light_mnist.py / api_train.py:57)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

define_py_data_sources2(
    train_list="512", test_list="128",
    module="demos.mnist_v1.mnist_provider", obj="process")

settings(batch_size=64, learning_rate=0.01,
         learning_method=MomentumOptimizer(momentum=0.9))

img = data_layer(name="pixel", size=784)

conv1 = simple_img_conv_pool(input=img, filter_size=5, num_filters=8,
                             num_channel=1, pool_size=2, pool_stride=2,
                             act=ReluActivation())
conv2 = simple_img_conv_pool(input=conv1, filter_size=5, num_filters=16,
                             pool_size=2, pool_stride=2,
                             act=ReluActivation())
fc1 = fc_layer(input=conv2, size=64, act=TanhActivation())
predict = fc_layer(input=fc1, size=10, act=SoftmaxActivation())

label = data_layer(name="label", size=10)
cost = classification_cost(input=predict, label=label)

outputs(cost)
