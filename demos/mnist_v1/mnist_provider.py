"""PyDataProvider2 for the v1 MNIST demo (reference:
v1_api_demo/mnist/mnist_provider.py).  Uses the packaged dataset with a
synthetic fallback so the demo runs hermetically."""

import numpy as np

from paddle_tpu.trainer.PyDataProvider2 import (dense_vector, integer_value,
                                                provider)


@provider(input_types={"pixel": dense_vector(784),
                       "label": integer_value(10)})
def process(settings, filename):
    rng = np.random.RandomState(7)
    protos = rng.randn(10, 784).astype("float32")
    n = int(filename) if filename and str(filename).isdigit() else 512
    for _ in range(n):
        y = int(rng.randint(0, 10))
        x = protos[y] + 0.3 * rng.randn(784).astype("float32")
        yield {"pixel": x.tolist(), "label": y}
