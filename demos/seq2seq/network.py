"""Shared seq2seq network pieces (reference: demo/seqToseq/
seqToseq_net.py, imported by both the train and gen configs so the
parameter names line up by construction)."""

from paddle_tpu.trainer_config_helpers import (LinearActivation, ParamAttr,
                                               SoftmaxActivation,
                                               TanhActivation,
                                               embedding_layer, fc_layer,
                                               grumemory, memory)
from paddle_tpu.trainer_config_helpers.networks import simple_attention

VOCAB = 16
EMB, HID = 24, 32
BOS, EOS = 0, 1


def encoder(src):
    """embedding -> 3H projection -> GRU; the states carry position,
    which the attention needs to track alignment."""
    src_emb = embedding_layer(input=src, size=EMB,
                              param_attr=ParamAttr(name="src_emb"))
    enc_proj = fc_layer(input=src_emb, size=3 * HID,
                        act=LinearActivation(),
                        param_attr=ParamAttr(name="enc_w"),
                        bias_attr=False, name="enc_proj")
    return grumemory(input=enc_proj, size=HID, name="enc_seq",
                     param_attr=ParamAttr(name="enc_gru_w"),
                     bias_attr=ParamAttr(name="enc_gru_b"))


def decoder_step(word_emb, enc_seq):
    """One decoder step: additive attention over the encoder states +
    a recurrent fc cell + softmax over the vocab.  Used for teacher-
    forced training (recurrent_group) AND beam-search generation."""
    dec_mem = memory(name="dec_h", size=HID)
    ctx = simple_attention(encoded_sequence=enc_seq, encoded_proj=enc_seq,
                           decoder_state=dec_mem, name="attn",
                           softmax_param_attr=ParamAttr(name="attn_w"))
    h = fc_layer(input=[word_emb, ctx, dec_mem], size=HID,
                 act=TanhActivation(), name="dec_h",
                 param_attr=[ParamAttr(name="dec_w_in"),
                             ParamAttr(name="dec_w_ctx"),
                             ParamAttr(name="dec_w_rec")],
                 bias_attr=False)
    return fc_layer(input=h, size=VOCAB, act=SoftmaxActivation(),
                    name="dec_out", param_attr=ParamAttr(name="dec_w_out"),
                    bias_attr=False)
