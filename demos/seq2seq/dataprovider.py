"""PyDataProvider2 for the seq2seq NMT demo (reference:
demo/seqToseq/dataprovider.py — parallel src/trg token sequences;
synthetic reverse-and-shift 'translation' corpus here so the demo
trains offline in seconds)."""

import numpy as np

from paddle_tpu.trainer.PyDataProvider2 import (integer_value_sequence,
                                                provider)

VOCAB = 16
BOS, EOS = 0, 1


@provider(input_types={"src": integer_value_sequence(VOCAB),
                       "trg_in": integer_value_sequence(VOCAB),
                       "trg_out": integer_value_sequence(VOCAB)})
def process(settings, filename):
    rng = np.random.RandomState(13)
    n = int(filename) if filename and str(filename).isdigit() else 512
    for _ in range(n):
        T = int(rng.randint(3, 7))
        src = rng.randint(2, VOCAB, T)
        # the 'translation': shift each token by one inside the
        # non-special vocab (monotonic alignment, so the attention has
        # a clean signal to learn), then close with EOS
        trg = ((src - 2 + 1) % (VOCAB - 2)) + 2
        trg = np.concatenate([trg, [EOS]])
        trg_in = np.concatenate([[BOS], trg[:-1]])
        yield {"src": src.tolist(), "trg_in": trg_in.tolist(),
               "trg_out": trg.tolist()}
