"""v1 seq2seq NMT config with additive attention (reference:
demo/seqToseq/seqToseq_net.py — GRU encoder, recurrent_group decoder
whose step runs simple_attention over the encoded states;
BASELINE.json acceptance config #3).

The same decoder step (demos/seq2seq/network.py) drives beam-search
generation — tests/test_demos.py::test_seq2seq_demo_trains_and_generates
reuses it with GeneratedInput + SequenceGenerator, the reference
gen.conf workflow (RecurrentGradientMachine.cpp:964)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

from demos.seq2seq.network import EMB, VOCAB, decoder_step, encoder

define_py_data_sources2(
    train_list="512", test_list="96",
    module="demos.seq2seq.dataprovider", obj="process")

settings(batch_size=16, learning_rate=0.01,
         learning_method=AdamOptimizer())

src = data_layer(name="src", size=VOCAB)
enc = encoder(src)

trg_in = data_layer(name="trg_in", size=VOCAB)
trg_out = data_layer(name="trg_out", size=VOCAB)
trg_emb = embedding_layer(input=trg_in, size=EMB,
                          param_attr=ParamAttr(name="trg_emb"))

probs = recurrent_group(step=decoder_step,
                        input=[trg_emb, StaticInput(enc, is_seq=True,
                                                    size=32)])
cost = classification_cost(input=probs, label=trg_out)
outputs(cost)
