"""Generation-serving config for the NMT demo (reference: the
demo/seqToseq gen.conf half of the train/gen config pair).

``paddle serve --gen_config=demos/seq2seq/gen_config.py`` exec's this
file and calls ``make_generator()`` for the ``(beam_gen, parameters)``
pair behind ``POST /generate``.  Parameters come from a trained
Parameters tar when ``PADDLE_GEN_PARAMS`` names one (written with
``parameters.to_tar``); otherwise the demo trains a few quick passes
in-process first — fine for the 16-token toy vocabulary, stand-in for
loading a real checkpoint.
"""

import os


def make_beam_gen(beam_size: int = 4, max_length: int = 9):
    """The demo's generation spec — the single builder the serving
    config, the decode benchmark, and the parity tests all share, so
    the oracle relationship can never drift between copies."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import (GeneratedInput,
                                                   StaticInput,
                                                   beam_search, data_layer)

    from demos.seq2seq.network import (BOS, EMB, EOS, HID, VOCAB,
                                       decoder_step, encoder)

    src = data_layer(name="src", size=VOCAB)
    src.input_type = paddle.data_type.integer_value_sequence(VOCAB)
    enc = encoder(src)
    return beam_search(
        step=decoder_step,
        input=[GeneratedInput(size=VOCAB, embedding_name="trg_emb",
                              embedding_size=EMB),
               StaticInput(enc, is_seq=True, size=HID)],
        bos_id=BOS, eos_id=EOS, beam_size=beam_size,
        max_length=max_length)


def make_generator():
    beam_gen = make_beam_gen(
        max_length=int(os.environ.get("PADDLE_GEN_MAXLEN", "9")))

    params_tar = os.environ.get("PADDLE_GEN_PARAMS")
    if params_tar:
        from paddle_tpu.executor import Scope

        class _Params:
            scope = Scope()

        parameters = _Params()
        import io as _io
        import tarfile
        import numpy as np

        with tarfile.open(params_tar) as tar:
            for m in tar.getmembers():
                name = m.name[:-4] if m.name.endswith(".npy") else m.name
                parameters.scope.set(name, np.load(
                    _io.BytesIO(tar.extractfile(m).read()),
                    allow_pickle=False))
    else:
        from paddle_tpu.trainer import train_from_config

        passes = int(os.environ.get("PADDLE_GEN_TRAIN_PASSES", "8"))
        tc, _ = train_from_config("demos/seq2seq/trainer_config.py",
                                  num_passes=passes, log_period=10 ** 9)
        parameters = tc.parameters
    return beam_gen, parameters
