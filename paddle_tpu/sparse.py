"""SelectedRows, TPU-style: static-shape sparse row gradients.

The reference carries embedding/sparse gradients as ``SelectedRows`` —
a dynamically sized (rows, value) pair over a notional dense height
(reference: paddle/framework/selected_rows.h:19, design
paddle/framework/selected_rows.md) — produced by ``lookup_table_grad``
(reference: paddle/operators/lookup_table_op.cc) and consumed row-wise
by the sparse branches of ``sgd``/``adagrad`` (reference:
paddle/operators/sgd_op.cc, adagrad_op.cc) and by the legacy
``SparseRowMatrix`` lazy-update machinery (reference:
paddle/math/SparseRowMatrix.h, parameter/FirstOrderOptimizer.h).

A static-shape compiler wants fixed buffer sizes, so the TPU encoding
is: ``rows`` is the *un-deduplicated* int32 id vector of length N
(N = number of lookups in the batch — static under jit) and ``values``
is the matching (N, D) cotangent rows.  Duplicate row merging
(``SelectedRows`` "merge_dup_rows") is done inside the consumer with
``jnp.unique(size=N)`` + ``segment_sum`` — fully jittable, no dense
(height, D) gradient ever materialises, and optimizer updates touch
only the N looked-up rows of the (height, D) parameter via XLA
scatter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SparseGrad:
    """Static-shape SelectedRows gradient: ``rows`` (N,) int32 indices
    into a dense (height, D) tensor, ``values`` (N, D) rows.  Rows may
    repeat; semantically the gradient is the scatter-add of ``values``
    at ``rows``.  ``height`` is static metadata (the dense row count)."""

    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, aux, children):
        rows, values = children
        return cls(rows, values, aux)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self):
        """Densify: scatter-add values at rows (duplicates accumulate)."""
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values, mode="drop")

    def merged(self):
        """Deduplicate rows (SelectedRows ``merge_dup_rows`` analog).

        Returns ``(urows, uvalues)`` of the same static length N; slots
        beyond the number of distinct rows are filled with the
        out-of-bounds index ``height`` so downstream ``.at[...]`` with
        ``mode='drop'`` ignores them.
        """
        n = self.rows.shape[0]
        urows, inv = jnp.unique(
            self.rows, size=n, fill_value=self.height, return_inverse=True
        )
        uvalues = jax.ops.segment_sum(self.values, inv.reshape(-1), num_segments=n)
        return urows, uvalues

    def __repr__(self):
        return f"SparseGrad(rows={self.rows.shape}, values={self.values.shape}, height={self.height})"


def is_sparse_grad(x) -> bool:
    return isinstance(x, SparseGrad)


def concat_sparse(grads) -> SparseGrad:
    """Sum of SelectedRows = row-wise concatenation (reference:
    operators/sum_op.h SelectedRows branch)."""
    height = grads[0].height
    rows = jnp.concatenate([g.rows for g in grads])
    values = jnp.concatenate([g.values for g in grads])
    return SparseGrad(rows, values, height)


def rowwise_update(param, sparse_grad: SparseGrad, update_rows, *states):
    """Apply ``update_rows(p_rows, g_rows, *state_rows) -> (p_rows_new,
    *state_rows_new)`` to the distinct touched rows only.

    ``states`` are dense (height, ...) optimizer-state tensors updated
    row-wise alongside the parameter (the legacy rowwise "lazy
    catch-up" — reference: parameter/FirstOrderOptimizer.h sparse
    variants — collapses to this under a compiled step, since rows are
    updated exactly when touched).

    Returns ``(param_new, *states_new)``.
    """
    urows, uvalues = sparse_grad.merged()
    safe = jnp.minimum(urows, sparse_grad.height - 1)
    p_rows = param[safe]
    state_rows = [s[safe] for s in states]
    # Gradients stay at their native (float32 cotangent) dtype so the
    # optimizer's float32 math matches the dense branch bit-for-bit
    # even when the parameter itself is bf16.
    out = update_rows(p_rows, uvalues, *state_rows)
    if not isinstance(out, tuple):
        out = (out,)
    p_new = param.at[urows].set(out[0].astype(param.dtype), mode="drop")
    states_new = [
        s.at[urows].set(o.astype(s.dtype), mode="drop")
        for s, o in zip(states, out[1:])
    ]
    return (p_new, *states_new)
