"""Static program analysis: verifier + lint passes over the Program IR.

The reference pushed every ProgramDesc through C++-side validation
(InferShape / OpDesc checks) before execution; this package is the
Python-IR equivalent for the TPU rebuild — a pass manager running
def-before-use, dtype, fetch-reachability, gradient-pairing, and
liveness checks over a ``Program`` *before* it burns an XLA compile.

Entry points:

- ``verify_program(program, feed_names, fetch_names, level)`` — run the
  passes, get structured ``Diagnostic`` records.
- ``check_or_raise(...)`` — the error-tier gate ``Executor.run`` uses
  when the ``check_program`` flag is on.
- ``audit_registry()`` — op-metadata coverage ratchet against the
  checked-in ``registry_baseline.json``.
- ``paddle lint <program.json|config.py>`` — the CLI front end.
"""

from paddle_tpu.analysis.verify import (  # noqa: F401
    Diagnostic,
    PassContext,
    PassInfo,
    PassManager,
    ProgramVerificationError,
    Severity,
    check_or_raise,
    default_pass_manager,
    format_report,
    register_pass,
    verify_program,
)
from paddle_tpu.analysis import dataflow  # noqa: F401
from paddle_tpu.analysis import passes  # noqa: F401  (registers passes)
from paddle_tpu.analysis.optimize import (  # noqa: F401
    DonationEntry,
    OptReport,
    backward_slice,
    check_parity,
    donation_mask,
    optimize_program,
)
from paddle_tpu.analysis.registry_audit import (  # noqa: F401
    audit_registry,
    current_gaps,
    load_baseline,
    write_baseline,
)
