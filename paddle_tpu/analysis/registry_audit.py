"""Registry coverage audit: op metadata can only ratchet up.

Every registered op should carry an ``infer_shape`` rule (the static
verifier's shape/dtype propagation driver) and declare its input slots.
Legacy ops that predate the verifier are grandfathered in a checked-in
baseline (``registry_baseline.json``); the audit errors on any op that
is missing coverage AND absent from the baseline, so new ops must ship
with metadata and the baseline can only shrink.

Regenerate the baseline (after adding coverage) with::

    python -m paddle_tpu.analysis.registry_audit --write-baseline
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from paddle_tpu.registry import OpRegistry
from paddle_tpu.analysis.verify import Diagnostic, Severity

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "registry_baseline.json")

# Keys in the baseline file, paired with the audit predicate they gate.
_CHECKS = (
    ("missing_infer_shape", "PVA01",
     lambda info: info.infer_shape is None,
     "has no infer_shape rule"),
    ("missing_input_slots", "PVA02",
     lambda info: not info.input_slots,
     "declares no input slots"),
)


def current_gaps() -> Dict[str, List[str]]:
    """Ops currently missing each kind of metadata (sorted).

    ``<base>_grad`` entries synthesized on demand from a registered
    forward (autodiff.synthesize_grad_info caches them into the
    registry) are skipped: their metadata is derived from the forward's
    vjp, and auditing them would make results depend on which grad ops
    some earlier program happened to exercise.
    """
    gaps: Dict[str, List[str]] = {key: [] for key, *_ in _CHECKS}
    for name in OpRegistry.all_ops():
        if name.endswith("_grad") and OpRegistry.has(name[: -len("_grad")]):
            continue
        info = OpRegistry.get(name)
        for key, _code, predicate, _msg in _CHECKS:
            if predicate(info):
                gaps[key].append(name)
    return gaps


def load_baseline(path: Optional[str] = None) -> Dict[str, List[str]]:
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {key: [] for key, *_ in _CHECKS}
    with open(path) as f:
        data = json.load(f)
    return {key: list(data.get(key, [])) for key, *_ in _CHECKS}


def write_baseline(path: Optional[str] = None) -> Dict[str, List[str]]:
    """Snapshot the current gaps as the new allowlist."""
    gaps = current_gaps()
    with open(path or BASELINE_PATH, "w") as f:
        json.dump(gaps, f, indent=1, sort_keys=True)
        f.write("\n")
    return gaps


def audit_registry(baseline: Optional[Dict[str, List[str]]] = None
                   ) -> List[Diagnostic]:
    """Compare current registry coverage against the baseline.

    Errors (PVA01/PVA02): an op is missing metadata and is NOT
    grandfathered — coverage regressed (or a new op shipped without
    metadata).  Info (PVA03): a baseline entry is stale (the op gained
    coverage or was unregistered) — shrink the baseline to lock in the
    gain.
    """
    baseline = load_baseline() if baseline is None else baseline
    gaps = current_gaps()
    diags: List[Diagnostic] = []
    for key, code, _predicate, msg in _CHECKS:
        allowed = set(baseline.get(key, ()))
        for name in gaps[key]:
            if name not in allowed:
                diags.append(Diagnostic(
                    code=code, severity=Severity.ERROR,
                    message=f"op {name!r} {msg} and is not in the "
                            f"{key} baseline",
                    var=name, pass_name="registry-audit",
                    hint="add the metadata to the registration (preferred) "
                         "or regenerate registry_baseline.json"))
        for name in sorted(allowed - set(gaps[key])):
            diags.append(Diagnostic(
                code="PVA03", severity=Severity.INFO,
                message=f"baseline entry {name!r} under {key} is stale "
                        "(op now covered or no longer registered)",
                var=name, pass_name="registry-audit",
                hint="re-run --write-baseline to ratchet coverage"))
    return diags


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--write-baseline", action="store_true",
                        help="snapshot current gaps as the new allowlist")
    args = parser.parse_args(argv)
    import paddle_tpu  # noqa: F401  (registers the op library)

    if args.write_baseline:
        gaps = write_baseline()
        total = sum(len(v) for v in gaps.values())
        print(f"baseline written: {BASELINE_PATH} ({total} entries)")
        return 0
    diags = audit_registry()
    for d in diags:
        print(d.format())
    errs = [d for d in diags if d.severity == Severity.ERROR]
    print(f"registry audit: {len(errs)} regression(s), "
          f"{len(diags) - len(errs)} note(s)")
    return 1 if errs else 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
