"""Program verifier core: Diagnostic records + the pass manager.

The reference ran C++-side validation (InferShape, op checks in
framework/op_registry.h) on every ProgramDesc before the executor saw
it; this module is the Python-IR equivalent.  Passes (analysis/passes.py)
run static checks over a ``Program`` and emit structured ``Diagnostic``
records; ``check_or_raise`` is the error-tier gate the Executor runs
before compiling when the ``check_program`` flag is on, so a malformed
program fails with "op 3 in block 0 reads 'x' before any write" instead
of a KeyError deep inside jax.jit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Set

from paddle_tpu import errors
from paddle_tpu.framework import Program


class Severity:
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    _RANK = {ERROR: 0, WARNING: 1, INFO: 2}

    @classmethod
    def includes(cls, level: str, severity: str) -> bool:
        """True when ``severity`` is at or above the requested level
        (level 'warning' includes errors and warnings, not info)."""
        if level == "all":
            level = cls.INFO
        return cls._RANK[severity] <= cls._RANK[level]


@dataclasses.dataclass
class Diagnostic:
    """One structured finding: stable check id, location, fix hint."""

    code: str                       # stable check id, e.g. "PVE01"
    severity: str                   # Severity.ERROR / WARNING / INFO
    message: str
    block_idx: int = 0
    op_idx: Optional[int] = None    # index within the block, if op-anchored
    op_type: Optional[str] = None
    var: Optional[str] = None       # variable the finding is about
    hint: Optional[str] = None      # actionable fix suggestion
    pass_name: str = ""

    def format(self) -> str:
        loc = f"block {self.block_idx}"
        if self.op_idx is not None:
            loc += f" op {self.op_idx}"
        if self.op_type:
            loc += f" ({self.op_type})"
        line = f"{self.severity} {self.code} [{loc}]: {self.message}"
        if self.hint:
            line += f" (hint: {self.hint})"
        return line

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ProgramVerificationError(errors.PaddleError):
    """Raised by ``check_or_raise`` when error-tier diagnostics fire."""

    def __init__(self, diagnostics: Sequence[Diagnostic],
                 header: str = "program verification failed"):
        self.diagnostics = list(diagnostics)
        lines = [header] + ["  " + d.format() for d in self.diagnostics]
        super().__init__("\n".join(lines))


class PassContext:
    """State shared across passes for one verification run.

    ``feeds`` / ``fetches`` are None when unknown (lint mode): passes
    then treat declared producer-less vars as the feedable input surface
    and skip fetch-dependent checks.
    """

    def __init__(self, program: Program,
                 feeds: Optional[Set[str]] = None,
                 fetches: Optional[Sequence[str]] = None):
        self.program = program
        self.feeds = set(feeds) if feeds is not None else None
        self.fetches = list(fetches) if fetches is not None else None
        self.diagnostics: List[Diagnostic] = []
        self._implicit_feeds: Optional[Set[str]] = None
        self._writes: Optional[Set[str]] = None

    @property
    def implicit_feeds(self) -> Set[str]:
        if self._implicit_feeds is None:
            from paddle_tpu.analysis import dataflow

            self._implicit_feeds = dataflow.implicit_feed_vars(self.program)
        return self._implicit_feeds

    @property
    def all_writes(self) -> Set[str]:
        if self._writes is None:
            from paddle_tpu.analysis import dataflow

            self._writes = dataflow.program_writes(self.program)
        return self._writes

    def feed_surface(self) -> Set[str]:
        """The names a run may supply from outside: the explicit feed
        set when known, else every declared producer-less var.  Feeding
        a sequence input also supplies its ``<name>@len`` length vector
        (v2/data_feeder.py convention), so declared @len companions of
        fed names count as fed."""
        if self.feeds is None:
            return self.implicit_feeds
        surface = set(self.feeds)
        for name in self.feeds:
            companion = name + "@len"
            if companion in self.implicit_feeds:
                surface.add(companion)
        return surface

    def emit(self, code: str, severity: str, message: str, *,
             block_idx: int = 0, op_idx: Optional[int] = None,
             op_type: Optional[str] = None, var: Optional[str] = None,
             hint: Optional[str] = None, pass_name: str = "") -> Diagnostic:
        d = Diagnostic(code=code, severity=severity, message=message,
                       block_idx=block_idx, op_idx=op_idx, op_type=op_type,
                       var=var, hint=hint, pass_name=pass_name)
        self.diagnostics.append(d)
        return d


@dataclasses.dataclass
class PassInfo:
    name: str
    tier: str                        # most severe diagnostic it can emit
    fn: Callable[[PassContext], None]
    doc: str = ""


class PassManager:
    """Ordered pass pipeline filtered by severity tier."""

    def __init__(self, passes: Optional[Sequence[PassInfo]] = None):
        self.passes: List[PassInfo] = list(passes or [])

    def register(self, info: PassInfo):
        if any(p.name == info.name for p in self.passes):
            raise ValueError(f"analysis pass {info.name!r} already registered")
        self.passes.append(info)

    def run(self, program: Program, feeds: Optional[Set[str]] = None,
            fetches: Optional[Sequence[str]] = None,
            level: str = Severity.WARNING,
            only: Optional[Sequence[str]] = None) -> List[Diagnostic]:
        ctx = PassContext(program, feeds=feeds, fetches=fetches)
        for info in self.passes:
            if only is not None and info.name not in only:
                continue
            if only is None and not Severity.includes(level, info.tier):
                continue
            before = len(ctx.diagnostics)
            info.fn(ctx)
            for d in ctx.diagnostics[before:]:
                if not d.pass_name:
                    d.pass_name = info.name
        if only is not None:
            return ctx.diagnostics
        return [d for d in ctx.diagnostics
                if Severity.includes(level, d.severity)]


_default_manager = PassManager()


def register_pass(name: str, tier: str = Severity.ERROR):
    """Decorator registering an analysis pass on the default manager."""

    def deco(fn):
        _default_manager.register(
            PassInfo(name=name, tier=tier, fn=fn, doc=fn.__doc__ or ""))
        return fn

    return deco


def default_pass_manager() -> PassManager:
    from paddle_tpu.analysis import passes  # noqa: F401  (registers passes)

    return _default_manager


def verify_program(program: Program,
                   feed_names: Optional[Set[str]] = None,
                   fetch_names: Optional[Sequence[str]] = None,
                   level: str = Severity.WARNING,
                   only: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Run the static checks; returns diagnostics at/above ``level``.

    ``feed_names=None`` means "unknown" — declared producer-less vars
    count as feedable; pass the actual feed set for strict checking.
    """
    return default_pass_manager().run(
        program, feeds=feed_names, fetches=fetch_names, level=level,
        only=only)


def check_or_raise(program: Program,
                   feed_names: Optional[Set[str]] = None,
                   fetch_names: Optional[Sequence[str]] = None,
                   header: str = "program verification failed"):
    """Error-tier gate: raise ProgramVerificationError on any error."""
    diags = verify_program(program, feed_names=feed_names,
                           fetch_names=fetch_names, level=Severity.ERROR)
    errs = [d for d in diags if d.severity == Severity.ERROR]
    if errs:
        raise ProgramVerificationError(errs, header=header)


def format_report(diagnostics: Sequence[Diagnostic]) -> str:
    """Human-readable multi-line report, most severe first."""
    order = {Severity.ERROR: 0, Severity.WARNING: 1, Severity.INFO: 2}
    by_sev = sorted(diagnostics, key=lambda d: (order[d.severity], d.code))
    counts: Dict[str, int] = {}
    for d in diagnostics:
        counts[d.severity] = counts.get(d.severity, 0) + 1
    lines = [d.format() for d in by_sev]
    summary = ", ".join(f"{counts.get(s, 0)} {s}(s)"
                        for s in (Severity.ERROR, Severity.WARNING,
                                  Severity.INFO) if counts.get(s))
    lines.append(summary or "clean: no diagnostics")
    return "\n".join(lines)
