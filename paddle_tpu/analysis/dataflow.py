"""Def-use and block-walking helpers for the program verifier.

The reference validated programs op-by-op in C++ at desc-build time
(framework/op_desc.cc InferShape, op_registry.h checks); here the whole
Program is data, so the analysis layer walks it like a compiler IR:
per-block def-use chains, recursive descent into Block-valued attrs
(while / recurrent / conditional_block sub-blocks), and liveness from
the fetch set.  Everything in this module is read-only over the IR.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Set, Tuple

from paddle_tpu.framework import Block, Operator, Program

# Executor pseudo-ops: present in pruned/serialized programs, skipped by
# the compiling executor (executor.py _compile).
PSEUDO_OPS = frozenset({"feed", "fetch"})

# Ops whose observable effect is not (only) a dataflow output —
# liveness must keep them even when nothing reads their outputs.
# Includes the distributed RPC pair (send ships a gradient to a
# parameter server, recv pulls the fresh value back — both fire a wire
# round-trip whether or not anything reads Out) and the
# checkpoint-writing ops (save persists scope state to disk): pruning
# any of these would silently drop a distributed update or a
# checkpoint commit.
SIDE_EFFECT_OPS = frozenset(
    {"print", "save", "grad_printer", "seq_text_printer",
     "send", "recv", "ncclInit"}
)


def op_has_side_effects(op: Operator) -> bool:
    """Conservative side-effect test for elimination decisions: named
    side-effect ops, plus any op that declares NO outputs at all — an
    op with nothing to write can only exist for its effect (send, save,
    ncclInit all match), so an unknown output-less op is never safe to
    prune."""
    if op.type in SIDE_EFFECT_OPS:
        return True
    return not any(n for ns in op.outputs.values() for n in ns)

# conditional_block's false branch passes through the outputs' prior
# values (ops/control_flow_ops.py _conditional_block reads outer[n] for
# every Out), so its outputs are implicit *reads* as well as writes.
_READS_OWN_OUTPUTS = frozenset({"conditional_block"})


def op_reads(op: Operator) -> List[str]:
    """Non-empty input names, plus op-specific implicit reads."""
    reads = [n for ns in op.inputs.values() for n in ns if n]
    if op.type in _READS_OWN_OUTPUTS:
        reads += [n for n in op.output("Out") if n]
    return reads


def op_writes(op: Operator) -> List[str]:
    return [n for ns in op.outputs.values() for n in ns if n]


def op_sub_blocks(op: Operator) -> List[Tuple[str, Block]]:
    """Block-valued attrs, i.e. the op's control-flow sub-blocks."""
    return [(k, v) for k, v in op.attrs.items() if isinstance(v, Block)]


def sub_block_bound_names(op: Operator) -> Set[str]:
    """Names the op binds in the sub-block scope before running it
    (recurrent injects loop state and per-step input slices; see
    ops/control_flow_ops.py _recurrent)."""
    bound: Set[str] = set()
    for key in ("state_names", "step_input_names"):
        v = op.attr(key)
        if isinstance(v, (list, tuple)):
            bound.update(n for n in v if isinstance(n, str) and n)
    return bound


def block_writes(block: Block, recursive: bool = True,
                 _seen: Optional[Set[int]] = None) -> Set[str]:
    """All names written by the block's ops (optionally including
    nested sub-blocks, whose writes land in the same traced scope)."""
    _seen = set() if _seen is None else _seen
    if id(block) in _seen:
        return set()
    _seen.add(id(block))
    out: Set[str] = set()
    for op in block.ops:
        out.update(op_writes(op))
        if recursive:
            for _, sub in op_sub_blocks(op):
                out |= block_writes(sub, recursive=True, _seen=_seen)
    return out


def program_writes(program: Program) -> Set[str]:
    """Every name any op (in any reachable block) writes."""
    return block_writes(program.global_block(), recursive=True)


def walk_ops(block: Block,
             _seen: Optional[Set[int]] = None
             ) -> Iterator[Tuple[Block, int, Operator]]:
    """Yield (block, op_idx, op) for the block and its sub-blocks."""
    _seen = set() if _seen is None else _seen
    if id(block) in _seen:
        return
    _seen.add(id(block))
    for idx, op in enumerate(block.ops):
        yield block, idx, op
        for _, sub in op_sub_blocks(op):
            yield from walk_ops(sub, _seen)


def implicit_feed_vars(program: Program) -> Set[str]:
    """Declared, non-persistable variables no op ever writes: the
    program's input surface (what ``layers.data`` declares).  Used when
    the caller gives no explicit feed set (lint mode)."""
    written = program_writes(program)
    feeds: Set[str] = set()
    for block in program.blocks:
        for name, var in block.vars.items():
            if not var.persistable and name not in written:
                feeds.add(name)
    return feeds


def declared_dtype(block: Block, name: str) -> Optional[str]:
    var = block.find_var(name)
    return var.dtype if var is not None else None


def dtype_family(dtype: Optional[str]) -> Optional[str]:
    if dtype is None:
        return None
    if dtype == "bool":
        return "bool"
    if dtype.startswith(("float", "bfloat")):
        return "float"
    if dtype.startswith(("int", "uint")):
        return "int"
    return None


def producers(block: Block) -> Dict[str, List[int]]:
    """name -> ordered list of op indices that write it (this block
    only; sub-block writes excluded so WAW stays branch-local)."""
    out: Dict[str, List[int]] = {}
    for idx, op in enumerate(block.ops):
        for n in op_writes(op):
            out.setdefault(n, []).append(idx)
    return out


# ---------------------------------------------------------------------------
# Dataflow engine (liveness / reaching definitions / use-def webs).
#
# The verifier's passes each re-derived ad-hoc slices of this
# information; the optimizer (analysis/optimize.py) needs it as first-
# class data, computed once per program.  Control-flow sub-blocks are
# handled the way the tracing executor actually runs them: a sub-block
# executes *inside* its owning op, reading outer names through the
# traced scope, so at the owning block's level a control-flow op reads
# everything its sub-blocks read from outside and writes its own
# declared outputs.
# ---------------------------------------------------------------------------


def sub_block_external_reads(op: Operator) -> Set[str]:
    """Names an op's sub-blocks read from the enclosing scope: union of
    sub-block op inputs (recursively) minus names produced earlier
    inside the same sub-block (reference: framework/prune.cc:133)."""
    reads: Set[str] = set()
    for _, sub in op_sub_blocks(op):
        produced: Set[str] = set(sub_block_bound_names(op))
        for sub_op in sub.ops:
            reads |= set(n for n in op_reads(sub_op) if n) - produced
            reads |= sub_block_external_reads(sub_op)
            produced |= set(op_writes(sub_op))
    return reads


def effective_reads(op: Operator) -> Set[str]:
    """Everything executing this op consumes from its block's scope:
    its declared inputs plus whatever its control-flow sub-blocks pull
    from outside themselves."""
    reads = set(op_reads(op))
    if any(True for _ in op_sub_blocks(op)):
        reads |= sub_block_external_reads(op)
    return reads


def sub_block_touched(program: Program) -> Set[str]:
    """Every name read OR written by any op inside any control-flow
    sub-block.  A buffer on this list is aliased into a nested traced
    scope — the donation analyzer refuses to donate it."""
    touched: Set[str] = set()
    for block, _idx, op in walk_ops(program.global_block()):
        if block.idx == 0:
            continue
        touched.update(op_reads(op))
        touched.update(op_writes(op))
    return touched


def liveness(block: Block, live_out: Set[str]) -> List[Set[str]]:
    """Backward liveness: ``result[i]`` is the set of names live
    immediately BEFORE op ``i`` runs (standard transfer
    ``live_in = reads ∪ (live_out − writes)``).  Sub-block reads count
    as reads of the owning op; ``live_out`` seeds the exit set
    (fetches + state the caller observes)."""
    live = set(live_out)
    before: List[Set[str]] = [set()] * len(block.ops)
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        live = (live - set(op_writes(op))) | effective_reads(op)
        before[idx] = set(live)
    return before


def reaching_definitions(block: Block,
                         entry: Optional[Set[str]] = None
                         ) -> List[Dict[str, Tuple[int, ...]]]:
    """Forward reaching definitions: ``result[i]`` maps each name to
    the op indices whose writes can reach op ``i``'s reads (index -1 =
    defined at entry: fed / scope state).  Straight-line per block —
    the executor runs a block's op list exactly in order, so gen/kill
    needs no fixpoint here."""
    reaching: Dict[str, Tuple[int, ...]] = {
        n: (-1,) for n in (entry or set())}
    out: List[Dict[str, Tuple[int, ...]]] = []
    for idx, op in enumerate(block.ops):
        out.append(dict(reaching))
        for n in op_writes(op):
            reaching[n] = (idx,)  # a straight-line write kills prior defs
    return out


class UseDefWeb:
    """Whole-program def/use index over every block (sub-blocks
    included): ``defs[name]`` / ``uses[name]`` are ordered lists of
    ``(block_idx, op_idx)`` sites.  Sub-block uses are what make a name
    "aliased into a sub-block" for the donation analyzer."""

    def __init__(self, program: Program):
        self.defs: Dict[str, List[Tuple[int, int]]] = {}
        self.uses: Dict[str, List[Tuple[int, int]]] = {}
        for block, idx, op in walk_ops(program.global_block()):
            site = (block.idx, idx)
            for n in op_writes(op):
                self.defs.setdefault(n, []).append(site)
            for n in op_reads(op):
                self.uses.setdefault(n, []).append(site)

    def single_writer(self, name: str) -> Optional[Tuple[int, int]]:
        sites = self.defs.get(name, [])
        return sites[0] if len(sites) == 1 else None

    def used_in_sub_block(self, name: str) -> bool:
        return any(b != 0 for b, _ in self.uses.get(name, ()))

    def read_after(self, name: str, block_idx: int, op_idx: int) -> bool:
        """Any top-level read of ``name`` strictly after the given
        top-level site (the donation analyzer's later-read test)."""
        return any(b == block_idx and i > op_idx
                   for b, i in self.uses.get(name, ()))
