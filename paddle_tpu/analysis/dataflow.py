"""Def-use and block-walking helpers for the program verifier.

The reference validated programs op-by-op in C++ at desc-build time
(framework/op_desc.cc InferShape, op_registry.h checks); here the whole
Program is data, so the analysis layer walks it like a compiler IR:
per-block def-use chains, recursive descent into Block-valued attrs
(while / recurrent / conditional_block sub-blocks), and liveness from
the fetch set.  Everything in this module is read-only over the IR.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Set, Tuple

from paddle_tpu.framework import Block, Operator, Program

# Executor pseudo-ops: present in pruned/serialized programs, skipped by
# the compiling executor (executor.py _compile).
PSEUDO_OPS = frozenset({"feed", "fetch"})

# Ops whose observable effect is host-side I/O, not a dataflow output —
# liveness must keep them even when nothing reads their outputs.
SIDE_EFFECT_OPS = frozenset(
    {"print", "save", "grad_printer", "seq_text_printer"}
)

# conditional_block's false branch passes through the outputs' prior
# values (ops/control_flow_ops.py _conditional_block reads outer[n] for
# every Out), so its outputs are implicit *reads* as well as writes.
_READS_OWN_OUTPUTS = frozenset({"conditional_block"})


def op_reads(op: Operator) -> List[str]:
    """Non-empty input names, plus op-specific implicit reads."""
    reads = [n for ns in op.inputs.values() for n in ns if n]
    if op.type in _READS_OWN_OUTPUTS:
        reads += [n for n in op.output("Out") if n]
    return reads


def op_writes(op: Operator) -> List[str]:
    return [n for ns in op.outputs.values() for n in ns if n]


def op_sub_blocks(op: Operator) -> List[Tuple[str, Block]]:
    """Block-valued attrs, i.e. the op's control-flow sub-blocks."""
    return [(k, v) for k, v in op.attrs.items() if isinstance(v, Block)]


def sub_block_bound_names(op: Operator) -> Set[str]:
    """Names the op binds in the sub-block scope before running it
    (recurrent injects loop state and per-step input slices; see
    ops/control_flow_ops.py _recurrent)."""
    bound: Set[str] = set()
    for key in ("state_names", "step_input_names"):
        v = op.attr(key)
        if isinstance(v, (list, tuple)):
            bound.update(n for n in v if isinstance(n, str) and n)
    return bound


def block_writes(block: Block, recursive: bool = True,
                 _seen: Optional[Set[int]] = None) -> Set[str]:
    """All names written by the block's ops (optionally including
    nested sub-blocks, whose writes land in the same traced scope)."""
    _seen = set() if _seen is None else _seen
    if id(block) in _seen:
        return set()
    _seen.add(id(block))
    out: Set[str] = set()
    for op in block.ops:
        out.update(op_writes(op))
        if recursive:
            for _, sub in op_sub_blocks(op):
                out |= block_writes(sub, recursive=True, _seen=_seen)
    return out


def program_writes(program: Program) -> Set[str]:
    """Every name any op (in any reachable block) writes."""
    return block_writes(program.global_block(), recursive=True)


def walk_ops(block: Block,
             _seen: Optional[Set[int]] = None
             ) -> Iterator[Tuple[Block, int, Operator]]:
    """Yield (block, op_idx, op) for the block and its sub-blocks."""
    _seen = set() if _seen is None else _seen
    if id(block) in _seen:
        return
    _seen.add(id(block))
    for idx, op in enumerate(block.ops):
        yield block, idx, op
        for _, sub in op_sub_blocks(op):
            yield from walk_ops(sub, _seen)


def implicit_feed_vars(program: Program) -> Set[str]:
    """Declared, non-persistable variables no op ever writes: the
    program's input surface (what ``layers.data`` declares).  Used when
    the caller gives no explicit feed set (lint mode)."""
    written = program_writes(program)
    feeds: Set[str] = set()
    for block in program.blocks:
        for name, var in block.vars.items():
            if not var.persistable and name not in written:
                feeds.add(name)
    return feeds


def declared_dtype(block: Block, name: str) -> Optional[str]:
    var = block.find_var(name)
    return var.dtype if var is not None else None


def dtype_family(dtype: Optional[str]) -> Optional[str]:
    if dtype is None:
        return None
    if dtype == "bool":
        return "bool"
    if dtype.startswith(("float", "bfloat")):
        return "float"
    if dtype.startswith(("int", "uint")):
        return "int"
    return None


def producers(block: Block) -> Dict[str, List[int]]:
    """name -> ordered list of op indices that write it (this block
    only; sub-block writes excluded so WAW stays branch-local)."""
    out: Dict[str, List[int]] = {}
    for idx, op in enumerate(block.ops):
        for n in op_writes(op):
            out.setdefault(n, []).append(idx)
    return out
