"""Whole-program optimizer: parity-gated rewrite passes over Program.

PR 2 built ``paddle_tpu/analysis`` as a read-only verifier; this module
promotes it to an optimizer.  Every pass here *transforms* a Program
using the same dataflow facts the verifier checks (liveness, use-def
webs from ``analysis/dataflow.py``), under a hard safety contract:

- passes run on a clone, never the caller's program;
- the pipeline refuses to optimize a program the verifier already
  rejects (garbage in stays garbage — unoptimized);
- after every pass the error-tier verifier re-runs on the output; any
  new error reverts that pass and records a PVO02 diagnostic;
- the differential harness (``check_parity``, driven by
  tests/test_optimizer.py) executes optimized-vs-original programs and
  demands bit-identical fetches.

Rewrite passes (in pipeline order, iterated to a fixpoint):

  constant-fold   ops whose inputs are all statically-known constants
                  are evaluated eagerly and replaced by a ``fill`` op
                  carrying the computed value (dtype preserved exactly)
  cse             common-subexpression elimination keyed by (op type,
                  inputs-at-version, attrs); global block only —
                  sub-blocks trace under their own control flow and
                  must never be merged across
  dce             dead-op/dead-var elimination: the executable version
                  of the verifier's PVI01/PVI02 findings (backward
                  liveness from fetches + persistable state + side
                  effects)

``backward_slice`` is the fetch-driven slicer that subsumes
``Program.prune`` (framework.py delegates here), and
``donation_mask`` is the donation-safety analyzer: a static proof, per
executor state input, that donating its buffer cannot be observed
(no top-level read after its last write, not aliased into a
control-flow sub-block, actually overwritten).  The Executor consults
the mask instead of donating the whole state dict.

Optimizer diagnostic codes (PVO*, stable — see analysis/passes.py for
the verifier's PVE/PVW/PVI tables):

  PVO01  optimizer skipped: input program already fails verification
  PVO02  pass output failed verification; pass reverted
  PVO03  dce/slice skipped: fetch set unknown
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from paddle_tpu.framework import Operator, Program
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.registry import LowerContext, OpRegistry
from paddle_tpu.analysis import dataflow
from paddle_tpu.analysis.verify import (
    Diagnostic,
    Severity,
    verify_program,
)

_M_OPS_REMOVED = _metrics.counter(
    "optimizer_ops_removed_total",
    "ops removed/replaced by optimizer rewrite passes, labeled by pass")
_M_DONATION = _metrics.gauge(
    "optimizer_donation_eligible",
    "state inputs the donation-safety analyzer proved donatable for the "
    "most recently compiled program")

# Mirrors executor._RANDOM_OPS (kept local: analysis must stay
# importable without jax).  Random ops draw from the step's threaded
# RNG key *in program order* — removing or merging one would shift the
# key stream of every later random op, so no rewrite pass touches them.
_RANDOM_OPS = frozenset(
    {"uniform_random", "gaussian_random", "dropout", "sampling_id",
     "random_crop", "nce", "segment_rng_key"}
)

# Zero-input op types safe to evaluate at optimize time.  ``load`` is
# excluded on purpose: it reads a file the deploy host may not share.
_CONST_SOURCE_OPS = frozenset({"fill", "fill_constant"})

# Folded results above this many elements would bloat the serialized
# program (fill embeds the data inline); leave big tensors to XLA.
_FOLD_SIZE_CAP = 65536


# ---------------------------------------------------------------------------
# Backward slicing (subsumes Program.prune)
# ---------------------------------------------------------------------------


def backward_slice(program: Program, targets: Sequence[str],
                   keep_side_effects: bool = False) -> Program:
    """Fetch-driven backward slice: clone the program keeping only ops
    whose outputs (transitively) feed a target.  ``feed`` ops are
    always kept (the executor skips them but exports carry them); a
    kept control-flow op pulls in everything its sub-blocks read from
    the enclosing scope.

    ``keep_side_effects=False`` reproduces the historical
    ``Program.prune`` contract (inference export: unrelated print/save
    ops are dropped); ``True`` is the DCE posture — side-effecting ops
    survive even when no target depends on them.
    """
    needed: Set[str] = set(
        t.name if hasattr(t, "name") else str(t) for t in targets)
    p = program.clone()
    block = p.global_block()
    kept: List[Operator] = []
    for op in reversed(block.ops):
        keep = (bool(needed & set(op.output_arg_names))
                or op.type == "feed"
                or (keep_side_effects
                    and (dataflow.op_has_side_effects(op)
                         or op.type in dataflow.PSEUDO_OPS)))
        if keep:
            kept.append(op)
            needed |= dataflow.effective_reads(op)
    block.ops = list(reversed(kept))
    p._version = getattr(p, "_version", 0) + 1
    p.invalidate_cache()
    return p


# ---------------------------------------------------------------------------
# Pass: dead-op / dead-var elimination
# ---------------------------------------------------------------------------


def _sub_block_keeps(op: Operator) -> bool:
    """A control-flow op must survive DCE when anything *inside* it has
    an effect the fetch-liveness walk cannot see: a side-effecting op,
    a random op (key-stream order), or a write to persistable state."""
    for _, sub in dataflow.op_sub_blocks(op):
        for _b, _i, sub_op in dataflow.walk_ops(sub):
            if (dataflow.op_has_side_effects(sub_op)
                    or sub_op.type in _RANDOM_OPS):
                return True
            for n in dataflow.op_writes(sub_op):
                var = sub.find_var(n)
                if var is not None and var.persistable:
                    return True
    return False


def dead_code_elimination(program: Program, feeds: Optional[Set[str]],
                          fetches: Sequence[str]) -> Tuple[int, int]:
    """Remove ops whose results cannot reach a fetch, persistable
    state, or a side effect (the executable form of PVI01), then drop
    variable declarations nothing references anymore (PVI02).  Mutates
    ``program`` in place; returns (ops_removed, vars_removed)."""
    block = program.global_block()
    live: Set[str] = set(fetches)
    kept: List[Operator] = []
    removed = 0
    for op in reversed(block.ops):
        writes = dataflow.op_writes(op)
        keep = (op.type in dataflow.PSEUDO_OPS
                or op.type in _RANDOM_OPS
                or dataflow.op_has_side_effects(op)
                or any(n in live for n in writes))
        if not keep:
            for n in writes:
                var = block.find_var(n)
                if var is not None and var.persistable:
                    keep = True
                    break
        if not keep and any(True for _ in dataflow.op_sub_blocks(op)):
            keep = _sub_block_keeps(op)
        if keep:
            kept.append(op)
            live |= dataflow.effective_reads(op)
        else:
            removed += 1
    block.ops = list(reversed(kept))

    # dead declarations: never referenced by a surviving op, not state,
    # not part of the feed/fetch surface.  With the feed set unknown
    # (lint mode), every producer-less var counts as the input surface.
    referenced: Set[str] = set(fetches)
    if feeds is None:
        referenced |= dataflow.implicit_feed_vars(program)
    else:
        referenced |= set(feeds)
        referenced |= {f + "@len" for f in feeds}
    for _b, _i, op in dataflow.walk_ops(block):
        referenced.update(dataflow.op_reads(op))
        referenced.update(dataflow.op_writes(op))
        referenced.update(dataflow.sub_block_bound_names(op))
    vars_removed = 0
    for blk in program.blocks:
        dead = [n for n, v in blk.vars.items()
                if n not in referenced and not v.persistable]
        for n in dead:
            del blk.vars[n]
            vars_removed += 1
    if removed or vars_removed:
        program._version = getattr(program, "_version", 0) + 1
        program.invalidate_cache()
    return removed, vars_removed


# ---------------------------------------------------------------------------
# Pass: constant folding
# ---------------------------------------------------------------------------


def _writes_persistable(op: Operator, block) -> bool:
    for n in dataflow.op_writes(op):
        var = block.find_var(n)
        if var is not None and var.persistable:
            return True
    return False


def _eval_const_op(op: Operator, consts: Dict[str, Any]):
    """Evaluate one op eagerly (outside any jit) over concrete inputs.
    Returns the single output value or None when evaluation is not
    possible/meaningful (any exception => not foldable)."""
    import jax.numpy as jnp  # deferred: analysis imports stay jax-free

    info = OpRegistry.get(op.type, none_ok=True)
    if info is None:
        return None
    values = {n: jnp.asarray(consts[n]) for n in dataflow.op_reads(op)}
    try:
        info.lower(LowerContext(op, values, rng=None))
    except Exception:
        return None
    out_names = dataflow.op_writes(op)
    result = values.get(out_names[0])
    if result is None or not isinstance(result, jnp.ndarray):
        return None  # LoDArray / SparseGrad / host objects: skip
    if result.size > _FOLD_SIZE_CAP:
        return None
    return np.asarray(result)


def constant_fold(program: Program, feeds: Optional[Set[str]]) -> int:
    """Replace pure ops whose inputs are all statically-known constants
    with ``fill`` ops carrying the computed value (dtype preserved from
    the actual computation).  Constants originate from zero-input
    ``fill``/``fill_constant`` ops and propagate forward; persistable
    writes are never folded (startup initializers must keep running —
    their values ARE the mutable state).  Mutates in place; returns the
    number of ops folded."""
    from paddle_tpu import amp

    if amp.is_enabled():
        # amp rewrites lowering dtypes at trace time; an eager fold here
        # would bake full-precision values into a half-precision program
        return 0
    block = program.global_block()
    consts: Dict[str, Any] = {}
    folds = 0
    for idx, op in enumerate(block.ops):
        reads = dataflow.op_reads(op)
        writes = dataflow.op_writes(op)
        foldable = (
            op.type not in dataflow.PSEUDO_OPS
            and op.type not in _RANDOM_OPS
            and not dataflow.op_has_side_effects(op)
            and not any(True for _ in dataflow.op_sub_blocks(op))
            and op.attr("__recompute_seg__") is None
            and len(writes) == 1
            and not _writes_persistable(op, block)
            and (all(n in consts for n in reads) if reads
                 else op.type in _CONST_SOURCE_OPS)
        )
        value = _eval_const_op(op, consts) if foldable else None
        if value is None:
            for n in writes:  # overwrite kills the known-constant fact
                consts.pop(n, None)
            continue
        consts[writes[0]] = value
        if op.type in _CONST_SOURCE_OPS:
            continue  # already a constant op; nothing to rewrite
        block.ops[idx] = Operator(
            block, "fill",
            inputs={},
            outputs={"Out": [writes[0]]},
            attrs={"shape": [int(s) for s in value.shape],
                   "dtype": str(value.dtype),
                   "data": value},
        )
        folds += 1
    if folds:
        program._version = getattr(program, "_version", 0) + 1
        program.invalidate_cache()
    return folds


# ---------------------------------------------------------------------------
# Pass: common-subexpression elimination
# ---------------------------------------------------------------------------


def _canonical_attrs(op: Operator) -> Optional[str]:
    """Stable attr serialization for CSE keys; None = not hashable
    (Block-valued attrs never get here — sub-block ops are skipped)."""
    try:
        return json.dumps(
            {k: v for k, v in op.attrs.items()},
            sort_keys=True, default=_attr_token)
    except Exception:
        return None


def _attr_token(v):
    if isinstance(v, np.ndarray):
        return ("__ndarray__", str(v.dtype), v.shape, v.tobytes().hex())
    return str(v)


def common_subexpression_elimination(program: Program,
                                     fetches: Sequence[str]) -> int:
    """Merge ops computing the same value: identical (type, inputs at
    their current def-version, attrs).  Global block only — an op in a
    ``while``/``recurrent`` sub-block runs under different control flow
    each iteration, so cross-block merging is forbidden by construction
    (pinned by tests/test_optimizer.py).  Mutates in place; returns the
    number of ops merged away."""
    block = program.global_block()
    web = dataflow.UseDefWeb(program)
    fetch_set = set(fetches)
    ver: Dict[str, int] = {}
    avail: Dict[tuple, Tuple[List[str], Tuple[Tuple[str, int], ...]]] = {}
    rename: Dict[str, str] = {}

    def resolve(name: str) -> str:
        while name in rename:
            name = rename[name]
        return name

    merged = 0
    kept: List[Operator] = []
    for op in block.ops:
        reads = [resolve(n) for n in dataflow.op_reads(op)]
        writes = dataflow.op_writes(op)
        attrs_key = _canonical_attrs(op)
        eligible = (
            op.type not in dataflow.PSEUDO_OPS
            and op.type not in _RANDOM_OPS
            and not dataflow.op_has_side_effects(op)
            and not any(True for _ in dataflow.op_sub_blocks(op))
            and op.attr("__recompute_seg__") is None
            and attrs_key is not None
            and bool(writes)
            and not set(reads) & set(writes)  # in-place update
            and not _writes_persistable(op, block)
        )
        if eligible:
            key = (
                op.type,
                tuple(sorted((slot, tuple(resolve(n) for n in ns if n))
                             for slot, ns in op.inputs.items())),
                tuple((n, ver.get(n, 0)) for n in sorted(set(reads))),
                tuple(sorted((slot, len([n for n in ns if n]))
                             for slot, ns in op.outputs.items())),
                attrs_key,
            )
            hit = avail.get(key)
            if hit is not None:
                canon_outs, canon_vers = hit
                # the canonical results must still hold their recorded
                # values, and the duplicate's outputs must be purely
                # local: single-writer, not fetched, never touched by a
                # sub-block (renaming only rewrites top-level reads)
                if (all(ver.get(n, 0) == v for n, v in canon_vers)
                        and all(
                            len(web.defs.get(n, ())) == 1
                            and n not in fetch_set
                            and not web.used_in_sub_block(n)
                            for n in writes)):
                    ordered_canon = dict(zip(
                        [n for _s, ns in sorted(op.outputs.items())
                         for n in ns if n],
                        canon_outs))
                    rename.update(ordered_canon)
                    merged += 1
                    continue
            else:
                out_names = [n for _s, ns in sorted(op.outputs.items())
                             for n in ns if n]
                avail[key] = (
                    out_names,
                    tuple((n, ver.get(n, 0) + 1) for n in out_names))
        for n in writes:
            ver[n] = ver.get(n, 0) + 1
        kept.append(op)

    if merged:
        block.ops = kept
        for op in block.ops:  # rewrite surviving top-level reads
            for slot, ns in op.inputs.items():
                op.inputs[slot] = [resolve(n) if n else n for n in ns]
        program._version = getattr(program, "_version", 0) + 1
        program.invalidate_cache()
    return merged


# ---------------------------------------------------------------------------
# Donation-safety analyzer
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DonationEntry:
    """Static verdict for one executor state input."""

    name: str
    eligible: bool
    reason: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def state_input_names(program: Program, feed_names: Set[str],
                      fetch_names: Sequence[str]) -> List[str]:
    """Persistables the compiled step takes as inputs — mirrors the
    executor's read-before-write classification (executor._compile)."""
    block = program.global_block()
    produced: Set[str] = set(feed_names)
    read_state: List[str] = []
    for op in block.ops:
        if op.type in dataflow.PSEUDO_OPS:
            continue
        for n in dataflow.op_reads(op):
            if n in produced or n in read_state:
                continue
            var = block.find_var(n)
            if var is not None and var.persistable:
                read_state.append(n)
        for n in dataflow.op_writes(op):
            produced.add(n)
    for n in fetch_names:
        if n not in produced and n not in read_state:
            var = block.find_var(n)
            if var is not None and var.persistable:
                read_state.append(n)
    return read_state


def donation_mask(program: Program, feed_names: Set[str],
                  fetch_names: Sequence[str]) -> Dict[str, DonationEntry]:
    """Per-state-input donation safety, proved from liveness.

    A state buffer may be donated to XLA (aliased, original storage
    clobbered) only when the program provably never observes the old
    value after the aliased write:

    - it must be overwritten by some top-level op (a read-only buffer
      has no aliasing write; donating it just destroys the scope copy);
    - no top-level op may read it after its last write (the PR-15
      corruption shape: a later read seeing the donated buffer's new —
      or garbage — contents);
    - it must not be read or written inside any control-flow sub-block
      (sub-blocks trace into the same executable but their reads are
      invisible to top-level last-write ordering).
    """
    web = dataflow.UseDefWeb(program)
    aliased = dataflow.sub_block_touched(program)
    mask: Dict[str, DonationEntry] = {}
    for name in state_input_names(program, feed_names, fetch_names):
        top_writes = [i for b, i in web.defs.get(name, ()) if b == 0]
        if name in aliased:
            entry = DonationEntry(name, False, "aliased into a sub-block")
        elif not top_writes:
            entry = DonationEntry(name, False,
                                  "read-only state (never overwritten)")
        else:
            last = max(top_writes)
            if web.read_after(name, 0, last):
                entry = DonationEntry(
                    name, False,
                    f"read after last write (op {last})")
            elif name in set(fetch_names):
                entry = DonationEntry(name, False, "fetched by the caller")
            else:
                entry = DonationEntry(
                    name, True, f"last write at op {last}, no later read")
        mask[name] = entry
    return mask


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OptReport:
    """What the pipeline did to one program (the ``--optimize`` payload)."""

    ops_before: int = 0
    ops_after: int = 0
    rounds: int = 0
    folds: int = 0
    cse_hits: int = 0
    dce_ops_removed: int = 0
    dce_vars_removed: int = 0
    donation: Dict[str, DonationEntry] = dataclasses.field(
        default_factory=dict)
    diagnostics: List[Diagnostic] = dataclasses.field(default_factory=list)
    optimized: bool = True

    def to_dict(self) -> dict:
        return {
            "ops_before": self.ops_before,
            "ops_after": self.ops_after,
            "rounds": self.rounds,
            "folds": self.folds,
            "cse_hits": self.cse_hits,
            "dce_ops_removed": self.dce_ops_removed,
            "dce_vars_removed": self.dce_vars_removed,
            "donation": {n: e.to_dict() for n, e in self.donation.items()},
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "optimized": self.optimized,
        }

    def format(self) -> str:
        lines = [
            f"ops: {self.ops_before} -> {self.ops_after} "
            f"({self.rounds} round(s))",
            f"  constant-fold: {self.folds} op(s) folded",
            f"  cse:           {self.cse_hits} op(s) merged",
            f"  dce:           {self.dce_ops_removed} op(s), "
            f"{self.dce_vars_removed} var(s) removed",
        ]
        if self.donation:
            eligible = sum(1 for e in self.donation.values() if e.eligible)
            lines.append(
                f"  donation mask: {eligible}/{len(self.donation)} state "
                "input(s) donatable")
            for name in sorted(self.donation):
                e = self.donation[name]
                tag = "donate" if e.eligible else "hold  "
                lines.append(f"    {tag} {name}: {e.reason}")
        for d in self.diagnostics:
            lines.append("  " + d.format())
        return "\n".join(lines)


def _verifier_errors(program: Program, feeds: Optional[Set[str]],
                     fetches: Optional[Sequence[str]]) -> List[Diagnostic]:
    diags = verify_program(program, feed_names=feeds, fetch_names=fetches,
                           level=Severity.ERROR)
    return [d for d in diags if d.severity == Severity.ERROR]


def optimize_program(program: Program,
                     feed_names: Optional[Set[str]] = None,
                     fetch_names: Optional[Sequence[str]] = None,
                     max_rounds: int = 3) -> Tuple[Program, OptReport]:
    """Run the full rewrite pipeline; returns (optimized_clone, report).

    Parity gate: each pass's output is re-verified at error tier; a
    pass that introduces any error is reverted wholesale (PVO02).  A
    program that fails verification *before* optimization is returned
    untouched (PVO01) — the optimizer only transforms programs the
    verifier accepts.
    """
    report = OptReport(
        ops_before=len(program.global_block().ops),
        ops_after=len(program.global_block().ops))
    feeds = set(feed_names) if feed_names is not None else None
    fetches = list(fetch_names) if fetch_names is not None else None

    if _verifier_errors(program, feeds, fetches):
        report.optimized = False
        report.diagnostics.append(Diagnostic(
            code="PVO01", severity=Severity.INFO,
            message="optimizer skipped: program fails verification as-is",
            hint="fix the verifier errors first (paddle lint)",
            pass_name="optimizer"))
        if fetches is not None:
            report.donation = donation_mask(program, feeds or set(), fetches)
        return program, report

    work = program.clone()
    if fetches is None:
        report.diagnostics.append(Diagnostic(
            code="PVO03", severity=Severity.INFO,
            message="fetch set unknown: dead-code elimination skipped",
            hint="pass fetch targets to enable dce",
            pass_name="dce"))

    def gated(name: str, fn) -> int:
        """Run one mutating pass under the verify-or-revert gate."""
        nonlocal work
        backup = work.clone()
        try:
            changed = fn(work)
        except Exception as exc:  # a pass must never take the program down
            work = backup
            report.diagnostics.append(Diagnostic(
                code="PVO02", severity=Severity.WARNING,
                message=f"pass {name!r} raised {exc!r}; reverted",
                pass_name=name))
            return 0
        if changed and _verifier_errors(work, feeds, fetches):
            work = backup
            report.diagnostics.append(Diagnostic(
                code="PVO02", severity=Severity.WARNING,
                message=f"pass {name!r} output failed verification; "
                        "reverted",
                pass_name=name))
            return 0
        if changed:
            _M_OPS_REMOVED.inc(changed, **{"pass": name})
        return changed

    for _ in range(max_rounds):
        report.rounds += 1
        folds = gated("constant-fold", lambda p: constant_fold(p, feeds))
        cse = (gated("cse",
                     lambda p: common_subexpression_elimination(p, fetches))
               if fetches is not None else 0)
        dce = 0
        if fetches is not None:
            removed = [0, 0]

            def _dce(p):
                removed[0], removed[1] = dead_code_elimination(
                    p, feeds, fetches)
                return removed[0] + removed[1]

            dce = gated("dce", _dce)
            if dce:
                report.dce_ops_removed += removed[0]
                report.dce_vars_removed += removed[1]
        report.folds += folds
        report.cse_hits += cse
        if not (folds or cse or dce):
            break

    report.ops_after = len(work.global_block().ops)
    if fetches is not None:
        report.donation = donation_mask(work, feeds or set(), fetches)
    work.invalidate_cache()
    return work, report


# ---------------------------------------------------------------------------
# Differential parity harness
# ---------------------------------------------------------------------------


def check_parity(program: Program, feed: Dict[str, Any],
                 fetch_names: Sequence[str],
                 state: Optional[Dict[str, Any]] = None) -> OptReport:
    """Execute ``program`` and its optimized form on identical state and
    feeds; raise AssertionError unless every fetch is bit-identical.
    Returns the optimizer report.  Test/CLI harness — imports the
    Executor lazily so the analysis package stays jax-free."""
    from paddle_tpu.executor import Executor, Scope

    optimized, report = optimize_program(
        program, feed_names=set(feed), fetch_names=fetch_names)

    outs = []
    for prog in (program, optimized):
        scope = Scope()
        for n, v in (state or {}).items():
            # per-run copy: if donation is live, the first run's step
            # would consume buffers the second run still needs
            scope.set(n, np.array(v, copy=True))
        exe = Executor()
        outs.append(exe.run(prog, feed=dict(feed),
                            fetch_list=list(fetch_names),
                            scope=scope, return_numpy=True))
    base, opt = outs
    for name, a, b in zip(fetch_names, base, opt):
        a, b = np.asarray(a), np.asarray(b)
        equal_nan = np.issubdtype(a.dtype, np.inexact)
        if a.shape != b.shape or a.dtype != b.dtype or not np.array_equal(
                a, b, equal_nan=equal_nan):
            raise AssertionError(
                f"optimizer parity violation on fetch {name!r}: "
                f"original {a.dtype}{a.shape} vs optimized "
                f"{b.dtype}{b.shape}\n{report.format()}")
    return report


def set_donation_gauge(program_label: str,
                       mask: Dict[str, DonationEntry]) -> None:
    """Publish the donation verdict for a compiled program."""
    _M_DONATION.set(sum(1 for e in mask.values() if e.eligible),
                    program=program_label)
