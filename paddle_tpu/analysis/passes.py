"""The built-in lint/verify passes.

Each pass is a function over a ``PassContext`` registered via
``@register_pass(name, tier)``; the tier is the most severe diagnostic
the pass can emit, and the pass manager only runs passes at or above
the requested level (the Executor's pre-compile gate runs error tier
only).

Stable diagnostic codes (asserted by tests — treat as API):

  PVE01  read-before-write / undefined input
  PVE02  dangling fetch target
  PVE03  dtype clash on an arithmetic op
  PVE04  malformed control-flow sub-block
  PVE05  unknown (unregistered) op type
  PVE06  @GRAD variable without a forward counterpart
  PVE07  registered infer_shape rule rejected the op
  PVW01  write-after-write (earlier value dead)
  PVW02  persistable-write hazard
  PVW03  fed variable never read
  PVW04  gradient/forward dtype mismatch
  PVW05  same-family dtype width mismatch
  PVI01  dead op (result unreachable from fetches/state)
  PVI02  dead variable (declared, never used)

Optimizer diagnostics (emitted by analysis/optimize.py's PassPipeline,
same Diagnostic records, same stability contract):

  PVO01  optimizer skipped: input program already fails verification
  PVO02  rewrite pass output failed verification; pass reverted
  PVO03  dce/slice skipped: fetch set unknown
"""

from __future__ import annotations

from typing import Set

from paddle_tpu.framework import GRAD_SUFFIX, Block, Parameter
from paddle_tpu.registry import OpRegistry, SkipInferShape
from paddle_tpu.analysis import dataflow
from paddle_tpu.analysis.verify import PassContext, Severity, register_pass

_ARITH_BINARY = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "mul",
})


# ---------------------------------------------------------------------------
# Error tier
# ---------------------------------------------------------------------------


@register_pass("def-before-use", Severity.ERROR)
def check_def_before_use(ctx: PassContext):
    """Every op input must be fed, produced by an earlier op, or
    persistable (scope state).  Top-level ops are checked in program
    order; sub-block reads are checked unordered (loop-carried state in
    ``while``/``recurrent`` legally reads names written later in the
    same block).  Malformed sub-block attrs surface here as PVE04."""
    program = ctx.program
    defined = set(ctx.feed_surface())
    _walk(ctx, program.global_block(), defined, ordered=True, seen=set())


def _walk(ctx: PassContext, block: Block, defined: Set[str],
          ordered: bool, seen: Set[int]):
    if id(block) in seen:
        return
    seen.add(id(block))
    # only unordered (sub-block) regions consult the full write set
    local_writes = (dataflow.block_writes(block, recursive=True)
                    if not ordered else frozenset())
    for idx, op in enumerate(block.ops):
        if op.type in dataflow.PSEUDO_OPS:
            defined.update(dataflow.op_writes(op))
            continue
        for name in dataflow.op_reads(op):
            if name in defined:
                continue
            if not ordered and name in local_writes:
                continue  # unordered region: loop carry / branch writes
            var = block.find_var(name)
            if var is not None and var.persistable:
                continue  # comes from scope state at run time
            # (lint mode needs no implicit-feed test here: `defined` is
            # seeded from feed_surface(), which IS implicit_feeds then)
            ctx.emit(
                "PVE01", Severity.ERROR,
                f"op reads {name!r} before any write",
                block_idx=block.idx, op_idx=idx, op_type=op.type, var=name,
                hint="feed it, produce it with an earlier op, or mark the "
                     "variable persistable")
        for attr_key, sub in dataflow.op_sub_blocks(op):
            if not _sub_block_ok(ctx, block, idx, op, attr_key, sub, seen):
                continue
            inner = defined | dataflow.sub_block_bound_names(op)
            _walk(ctx, sub, inner, ordered=False, seen=seen)
        defined.update(dataflow.op_writes(op))


def _sub_block_ok(ctx: PassContext, block: Block, idx: int, op, attr_key: str,
                  sub: Block, seen: Set[int]) -> bool:
    """Validate a Block-valued attr (PVE04); False skips the descent."""

    def bad(why: str) -> bool:
        ctx.emit("PVE04", Severity.ERROR,
                 f"attr {attr_key!r} references a malformed sub-block: {why}",
                 block_idx=block.idx, op_idx=idx, op_type=op.type,
                 hint="sub-blocks must be created with "
                      "program.create_block() on the same program")
        return False

    if sub.program is not ctx.program:
        return bad("it belongs to a different Program")
    if not (0 <= sub.idx < len(ctx.program.blocks)):
        return bad(f"block idx {sub.idx} out of range")
    if ctx.program.blocks[sub.idx] is not sub:
        return bad(f"block idx {sub.idx} does not match program.blocks")
    if id(sub) in seen:
        return bad("sub-block cycle (block reachable from itself)")
    return True


@register_pass("unknown-op", Severity.ERROR)
def check_unknown_ops(ctx: PassContext):
    """Every op type must resolve in the OpRegistry (``*_grad`` types
    synthesize from the forward rule, so they resolve too)."""
    for block, idx, op in dataflow.walk_ops(ctx.program.global_block()):
        if op.type in dataflow.PSEUDO_OPS:
            continue
        if OpRegistry.get(op.type, none_ok=True) is not None:
            continue
        close = OpRegistry.suggest(op.type, n=1)
        ctx.emit("PVE05", Severity.ERROR,
                 f"op type {op.type!r} is not registered",
                 block_idx=block.idx, op_idx=idx, op_type=op.type,
                 hint=(f"did you mean {close[0]!r}?" if close
                       else "register it with @register_op"))


@register_pass("fetch-reachability", Severity.ERROR)
def check_fetch_reachability(ctx: PassContext):
    """Every fetch target must be produced by some op, fed, or
    persistable — otherwise the jit trace dies on a KeyError long after
    the actual mistake.  Skipped when the fetch list is unknown."""
    if not ctx.fetches:
        return
    available = ctx.all_writes | ctx.feed_surface()
    block = ctx.program.global_block()
    for name in ctx.fetches:
        if name in available:
            continue
        var = block.find_var(name)
        if var is not None and var.persistable:
            continue
        ctx.emit("PVE02", Severity.ERROR,
                 f"fetch target {name!r} is never written by any op "
                 f"(fetch list: {list(ctx.fetches)!r})",
                 var=name,
                 hint="fetch a variable some op produces, feed it, or "
                      "mark it persistable")


@register_pass("dtype-flow", Severity.ERROR)
def check_dtype_flow(ctx: PassContext):
    """Arithmetic ops over operands from different dtype families
    (float vs int vs bool) are an error — XLA would either refuse or
    silently promote; same-family width mixes (float32+float64,
    int32+int64) downgrade to a warning since the executor's feed
    canonicalization often papers over them."""
    for block, idx, op in dataflow.walk_ops(ctx.program.global_block()):
        if op.type not in _ARITH_BINARY and op.type != "sum":
            continue
        names = ([n for n in op.input("X") if n]
                 + [n for n in op.input("Y") if n])
        typed = [(n, dataflow.declared_dtype(block, n)) for n in names]
        typed = [(n, d) for n, d in typed if d is not None]
        if len(typed) < 2:
            continue
        base_name, base = typed[0]
        for name, dtype in typed[1:]:
            if dtype == base:
                continue
            fam_a = dataflow.dtype_family(base)
            fam_b = dataflow.dtype_family(dtype)
            if fam_a != fam_b:
                ctx.emit("PVE03", Severity.ERROR,
                         f"dtype clash: {base_name!r} is {base} but "
                         f"{name!r} is {dtype}",
                         block_idx=block.idx, op_idx=idx, op_type=op.type,
                         var=name,
                         hint="insert a cast op (layers.cast) on one operand")
            else:
                ctx.emit("PVW05", Severity.WARNING,
                         f"dtype width mismatch: {base_name!r} is {base} "
                         f"but {name!r} is {dtype}",
                         block_idx=block.idx, op_idx=idx, op_type=op.type,
                         var=name,
                         hint="widths are silently promoted; cast "
                              "explicitly if intended")
            break


@register_pass("shape-infer", Severity.ERROR)
def check_shape_inference(ctx: PassContext):
    """Re-run each op's registered ``infer_shape`` rule over the built
    program.  ``SkipInferShape`` means "cannot infer statically" and is
    fine; any other exception is the rule rejecting the op's metadata."""
    ran_any = False
    for block, idx, op in dataflow.walk_ops(ctx.program.global_block()):
        info = OpRegistry.get(op.type, none_ok=True)
        if info is None or info.infer_shape is None:
            continue
        try:
            ran_any = True
            info.infer_shape(op, block)
        except SkipInferShape:
            continue
        except Exception as exc:  # the rule rejected the op
            ctx.emit("PVE07", Severity.ERROR,
                     f"infer_shape rejected the op: {exc}",
                     block_idx=block.idx, op_idx=idx, op_type=op.type,
                     hint="fix the op's input/output shapes or dtypes")
    if ran_any:
        # rules may backfill var metadata (shape/lod) the program was
        # built without (e.g. loaded via Program.from_dict, which skips
        # append-time InferShape); drop any cached content fingerprint
        # so the executor's compile-cache key reflects the filled state
        ctx.program.invalidate_cache()


@register_pass("grad-pairing", Severity.ERROR)
def check_grad_pairing(ctx: PassContext):
    """After append_backward every ``x@GRAD`` (and ``@RENAME`` alias)
    must pair with a declared forward ``x``; mismatched grad/forward
    dtypes are a warning (the vjp would emit the forward dtype)."""
    for block in ctx.program.blocks:
        for name, var in block.vars.items():
            if GRAD_SUFFIX not in name:
                continue
            base = name.split(GRAD_SUFFIX, 1)[0]
            if not base:
                continue
            fwd = block.find_var(base)
            if fwd is None:
                ctx.emit("PVE06", Severity.ERROR,
                         f"gradient variable {name!r} has no forward "
                         f"counterpart {base!r}",
                         block_idx=block.idx, var=name,
                         hint="gradient vars are created by "
                              "append_backward; do not hand-declare them")
            elif fwd.dtype != var.dtype:
                ctx.emit("PVW04", Severity.WARNING,
                         f"gradient {name!r} is {var.dtype} but forward "
                         f"{base!r} is {fwd.dtype}",
                         block_idx=block.idx, var=name,
                         hint="grads inherit the forward dtype; a clash "
                              "means the var was redeclared")


# ---------------------------------------------------------------------------
# Warning tier
# ---------------------------------------------------------------------------


@register_pass("waw-overwrite", Severity.WARNING)
def check_waw(ctx: PassContext):
    """Two writes to the same name with no read in between: the first
    value is dead — usually a copy-paste slip or a shadowed temp.
    In-place updates (op reads what it writes) are exempt."""
    for block in ctx.program.blocks:
        writers = dataflow.producers(block)
        for name, idxs in writers.items():
            for prev, cur in zip(idxs, idxs[1:]):
                cur_op = block.ops[cur]
                if name in dataflow.op_reads(cur_op):
                    continue  # read-modify-write
                if any(_op_or_sub_reads(block.ops[i], name)
                       for i in range(prev + 1, cur)):
                    continue
                ctx.emit("PVW01", Severity.WARNING,
                         f"{name!r} written at op {prev} is overwritten "
                         f"unread (write-after-write)",
                         block_idx=block.idx, op_idx=cur,
                         op_type=cur_op.type, var=name,
                         hint="drop the first write or rename the second "
                              "target")


def _op_or_sub_reads(op, name: str) -> bool:
    if name in dataflow.op_reads(op):
        return True
    for _, sub in dataflow.op_sub_blocks(op):
        for _b, _i, sub_op in dataflow.walk_ops(sub):
            if name in dataflow.op_reads(sub_op):
                return True
    return False


@register_pass("persistable-hazard", Severity.WARNING)
def check_persistable_writes(ctx: PassContext):
    """Persistable state threads functionally through the compiled step
    (executor.py); hazards: (a) the same persistable written by two ops
    in one step (double update — last silently wins), (b) a trainable
    Parameter blindly overwritten by a non-optimizer, non-initializer
    op (clobbers checkpointed state)."""
    block = ctx.program.global_block()
    writers = dataflow.producers(block)
    for name, idxs in writers.items():
        var = block.find_var(name)
        if var is None or not var.persistable:
            continue
        if len(idxs) > 1:
            ctx.emit("PVW02", Severity.WARNING,
                     f"persistable {name!r} is written by ops "
                     f"{list(idxs)} in one step; the last write wins",
                     block_idx=block.idx, op_idx=idxs[-1],
                     op_type=block.ops[idxs[-1]].type, var=name,
                     hint="fold the updates into one op or split the "
                          "program")
            continue
        op = block.ops[idxs[0]]
        if not isinstance(var, Parameter):
            continue
        reads = dataflow.op_reads(op)
        is_init = not reads  # pure initializer (fill/load/random)
        if name in reads or is_init:
            continue
        if op.attr("op_role") == "optimize" or op.type.endswith("_grad"):
            continue
        ctx.emit("PVW02", Severity.WARNING,
                 f"parameter {name!r} is overwritten by {op.type!r} "
                 "without reading it (outside any optimizer update)",
                 block_idx=block.idx, op_idx=idxs[0], op_type=op.type,
                 var=name,
                 hint="parameter writes outside op_role='optimize' "
                      "clobber trained state")


@register_pass("feed-usage", Severity.WARNING)
def check_feed_usage(ctx: PassContext):
    """Explicitly-fed names nothing reads: dead host->device transfers
    every step.  Only runs when the caller supplied the feed set."""
    if not ctx.feeds:
        return
    read: Set[str] = set()
    for _b, _i, op in dataflow.walk_ops(ctx.program.global_block()):
        read.update(dataflow.op_reads(op))
    for name in sorted(ctx.feeds):
        if name in read or (ctx.fetches and name in ctx.fetches):
            continue
        ctx.emit("PVW03", Severity.WARNING,
                 f"fed variable {name!r} is never read by any op",
                 var=name,
                 hint="drop it from the feed dict")


# ---------------------------------------------------------------------------
# Info tier
# ---------------------------------------------------------------------------


@register_pass("dead-code", Severity.INFO)
def check_dead_code(ctx: PassContext):
    """Backward liveness from the fetch set: ops whose results cannot
    reach a fetch, persistable state, or a side effect are dead weight
    in every compile.  Needs the fetch list; skipped otherwise."""
    if ctx.fetches is None:
        return
    block = ctx.program.global_block()
    live: Set[str] = set(ctx.fetches)
    dead_ops = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        writes = dataflow.op_writes(op)
        keep = (dataflow.op_has_side_effects(op)
                or op.type in dataflow.PSEUDO_OPS
                or any(n in live for n in writes))
        if not keep:
            for n in writes:
                var = block.find_var(n)
                if var is not None and var.persistable:
                    keep = True
                    break
        if keep:
            live.update(dataflow.op_reads(op))
            for _, sub in dataflow.op_sub_blocks(op):
                for _b, _i, sub_op in dataflow.walk_ops(sub):
                    live.update(dataflow.op_reads(sub_op))
        else:
            dead_ops.append((idx, op))
    for idx, op in reversed(dead_ops):
        ctx.emit("PVI01", Severity.INFO,
                 "op result never reaches a fetch, persistable, or "
                 "side effect",
                 block_idx=block.idx, op_idx=idx, op_type=op.type,
                 hint="prune it with Program.prune(targets)")
    used: Set[str] = set(ctx.fetches) | ctx.feed_surface()
    for _b, _i, op in dataflow.walk_ops(block):
        used.update(dataflow.op_reads(op))
        used.update(dataflow.op_writes(op))
    for blk in ctx.program.blocks:
        for name in blk.vars:
            if name not in used:
                ctx.emit("PVI02", Severity.INFO,
                         f"variable {name!r} is declared but never used",
                         block_idx=blk.idx, var=name,
                         hint="delete the declaration")
