"""`paddle compile`: export AOT serving artifacts.

Usage:
  paddle compile --model_dir=DIR --out=DIR [--max_batch=N]
                 [--buckets=1,2,4] [--no-optimize]
                 [--gen_config=SCRIPT [--gen_*=...]]
  paddle compile --smoke

Runs the serving warmup paths under export capture (paddle_tpu/aot):
every bucket-ladder program (and, with --gen_config, every decode-step
program one synthetic generation compiles) is lowered AOT, serialized,
and pinned in a versioned manifest.  `paddle serve --artifacts=DIR`
then boots replicas from the store instead of JIT-compiling.

--smoke is the self-contained CI gate: build a throwaway MLP export,
compile it, boot one server cold-JIT and one from the artifacts, and
assert the artifact boot (a) answered from loaded executables only and
(b) produced byte-identical /predict output.
"""

from __future__ import annotations

import json
import os
import sys

from paddle_tpu.aot.artifact import ArtifactWriter
from paddle_tpu.aot.export import export_generator, export_model


def _parse(argv):
    args, rest = {}, []
    for a in argv:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            args[k] = v
        else:
            rest.append(a)
    return args, rest


def main(argv) -> int:
    args, rest = _parse(argv)
    if "--smoke" in rest:
        return smoke()
    model_dir = args.get("model_dir")
    out = args.get("out")
    if not out or (not model_dir and not args.get("gen_config")):
        print("usage: paddle compile --model_dir=DIR --out=DIR "
              "[--max_batch=N] [--buckets=1,2,...] [--no-optimize] "
              "[--gen_config=SCRIPT ...] | paddle compile --smoke",
              file=sys.stderr)
        return 2
    buckets = None
    if args.get("buckets"):
        buckets = [int(b) for b in args["buckets"].split(",") if b]
    writer = ArtifactWriter(out)
    if model_dir:
        export_model(model_dir, out,
                     max_batch=int(args.get("max_batch", 8)),
                     buckets=buckets,
                     optimize="--no-optimize" not in rest,
                     writer=writer, finish=False)
    if args.get("gen_config"):
        from paddle_tpu.cli import _load_generator

        gen = _load_generator(args, rest)
        try:
            export_generator(gen, out, writer=writer, finish=False)
        finally:
            gen.stop()
    manifest = writer.finish(
        extra={"model_dir": model_dir} if model_dir else None)
    total = sum(e["nbytes"] for e in writer.entries.values())
    print(f"exported {len(writer.entries)} executable(s), "
          f"{total} bytes -> {manifest}")
    for e in sorted(writer.entries.values(), key=lambda e: e["id"]):
        print(f"  {e['id']}  fp={e['program_fp'][:12]}  "
              f"sig={e['feed_sig']}  {e['nbytes']}B")
    return 0


def smoke() -> int:
    """Export -> artifact-booted serve -> one request -> parity vs JIT.

    Exercised by scripts/lint_self.sh; everything runs in-process
    against throwaway temp dirs so the gate needs no fixtures."""
    import tempfile
    import urllib.request

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.serving import InferenceServer

    def _predict(srv, body):
        req = urllib.request.Request(
            f"http://{srv.address}/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.read()

    with tempfile.TemporaryDirectory(prefix="paddle_aot_smoke_") as tmp:
        model_dir = os.path.join(tmp, "model")
        art_dir = os.path.join(tmp, "artifacts")
        fluid.framework.reset_default_programs()
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=4, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe)

        writer = export_model(model_dir, art_dir, max_batch=4)
        print(f"smoke: exported {len(writer.entries)} executables")
        body = json.dumps(
            {"x": np.linspace(-1.0, 1.0, 18).reshape(3, 6).tolist()}
        ).encode()

        jit_srv = InferenceServer(model_dir, max_batch=4, warmup=True)
        try:
            jit_bytes = _predict(jit_srv, body)
        finally:
            jit_srv.stop()

        aot_srv = InferenceServer(model_dir, max_batch=4, warmup=True,
                                  artifacts=art_dir)
        try:
            aot_bytes = _predict(aot_srv, body)
            results = dict(aot_srv._artifact_store.results)
            boot = aot_srv._pool.boot_source()
        finally:
            aot_srv.stop()

    if aot_bytes != jit_bytes:
        print("smoke FAIL: artifact-booted /predict output differs from "
              f"JIT ({aot_bytes!r} != {jit_bytes!r})", file=sys.stderr)
        return 1
    if boot != "aot" or not results.get("loaded"):
        print(f"smoke FAIL: expected a pure artifact boot, got "
              f"boot={boot!r} store results={results}", file=sys.stderr)
        return 1
    rejected = {k: v for k, v in results.items() if k != "loaded"}
    if rejected:
        print(f"smoke FAIL: artifact lookups rejected: {rejected}",
              file=sys.stderr)
        return 1
    print(f"smoke OK: boot={boot} store={results} parity=bit-identical")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main(sys.argv[1:]))
