"""Export drivers: run the serving warmup paths under capture.

Export is *capture-mode* compilation: an ``ArtifactWriter`` is made the
process-active exporter, then the exact code paths a serving boot runs
(the replica bucket ladder; optionally one synthetic generation through
the decode engine) are driven with zero-filled feeds.  Every
Executor.run compile miss inside the capture window lowers its jitted
step AOT (``fn.lower(...).compile()``), serializes the executable, and
records a manifest entry — so what lands in the artifact directory is
by construction exactly the set of programs a ``--warmup`` boot needs,
already optimized (ModelBundle applies the rewrite pipeline before any
replica compiles, and the OPTIMIZED fingerprint keys the entry).
"""

from __future__ import annotations

from typing import Optional, Sequence

from paddle_tpu.aot.artifact import ArtifactWriter


def export_model(model_dir: str, out_dir: str, *,
                 max_batch: int = 8,
                 buckets: Optional[Sequence[int]] = None,
                 optimize: bool = True,
                 place=None,
                 writer: Optional[ArtifactWriter] = None,
                 finish: bool = True) -> ArtifactWriter:
    """Export the bucket ladder of a save_inference_model directory.

    Mirrors ``ReplicaPool.warmup()``: one replica (own Scope + Executor)
    runs a zero-filled synthetic batch per bucket, each compile captured
    into ``writer``.  Returns the writer; with ``finish`` (default) the
    manifest is written too."""
    import numpy as np

    from paddle_tpu import aot as _aot
    from paddle_tpu.serving.batching import bucket_ladder
    from paddle_tpu.serving.replica import ModelBundle, Replica

    bundle = ModelBundle(model_dir, optimize=optimize)
    spec = bundle.batch_spec()
    if not spec.batchable:
        raise RuntimeError(
            f"cannot export {model_dir}: the model is not batch-major "
            f"({spec.reason}) so there is no static bucket ladder to "
            "compile ahead of time")
    rep = Replica(bundle, 0, place)
    writer = writer or ArtifactWriter(out_dir)
    buckets = tuple(buckets or bucket_ladder(max_batch))
    with _aot.capture(writer):
        for b in buckets:
            feeds = {
                name: np.zeros((b,) + spec.row_shapes[name],
                               dtype=spec.dtypes[name])
                for name in spec.feed_names
            }
            rep.run(feeds)
    if finish:
        writer.finish(extra={"model_dir": model_dir,
                             "buckets": list(buckets)})
    return writer


def export_generator(generator, out_dir: str, *,
                     prompt_ids: Optional[Sequence[int]] = None,
                     max_new_tokens: int = 2,
                     writer: Optional[ArtifactWriter] = None,
                     finish: bool = True) -> ArtifactWriter:
    """Export the decode-step programs of a GenerationEngine by running
    one short synthetic generation under capture.

    Covers every program the engine routes through an Executor (the
    paged seq2seq prefill/decode steps); models that jit directly (the
    tiny decoder LM demo) compile nothing through the executor and so
    export nothing — they were never part of the cold-start cost this
    subsystem removes."""
    from paddle_tpu import aot as _aot

    writer = writer or ArtifactWriter(out_dir)
    ids = list(prompt_ids) if prompt_ids else [
        int(getattr(generator.model, "bos_id", 1) or 1)]
    with _aot.capture(writer):
        req = generator.submit(ids, max_new_tokens=max_new_tokens)
        req.result(timeout=600)
    if finish:
        writer.finish(extra={"generator": True})
    return writer
