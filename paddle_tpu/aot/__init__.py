"""AOT serving artifacts: kill the cold start.

``paddle compile`` exports a model's serving programs — the bucket
ladder every replica would JIT at boot, optionally the decode step —
as serialized XLA executables in a versioned artifact directory
(``artifact.py``).  ``paddle serve --artifacts=DIR`` boots replicas
from that store: the Executor consults it at every compile-cache miss
and, on a manifest match, deserializes instead of tracing+compiling.

Unlike the jax persistent compile cache (unusable on this jaxlib —
PR 15's ``_donation_ok()`` kill-switch exists because cache-loaded
executables corrupt donation aliasing), this path serializes through
``jax.experimental.serialize_executable`` with the donation mask pinned
in the manifest and re-proved at load: donation stays ACTIVE on
artifact-booted replicas.  Any mismatch — version skew, device kind,
tuning-DB drift, fingerprint drift, corrupt payload, donation drift —
is a loud JIT fallback counted in ``aot_load_total{result}``: slower,
never wrong.

Two attachment surfaces:

- per-Executor: ``executor.aot_store = store`` (the serving replica
  pool wires each replica this way — no process-global state);
- process-global ``attach(store)`` — for paths that build executors
  deep inside a model (the paged decode engine) where threading a
  store handle through would touch every layer.

``capture(writer)`` is the export side: inside the context every
compile miss is lowered AOT, serialized, and recorded.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from paddle_tpu.aot.artifact import (
    ArtifactStore,
    ArtifactWriter,
    MANIFEST_NAME,
    SCHEMA,
)
from paddle_tpu.aot.export import export_generator, export_model

__all__ = [
    "ArtifactStore", "ArtifactWriter", "MANIFEST_NAME", "SCHEMA",
    "active_exporter", "active_store", "attach", "capture", "detach",
    "export_generator", "export_model",
]

_ACTIVE_STORE: Optional[ArtifactStore] = None
_ACTIVE_EXPORTER: Optional[ArtifactWriter] = None


def attach(store: ArtifactStore) -> ArtifactStore:
    """Make ``store`` the process-global artifact store every Executor
    consults on a compile miss (executors with an explicit
    ``aot_store`` attribute keep their own)."""
    global _ACTIVE_STORE
    _ACTIVE_STORE = store
    return store


def detach() -> None:
    global _ACTIVE_STORE
    _ACTIVE_STORE = None


def active_store() -> Optional[ArtifactStore]:
    return _ACTIVE_STORE


def active_exporter() -> Optional[ArtifactWriter]:
    return _ACTIVE_EXPORTER


@contextlib.contextmanager
def capture(writer: ArtifactWriter):
    """Every Executor compile miss inside the context is exported into
    ``writer`` (and the captured AOT executable is what actually runs,
    so the export is validated by execution, not just serialization)."""
    global _ACTIVE_EXPORTER
    prev = _ACTIVE_EXPORTER
    _ACTIVE_EXPORTER = writer
    try:
        yield writer
    finally:
        _ACTIVE_EXPORTER = prev
