"""Versioned AOT serving artifacts: manifest + executable store.

An artifact directory is one ``paddle compile`` run::

    <dir>/MANIFEST.json            # schema, environment pins, entries
    <dir>/executables/<id>.bin     # pickled (payload, in_tree, out_tree)
                                   #   from jax.experimental
                                   #   .serialize_executable

Each entry is one compiled executor step, keyed exactly like the
Executor's in-process compile cache: (optimized-program fingerprint,
feed signature, fetch set).  The manifest pins everything that could
make a stored executable wrong or slow to reuse:

- jax / jaxlib versions, backend platform and device kind (an XLA
  binary is not portable across any of these);
- the Pallas tuning-DB digest (a re-tuned kernel config changes the
  lowering, so stale artifacts must re-export, not silently serve the
  old schedule);
- compile-context flags (amp, pallas mode, interpret, trace_ops) —
  the same bits that key the executor cache;
- per entry: the donation mask the analyzer proved at export time.
  The load side re-runs the analysis and REFUSES the entry on drift,
  because the serialized executable's input-output aliasing is baked
  in — running it with a different donation contract would either leak
  the aliasing win or read freed buffers.

Every lookup lands in ``aot_load_total{result=...}``: ``loaded`` or a
``rejected_*`` reason.  A rejection is always a loud JIT fallback —
slower, never wrong.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import sys
from typing import Any, Dict, Optional, Tuple

from paddle_tpu.observability import metrics as _metrics

SCHEMA = "paddle_tpu.aot.v1"
MANIFEST_NAME = "MANIFEST.json"
EXEC_DIR = "executables"

_M_AOT_LOAD = _metrics.counter(
    "aot_load_total",
    "artifact-store lookups by outcome: loaded, or rejected_* (version "
    "skew / device / tuning-db / flags / fingerprint / bucket / corrupt "
    "/ donation drift) — every rejection is a loud JIT fallback")
_M_AOT_EXPORT = _metrics.counter(
    "aot_export_total",
    "executables serialized into an artifact directory by paddle compile")


def sig_json(feed_sig) -> str:
    """Canonical JSON for an Executor ``_feed_signature`` tuple (tuples
    become lists; the string is the manifest's entry key component)."""
    return json.dumps(feed_sig, separators=(",", ":"), sort_keys=False)


def environment_fingerprint(backend: Optional[str] = None) -> Dict[str, str]:
    import jax

    try:
        import jaxlib.version as _jlv

        jaxlib_version = _jlv.__version__
    except Exception:  # pragma: no cover - jaxlib always ships version
        jaxlib_version = "unknown"
    devs = jax.devices(backend) if backend else jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
    }


def tuning_db_digest() -> str:
    """Content hash of the process-active Pallas tuning database.

    Kernel dispatch consults the DB at trace time, so two exports under
    different DBs can embed different schedules for the same program —
    the digest makes that visible to the load-side match."""
    try:
        from paddle_tpu.pallas.tuning import get_db

        entries = get_db().entries
    except Exception:  # pragma: no cover - tuning import must not kill AOT
        return "unavailable"
    if not entries:
        return "empty"
    blob = json.dumps(entries, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def flags_fingerprint() -> Dict[str, Any]:
    """The compile-context bits the executor cache keys on (beyond the
    program/feed/fetch triple): flipping any retraces, so an artifact
    exported under different flags must not load."""
    from paddle_tpu import amp
    from paddle_tpu import pallas as pk
    from paddle_tpu.flags import FLAGS

    return {
        "amp": bool(amp.is_enabled()),
        "pallas_mode": str(pk.mode()),
        "pallas_interpret": bool(pk.interpret_mode()),
        "trace_ops": bool(FLAGS.get("trace_ops")),
    }


def _entry_id(program_fp: str, sig: str, fetch_names) -> str:
    h = hashlib.sha256()
    h.update(program_fp.encode())
    h.update(b"\x00")
    h.update(sig.encode())
    h.update(b"\x00")
    h.update(json.dumps(list(fetch_names)).encode())
    return h.hexdigest()[:24]


class ArtifactWriter:
    """Accumulates serialized executables + manifest entries; one
    ``paddle compile`` run writes one of these and calls ``finish()``."""

    def __init__(self, out_dir: str, backend: Optional[str] = None):
        self.out_dir = out_dir
        self.backend = backend
        self.entries: Dict[str, dict] = {}
        os.makedirs(os.path.join(out_dir, EXEC_DIR), exist_ok=True)

    def add(self, *, program_fp: str, feed_sig, fetch_names,
            executable, state_names, donated_names, held_names,
            out_state_names, written_names, uses_rng: bool) -> dict:
        """Serialize one ``jax.stages.Compiled`` under its cache key.
        Idempotent per key (warmup may hit the same bucket twice)."""
        from jax.experimental import serialize_executable as _ser

        sig = sig_json(feed_sig)
        eid = _entry_id(program_fp, sig, fetch_names)
        if eid in self.entries:
            return self.entries[eid]
        payload, in_tree, out_tree = _ser.serialize(executable)
        buf = io.BytesIO()
        pickle.dump({"payload": payload, "in_tree": in_tree,
                     "out_tree": out_tree}, buf,
                    protocol=pickle.HIGHEST_PROTOCOL)
        blob = buf.getvalue()
        rel = os.path.join(EXEC_DIR, f"{eid}.bin")
        with open(os.path.join(self.out_dir, rel), "wb") as f:
            f.write(blob)
        entry = {
            "id": eid,
            "program_fp": program_fp,
            "feed_sig": sig,
            "fetch_names": list(fetch_names),
            "state_names": list(state_names),
            "donated_names": list(donated_names),
            "held_names": list(held_names),
            "out_state_names": list(out_state_names),
            "written_names": list(written_names),
            "uses_rng": bool(uses_rng),
            "file": rel,
            "sha256": hashlib.sha256(blob).hexdigest(),
            "nbytes": len(blob),
        }
        self.entries[eid] = entry
        _M_AOT_EXPORT.inc()
        return entry

    def finish(self, extra: Optional[dict] = None) -> str:
        """Write MANIFEST.json; returns its path."""
        doc = {
            "schema": SCHEMA,
            "env": environment_fingerprint(self.backend),
            "tuning_db": tuning_db_digest(),
            "flags": flags_fingerprint(),
            "entries": sorted(self.entries.values(),
                              key=lambda e: e["id"]),
        }
        if extra:
            doc.update(extra)
        path = os.path.join(self.out_dir, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path


class ArtifactStore:
    """Read side of an artifact directory.

    Store-level pins (schema, versions, device, tuning DB, flags) are
    validated once at open; a mismatch poisons the store — every lookup
    then counts its ``rejected_<reason>`` and falls back to JIT.
    Entry-level problems (unknown fingerprint, missing bucket, corrupt
    payload, donation drift) reject per lookup.  ``results`` mirrors
    the global ``aot_load_total`` series for this store instance, so
    tests and the CLI can assert without diffing process metrics."""

    def __init__(self, root: str):
        self.root = root
        self.poisoned: Optional[str] = None
        self.entries: Dict[Tuple[str, str, Tuple[str, ...]], dict] = {}
        self.fingerprints: set = set()
        self.results: Dict[str, int] = {}
        self.manifest: Optional[dict] = None
        self._warned: set = set()
        try:
            with open(os.path.join(root, MANIFEST_NAME)) as f:
                self.manifest = json.load(f)
        except Exception as exc:
            self.poisoned = "corrupt"
            self._warn(f"unreadable manifest ({exc}); serving will JIT")
            return
        self.poisoned = self._validate(self.manifest)
        if self.poisoned is not None:
            return
        for e in self.manifest.get("entries", ()):
            key = (e["program_fp"], e["feed_sig"],
                   tuple(e["fetch_names"]))
            self.entries[key] = e
            self.fingerprints.add(e["program_fp"])

    # -- validation ---------------------------------------------------------

    def _validate(self, doc: dict) -> Optional[str]:
        if doc.get("schema") != SCHEMA:
            self._warn(f"schema {doc.get('schema')!r} != {SCHEMA!r}")
            return "schema"
        env, here = doc.get("env", {}), environment_fingerprint()
        for k in ("jax", "jaxlib"):
            if env.get(k) != here[k]:
                self._warn(f"{k} {env.get(k)!r} != running {here[k]!r}")
                return "version"
        for k in ("platform", "device_kind"):
            if env.get(k) != here[k]:
                self._warn(f"{k} {env.get(k)!r} != running {here[k]!r}")
                return "device"
        if doc.get("tuning_db") != tuning_db_digest():
            self._warn("tuning DB drifted since export (re-run "
                       "`paddle compile` after `paddle tune`)")
            return "tuning_db"
        if doc.get("flags") != flags_fingerprint():
            self._warn(f"compile-context flags {doc.get('flags')!r} != "
                       f"running {flags_fingerprint()!r}")
            return "flags"
        return None

    def _warn(self, msg: str) -> None:
        if msg in self._warned:
            return
        self._warned.add(msg)
        print(f"[paddle_tpu.aot] artifact store {self.root}: {msg} "
              "-- falling back to JIT compilation", file=sys.stderr)

    def _count(self, result: str) -> None:
        self.results[result] = self.results.get(result, 0) + 1
        _M_AOT_LOAD.inc(result=result)

    # -- lookup -------------------------------------------------------------

    def lookup(self, program_fp: str, sig: str, fetch_names,
               validate=None):
        """Return ``(meta, loaded_executable)`` for a manifest match, or
        ``None`` (after counting the rejection reason).  ``validate``
        is an optional ``meta -> reason-or-None`` hook run before the
        payload is touched — the executor uses it to re-prove the
        donation mask."""
        if self.poisoned is not None:
            self._count(f"rejected_{self.poisoned}")
            return None
        meta = self.entries.get((program_fp, sig, tuple(fetch_names)))
        if meta is None:
            if program_fp in self.fingerprints:
                # the program is known but this (bucket, fetch) combo
                # was never exported — likely a wider serve ladder
                self._warn(f"no entry for bucket sig {sig} "
                           f"(program {program_fp[:12]})")
                self._count("rejected_bucket")
            else:
                self._warn(f"program fingerprint {program_fp[:12]} not "
                           "in manifest (model or optimizer drifted "
                           "since export)")
                self._count("rejected_fingerprint")
            return None
        if validate is not None:
            reason = validate(meta)
            if reason is not None:
                self._warn(f"entry {meta['id']}: {reason}")
                self._count(f"rejected_{reason.split(':')[0]}")
                return None
        loaded = self._deserialize(meta)
        if loaded is None:
            return None
        self._count("loaded")
        return meta, loaded

    def _deserialize(self, meta: dict):
        from jax.experimental import serialize_executable as _ser

        path = os.path.join(self.root, meta["file"])
        try:
            with open(path, "rb") as f:
                blob = f.read()
            if hashlib.sha256(blob).hexdigest() != meta["sha256"]:
                raise ValueError("payload sha256 mismatch (truncated or "
                                 "corrupt executable file)")
            doc = pickle.loads(blob)
            return _ser.deserialize_and_load(
                doc["payload"], doc["in_tree"], doc["out_tree"])
        except Exception as exc:
            self._warn(f"entry {meta['id']}: {type(exc).__name__}: {exc}")
            self._count("rejected_corrupt")
            return None

    # -- introspection ------------------------------------------------------

    def info(self) -> dict:
        return {
            "root": self.root,
            "poisoned": self.poisoned,
            "entries": len(self.entries),
            "results": dict(self.results),
        }
