"""Process-level flag registry (reference: the gflags tier —
paddle/utils/Flags.cpp:18-39 declares ~40 flags like use_gpu,
trainer_count, log_period, seed; Python initialized them via
init_gflags, pybind/pybind.cc:441)."""

from __future__ import annotations

import os
from typing import Any, Dict


class _Flags:
    def __init__(self):
        self._defs: Dict[str, tuple] = {}   # name -> (default, type, help)
        self._vals: Dict[str, Any] = {}

    def define(self, name: str, default, help: str = ""):
        self._defs[name] = (default, type(default), help)

    def set(self, name: str, value):
        if name in self._defs:
            _, t, _ = self._defs[name]
            if t is bool and isinstance(value, str):
                value = value.lower() in ("1", "true", "yes")
            else:
                value = t(value)
        self._vals[name] = value

    def get(self, name: str, default=None):
        if name in self._vals:
            return self._vals[name]
        if name in self._defs:
            return self._defs[name][0]
        return default

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)


FLAGS = _Flags()

# the reference's commonly used flags (utils/Flags.cpp), same defaults
FLAGS.define("use_gpu", False, "kept for surface parity; XLA picks devices")
FLAGS.define("trainer_count", 1, "data-parallel replica count")
FLAGS.define("seed", 1, "RNG seed (0 = nondeterministic)")
FLAGS.define("log_period", 100, "batches between log lines")
FLAGS.define("show_layer_stat", False, "dump per-layer timing each pass")
FLAGS.define("save_dir", "", "checkpoint directory")
FLAGS.define("num_passes", 1, "training passes")
FLAGS.define("parallel_nn", False, "model-parallel layer placement")
FLAGS.define("port", 20134, "pserver base port")
FLAGS.define("num_gradient_servers", 1, "sync-SGD barrier width")
# TPU-era addition: run the static verifier (paddle_tpu/analysis) over a
# program on every compile-cache miss, turning mid-trace KeyErrors into
# structured diagnostics before any XLA work.  The PADDLE_CHECK_PROGRAM
# env var seeds the default so the gate works without touching code.
FLAGS.define("check_program",
             os.environ.get("PADDLE_CHECK_PROGRAM", "").lower()
             in ("1", "true", "yes"),
             "verify programs before compiling (error-tier analysis passes)")
# TPU-era addition: per-op trace spans (paddle_tpu/observability).  With
# trace_ops=1 the executor wraps each op's lowering in jax.named_scope
# + jax.profiler.TraceAnnotation so xprof traces name ops instead of
# anonymous XLA regions.  Flipping it retraces (part of the compile
# cache key); seeded from PADDLE_TRACE_OPS so profiling runs need no
# code change.
FLAGS.define("trace_ops",
             os.environ.get("PADDLE_TRACE_OPS", "").lower()
             in ("1", "true", "yes"),
             "name each op in device traces (named_scope/TraceAnnotation)")


def init_gflags(argv):
    """Parse --k=v strings (reference: init_gflags, pybind.cc:441)."""
    rest = []
    for a in argv:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            FLAGS.set(k, v)
        else:
            rest.append(a)
    return rest


def init_from_env(prefix: str = "PADDLE_"):
    for k, v in os.environ.items():
        if k.startswith(prefix):
            FLAGS.set(k[len(prefix):].lower(), v)
