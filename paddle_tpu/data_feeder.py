"""DataFeeder: minibatch rows -> feed dict (reference:
python/paddle/v2/fluid/data_feeder.py + py_paddle numpy converters).

For LoD inputs (lod_level > 0) the feeder packs per-example ragged rows
into one dense array + offset vector, optionally padding the total row
count to a bucket size so compiled shapes are reused across batches
(the TPU answer to the reference's no-padding LoD batching)."""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from paddle_tpu.framework import Variable
from paddle_tpu.lod import LoDArray, create_lod_array


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


class DataFeeder:
    def __init__(self, feed_list: Sequence[Variable], place=None,
                 lod_bucket: int = 128):
        self.feed_list = list(feed_list)
        self.place = place
        self.lod_bucket = lod_bucket

    def feed(self, minibatch: Sequence[Sequence]) -> Dict[str, object]:
        """minibatch: list of examples, each a tuple aligned with feed_list."""
        out: Dict[str, object] = {}
        for i, var in enumerate(self.feed_list):
            column = [row[i] for row in minibatch]
            if var.lod_level > 0:
                out[var.name] = self._pack_lod(column, var)
            else:
                arr = np.asarray(column)
                if arr.ndim == 1:
                    # a column of scalars feeds a (batch, 1) variable
                    arr = arr.reshape(-1, 1)
                out[var.name] = arr.astype(_np_dtype(var.dtype))
        return out

    def _pack_lod(self, column: List, var: Variable) -> LoDArray:
        seqs = [np.asarray(s) for s in column]
        lens = [s.shape[0] for s in seqs]
        total = sum(lens)
        padded_total = _round_up(max(total, 1), self.lod_bucket)
        feat_shape = seqs[0].shape[1:]
        dtype = _np_dtype(var.dtype)
        data = np.zeros((padded_total,) + tuple(feat_shape), dtype=dtype)
        off = 0
        offsets = [0]
        for s in seqs:
            data[off: off + s.shape[0]] = s
            off += s.shape[0]
            offsets.append(off)
        if var.dtype in ("int64", "int32") and data.ndim == 1:
            data = data.reshape(-1, 1)
        return create_lod_array(data, [offsets])


def _np_dtype(name: str):
    import jax.numpy as jnp

    return {"bfloat16": jnp.bfloat16}.get(name, np.dtype(name))
