"""Model persistence (reference: python/paddle/v2/fluid/io.py —
save/load_persistables:81, save/load_inference_model:165-224; tensor
serialization: operators/save_op.cc).

Checkpoints are directories of ``.npz`` per-variable files plus a JSON
manifest; ``save_inference_model`` stores the pruned program alongside.
(A sharded TensorStore/orbax path is the scaling follow-up.)
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

import numpy as np

from paddle_tpu import framework
from paddle_tpu.executor import Executor, global_scope
from paddle_tpu.framework import Parameter, Program, Variable

_FORMAT_VERSION = 1


def _is_persistable(var: Variable) -> bool:
    return var.persistable


def _is_parameter(var: Variable) -> bool:
    return isinstance(var, Parameter)


_TENSOR_MAGIC = b"PTPU"
_TENSOR_VERSION = 0


def serialize_tensor_bytes(arr) -> bytes:
    """Single-tensor file format (reference analog: the version-headered
    format of operators/save_op.cc / doc/design/model_format.md):
    magic, uint32 version, dtype-name, dims, raw little-endian data."""
    import struct

    arr = np.ascontiguousarray(np.asarray(arr))
    dt = arr.dtype.name.encode()
    head = _TENSOR_MAGIC + struct.pack("<I", _TENSOR_VERSION)
    head += struct.pack("<H", len(dt)) + dt
    head += struct.pack("<I", arr.ndim) + struct.pack(
        f"<{arr.ndim}q", *arr.shape)
    return head + arr.tobytes()


def deserialize_tensor_bytes(buf: bytes) -> np.ndarray:
    import struct

    if buf[:4] != _TENSOR_MAGIC:
        raise ValueError("not a paddle_tpu tensor file")
    off = 4
    (version,) = struct.unpack_from("<I", buf, off); off += 4
    if version != _TENSOR_VERSION:
        raise ValueError(f"unsupported tensor format version {version}")
    (dtlen,) = struct.unpack_from("<H", buf, off); off += 2
    dtype = np.dtype(buf[off:off + dtlen].decode()); off += dtlen
    (ndim,) = struct.unpack_from("<I", buf, off); off += 4
    dims = struct.unpack_from(f"<{ndim}q", buf, off); off += 8 * ndim
    return np.frombuffer(buf, dtype=dtype, offset=off).reshape(dims).copy()


def save_vars(executor, dirname: str, main_program: Optional[Program] = None,
              predicate=_is_persistable, vars=None):
    main_program = main_program or framework.default_main_program()
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    if vars is None:
        vars = [v for v in main_program.global_block().vars.values() if predicate(v)]
    manifest = {"format_version": _FORMAT_VERSION, "vars": {}}
    for v in vars:
        val = scope.get(v.name)
        if val is None:
            continue
        arr = np.asarray(val)
        np.save(os.path.join(dirname, v.name + ".npy"), arr, allow_pickle=False)
        manifest["vars"][v.name] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
    with open(os.path.join(dirname, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=1)


def load_vars(executor, dirname: str, main_program: Optional[Program] = None,
              predicate=_is_persistable, vars=None):
    main_program = main_program or framework.default_main_program()
    scope = global_scope()
    if vars is None:
        vars = [v for v in main_program.global_block().vars.values() if predicate(v)]
    for v in vars:
        path = os.path.join(dirname, v.name + ".npy")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no saved value for variable {v.name!r} in {dirname}")
        scope.set(v.name, np.load(path))


def save_params(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, predicate=_is_parameter)


def load_params(executor, dirname, main_program=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter)


def save_persistables(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable)


def load_persistables(executor, dirname, main_program=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable)


def get_inference_program(target_vars, main_program: Optional[Program] = None) -> Program:
    """Prune the program to the given targets and flip it to inference
    mode (reference: fluid/io.py:154 get_inference_program =
    ``prune(targets)`` + ``inference_optimize()``; here the test flip is
    ``clone(for_test=True)``, which also strips training-only ops)."""
    main_program = main_program or framework.default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    return main_program.clone(for_test=True).prune(list(target_vars))


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable], executor,
                         main_program: Optional[Program] = None):
    """Prune to the inference slice and save program + params
    (reference: fluid/io.py:165 + framework/prune.cc)."""
    main_program = main_program or framework.default_main_program()
    os.makedirs(dirname, exist_ok=True)
    inference_program = get_inference_program(list(target_vars), main_program)
    with open(os.path.join(dirname, "__model__.json"), "w") as f:
        json.dump({
            "program": inference_program.to_dict(),
            "feed_names": list(feeded_var_names),
            "fetch_names": [v.name if isinstance(v, Variable) else v for v in target_vars],
        }, f, default=str)
    save_params(executor, dirname, main_program)
    return inference_program


def read_inference_export(dirname: str):
    """Parse a ``save_inference_model`` directory without touching any
    scope: ``(program, feed_names, fetch_names, param_names)``.  The
    single reader of the export layout — ``load_inference_model`` and
    the serving engine's per-replica param loads both go through it."""
    with open(os.path.join(dirname, "__model__.json")) as f:
        meta = json.load(f)
    program = _program_from_dict(meta["program"])
    manifest_path = os.path.join(dirname, "MANIFEST.json")
    param_names = []
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            param_names = list(json.load(f)["vars"])
    return program, meta["feed_names"], meta["fetch_names"], param_names


def load_exported_param(dirname: str, name: str) -> np.ndarray:
    """One parameter from a ``save_inference_model`` export."""
    return np.load(os.path.join(dirname, name + ".npy"))


def load_inference_model(dirname: str, executor, scope=None):
    program, feed_names, fetch_names, param_names = \
        read_inference_export(dirname)
    scope = scope if scope is not None else global_scope()
    for name in param_names:
        scope.set(name, load_exported_param(dirname, name))
    return program, feed_names, fetch_names


def _program_from_dict(d) -> Program:
    # implementation moved to framework.Program.from_dict so the lint
    # CLI and analysis passes can load programs without importing io
    return Program.from_dict(d)


# ---------------------------------------------------------------------------
# Sharded checkpoints (TPU-native): orbax/TensorStore-backed saves of the
# whole persistable state.  This is the pod-scale replacement for the
# reference's per-pass parameter dirs + pserver gob checkpoints
# (trainer/ParamUtil.h saveParameters; go/pserver/service.go:119-174):
# each host writes only its shards, restore re-shards to the current
# mesh (SURVEY §2.5 "checkpoint via TensorStore-style sharded saves").
# ---------------------------------------------------------------------------


def _step_dir(dirname, step) -> str:
    return os.path.join(os.path.abspath(dirname), f"step_{int(step)}")


def _marker_path(step_path: str) -> str:
    # sibling file, not a file inside the orbax directory (orbax treats
    # every entry under the step dir as part of the checkpoint tree)
    return step_path + ".complete"


def checkpoint_complete(dirname, step) -> bool:
    """True when step_N was fully written (its commit marker exists)."""
    return os.path.exists(_marker_path(_step_dir(dirname, step)))


def save_state_tree(dirname, step, state, max_to_keep=None):
    """Save an arbitrary pytree (dict of arrays) as step_N under
    ``dirname`` with orbax, then commit it by writing a ``step_N.complete``
    marker — readers (``latest_checkpoint_step``) only see marked steps,
    so a crash mid-write can never surface a half-checkpoint.

    ``max_to_keep`` prunes the oldest *complete* steps beyond the newest
    N (the reference kept the last few pass-%05d dirs by hand); the step
    just written always survives.  Returns the step path.
    """
    import orbax.checkpoint as ocp

    path = _step_dir(dirname, step)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, state, force=True)
    with open(_marker_path(path), "w") as f:
        f.write(f"{int(step)}\n")
    if max_to_keep:
        prune_checkpoints(dirname, max_to_keep)
    return path


def load_state_tree(dirname, step):
    """Restore the pytree saved by :func:`save_state_tree`."""
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer().restore(_step_dir(dirname, step))


def prune_checkpoints(dirname, max_to_keep):
    """Delete all but the newest ``max_to_keep`` *complete* step_N
    checkpoints.  Incomplete (unmarked) dirs are crash leftovers and are
    removed too once older than the newest complete step.  Returns the
    pruned step numbers."""
    import shutil

    if not os.path.isdir(dirname) or max_to_keep is None:
        return []
    complete, incomplete = [], []
    for d in os.listdir(dirname):
        if d.startswith("step_") and d[5:].isdigit():
            step = int(d[5:])
            (complete if checkpoint_complete(dirname, step)
             else incomplete).append(step)
    complete.sort()
    doomed = complete[:-int(max_to_keep)] if max_to_keep > 0 else []
    newest = complete[-1] if complete else None
    doomed += [s for s in incomplete if newest is not None and s < newest]
    for step in doomed:
        path = _step_dir(dirname, step)
        # marker first: a partially-deleted checkpoint must read as
        # incomplete, never as the latest valid step
        try:
            os.remove(_marker_path(path))
        except FileNotFoundError:
            pass
        shutil.rmtree(path, ignore_errors=True)
    return sorted(doomed)


def _collect_persistable_state(main_program, scope):
    state = {}
    for var in main_program.global_block().vars.values():
        if getattr(var, "persistable", False):
            holder = scope.find_var(var.name)
            if holder is not None:
                v = holder.get_tensor()
                if v is not None:
                    state[var.name] = np.asarray(v)
    return state


def save_checkpoint(dirname, executor=None, main_program=None, step=None,
                    scope=None, max_to_keep=None):
    """Save every persistable var (params + optimizer state) with orbax.
    ``step`` appends /step_N (the pass-%05d analog) committed atomically
    via a ``step_N.complete`` marker, and ``max_to_keep`` bounds on-disk
    retention (oldest complete steps pruned).  Returns the path."""
    import orbax.checkpoint as ocp

    main_program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    state = _collect_persistable_state(main_program, scope)
    if step is not None:
        return save_state_tree(dirname, step, state, max_to_keep=max_to_keep)
    path = os.path.abspath(dirname)
    ocp.PyTreeCheckpointer().save(path, state, force=True)
    return path


def load_checkpoint(dirname, executor=None, main_program=None, step=None,
                    scope=None):
    """Restore persistable vars saved by save_checkpoint into the scope;
    returns the list of restored names."""
    import orbax.checkpoint as ocp

    scope = scope or global_scope()
    path = os.path.abspath(dirname)
    if step is not None:
        path = _step_dir(dirname, step)
    ckptr = ocp.PyTreeCheckpointer()
    state = ckptr.restore(path)
    for name, value in state.items():
        scope.set(name, np.asarray(value))
    return sorted(state)


def latest_checkpoint_step(dirname):
    """Highest *complete* step_N under dirname, or None (resume
    discovery).  Steps without their ``step_N.complete`` marker are
    in-progress or torn writes and are never returned."""
    if not os.path.isdir(dirname):
        return None
    steps = [int(d[5:]) for d in os.listdir(dirname)
             if d.startswith("step_") and d[5:].isdigit()
             and checkpoint_complete(dirname, int(d[5:]))]
    return max(steps) if steps else None
