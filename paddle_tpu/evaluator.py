"""Stateful evaluators (reference: python/paddle/v2/fluid/evaluator.py —
Accuracy/ChunkEvaluator as state-accumulating sub-programs).  Here the
state lives host-side: metrics ops run in-graph per batch and the
evaluator accumulates numpy scalars between ``reset``s."""

from __future__ import annotations

import numpy as np

from paddle_tpu import layers
from paddle_tpu.layer_helper import LayerHelper


class Evaluator:
    def reset(self):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(Evaluator):
    """accuracy = accumulated correct / accumulated total."""

    def __init__(self, input, label, k: int = 1, **kwargs):
        helper = LayerHelper("accuracy_eval", **kwargs)
        vals, idx = layers.topk(input, k=k)
        self._acc = helper.create_tmp_variable("float32", (1,))
        self._correct = helper.create_tmp_variable("int32", ())
        self._total = helper.create_tmp_variable("int32", ())
        helper.append_op(
            type="accuracy",
            inputs={"Out": [vals], "Indices": [idx], "Label": [label]},
            outputs={"Accuracy": [self._acc], "Correct": [self._correct],
                     "Total": [self._total]},
        )
        self.reset()

    @property
    def metrics(self):
        """Fetch targets to pass to executor.run."""
        return [self._acc, self._correct, self._total]

    def update(self, correct, total):
        self._c += int(np.asarray(correct))
        self._t += int(np.asarray(total))

    def reset(self, executor=None):
        self._c = 0
        self._t = 0

    def eval(self, executor=None):
        return self._c / max(self._t, 1)
