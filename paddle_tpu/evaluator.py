"""Stateful evaluators (reference: python/paddle/v2/fluid/evaluator.py —
Accuracy/ChunkEvaluator as state-accumulating sub-programs).  Here the
state lives host-side: metrics ops run in-graph per batch and the
evaluator accumulates numpy scalars between ``reset``s."""

from __future__ import annotations

import numpy as np

from paddle_tpu import layers
from paddle_tpu.layer_helper import LayerHelper


class Evaluator:
    def reset(self):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(Evaluator):
    """accuracy = accumulated correct / accumulated total."""

    def __init__(self, input, label, k: int = 1, **kwargs):
        helper = LayerHelper("accuracy_eval", **kwargs)
        vals, idx = layers.topk(input, k=k)
        self._acc = helper.create_tmp_variable("float32", (1,))
        self._correct = helper.create_tmp_variable("int32", ())
        self._total = helper.create_tmp_variable("int32", ())
        helper.append_op(
            type="accuracy",
            inputs={"Out": [vals], "Indices": [idx], "Label": [label]},
            outputs={"Accuracy": [self._acc], "Correct": [self._correct],
                     "Total": [self._total]},
        )
        self.reset()

    @property
    def metrics(self):
        """Fetch targets to pass to executor.run."""
        return [self._acc, self._correct, self._total]

    def update(self, correct, total):
        self._c += int(np.asarray(correct))
        self._t += int(np.asarray(total))

    def reset(self, executor=None):
        self._c = 0
        self._t = 0

    def eval(self, executor=None):
        return self._c / max(self._t, 1)


class DetectionMAP(Evaluator):
    """Mean average precision over accumulated detections (reference:
    gserver/evaluators/DetectionMAPEvaluator.cpp — 11point/integral AP).

    Host-side accumulator: feed it the padded ``multiclass_nms`` output
    (rows [label, score, x1, y1, x2, y2], label -1 = pad) and the padded
    ground truth per batch via :meth:`update`.
    """

    def __init__(self, overlap_threshold: float = 0.5,
                 ap_version: str = "integral", background_label: int = 0):
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.background_label = background_label
        self.reset()

    def reset(self, executor=None):
        self._dets = []   # (img_id, label, score, box)
        self._gts = []    # (img_id, label, box)
        self._img = 0

    @staticmethod
    def _iou(a, b):
        lt = np.maximum(a[:2], b[:2])
        rb = np.minimum(a[2:], b[2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[0] * wh[1]
        ua = (max(a[2] - a[0], 0) * max(a[3] - a[1], 0)
              + max(b[2] - b[0], 0) * max(b[3] - b[1], 0) - inter)
        return inter / max(ua, 1e-10)

    def update(self, nms_out, gt_boxes, gt_labels):
        """nms_out (B, K, 6); gt_boxes (B, G, 4); gt_labels (B, G),
        -1 padded."""
        nms_out = np.asarray(nms_out)
        gt_boxes = np.asarray(gt_boxes)
        gt_labels = np.asarray(gt_labels)
        for b in range(nms_out.shape[0]):
            img = self._img
            self._img += 1
            for row in nms_out[b]:
                if row[0] >= 0:
                    self._dets.append((img, int(row[0]), float(row[1]),
                                       row[2:6].copy()))
            for g in range(gt_boxes.shape[1]):
                if gt_labels[b, g] >= 0:
                    self._gts.append((img, int(gt_labels[b, g]),
                                      gt_boxes[b, g].copy()))

    def eval(self, executor=None):
        classes = sorted({g[1] for g in self._gts})
        aps = []
        for c in classes:
            if c == self.background_label:
                continue
            gts = [(i, box) for i, lab, box in self._gts if lab == c]
            dets = sorted((d for d in self._dets if d[1] == c),
                          key=lambda d: -d[2])
            npos = len(gts)
            if npos == 0:
                continue
            used = set()
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            for k, (img, _, score, box) in enumerate(dets):
                # VOC semantics (DetectionMAPEvaluator.cpp): match the
                # argmax-IoU GT; if it's below threshold OR already
                # claimed by a higher-scoring det, this det is a FP —
                # it does NOT fall through to the next-best GT.
                best_j, best_ov = -1, 0.0
                for j, (gi, g) in enumerate(gts):
                    if gi != img:
                        continue
                    ov = self._iou(box, g)
                    if ov > best_ov:
                        best_j, best_ov = j, ov
                if (best_j >= 0 and best_ov >= self.overlap_threshold
                        and best_j not in used):
                    used.add(best_j)
                    tp[k] = 1
                else:
                    fp[k] = 1
            ctp = np.cumsum(tp)
            cfp = np.cumsum(fp)
            recall = ctp / npos
            precision = ctp / np.maximum(ctp + cfp, 1e-10)
            if self.ap_version == "11point":
                ap = float(np.mean([
                    max([p for r, p in zip(recall, precision) if r >= t],
                        default=0.0)
                    for t in np.linspace(0, 1, 11)]))
            else:  # integral
                ap = 0.0
                prev_r = 0.0
                for r, p in zip(recall, precision):
                    ap += (r - prev_r) * p
                    prev_r = r
                ap = float(ap)
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0


def edit_distance(a, b) -> int:
    """Levenshtein distance (host-side helper for CTC error rates)."""
    a, b = list(a), list(b)
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


class CTCError(Evaluator):
    """Sequence error rate = total edit distance / total label length
    (reference: gserver/evaluators/CTCErrorEvaluator.cpp).  Feed it
    decoded id sequences + references via :meth:`update`."""

    def __init__(self):
        self.reset()

    def reset(self, executor=None):
        self._dist = 0
        self._len = 0
        self._seq_errors = 0
        self._seqs = 0

    def update(self, decoded, references):
        for d, r in zip(decoded, references):
            dist = edit_distance(d, r)
            self._dist += dist
            self._len += max(len(r), 1)
            self._seqs += 1
            self._seq_errors += int(dist > 0)

    def eval(self, executor=None):
        return self._dist / max(self._len, 1)

    def sequence_error_rate(self):
        return self._seq_errors / max(self._seqs, 1)
