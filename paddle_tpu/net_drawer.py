"""Program visualization (reference: python/paddle/v2/fluid/net_drawer.py
— graphviz rendering of the op graph).  Emits DOT text; rendering is the
caller's concern (graphviz isn't a runtime dependency)."""

from __future__ import annotations

from paddle_tpu import framework


def draw_graph(program=None, block_idx: int = 0, name: str = "program"):
    """-> DOT source for one block: op nodes (box) + var nodes (ellipse,
    parameters shaded), edges input->op->output."""
    program = program or framework.default_main_program()
    block = program.blocks[block_idx]
    lines = [f"digraph {name} {{", "  rankdir=TB;"]
    seen_vars = set()

    def var_node(v):
        if v in seen_vars:
            return
        seen_vars.add(v)
        var = block.find_var(v)
        is_param = var is not None and isinstance(var, framework.Parameter)
        style = ' style=filled fillcolor="lightgrey"' if is_param else ""
        shape = ""
        if var is not None and var.shape is not None:
            shape = " " + "x".join(str(s) for s in var.shape)
        lines.append(f'  "{v}" [shape=ellipse label="{v}{shape}"{style}];')

    for i, op in enumerate(block.ops):
        op_id = f"op{i}_{op.type}"
        lines.append(f'  "{op_id}" [shape=box label="{op.type}" '
                     'style=filled fillcolor="lightblue"];')
        for v in op.input_arg_names:
            var_node(v)
            lines.append(f'  "{v}" -> "{op_id}";')
        for v in op.output_arg_names:
            var_node(v)
            lines.append(f'  "{op_id}" -> "{v}";')
    lines.append("}")
    return "\n".join(lines)


def save_graph(path: str, program=None, block_idx: int = 0):
    dot = draw_graph(program, block_idx)
    with open(path, "w") as f:
        f.write(dot)
    return path
