"""Default-scope helpers (reference:
python/paddle/v2/fluid/default_scope_funcs.py — a thread-local scope
stack with enter/leave and var lookup in the innermost scope)."""

from __future__ import annotations

import threading

from paddle_tpu.executor import Scope, global_scope

__all__ = ["get_cur_scope", "enter_local_scope", "leave_local_scope",
           "var", "find_var", "scoped_function"]

_local = threading.local()


def _stack():
    if not hasattr(_local, "stack"):
        _local.stack = [global_scope()]
    return _local.stack


def get_cur_scope() -> Scope:
    return _stack()[-1]


def enter_local_scope() -> Scope:
    s = get_cur_scope().new_scope()
    _stack().append(s)
    return s


def leave_local_scope():
    stack = _stack()
    if len(stack) > 1:
        stack.pop()


def var(name: str):
    return get_cur_scope().var(name)


def find_var(name: str):
    return get_cur_scope().find_var(name)


def scoped_function(fn):
    """Run ``fn`` inside a fresh local scope (decorator or direct)."""
    def wrapper(*a, **k):
        enter_local_scope()
        try:
            return fn(*a, **k)
        finally:
            leave_local_scope()

    wrapper.__name__ = getattr(fn, "__name__", "scoped")
    return wrapper
