"""Timer/stat registry (reference: paddle/utils/Stat.h:63-233 —
REGISTER_TIMER/REGISTER_TIMER_INFO accumulate into a global StatSet
printed per N batches / per pass; enabled with WITH_TIMER).

Host-side timers measure the interpreter/driver path (data feed, feed
conversion, dispatch); device time belongs to jax.profiler
(paddle_tpu.profiler) — same split as the reference's Stat vs nvprof.

Kept as the reference-compatible surface; the general-purpose metrics
layer (labels, histograms, Prometheus exposition) lives in
``paddle_tpu.observability``, whose table formatter this module's
``print_status`` delegates to.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Dict


class StatItem:
    __slots__ = ("total", "count", "max")

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def add(self, dt: float):
        self.total += dt
        self.count += 1
        if dt > self.max:
            self.max = dt


class StatSet:
    def __init__(self, name: str = "GlobalStatInfo"):
        self.name = name
        self._items: Dict[str, StatItem] = {}
        self._lock = threading.Lock()

    def add(self, key: str, dt: float):
        with self._lock:
            self._items.setdefault(key, StatItem()).add(dt)

    def reset(self):
        with self._lock:
            self._items.clear()

    def items(self):
        with self._lock:
            return dict(self._items)

    def print_status(self, out=None):
        """The per-pass dump (Stat.h printAllStatus, via the shared
        observability table formatter)."""
        import sys

        from paddle_tpu.observability.metrics import format_table

        out = out or sys.stderr
        rows = [
            (key, f"{it.total * 1e3:.2f}",
             f"{it.total / max(it.count, 1) * 1e3:.3f}",
             f"{it.max * 1e3:.3f}", str(it.count))
            for key, it in sorted(self.items().items(),
                                  key=lambda kv: -kv[1].total)
        ]
        print(f"======= StatSet: [{self.name}] =======", file=out)
        print(format_table(rows, headers=("timer", "total_ms", "avg_ms",
                                          "max_ms", "count")), file=out)


GLOBAL_STATS = StatSet()


@contextlib.contextmanager
def timer(name: str, stats: StatSet = None):
    """``with stat.timer("forwardBackward"):`` — REGISTER_TIMER."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        (stats or GLOBAL_STATS).add(name, time.perf_counter() - t0)


def timed(name: str, stats: StatSet = None):
    """Decorator form."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with timer(name, stats):
                return fn(*a, **k)

        return wrapper

    return deco
