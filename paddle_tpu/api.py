"""SWIG-api compatibility surface.

Reference: paddle/api/PaddleAPI.h:103-546 — the `swig_paddle` module the
v2 Python API was built on: `initPaddle`, `Matrix`/`Vector`,
`Arguments`, `GradientMachine` (createFromConfigProto / forward /
forwardBackward / getParameters), `ParameterUpdater`, and
`SequenceGenerator`.  The v2 facade here runs natively on the fluid
core, so these classes are thin adapters kept for programs written
against the SWIG layer; numpy replaces the Matrix/Vector buffer types
exactly as py_paddle's converters did
(paddle/py_paddle/dataprovider_converter.py).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def initPaddle(*args):
    """swig_paddle.initPaddle('--use_gpu=false', ...) — flag strings are
    accepted and recorded; device selection is XLA's."""
    from paddle_tpu import flags as _flags

    for a in args:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            _flags.FLAGS.set(k, v)


class Arguments:
    """Positional in/out slots (reference: api/PaddleAPI.h Arguments +
    paddle/parameter/Argument.h).  Values are numpy arrays; sequence
    slots carry (value, lengths)."""

    def __init__(self, n: int):
        self._vals: List[Optional[np.ndarray]] = [None] * n
        self._lens: List[Optional[np.ndarray]] = [None] * n

    @staticmethod
    def createArguments(n: int) -> "Arguments":
        return Arguments(n)

    def getSlotNum(self) -> int:
        return len(self._vals)

    def resize(self, n: int):
        self._vals = (self._vals + [None] * n)[:n]
        self._lens = (self._lens + [None] * n)[:n]

    def setSlotValue(self, i: int, value):
        self._vals[i] = np.asarray(value)

    def getSlotValue(self, i: int):
        return self._vals[i]

    def setSlotIds(self, i: int, ids):
        self._vals[i] = np.asarray(ids, np.int64)

    def getSlotIds(self, i: int):
        return self._vals[i]

    def setSlotSequenceStartPositions(self, i: int, lens):
        self._lens[i] = np.asarray(lens, np.int32)

    def getSlotSequenceStartPositions(self, i: int):
        return self._lens[i]


class GradientMachine:
    """Forward/backward engine over a v2 Topology (reference:
    api/GradientMachine.cpp over gserver GradientMachine::create)."""

    def __init__(self, cost_or_outputs, parameters=None, is_test=False):
        from paddle_tpu.v2 import parameters as v2p
        from paddle_tpu.v2.topology import Topology
        from paddle_tpu.v2.layer import LayerOutput

        outs = (cost_or_outputs if isinstance(cost_or_outputs, (list, tuple))
                else [cost_or_outputs])
        self._output_layers = list(outs)
        if is_test:
            self.topology = Topology(cost=None, output_layers=self._output_layers,
                                     is_test=True)
            self.parameters = parameters
        else:
            self.topology = Topology(outs[0])
            self.parameters = parameters or v2p.Parameters(self.topology)
        from paddle_tpu.executor import Executor
        from paddle_tpu.framework import TPUPlace
        from paddle_tpu import backward as backward_mod
        from paddle_tpu import framework

        self._exe = Executor(TPUPlace())
        self._grad_names = None
        if not is_test:
            with framework.program_guard(self.topology.main_program,
                                         self.topology.startup_program):
                pgs = backward_mod.append_backward(self.topology.cost_var)
            self._grad_names = [(p.name, g.name) for p, g in pgs]
        self._init()

    @staticmethod
    def createFromConfigProto(conf, *args, **kwargs) -> "GradientMachine":
        """Accepts a parsed v1 TrainerConfig (trainer.config_parser) or
        a cost LayerOutput."""
        cost = getattr(conf, "cost", conf)
        return GradientMachine(cost)

    def _init(self):
        from paddle_tpu import executor as executor_mod

        if self.parameters is not None:
            with executor_mod.scope_guard(self.parameters.scope):
                self._exe.run(self.topology.startup_program)

    def _feed_from_args(self, in_args: Arguments):
        feed = {}
        for i, (name, t) in enumerate(self.topology.feed_types):
            v = in_args.getSlotValue(i)
            if v is None:
                raise ValueError(f"slot {i} ({name}) not set")
            feed[name] = v
            lens = in_args.getSlotSequenceStartPositions(i)
            if lens is not None:
                feed[name + "@len"] = lens
        return feed

    def forward(self, in_args: Arguments, out_args: Arguments, pass_type=None):
        from paddle_tpu import executor as executor_mod

        prog = self.topology.main_program.clone(for_test=True)
        fetch = self.topology.output_vars
        with executor_mod.scope_guard(self.parameters.scope):
            outs = self._exe.run(prog, feed=self._feed_from_args(in_args),
                                 fetch_list=fetch)
        out_args.resize(len(outs))
        for i, o in enumerate(outs):
            out_args.setSlotValue(i, np.asarray(o))
        return outs

    def forwardBackward(self, in_args: Arguments, out_args: Arguments,
                        pass_type=None):
        """One fwd+bwd; gradients land in scope (param@GRAD) for the
        updater, like the UpdateCallback contract."""
        from paddle_tpu import executor as executor_mod

        assert self._grad_names is not None, "test-mode machine"
        fetch = [self.topology.cost_var] + [g for _, g in self._grad_names]
        with executor_mod.scope_guard(self.parameters.scope):
            outs = self._exe.run(self.topology.main_program,
                                 feed=self._feed_from_args(in_args),
                                 fetch_list=fetch)
        out_args.resize(1)
        out_args.setSlotValue(0, np.asarray(outs[0]))
        self._last_grads = {p: np.asarray(g)
                            for (p, _), g in zip(self._grad_names, outs[1:])}
        return outs[0]

    def getParameters(self):
        return self.parameters

    def getLayerOutputs(self, names):
        raise NotImplementedError(
            "fetch intermediate layers by adding them to output_layers")


class SequenceGenerator:
    """Reference api/PaddleAPI.h:546 — generation driver; adapter over
    paddle_tpu.generation.SequenceGenerator."""

    def __init__(self, beam_gen, parameters):
        from paddle_tpu.generation import SequenceGenerator as _Gen

        self._gen = _Gen(beam_gen, parameters)

    def generate(self, row):
        return self._gen.generate(row)
