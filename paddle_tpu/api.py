"""SWIG-api compatibility surface.

Reference: paddle/api/PaddleAPI.h:103-546 — the `swig_paddle` module the
v2 Python API was built on: `initPaddle`, `Matrix`/`Vector`,
`Arguments`, `GradientMachine` (createFromConfigProto / forward /
forwardBackward / getParameters), `ParameterUpdater`, and
`SequenceGenerator`.  The v2 facade here runs natively on the fluid
core, so these classes are thin adapters kept for programs written
against the SWIG layer; numpy replaces the Matrix/Vector buffer types
exactly as py_paddle's converters did
(paddle/py_paddle/dataprovider_converter.py).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def initPaddle(*args):
    """swig_paddle.initPaddle('--use_gpu=false', ...) — flag strings are
    accepted and recorded; device selection is XLA's."""
    from paddle_tpu import flags as _flags

    for a in args:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            _flags.FLAGS.set(k, v)


class Arguments:
    """Positional in/out slots (reference: api/PaddleAPI.h Arguments +
    paddle/parameter/Argument.h).  Values are numpy arrays; sequence
    slots carry (value, lengths)."""

    def __init__(self, n: int):
        self._vals: List[Optional[np.ndarray]] = [None] * n
        self._lens: List[Optional[np.ndarray]] = [None] * n

    @staticmethod
    def createArguments(n: int) -> "Arguments":
        return Arguments(n)

    def getSlotNum(self) -> int:
        return len(self._vals)

    def resize(self, n: int):
        self._vals = (self._vals + [None] * n)[:n]
        self._lens = (self._lens + [None] * n)[:n]

    def setSlotValue(self, i: int, value):
        self._vals[i] = np.asarray(value)

    def getSlotValue(self, i: int):
        return self._vals[i]

    def setSlotIds(self, i: int, ids):
        self._vals[i] = np.asarray(ids, np.int64)

    def getSlotIds(self, i: int):
        return self._vals[i]

    def setSlotSequenceStartPositions(self, i: int, lens):
        self._lens[i] = np.asarray(lens, np.int32)

    def getSlotSequenceStartPositions(self, i: int):
        return self._lens[i]


class GradientMachine:
    """Forward/backward engine over a v2 Topology (reference:
    api/GradientMachine.cpp over gserver GradientMachine::create)."""

    def __init__(self, cost_or_outputs, parameters=None, is_test=False):
        from paddle_tpu.v2 import parameters as v2p
        from paddle_tpu.v2.topology import Topology
        from paddle_tpu.v2.layer import LayerOutput

        outs = (cost_or_outputs if isinstance(cost_or_outputs, (list, tuple))
                else [cost_or_outputs])
        self._output_layers = list(outs)
        if is_test:
            self.topology = Topology(cost=None, output_layers=self._output_layers,
                                     is_test=True)
            self.parameters = parameters
        else:
            self.topology = Topology(outs[0])
            self.parameters = parameters or v2p.Parameters(self.topology)
        from paddle_tpu.executor import Executor
        from paddle_tpu.framework import TPUPlace
        from paddle_tpu import backward as backward_mod
        from paddle_tpu import framework

        self._exe = Executor(TPUPlace())
        self._grad_names = None
        if not is_test:
            with framework.program_guard(self.topology.main_program,
                                         self.topology.startup_program):
                pgs = backward_mod.append_backward(self.topology.cost_var)
            self._grad_names = [(p.name, g.name) for p, g in pgs]
        self._init()

    @staticmethod
    def createFromConfigProto(conf, *args, **kwargs) -> "GradientMachine":
        """Accepts a parsed v1 TrainerConfig (trainer.config_parser) or
        a cost LayerOutput."""
        cost = getattr(conf, "cost", conf)
        return GradientMachine(cost)

    def _init(self):
        from paddle_tpu import executor as executor_mod

        if self.parameters is not None:
            with executor_mod.scope_guard(self.parameters.scope):
                self._exe.run(self.topology.startup_program)

    def _feed_from_args(self, in_args: Arguments):
        feed = {}
        for i, (name, t) in enumerate(self.topology.feed_types):
            v = in_args.getSlotValue(i)
            if v is None:
                raise ValueError(f"slot {i} ({name}) not set")
            feed[name] = v
            lens = in_args.getSlotSequenceStartPositions(i)
            if lens is not None:
                feed[name + "@len"] = lens
        return feed

    def forward(self, in_args: Arguments, out_args: Arguments, pass_type=None):
        from paddle_tpu import executor as executor_mod

        prog = self.topology.main_program.clone(for_test=True)
        fetch = self.topology.output_vars
        with executor_mod.scope_guard(self.parameters.scope):
            outs = self._exe.run(prog, feed=self._feed_from_args(in_args),
                                 fetch_list=fetch)
        out_args.resize(len(outs))
        for i, o in enumerate(outs):
            out_args.setSlotValue(i, np.asarray(o))
        return outs

    def forwardBackward(self, in_args: Arguments, out_args: Arguments,
                        pass_type=None):
        """One fwd+bwd; gradients land in scope (param@GRAD) for the
        updater, like the UpdateCallback contract."""
        from paddle_tpu import executor as executor_mod

        assert self._grad_names is not None, "test-mode machine"
        fetch = [self.topology.cost_var] + [g for _, g in self._grad_names]
        with executor_mod.scope_guard(self.parameters.scope):
            outs = self._exe.run(self.topology.main_program,
                                 feed=self._feed_from_args(in_args),
                                 fetch_list=fetch)
        out_args.resize(1)
        out_args.setSlotValue(0, np.asarray(outs[0]))
        self._last_grads = {p: np.asarray(g)
                            for (p, _), g in zip(self._grad_names, outs[1:])}
        return outs[0]

    def getParameters(self):
        return self.parameters

    def getParameterSize(self):
        """reference api GradientMachine::getParameterSize."""
        return len(self.parameters.keys())

    def getParameter(self, i):
        """reference api GradientMachine::getParameter — the swig
        Parameter wrapper (defined below) over the i-th parameter."""
        names = self.parameters.keys()
        if not 0 <= i < len(names):
            raise RangeError(i)
        return Parameter(self.parameters, names[i])

    def getLayerOutputs(self, names):
        raise NotImplementedError(
            "fetch intermediate layers by adding them to output_layers")


class SequenceGenerator:
    """Reference api/PaddleAPI.h:546 — generation driver; adapter over
    paddle_tpu.generation.SequenceGenerator."""

    def __init__(self, beam_gen, parameters):
        from paddle_tpu.generation import SequenceGenerator as _Gen

        self._gen = _Gen(beam_gen, parameters)

    def generate(self, row):
        return self._gen.generate(row)


# ---------------------------------------------------------------------------
# SWIG numeric buffer types (reference: api/PaddleAPI.h Matrix:103,
# Vector:244, IVector:323 + api/Matrix.cpp / Vector.cpp).  numpy IS the
# buffer; `inplace` accessors return views, `copyTo*` return copies,
# exactly the py_paddle contract.
# ---------------------------------------------------------------------------


class UnsupportError(RuntimeError):
    """reference api/PaddleAPI.h:61"""


class RangeError(IndexError):
    """reference api/PaddleAPI.h:58"""


class Matrix:
    """Dense (numpy f32) or CSR-sparse 2-D buffer."""

    def __init__(self, arr=None, sparse=None, shape=None):
        self._arr = arr          # np (h, w) f32 when dense
        self._sparse = sparse    # (indptr, cols, vals|None) when sparse
        self._shape = shape if shape is not None else (
            arr.shape if arr is not None else (0, 0))

    # -- constructors ------------------------------------------------------
    @staticmethod
    def createZero(height, width, useGpu=False):
        return Matrix(np.zeros((height, width), np.float32))

    @staticmethod
    def createDense(data, height, width, useGpu=False):
        return Matrix(np.asarray(data, np.float32).reshape(height, width)
                      .copy())

    @staticmethod
    def createDenseFromNumpy(data, copy=True, useGpu=False):
        a = np.asarray(data, np.float32)
        if a.ndim != 2:
            raise UnsupportError("createDenseFromNumpy needs a 2-D array")
        return Matrix(a.copy() if copy else a)

    createCpuDenseFromNumpy = createDenseFromNumpy
    createGpuDenseFromNumpy = createDenseFromNumpy

    @staticmethod
    def createSparse(height, width, nnz, isNonVal=True, trans=False,
                     useGpu=False):
        m = Matrix(shape=(height, width))
        m._sparse = (np.zeros(height + 1, np.int64),
                     np.zeros(0, np.int64),
                     None if isNonVal else np.zeros(0, np.float32))
        return m

    def sparseCopyFrom(self, rows, cols, values=()):
        """CSR fill: ``rows`` = row offsets (len h+1), ``cols`` = column
        indices, ``values`` empty for binary (non-value) sparse."""
        if self._sparse is None:
            raise UnsupportError("sparseCopyFrom on a dense Matrix")
        vals = (np.asarray(values, np.float32) if len(values)
                else (None if self._sparse[2] is None
                      else np.zeros(len(cols), np.float32)))
        self._sparse = (np.asarray(rows, np.int64),
                        np.asarray(cols, np.int64), vals)

    # -- accessors ---------------------------------------------------------
    def isSparse(self):
        return self._sparse is not None

    def isGpu(self):
        return False

    def getHeight(self):
        return int(self._shape[0])

    def getWidth(self):
        return int(self._shape[1])

    def get(self, x, y):
        self._check_dense()
        if not (0 <= x < self._shape[0] and 0 <= y < self._shape[1]):
            raise RangeError((x, y))
        return float(self._arr[x, y])

    def set(self, x, y, val):
        self._check_dense()
        if not (0 <= x < self._shape[0] and 0 <= y < self._shape[1]):
            raise RangeError((x, y))
        self._arr[x, y] = val

    def getData(self):
        self._check_dense()
        return self._arr.ravel().tolist()

    def toNumpyMatInplace(self):
        self._check_dense()
        return self._arr

    def toNumpyMat(self):
        self._check_dense()
        return self._arr.copy()

    copyToNumpyMat = toNumpyMat

    def copyFromNumpyMat(self, data):
        self._check_dense()
        a = np.asarray(data, np.float32)
        if a.shape != self._arr.shape:
            raise RangeError((a.shape, self._arr.shape))
        self._arr[...] = a

    def getSparseRowCols(self, i):
        if self._sparse is None:
            raise UnsupportError("dense Matrix")
        indptr, cols, _ = self._sparse
        if not 0 <= i < self._shape[0]:
            raise RangeError(i)
        return cols[indptr[i]:indptr[i + 1]].tolist()

    def getSparseRowColsVal(self, i):
        if self._sparse is None or self._sparse[2] is None:
            raise UnsupportError("not a value-sparse Matrix")
        indptr, cols, vals = self._sparse
        if not 0 <= i < self._shape[0]:
            raise RangeError(i)
        sl = slice(indptr[i], indptr[i + 1])
        return list(zip(cols[sl].tolist(), vals[sl].tolist()))

    def _check_dense(self):
        if self._arr is None:
            raise UnsupportError("sparse Matrix has no dense buffer")


class _VectorBase:
    _dtype = np.float32

    def __init__(self, arr):
        self._arr = arr

    @classmethod
    def createZero(cls, sz, useGpu=False):
        return cls(np.zeros(sz, cls._dtype))

    @classmethod
    def create(cls, data, useGpu=False):
        return cls(np.asarray(data, cls._dtype).copy())

    @classmethod
    def createVectorFromNumpy(cls, data, copy=True, useGpu=False):
        a = np.asarray(data, cls._dtype)
        if a.ndim != 1:
            raise UnsupportError("vector needs a 1-D array")
        return cls(a.copy() if copy else a)

    @classmethod
    def createCpuVectorFromNumpy(cls, data, copy=True):
        return cls.createVectorFromNumpy(data, copy)

    @classmethod
    def createGpuVectorFromNumpy(cls, data):
        return cls.createVectorFromNumpy(data, True)

    def copyFrom(self, src):
        if src.getSize() != self.getSize():
            raise RangeError((src.getSize(), self.getSize()))
        self._arr[...] = src._arr

    def toNumpyArrayInplace(self):
        return self._arr

    def copyToNumpyArray(self):
        return self._arr.copy()

    def copyFromNumpyArray(self, data):
        a = np.asarray(data, self._dtype)
        if a.shape != self._arr.shape:
            raise RangeError((a.shape, self._arr.shape))
        self._arr[...] = a

    def get(self, idx):
        if not 0 <= idx < self._arr.size:
            raise RangeError(idx)
        return self._arr[idx].item()

    def set(self, idx, val):
        if not 0 <= idx < self._arr.size:
            raise RangeError(idx)
        self._arr[idx] = val

    def getData(self):
        return self._arr.tolist()

    def getSize(self):
        return int(self._arr.size)

    __len__ = getSize

    def isGpu(self):
        return False


class Vector(_VectorBase):
    """f32 1-D buffer (reference api/PaddleAPI.h:244)."""


class IVector(_VectorBase):
    """int 1-D buffer (reference api/PaddleAPI.h:323)."""

    _dtype = np.int32


# ---------------------------------------------------------------------------
# Parameter surface (reference: api/PaddleAPI.h ParameterConfig:498,
# Parameter:551, OptimizationConfig:528, ParameterOptimizer:685 +
# api/Parameter.cpp / ParameterOptimizer.cpp).
# ---------------------------------------------------------------------------


class ParameterConfig:
    """Proto-shaped view; toProtoString serializes as JSON (the repo's
    program-as-JSON redesign, PARITY §2.7)."""

    def __init__(self, name, dims):
        self.name = name
        self.dims = list(dims)

    def getName(self):
        return self.name

    def toProtoString(self):
        import json

        return json.dumps({"name": self.name, "dims": self.dims,
                           "size": int(np.prod(self.dims))}).encode()


class Parameter:
    """One named parameter over the v2 Parameters scope; getBuf returns
    a Vector VIEW (mutations write through, the swig contract)."""

    PARAMETER_VALUE = 0

    def __init__(self, v2_parameters, name):
        self._params = v2_parameters
        self._name = name

    def getName(self):
        return self._name

    def getSize(self):
        return int(np.prod(self._params.get_shape(self._name)))

    def getConfig(self):
        return ParameterConfig(self._name,
                               self._params.get_shape(self._name))

    def getBuf(self, which=PARAMETER_VALUE):
        arr = np.asarray(self._params.get(self._name), np.float32)
        flat = arr.reshape(-1).copy()
        v = Vector(flat)
        v._write_back = lambda: self._params.set(
            self._name, flat.reshape(arr.shape))
        return v

    def setBuf(self, vec):
        shape = self._params.get_shape(self._name)
        self._params.set(self._name,
                         np.asarray(vec._arr, np.float32).reshape(shape))


class OptimizationConfig:
    """Holds the optimizer config string consumed by the native C
    optimizer library (native/src/optimizer.cc; e.g. 'type=sgd lr=0.1'
    — the reference's OptimizationConfig proto equivalent)."""

    def __init__(self, config_str="type=sgd lr=0.01"):
        self.config = config_str

    @staticmethod
    def createFromProtoString(s):
        return OptimizationConfig(s.decode() if isinstance(s, bytes) else s)

    def toProtoString(self):
        return self.config.encode()


class ParameterOptimizer:
    """Per-parameter optimizer over the native C-ABI library
    (reference: api ParameterOptimizer over paddle/parameter
    optimizers; here native opt_create/opt_update — the same library
    the parameter server applies updates with)."""

    def __init__(self, opt_config):
        self._cfg = (opt_config.config
                     if isinstance(opt_config, OptimizationConfig)
                     else str(opt_config))
        self._h = None
        self._lib = None

    @staticmethod
    def create(opt_config):
        return ParameterOptimizer(opt_config)

    def init(self, weights):
        """Bind initial weights (a Vector, numpy array, or list)."""
        import ctypes

        from paddle_tpu.native import lib as _native_lib

        w = np.ascontiguousarray(
            weights._arr if isinstance(weights, _VectorBase) else weights,
            np.float32)
        self._lib = _native_lib()
        self._h = self._lib.opt_create(
            self._cfg.encode(),
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), w.size)
        if not self._h:
            raise UnsupportError(f"bad optimizer config {self._cfg!r}")

    def update(self, grad):
        import ctypes

        if self._h is None:
            raise UnsupportError("init() first")
        g = np.ascontiguousarray(
            grad._arr if isinstance(grad, _VectorBase) else grad,
            np.float32)
        if self._lib.opt_update(
                self._h,
                g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                g.size) != 0:
            raise RuntimeError("opt_update failed")

    def getWeights(self):
        import ctypes

        n = self._lib.opt_weight_count(self._h)
        out = np.zeros(n, np.float32)
        self._lib.opt_get_weights(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n)
        return Vector(out)

    def __del__(self):
        if self._h is not None and self._lib is not None:
            self._lib.opt_destroy(self._h)
