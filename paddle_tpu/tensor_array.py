"""TensorArray: the LoDTensorArray equivalent under a static-shape
compiler (reference: framework/lod_tensor_array.h, operators/
tensor_array_read_write_op.cc).

A pytree of (stack, length): ``stack`` is a dense (capacity, ...) buffer,
``length`` an int32 scalar.  Writes are lax.dynamic_update_slice at a
traced index, so arrays live inside while-loops/scans without dynamic
shapes."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


@jax.tree_util.register_pytree_node_class
class TensorArray:
    def __init__(self, stack, length):
        self.stack = stack
        self.length = length

    def tree_flatten(self):
        return (self.stack, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- api ----------------------------------------------------------------

    @classmethod
    def create(cls, capacity: int, elem_shape, dtype=jnp.float32) -> "TensorArray":
        return cls(jnp.zeros((capacity,) + tuple(elem_shape), dtype),
                   jnp.asarray(0, jnp.int32))

    def write(self, index, value) -> "TensorArray":
        idx = jnp.asarray(index, jnp.int32).reshape(())
        stack = lax.dynamic_update_slice(
            self.stack, value[None], (idx,) + (0,) * value.ndim)
        return TensorArray(stack, jnp.maximum(self.length, idx + 1))

    def read(self, index):
        idx = jnp.asarray(index, jnp.int32).reshape(())
        return lax.dynamic_slice(
            self.stack, (idx,) + (0,) * (self.stack.ndim - 1),
            (1,) + self.stack.shape[1:])[0]

    @property
    def capacity(self):
        return self.stack.shape[0]

    def __repr__(self):
        return f"TensorArray(capacity={self.capacity}, elem={self.stack.shape[1:]})"
