"""Gradient clipping (reference: the era's clip/clip_by_norm ops,
operators/clip_op.cc, clip_by_norm_op.cc, plus fluid's later
GradientClipBy* attrs).  Clip transforms append ops rewriting each
(param, grad) pair before the optimizer update."""

from __future__ import annotations

from typing import List, Tuple

from paddle_tpu.framework import Block, unique_name


class BaseGradientClip:
    def append_clip_ops(self, block: Block, params_grads):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClip):
    def __init__(self, max_value, min_value=None):
        self.max_value = float(max_value)
        self.min_value = float(min_value if min_value is not None else -max_value)

    def append_clip_ops(self, block, params_grads):
        out = []
        for p, g in params_grads:
            ng = block.create_var(name=unique_name(g.name + "_clip"),
                                  shape=g.shape, dtype=g.dtype,
                                  stop_gradient=True)
            block.append_op(type="clip", inputs={"X": [g]},
                            outputs={"Out": [ng]},
                            attrs={"min": self.min_value, "max": self.max_value})
            out.append((p, ng))
        return out


class GradientClipByNorm(BaseGradientClip):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def append_clip_ops(self, block, params_grads):
        out = []
        for p, g in params_grads:
            ng = block.create_var(name=unique_name(g.name + "_clip"),
                                  shape=g.shape, dtype=g.dtype,
                                  stop_gradient=True)
            block.append_op(type="clip_by_norm", inputs={"X": [g]},
                            outputs={"Out": [ng]},
                            attrs={"max_norm": self.clip_norm})
            out.append((p, ng))
        return out


class GradientClipByGlobalNorm(BaseGradientClip):
    """g_i *= clip_norm / max(global_norm, clip_norm), with
    global_norm = sqrt(sum_i ||g_i||^2)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def append_clip_ops(self, block, params_grads):
        sq_norms = []
        for _, g in params_grads:
            n = block.create_var(name=unique_name(g.name + "_sqn"),
                                 shape=(1,), dtype="float32", stop_gradient=True)
            block.append_op(type="squared_l2_norm", inputs={"X": [g]},
                            outputs={"Out": [n]})
            sq_norms.append(n)
        total = block.create_var(name=unique_name("global_sqn"), shape=(1,),
                                 dtype="float32", stop_gradient=True)
        block.append_op(type="sum", inputs={"X": sq_norms},
                        outputs={"Out": [total]})
        gnorm = block.create_var(name=unique_name("global_norm"), shape=(1,),
                                 dtype="float32", stop_gradient=True)
        block.append_op(type="sqrt", inputs={"X": [total]},
                        outputs={"Out": [gnorm]})
        # scale = clip / max(gnorm, clip)
        denom = block.create_var(name=unique_name("clip_denom"), shape=(1,),
                                 dtype="float32", stop_gradient=True)
        cvar = block.create_var(name=unique_name("clip_const"), shape=(1,),
                                dtype="float32", stop_gradient=True)
        block.append_op(type="fill_constant", outputs={"Out": [cvar]},
                        attrs={"shape": [1], "dtype": "float32",
                               "value": self.clip_norm})
        block.append_op(type="elementwise_max", inputs={"X": [gnorm], "Y": [cvar]},
                        outputs={"Out": [denom]})
        scale = block.create_var(name=unique_name("clip_scale"), shape=(1,),
                                 dtype="float32", stop_gradient=True)
        block.append_op(type="elementwise_div", inputs={"X": [cvar], "Y": [denom]},
                        outputs={"Out": [scale]})
        out = []
        for p, g in params_grads:
            ng = block.create_var(name=unique_name(g.name + "_clip"),
                                  shape=g.shape, dtype=g.dtype,
                                  stop_gradient=True)
            block.append_op(type="elementwise_mul",
                            inputs={"X": [g], "Y": [scale]},
                            outputs={"Out": [ng]}, attrs={"axis": 0})
            out.append((p, ng))
        return out


# reference-style aliases
ClipByValue = GradientClipByValue
ClipByNorm = GradientClipByNorm
ClipByGlobalNorm = GradientClipByGlobalNorm
