"""Composite network helpers (reference: python/paddle/v2/fluid/nets.py)."""

from __future__ import annotations

from paddle_tpu import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size, pool_stride,
                         act=None, param_attr=None, pool_type="max"):
    conv_out = layers.conv2d(input=input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr, act=act)
    return layers.pool2d(input=conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=None, pool_stride=1, pool_type="max"):
    tmp = input
    if not isinstance(conv_padding, (list, tuple)):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if not isinstance(conv_with_batchnorm, (list, tuple)):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if conv_batchnorm_drop_rate is None:
        conv_batchnorm_drop_rate = [0.0] * len(conv_num_filter)
    elif not isinstance(conv_batchnorm_drop_rate, (list, tuple)):
        conv_batchnorm_drop_rate = [conv_batchnorm_drop_rate] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        local_act = None if conv_with_batchnorm[i] else conv_act
        tmp = layers.conv2d(input=tmp, num_filters=nf,
                            filter_size=conv_filter_size,
                            padding=conv_padding[i], act=local_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i] > 0:
                tmp = layers.dropout(x=tmp, dropout_prob=conv_batchnorm_drop_rate[i])
    return layers.pool2d(input=tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, act="sigmoid",
                       pool_type="max"):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(x=a, y=layers.sigmoid(b))
