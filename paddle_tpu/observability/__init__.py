"""Runtime telemetry: metrics registry, trace spans, Chrome-trace export.

The instrument panel for everything the ROADMAP wants measured:

- ``metrics``   — typed counters/gauges/histograms with labels; the
  Executor and InferenceServer update the process-global ``REGISTRY``
  on every compile/step/request.  Exposed as Prometheus text on the
  server's ``GET /metrics``, as JSON/tables via ``paddle stats``, and
  as the bench telemetry artifact.
- ``events``    — bounded host-side event ring exporting Chrome-trace
  JSON (compile/step/serving spans) for ``chrome://tracing``.
- device-side naming — ``flags trace_ops=1`` wraps each op's lowering
  in ``jax.named_scope`` + ``jax.profiler.TraceAnnotation`` so xprof
  traces show op names instead of anonymous XLA regions (executor.py).

``reset()`` clears recorded values (registered metric families survive,
so module-level handles stay valid) — tests call it per-case.
"""

from __future__ import annotations

import time

from paddle_tpu.observability.metrics import (  # noqa: F401
    COMPILE_TIME_BUCKETS,
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    counter,
    format_snapshot,
    format_table,
    gauge,
    histogram,
    render_prometheus,
    snapshot,
)
from paddle_tpu.observability.events import (  # noqa: F401
    EventRecorder,
    GLOBAL_EVENTS,
)


def reset():
    """Clear all recorded metric values and host events."""
    REGISTRY.reset()
    GLOBAL_EVENTS.clear()


def export_chrome_trace(path: str) -> str:
    """Dump the global host-event ring as Chrome-trace JSON."""
    return GLOBAL_EVENTS.export(path)


def measure_step_overhead(iters: int = 2000) -> float:
    """Average wall cost (seconds) of the telemetry writes Executor.run
    adds to one *cached* step: the cache-hit counter, the feed/step
    histogram observes, the fetch-bytes counter, and one host event.

    Runs against private registry/recorder instances so measuring does
    not pollute live metrics.  Recorded into the bench telemetry
    artifact (``telemetry_overhead`` fields) and asserted ≤ budget in
    tests — the hot-path ≤2% guarantee, measured instead of promised.
    """
    reg = MetricsRegistry()
    hits = reg.counter("overhead_probe_hits_total")
    fetched = reg.counter("overhead_probe_bytes_total")
    steps = reg.histogram("overhead_probe_seconds")
    ev = EventRecorder(max_events=16)
    t0 = time.perf_counter()
    for _ in range(iters):
        t = ev.now()
        hits.inc(program="fingerprint0")
        steps.observe(1e-4, program="fingerprint0", stage="feed")
        steps.observe(1e-3, program="fingerprint0", cached="hit")
        fetched.inc(4096, program="fingerprint0")
        ev.complete("executor.step", t, 1e-3, program="fingerprint0")
    return (time.perf_counter() - t0) / iters
