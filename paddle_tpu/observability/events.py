"""Host-side event recorder exporting Chrome-trace JSON.

Complements jax.profiler (device timeline): this records the *host*
story — compile vs cached step vs serving request — as complete ("X")
events loadable in ``chrome://tracing`` / Perfetto alongside an xprof
capture.  The ring is bounded (``max_events``) so an always-on recorder
cannot grow without limit under serving traffic.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List


class EventRecorder:
    """Thread-safe bounded ring of Chrome-trace events.

    Timestamps are microseconds since the recorder's epoch
    (``perf_counter`` based, monotonic), which is what the trace viewer
    expects; wall-clock anchoring is recorded once in metadata.
    """

    def __init__(self, max_events: int = 100_000):
        self._t0 = time.perf_counter()
        self._epoch_unix = time.time()
        self._events: collections.deque = collections.deque(maxlen=max_events)
        self._lock = threading.Lock()

    def now(self) -> float:
        """Seconds since the recorder epoch."""
        return time.perf_counter() - self._t0

    def complete(self, name: str, start: float, dur: float,
                 cat: str = "paddle", **args):
        """Record a complete ("X") event; ``start``/``dur`` in seconds
        on the ``now()`` clock."""
        ev: Dict[str, Any] = {
            "name": name, "cat": cat, "ph": "X",
            "ts": start * 1e6, "dur": max(dur, 0.0) * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "paddle", **args):
        t0 = self.now()
        try:
            yield
        finally:
            self.complete(name, t0, self.now() - t0, cat, **args)

    def instant(self, name: str, cat: str = "paddle", **args):
        ev: Dict[str, Any] = {
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": self.now() * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self):
        """Drop recorded events.  The epoch is deliberately NOT rebased:
        a span in flight on another thread (serving handlers) captured
        its start against the current epoch, and rebasing would give it
        a garbage/negative timestamp when it completes."""
        with self._lock:
            self._events.clear()

    def to_chrome_trace(self) -> dict:
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "recorder": "paddle_tpu.observability",
                "epoch_unix_sec": self._epoch_unix,
            },
        }

    def export(self, path: str) -> str:
        """Write ``chrome://tracing``-loadable JSON; returns ``path``."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


GLOBAL_EVENTS = EventRecorder()
