"""Typed metrics registry: counters, gauges, histograms with labels.

This is the measurement substrate every perf/serving claim in the repo
stands on (ROADMAP: "measured, not asserted").  It subsumes the old
``stat.StatSet`` timer registry: a Histogram tracks the same
total/count/max summary *plus* fixed-bucket distribution, so latency
quantiles (p50/p95/p99) come out of the same object the hot path
updates.  Design constraints:

- hot-path writes are one lock acquire + a dict/bisect update (a few
  microseconds; see ``observability.measure_step_overhead``), so the
  Executor can update per-step metrics unconditionally;
- exposition is pull-based and allocation-free until asked:
  ``render_prometheus()`` for a /metrics scrape,
  ``snapshot()`` (plain JSON-able dicts) for ``paddle stats`` and the
  bench telemetry artifact, ``format_snapshot()`` for humans.

The Prometheus text format follows the 0.0.4 exposition spec
(cumulative ``_bucket{le=...}`` counts, ``_sum``/``_count`` rows).
"""

from __future__ import annotations

import bisect
import contextlib
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Default latency buckets (seconds): 0.5 ms .. 10 s, the span from a
# cached executor step to a cold serving request.
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Compile-time buckets (seconds): tracing + XLA compilation of a full
# training step ranges from tens of ms (toy nets) to minutes (ResNet).
COMPILE_TIME_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0,
)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _fmt_num(v: float) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(key: _LabelKey, extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + body + "}"


class _Metric:
    """Shared family plumbing: name, help text, labeled children."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[_LabelKey, Any] = {}

    def _clear(self):
        with self._lock:
            self._children.clear()

    def label_sets(self) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(k) for k in self._children]


class Counter(_Metric):
    """Monotonically increasing count (Prometheus counter)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(_label_key(labels), 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._children.items())
        return {
            "type": self.kind, "help": self.help,
            "values": [{"labels": dict(k), "value": v} for k, v in items],
        }

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._children.items())
        return [f"{self.name}{_prom_labels(k)} {_fmt_num(v)}"
                for k, v in items]


class Gauge(_Metric):
    """Point-in-time value (Prometheus gauge)."""

    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            self._children[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = _label_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(_label_key(labels), 0.0)

    snapshot = Counter.snapshot
    render = Counter.render


class _HistState:
    __slots__ = ("counts", "sum", "count", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (not cumulative)
        self.sum = 0.0
        self.count = 0
        self.max = 0.0


class Histogram(_Metric):
    """Fixed-bucket distribution (Prometheus histogram) that also keeps
    the StatSet-style total/count/max summary."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"histogram {name}: empty bucket list")
        self.buckets = b

    def observe(self, value: float, **labels):
        key = _label_key(labels)
        v = float(value)
        # bisect_left: v == bound lands in that bucket (le is inclusive)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            st = self._children.get(key)
            if st is None:
                st = self._children[key] = _HistState(len(self.buckets) + 1)
            st.counts[i] += 1
            st.sum += v
            st.count += 1
            if v > st.max:
                st.max = v

    @contextlib.contextmanager
    def time(self, **labels):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, **labels)

    def count(self, **labels) -> int:
        with self._lock:
            st = self._children.get(_label_key(labels))
            return st.count if st else 0

    def quantile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile (the Prometheus
        ``histogram_quantile`` estimate); the +Inf bucket is clamped to
        the max observed value instead of an unbounded edge."""
        with self._lock:
            st = self._children.get(_label_key(labels))
            if st is None or st.count == 0:
                return float("nan")
            counts, total, vmax = list(st.counts), st.count, st.max
        return self._quantile_from(counts, total, vmax, q, self.buckets)

    @staticmethod
    def _quantile_from(counts, total, vmax, q, buckets) -> float:
        target = q * total
        cum = 0
        lower = 0.0
        for i, edge in enumerate(buckets):
            nxt = cum + counts[i]
            if nxt >= target and counts[i] > 0:
                frac = (target - cum) / counts[i]
                est = lower + (edge - lower) * frac
                # no observation exceeds vmax, so no quantile can either
                # (an all-zeros histogram must report 0, not bucket-edge
                # interpolation)
                return min(est, vmax)
            cum = nxt
            lower = edge
        return vmax  # landed in the +Inf bucket

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted((k, (list(st.counts), st.sum, st.count, st.max))
                           for k, st in self._children.items())
        values = []
        for k, (counts, total_sum, count, vmax) in items:
            cum, bucket_map = 0, {}
            for i, edge in enumerate(self.buckets):
                cum += counts[i]
                bucket_map[f"{edge:g}"] = cum
            bucket_map["+Inf"] = count
            values.append({
                "labels": dict(k), "count": count, "sum": total_sum,
                "max": vmax, "buckets": bucket_map,
                "p50": self._quantile_from(counts, count, vmax, 0.50,
                                           self.buckets),
                "p95": self._quantile_from(counts, count, vmax, 0.95,
                                           self.buckets),
                "p99": self._quantile_from(counts, count, vmax, 0.99,
                                           self.buckets),
            })
        return {"type": self.kind, "help": self.help, "values": values}

    def render(self) -> List[str]:
        snap = self.snapshot()
        lines: List[str] = []
        for child in snap["values"]:
            key = _label_key(child["labels"])
            for edge, cum in child["buckets"].items():
                lines.append(
                    f"{self.name}_bucket{_prom_labels(key, (('le', edge),))}"
                    f" {_fmt_num(float(cum))}")
            lines.append(f"{self.name}_sum{_prom_labels(key)}"
                         f" {_fmt_num(child['sum'])}")
            lines.append(f"{self.name}_count{_prom_labels(key)}"
                         f" {_fmt_num(float(child['count']))}")
        return lines


class MetricsRegistry:
    """Name -> metric family map.  ``counter``/``gauge``/``histogram``
    are get-or-create (idempotent), erroring on a kind clash."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"not {cls.kind}")
                return m
            m = cls(name, help, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self):
        """Clear recorded values; registered families survive (module
        level handles into the registry stay valid)."""
        with self._lock:
            fams = list(self._metrics.values())
        for m in fams:
            m._clear()

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able {name: family snapshot}; empty families omitted."""
        with self._lock:
            fams = sorted(self._metrics.items())
        out = {}
        for name, m in fams:
            snap = m.snapshot()
            if snap["values"]:
                out[name] = snap
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4)."""
        with self._lock:
            fams = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in fams:
            body = m.render()
            if not body:
                continue
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(body)
        return "\n".join(lines) + ("\n" if lines else "")

    def render_table(self) -> str:
        return format_snapshot(self.snapshot())


# ---------------------------------------------------------------------------
# Human rendering (shared with stat.StatSet.print_status)
# ---------------------------------------------------------------------------


def format_table(rows: Sequence[Sequence[str]],
                 headers: Optional[Sequence[str]] = None) -> str:
    """Align columns: first column left, the rest right."""
    all_rows = ([list(headers)] if headers else []) + [list(r) for r in rows]
    if not all_rows:
        return ""
    ncols = max(len(r) for r in all_rows)
    widths = [0] * ncols
    for r in all_rows:
        for i, cell in enumerate(r):
            widths[i] = max(widths[i], len(str(cell)))
    lines = []
    for r in all_rows:
        cells = [str(c) for c in r] + [""] * (ncols - len(r))
        lines.append("  ".join(
            cells[i].ljust(widths[i]) if i == 0 else cells[i].rjust(widths[i])
            for i in range(ncols)).rstrip())
    return "\n".join(lines)


def _g(v) -> str:
    try:
        return f"{float(v):.6g}"
    except (TypeError, ValueError):
        return str(v)


def format_snapshot(snap: Dict[str, dict]) -> str:
    """Human table from a ``snapshot()`` dict (also accepts the same
    structure parsed back from JSON — ``paddle stats --file/--url``)."""
    rows = []
    for name in sorted(snap):
        fam = snap[name]
        for child in fam.get("values", []):
            labels = child.get("labels", {})
            label_str = " ".join(f"{k}={labels[k]}" for k in sorted(labels)) \
                or "-"
            if fam.get("type") == "histogram":
                val = (f"count={child['count']} sum={_g(child['sum'])} "
                       f"p50={_g(child.get('p50'))} "
                       f"p95={_g(child.get('p95'))} "
                       f"p99={_g(child.get('p99'))} max={_g(child['max'])}")
            else:
                val = _fmt_num(float(child["value"]))
            rows.append((name, label_str, val))
    if not rows:
        return ""
    return format_table(rows, headers=("metric", "labels", "value"))


# ---------------------------------------------------------------------------
# Process-global registry + module-level conveniences
# ---------------------------------------------------------------------------

REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)


def snapshot() -> Dict[str, dict]:
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()
