"""LayerHelper: shared plumbing for the layers API (reference:
python/paddle/v2/fluid/layer_helper.py) — creates parameters in the
startup+main programs, appends bias/activation ops."""

from __future__ import annotations

from typing import Optional

from paddle_tpu import framework
from paddle_tpu.framework import Variable, unique_name
from paddle_tpu.initializer import ConstantInitializer, XavierInitializer
from paddle_tpu.param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        self.name = kwargs.get("name") or unique_name(layer_type)

    @property
    def main_program(self):
        return self.kwargs.get("main_program") or framework.default_main_program()

    @property
    def startup_program(self):
        return self.kwargs.get("startup_program") or framework.default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def create_parameter(
        self,
        attr,
        shape,
        dtype,
        is_bias: bool = False,
        default_initializer=None,
    ):
        import copy

        # copy: never mutate a caller-owned ParamAttr (it may be reused
        # across layers, which must get distinct parameter names)
        attr = copy.copy(ParamAttr.to_attr(attr))
        if attr.name is None:
            attr.name = unique_name(".".join([self.name, "b" if is_bias else "w"]))
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
        # declare in main program (for the graph) ...
        param = self.block.create_parameter(
            shape=shape,
            dtype=dtype,
            name=attr.name,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            gradient_clip=attr.gradient_clip,
            optimize_attr={"learning_rate": attr.learning_rate},
        )
        # ... and append its init op to the startup program.
        sblock = self.startup_program.global_block()
        if attr.name not in sblock.vars:
            svar = sblock.create_var(
                name=attr.name, shape=shape, dtype=dtype, persistable=True
            )
            init(svar, sblock)
        if getattr(attr, "shard", None) is not None:
            param.dist_spec = attr.shard
            # mirror onto the startup-program var so the startup run
            # already places shards correctly (no post-hoc reshard)
            sv = sblock.vars.get(attr.name)
            if sv is not None:
                sv.dist_spec = attr.shard
        return param

    def create_tmp_variable(self, dtype, shape=None, lod_level=0) -> Variable:
        return self.block.create_var(
            name=unique_name(".".join([self.name, "tmp"])),
            dtype=dtype,
            shape=shape,
            lod_level=lod_level,
        )

    create_variable_for_type_inference = create_tmp_variable

    def append_op(self, **kwargs):
        return self.block.append_op(**kwargs)

    def input(self, name="input"):
        return self.kwargs[name]

    @property
    def param_attr(self):
        return self.kwargs.get("param_attr")

    @property
    def bias_attr(self):
        return self.kwargs.get("bias_attr")

    def append_bias_op(self, input_var: Variable, dim_start=1, dim_end=None) -> Variable:
        bias_attr = self.bias_attr
        if bias_attr is False or bias_attr is None and not self.kwargs.get("bias_default", True):
            return input_var
        size = list(input_var.shape[dim_start:dim_end]) if input_var.shape else [1]
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        out = self.create_tmp_variable(input_var.dtype, input_var.shape, input_var.lod_level)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [out]},
            attrs={"axis": dim_start},
        )
        return out

    def append_activation(self, input_var: Variable) -> Variable:
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_tmp_variable(input_var.dtype, input_var.shape, input_var.lod_level)
        self.append_op(
            type=act_type, inputs={"X": [input_var]}, outputs={"Out": [out]}, attrs=act
        )
        return out
