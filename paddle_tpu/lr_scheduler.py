"""Learning-rate schedules as in-graph sub-programs.

Reference: the legacy LR policies (paddle/parameter/
LearningRateScheduler.cpp — poly/const/linear/exp/discexp) configured
via TrainerConfig.  Here each schedule is a small set of ops computing
lr from a persistable ``global_step`` counter, so the schedule compiles
into the training step (no host-side LR push per batch as the pserver
path needed)."""

from __future__ import annotations

from paddle_tpu import framework
from paddle_tpu.framework import unique_name
from paddle_tpu.initializer import ConstantInitializer
from paddle_tpu.layer_helper import LayerHelper


def _counter(helper: LayerHelper, step_name="@lr_global_step@"):
    main = helper.main_program.global_block()
    if main.has_var(step_name):
        return main.var(step_name)
    startup = helper.startup_program.global_block()
    svar = startup.create_var(name=step_name, shape=(1,), dtype="float32",
                              persistable=True)
    ConstantInitializer(0.0)(svar, startup)
    var = main.create_var(name=step_name, shape=(1,), dtype="float32",
                          persistable=True)
    # bump once per executed step
    main.append_op(type="increment", inputs={"X": [var]},
                   outputs={"Out": [var]}, attrs={"step": 1.0})
    return var


def _unary_chain(helper, x, ops):
    """ops: list of (op_type, attrs); threads x through."""
    for op_type, attrs in ops:
        out = helper.create_tmp_variable("float32", (1,))
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        x = out
    return x


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False, **kwargs):
    """lr * decay_rate ^ (step / decay_steps)"""
    helper = LayerHelper("exponential_decay", **kwargs)
    step = _counter(helper)
    div = _unary_chain(helper, step, [("scale", {"scale": 1.0 / decay_steps})])
    if staircase:
        div = _unary_chain(helper, div, [("floor", {})])
    import math

    # decay_rate^d = exp(d * ln(decay_rate))
    lr = _unary_chain(helper, div, [
        ("scale", {"scale": math.log(decay_rate)}),
        ("exp", {}),
        ("scale", {"scale": float(learning_rate)}),
    ])
    return lr


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False, **kwargs):
    """lr * exp(-decay_rate * step / decay_steps)"""
    helper = LayerHelper("natural_exp_decay", **kwargs)
    step = _counter(helper)
    div = _unary_chain(helper, step, [("scale", {"scale": 1.0 / decay_steps})])
    if staircase:
        div = _unary_chain(helper, div, [("floor", {})])
    return _unary_chain(helper, div, [
        ("scale", {"scale": -float(decay_rate)}),
        ("exp", {}),
        ("scale", {"scale": float(learning_rate)}),
    ])


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False, **kwargs):
    """lr / (1 + decay_rate * step / decay_steps)"""
    helper = LayerHelper("inverse_time_decay", **kwargs)
    step = _counter(helper)
    div = _unary_chain(helper, step, [("scale", {"scale": 1.0 / decay_steps})])
    if staircase:
        div = _unary_chain(helper, div, [("floor", {})])
    return _unary_chain(helper, div, [
        ("scale", {"scale": float(decay_rate), "bias": 1.0}),
        ("reciprocal", {}),
        ("scale", {"scale": float(learning_rate)}),
    ])


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False, **kwargs):
    """(lr - end_lr) * (1 - min(step, decay_steps)/decay_steps)^power + end_lr"""
    helper = LayerHelper("polynomial_decay", **kwargs)
    step = _counter(helper)
    frac = _unary_chain(helper, step, [
        ("scale", {"scale": 1.0 / decay_steps}),
        ("clip", {"min": 0.0, "max": 1.0}),
        ("scale", {"scale": -1.0, "bias": 1.0}),
        ("pow", {"factor": float(power)}),
        ("scale", {"scale": float(learning_rate - end_learning_rate),
                   "bias": float(end_learning_rate)}),
    ])
    return frac


def piecewise_decay(boundaries, values, **kwargs):
    """Step function: lr = values[i] for step in (boundaries[i-1], boundaries[i]]."""
    assert len(values) == len(boundaries) + 1
    helper = LayerHelper("piecewise_decay", **kwargs)
    step = _counter(helper)
    # lr = v0 + sum_i (v_{i+1}-v_i) * [step >= b_i], via sigmoid-free compare
    from paddle_tpu.layers import tensor as tl

    lr = None
    prev = values[0]
    acc = helper.create_tmp_variable("float32", (1,))
    helper.append_op(type="fill_constant", outputs={"Out": [acc]},
                     attrs={"shape": [1], "dtype": "float32",
                            "value": float(values[0])})
    for b, v in zip(boundaries, values[1:]):
        geq = helper.create_tmp_variable("bool", (1,))
        bvar = helper.create_tmp_variable("float32", (1,))
        helper.append_op(type="fill_constant", outputs={"Out": [bvar]},
                         attrs={"shape": [1], "dtype": "float32",
                                "value": float(b)})
        helper.append_op(type="greater_equal", inputs={"X": [step], "Y": [bvar]},
                         outputs={"Out": [geq]})
        gf = helper.create_tmp_variable("float32", (1,))
        helper.append_op(type="cast", inputs={"X": [geq]}, outputs={"Out": [gf]},
                         attrs={"out_dtype": "float32"})
        deltav = helper.create_tmp_variable("float32", (1,))
        helper.append_op(type="scale", inputs={"X": [gf]},
                         outputs={"Out": [deltav]},
                         attrs={"scale": float(v - prev)})
        nacc = helper.create_tmp_variable("float32", (1,))
        helper.append_op(type="sum", inputs={"X": [acc, deltav]},
                         outputs={"Out": [nacc]})
        acc = nacc
        prev = v
    return acc
