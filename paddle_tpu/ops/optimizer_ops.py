"""Optimizer update ops (reference: paddle/operators/{sgd,momentum,adam,
adamax,adagrad,adadelta,decayed_adagrad,rmsprop,ftrl,proximal_gd,
proximal_adagrad}_op.cc).  Pure elementwise updates; with the whole step
compiled as one XLA program, every optimizer fuses into the backward
pass — there is no separate "apply" launch as in the reference."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import SkipInferShape, register_op


def _infer_update(**pairs):
    """Functional in-place updates: each output slot mirrors its paired
    input slot (``ParamOut=Param``, ``MomentOut=Moment``, ...)."""

    def infer(op, block):
        hit = False
        for out_slot, in_slot in pairs.items():
            ins = op.inputs.get(in_slot, [])
            outs = op.outputs.get(out_slot, [])
            if len(ins) != 1 or len(outs) != 1 or not ins[0] or not outs[0]:
                continue
            iv = block.find_var(ins[0])
            ov = block.find_var(outs[0])
            if iv is None or ov is None or iv.shape is None:
                continue
            hit = True
            if ov.shape is None:
                ov.shape = tuple(iv.shape)
        if not hit:
            raise SkipInferShape

    return infer


def _lr(ctx):
    lr = unwrap(ctx.input("LearningRate"))
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


@register_op("sgd", inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param"))
def _sgd(ctx):
    from paddle_tpu.sparse import is_sparse_grad

    p = unwrap(ctx.input("Param"))
    graw = ctx.input("Grad")
    lr = _lr(ctx).astype(p.dtype)
    if is_sparse_grad(graw):
        # SelectedRows branch (reference: operators/sgd_op.cc sparse
        # kernel): scatter-add touches only the looked-up rows;
        # duplicate rows accumulate, which is exact for SGD.
        out = p.at[graw.rows].add(-lr * graw.values.astype(p.dtype), mode="drop")
        ctx.set_output("ParamOut", out)
        return
    g = unwrap(graw)
    ctx.set_output("ParamOut", p - lr * g.astype(p.dtype))


@register_op("momentum", inputs=("Param", "Grad", "Velocity", "LearningRate"),
             outputs=("ParamOut", "VelocityOut"), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param", VelocityOut="Velocity"))
def _momentum(ctx):
    from paddle_tpu.sparse import is_sparse_grad, rowwise_update

    p = unwrap(ctx.input("Param"))
    graw = ctx.input("Grad")
    v = unwrap(ctx.input("Velocity"))
    mu = ctx.attr("mu", 0.9)
    lr = _lr(ctx).astype(p.dtype)
    nesterov = ctx.attr("use_nesterov", False)
    if is_sparse_grad(graw):
        # Row-wise lazy momentum: untouched rows keep their velocity
        # (legacy SparseRowMatrix semantics, parameter/FirstOrderOptimizer.h).
        def upd(p_rows, g_rows, v_rows):
            v_new = mu * v_rows + g_rows
            if nesterov:
                return p_rows - (g_rows + mu * v_new) * lr, v_new
            return p_rows - lr * v_new, v_new

        p_new, v_new = rowwise_update(p, graw, upd, v)
        ctx.set_output("ParamOut", p_new)
        ctx.set_output("VelocityOut", v_new)
        return
    g = unwrap(graw).astype(p.dtype)
    v_new = mu * v + g
    if nesterov:
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    ctx.set_output("ParamOut", p_new)
    ctx.set_output("VelocityOut", v_new)


@register_op("adam",
             inputs=("Param", "Grad", "LearningRate", "Moment1", "Moment2",
                     "Beta1Pow", "Beta2Pow"),
             outputs=("ParamOut", "Moment1Out", "Moment2Out"),
             stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param", Moment1Out="Moment1",
                                       Moment2Out="Moment2"))
def _adam(ctx):
    from paddle_tpu.sparse import is_sparse_grad, rowwise_update

    p = unwrap(ctx.input("Param"))
    m1 = unwrap(ctx.input("Moment1"))
    m2 = unwrap(ctx.input("Moment2"))
    b1p = unwrap(ctx.input("Beta1Pow")).reshape(())
    b2p = unwrap(ctx.input("Beta2Pow")).reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    graw = ctx.input("Grad")
    if is_sparse_grad(graw):
        # Lazy Adam over SelectedRows: only touched rows advance their
        # moments (duplicates merged first).
        def upd(p_rows, g_rows, m1_rows, m2_rows):
            g32 = g_rows.astype(jnp.float32)
            m1n = b1 * m1_rows + (1 - b1) * g32
            m2n = b2 * m2_rows + (1 - b2) * jnp.square(g32)
            pn = p_rows.astype(jnp.float32) - lr_t * m1n / (jnp.sqrt(m2n) + eps)
            return pn, m1n, m2n

        p_new, m1n, m2n = rowwise_update(p, graw, upd, m1, m2)
        ctx.set_output("ParamOut", p_new)
        ctx.set_output("Moment1Out", m1n)
        ctx.set_output("Moment2Out", m2n)
        return
    g = unwrap(graw).astype(jnp.float32)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    p_new = p.astype(jnp.float32) - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    ctx.set_output("ParamOut", p_new.astype(p.dtype))
    ctx.set_output("Moment1Out", m1n)
    ctx.set_output("Moment2Out", m2n)


@register_op("adamax",
             inputs=("Param", "Grad", "LearningRate", "Moment", "InfNorm", "Beta1Pow"),
             outputs=("ParamOut", "MomentOut", "InfNormOut"), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param", MomentOut="Moment",
                                       InfNormOut="InfNorm"))
def _adamax(ctx):
    p = unwrap(ctx.input("Param"))
    g = unwrap(ctx.input("Grad")).astype(jnp.float32)
    m = unwrap(ctx.input("Moment"))
    u = unwrap(ctx.input("InfNorm"))
    b1p = unwrap(ctx.input("Beta1Pow")).reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    m_new = b1 * m + (1 - b1) * g
    u_new = jnp.maximum(b2 * u, jnp.abs(g))
    p_new = p.astype(jnp.float32) - (lr / (1 - b1p)) * m_new / (u_new + eps)
    ctx.set_output("ParamOut", p_new.astype(p.dtype))
    ctx.set_output("MomentOut", m_new)
    ctx.set_output("InfNormOut", u_new)


@register_op("adagrad", inputs=("Param", "Grad", "Moment", "LearningRate"),
             outputs=("ParamOut", "MomentOut"), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param", MomentOut="Moment"))
def _adagrad(ctx):
    from paddle_tpu.sparse import is_sparse_grad, rowwise_update

    p = unwrap(ctx.input("Param"))
    m = unwrap(ctx.input("Moment"))
    eps = ctx.attr("epsilon", 1e-6)
    lr = _lr(ctx)
    graw = ctx.input("Grad")
    if is_sparse_grad(graw):
        # SelectedRows branch (reference: operators/adagrad_op.cc):
        # duplicate rows are merged before the non-linear update.
        def upd(p_rows, g_rows, m_rows):
            g32 = g_rows.astype(jnp.float32)
            m_new = m_rows + jnp.square(g32)
            return (p_rows.astype(jnp.float32)
                    - lr * g32 / (jnp.sqrt(m_new) + eps)), m_new

        p_new, m_new = rowwise_update(p, graw, upd, m)
        ctx.set_output("ParamOut", p_new)
        ctx.set_output("MomentOut", m_new)
        return
    g = unwrap(graw).astype(jnp.float32)
    m_new = m + jnp.square(g)
    p_new = p.astype(jnp.float32) - lr * g / (jnp.sqrt(m_new) + eps)
    ctx.set_output("ParamOut", p_new.astype(p.dtype))
    ctx.set_output("MomentOut", m_new)


@register_op("decayed_adagrad", inputs=("Param", "Grad", "Moment", "LearningRate"),
             outputs=("ParamOut", "MomentOut"), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param", MomentOut="Moment"))
def _decayed_adagrad(ctx):
    p = unwrap(ctx.input("Param"))
    g = unwrap(ctx.input("Grad")).astype(jnp.float32)
    m = unwrap(ctx.input("Moment"))
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * jnp.square(g)
    p_new = p.astype(jnp.float32) - _lr(ctx) * g / (jnp.sqrt(m_new) + eps)
    ctx.set_output("ParamOut", p_new.astype(p.dtype))
    ctx.set_output("MomentOut", m_new)


@register_op("adadelta", inputs=("Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"),
             outputs=("ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"),
             stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param",
                                       AvgSquaredGradOut="AvgSquaredGrad",
                                       AvgSquaredUpdateOut="AvgSquaredUpdate"))
def _adadelta(ctx):
    p = unwrap(ctx.input("Param"))
    g = unwrap(ctx.input("Grad")).astype(jnp.float32)
    ag = unwrap(ctx.input("AvgSquaredGrad"))
    au = unwrap(ctx.input("AvgSquaredUpdate"))
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    ag_new = rho * ag + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((au + eps) / (ag_new + eps)) * g
    au_new = rho * au + (1 - rho) * jnp.square(update)
    ctx.set_output("ParamOut", (p.astype(jnp.float32) + update).astype(p.dtype))
    ctx.set_output("AvgSquaredGradOut", ag_new)
    ctx.set_output("AvgSquaredUpdateOut", au_new)


@register_op("rmsprop", inputs=("Param", "MeanSquare", "LearningRate", "Grad", "Moment"),
             outputs=("ParamOut", "MomentOut", "MeanSquareOut"), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param", MomentOut="Moment",
                                       MeanSquareOut="MeanSquare"))
def _rmsprop(ctx):
    p = unwrap(ctx.input("Param"))
    g = unwrap(ctx.input("Grad")).astype(jnp.float32)
    ms = unwrap(ctx.input("MeanSquare"))
    mom = unwrap(ctx.input("Moment"))
    eps = ctx.attr("epsilon", 1e-10)
    decay = ctx.attr("decay", 0.9)
    momentum = ctx.attr("momentum", 0.0)
    ms_new = decay * ms + (1 - decay) * jnp.square(g)
    mom_new = momentum * mom + _lr(ctx) * g / jnp.sqrt(ms_new + eps)
    ctx.set_output("ParamOut", (p.astype(jnp.float32) - mom_new).astype(p.dtype))
    ctx.set_output("MomentOut", mom_new)
    ctx.set_output("MeanSquareOut", ms_new)


@register_op("ftrl",
             inputs=("Param", "SquaredAccumulator", "LinearAccumulator", "Grad",
                     "LearningRate"),
             outputs=("ParamOut", "SquaredAccumOut", "LinearAccumOut"),
             stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param",
                                       SquaredAccumOut="SquaredAccumulator",
                                       LinearAccumOut="LinearAccumulator"))
def _ftrl(ctx):
    p = unwrap(ctx.input("Param")).astype(jnp.float32)
    sq = unwrap(ctx.input("SquaredAccumulator"))
    lin = unwrap(ctx.input("LinearAccumulator"))
    g = unwrap(ctx.input("Grad")).astype(jnp.float32)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(ctx)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    pre = -jnp.sign(new_lin) * jnp.maximum(jnp.abs(new_lin) - l1, 0.0)
    denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    p_new = pre / denom
    ctx.set_output("ParamOut", p_new.astype(unwrap(ctx.input("Param")).dtype))
    ctx.set_output("SquaredAccumOut", new_sq)
    ctx.set_output("LinearAccumOut", new_lin)


@register_op("proximal_gd", inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param"))
def _proximal_gd(ctx):
    p = unwrap(ctx.input("Param")).astype(jnp.float32)
    g = unwrap(ctx.input("Grad")).astype(jnp.float32)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr = _lr(ctx)
    prox = p - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    ctx.set_output("ParamOut", p_new.astype(unwrap(ctx.input("Param")).dtype))


@register_op("proximal_adagrad", inputs=("Param", "Moment", "Grad", "LearningRate"),
             outputs=("ParamOut", "MomentOut"), stop_gradient=True,
             infer_shape=_infer_update(ParamOut="Param", MomentOut="Moment"))
def _proximal_adagrad(ctx):
    p = unwrap(ctx.input("Param")).astype(jnp.float32)
    m = unwrap(ctx.input("Moment"))
    g = unwrap(ctx.input("Grad")).astype(jnp.float32)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    m_new = m + jnp.square(g)
    lr_eff = _lr(ctx) / jnp.sqrt(m_new)
    prox = p - lr_eff * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_eff * l1, 0.0) / (1.0 + lr_eff * l2)
    ctx.set_output("ParamOut", p_new.astype(unwrap(ctx.input("Param")).dtype))
    ctx.set_output("MomentOut", m_new)
