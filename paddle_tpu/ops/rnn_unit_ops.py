"""Single-step recurrent cell ops.

Reference: operators/lstm_unit_op.cc (inputs X = packed gates (B, 4D)
and C_prev; outputs C, H) and operators/gru_unit_op.cc (inputs Input
(B, 3D), HiddenPrev, Weight (D, 3D), Bias; outputs Gate,
ResetHiddenPrev, Hidden).

These are the building blocks fluid's StaticRNN uses; the fused
whole-sequence ``lstm``/``gru`` ops (control_flow_ops / sequence path)
are the fast path — these unit ops exist for per-step graphs and
parity.  Gate math runs in one fused elementwise region after the
caller's big matmul, exactly what XLA fuses onto the MXU output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import SkipInferShape, register_op


def _make_unit_infer(in_slot, state_slot, mult, gate_slots, state_slots):
    """Single-step cell shape rule: ``in_slot`` packs ``mult`` gates per
    hidden unit, ``state_slot`` is (B, D).  Gate-sized outputs mirror
    the input, state-sized outputs mirror the previous state (derived
    from the input's last dim when the state shape is unknown).
    Backfill-only — the registry-audit ratchet's lstm/gru family."""

    def infer(op, block):
        def var_of(slot):
            names = op.inputs.get(slot, [])
            if len(names) != 1 or not names[0]:
                return None
            v = block.find_var(names[0])
            return v if v is not None and v.shape else None

        xv = var_of(in_slot)
        sv = var_of(state_slot)
        if sv is not None:
            state_shape = tuple(sv.shape)
        elif xv is not None:
            last = xv.shape[-1]
            if last >= 0 and last % mult:
                raise ValueError(
                    f"{op.type}: {in_slot} last dim {last} must carry "
                    f"{mult} packed gates per hidden unit")
            state_shape = tuple(xv.shape[:-1]) + (
                last // mult if last >= 0 else -1,)
        else:
            raise SkipInferShape
        hit = False
        targets = [(s, state_shape) for s in state_slots]
        if xv is not None:
            targets += [(s, tuple(xv.shape)) for s in gate_slots]
        for slot, shape in targets:
            outs = op.outputs.get(slot, [])
            if len(outs) != 1 or not outs[0]:
                continue
            ov = block.find_var(outs[0])
            if ov is None:
                continue
            hit = True
            if ov.shape is None:
                ov.shape = shape
        if not hit:
            raise SkipInferShape

    return infer


@register_op("lstm_unit", inputs=("X", "C_prev"), outputs=("C", "H"),
             infer_shape=_make_unit_infer("X", "C_prev", 4, (),
                                          ("C", "H")))
def _lstm_unit(ctx):
    x = unwrap(ctx.input("X"))                # (B, 4D): i, g (cell cand), f, o
    c_prev = unwrap(ctx.input("C_prev"))      # (B, D)
    forget_bias = float(ctx.attr("forget_bias", 0.0))
    d = c_prev.shape[-1]
    i, g, f, o = (x[..., 0:d], x[..., d:2 * d], x[..., 2 * d:3 * d],
                  x[..., 3 * d:4 * d])
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    o = jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    ctx.set_output("C", c)
    ctx.set_output("H", h)


@register_op("gru_unit", inputs=("Input", "HiddenPrev", "Weight", "Bias"),
             outputs=("Gate", "ResetHiddenPrev", "Hidden"),
             infer_shape=_make_unit_infer("Input", "HiddenPrev", 3,
                                          ("Gate",),
                                          ("ResetHiddenPrev", "Hidden")))
def _gru_unit(ctx):
    """u = sigma(xu + h W_u); r = sigma(xr + h W_r);
    c = act(xc + (r*h) W_c); h' = u*h + (1-u)*c  (reference gate order
    update/reset/candidate, gru_unit_op.cc)."""
    x = unwrap(ctx.input("Input"))            # (B, 3D)
    h_prev = unwrap(ctx.input("HiddenPrev"))  # (B, D)
    w = unwrap(ctx.input("Weight"))           # (D, 3D)
    b = unwrap(ctx.input("Bias")) if ctx.has_input("Bias") else None
    d = h_prev.shape[-1]
    if b is not None:
        x = x + b.reshape((1, 3 * d))
    w_rz, w_c = w[:, : 2 * d], w[:, 2 * d:]
    gates = x[..., : 2 * d] + h_prev @ w_rz
    u = jax.nn.sigmoid(gates[..., :d])
    r = jax.nn.sigmoid(gates[..., d: 2 * d])
    act = {"tanh": jnp.tanh, "relu": jax.nn.relu,
           "sigmoid": jax.nn.sigmoid, "identity": lambda v: v}[
        ctx.attr("activation", "tanh")]
    c = act(x[..., 2 * d:] + (r * h_prev) @ w_c)
    h = u * h_prev + (1.0 - u) * c
    ctx.set_output("Gate", jnp.concatenate([u, r, c], axis=-1))
    ctx.set_output("ResetHiddenPrev", r * h_prev)
    ctx.set_output("Hidden", h)
