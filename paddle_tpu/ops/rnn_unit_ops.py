"""Single-step recurrent cell ops.

Reference: operators/lstm_unit_op.cc (inputs X = packed gates (B, 4D)
and C_prev; outputs C, H) and operators/gru_unit_op.cc (inputs Input
(B, 3D), HiddenPrev, Weight (D, 3D), Bias; outputs Gate,
ResetHiddenPrev, Hidden).

These are the building blocks fluid's StaticRNN uses; the fused
whole-sequence ``lstm``/``gru`` ops (control_flow_ops / sequence path)
are the fast path — these unit ops exist for per-step graphs and
parity.  Gate math runs in one fused elementwise region after the
caller's big matmul, exactly what XLA fuses onto the MXU output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import register_op


@register_op("lstm_unit", inputs=("X", "C_prev"), outputs=("C", "H"))
def _lstm_unit(ctx):
    x = unwrap(ctx.input("X"))                # (B, 4D): i, g (cell cand), f, o
    c_prev = unwrap(ctx.input("C_prev"))      # (B, D)
    forget_bias = float(ctx.attr("forget_bias", 0.0))
    d = c_prev.shape[-1]
    i, g, f, o = (x[..., 0:d], x[..., d:2 * d], x[..., 2 * d:3 * d],
                  x[..., 3 * d:4 * d])
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    o = jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    ctx.set_output("C", c)
    ctx.set_output("H", h)


@register_op("gru_unit", inputs=("Input", "HiddenPrev", "Weight", "Bias"),
             outputs=("Gate", "ResetHiddenPrev", "Hidden"))
def _gru_unit(ctx):
    """u = sigma(xu + h W_u); r = sigma(xr + h W_r);
    c = act(xc + (r*h) W_c); h' = u*h + (1-u)*c  (reference gate order
    update/reset/candidate, gru_unit_op.cc)."""
    x = unwrap(ctx.input("Input"))            # (B, 3D)
    h_prev = unwrap(ctx.input("HiddenPrev"))  # (B, D)
    w = unwrap(ctx.input("Weight"))           # (D, 3D)
    b = unwrap(ctx.input("Bias")) if ctx.has_input("Bias") else None
    d = h_prev.shape[-1]
    if b is not None:
        x = x + b.reshape((1, 3 * d))
    w_rz, w_c = w[:, : 2 * d], w[:, 2 * d:]
    gates = x[..., : 2 * d] + h_prev @ w_rz
    u = jax.nn.sigmoid(gates[..., :d])
    r = jax.nn.sigmoid(gates[..., d: 2 * d])
    act = {"tanh": jnp.tanh, "relu": jax.nn.relu,
           "sigmoid": jax.nn.sigmoid, "identity": lambda v: v}[
        ctx.attr("activation", "tanh")]
    c = act(x[..., 2 * d:] + (r * h_prev) @ w_c)
    h = u * h_prev + (1.0 - u) * c
    ctx.set_output("Gate", jnp.concatenate([u, r, c], axis=-1))
    ctx.set_output("ResetHiddenPrev", r * h_prev)
    ctx.set_output("Hidden", h)
