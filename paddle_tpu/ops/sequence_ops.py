"""Sequence / LoD ops.

Reference: operators/{sequence_pool,sequence_softmax,sequence_concat,
sequence_expand,seq_expand,lod_reset,sequence_slice}_op.cc and the
fused RNN ops operators/{lstm,gru}_op.cc.

TPU design: LoDArray = packed dense rows + offset vectors as traced
device values (see paddle_tpu.lod).  Ragged reductions become
segment-sum/max over static row counts; the fused RNNs run `lax.scan`
over a batch-major padded view (reference analog:
operators/math/sequence2batch.h) so each step is one big MXU matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import LoDArray, rewrap, row_segment_ids, unwrap
from paddle_tpu.registry import SkipInferShape, infer_same_shape, register_op


# ---------------------------------------------------------------------------
# infer_shape rules (registry-audit ratchet: padded-sequence family).
# The padded ops are plain dense tensors + length side-feeds, so their
# shapes are statically knowable; the LoD ops (packed rows + offsets)
# stay dynamic and keep SkipInferShape semantics via omission.
# ---------------------------------------------------------------------------


def _seq_io_vars(op, block):
    # the one slot-resolution contract, shared with the conv/pool rules
    from paddle_tpu.ops.nn_ops import _io_vars

    return _io_vars(op, block, "X", "Out")


def _infer_drop_time_shape(op, block):
    """Pooling over the padded time dim: (B, T, ...) -> (B, ...)."""
    xv, ov = _seq_io_vars(op, block)
    if len(xv.shape) < 2:
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = (xv.shape[0],) + tuple(xv.shape[2:])


def _infer_drop_subseq_time_shape(op, block):
    """Nested pooling: (B, S, T, ...) -> (B, S, ...)."""
    xv, ov = _seq_io_vars(op, block)
    if len(xv.shape) < 3:
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(xv.shape[:2]) + tuple(xv.shape[3:])


def _infer_stride_pool_shape(op, block):
    xv, ov = _seq_io_vars(op, block)
    if len(xv.shape) < 2:
        raise SkipInferShape
    stride = op.attr("stride", None)
    if not stride:
        raise SkipInferShape
    t = xv.shape[1]
    if ov.shape is None:
        w = -(-t // int(stride)) if t >= 0 else -1
        ov.shape = (xv.shape[0], w) + tuple(xv.shape[2:])
    outs = op.outputs.get("OutLength", [])
    if len(outs) == 1 and outs[0]:
        lv = block.find_var(outs[0])
        if lv is not None and lv.shape is None:
            lv.shape = (xv.shape[0],)


def _infer_subseq_mask_flatten_shape(op, block):
    """mask_padded_subseq_scores: (B, S, T[, 1]) -> (B, S*T)."""
    xv, ov = _seq_io_vars(op, block)
    shape = tuple(xv.shape)
    if len(shape) == 4 and shape[-1] == 1:
        shape = shape[:-1]
    if len(shape) != 3:
        raise SkipInferShape
    if ov.shape is None:
        b, s, t = shape
        ov.shape = (b, s * t if s >= 0 and t >= 0 else -1)


def _infer_context_project_shape(op, block):
    xv, ov = _seq_io_vars(op, block)
    if ov.shape is not None:
        return
    ctx_len = op.attr("context_length", None)
    if not ctx_len or len(xv.shape) < 2:
        raise SkipInferShape
    last = xv.shape[-1]
    ov.shape = tuple(xv.shape[:-1]) + (
        last * int(ctx_len) if last >= 0 else -1,)


def _infer_sequence_concat_shape(op, block):
    """axis=1 (feature concat) is statically knowable; the temporal
    axis=0 mode joins along a LoD-dynamic time dim and stays skipped."""
    if op.attr("axis", 0) != 1:
        raise SkipInferShape
    ins = op.inputs.get("X", [])
    outs = op.outputs.get("Out", [])
    if not ins or len(outs) != 1 or not outs[0]:
        raise SkipInferShape
    xvs = [block.find_var(n) for n in ins if n]
    ov = block.find_var(outs[0])
    if len(xvs) != len(ins) or ov is None or any(
            v is None or v.shape is None or len(v.shape) < 2 for v in xvs):
        raise SkipInferShape
    dims = [v.shape[1] for v in xvs]
    base = list(xvs[0].shape)
    base[1] = -1 if any(d < 0 for d in dims) else sum(dims)
    if ov.shape is None:
        ov.shape = tuple(base)
    if ov.lod_level == 0 and xvs[0].lod_level:
        ov.lod_level = xvs[0].lod_level


def _seg_ids(x: LoDArray):
    off = x.last_level()
    return row_segment_ids(off, x.data.shape[0]), off.shape[0] - 1


@register_op("sequence_pool", inputs=("X",), outputs=("Out", "MaxIndex"))
def _sequence_pool(ctx):
    x = ctx.input("X")
    assert isinstance(x, LoDArray), "sequence_pool needs a LoD input"
    ids, nseq = _seg_ids(x)
    data = x.data
    ptype = ctx.attr("pooltype", "AVERAGE").upper()
    if ptype == "SUM":
        out = jax.ops.segment_sum(data, ids, num_segments=nseq)
    elif ptype == "AVERAGE":
        s = jax.ops.segment_sum(data, ids, num_segments=nseq)
        lens = x.seq_lens().astype(data.dtype).reshape(-1, *([1] * (data.ndim - 1)))
        out = s / jnp.maximum(lens, 1)
    elif ptype == "SQRT":
        s = jax.ops.segment_sum(data, ids, num_segments=nseq)
        lens = x.seq_lens().astype(data.dtype).reshape(-1, *([1] * (data.ndim - 1)))
        out = s / jnp.sqrt(jnp.maximum(lens, 1))
    elif ptype == "MAX":
        out = jax.ops.segment_max(data, ids, num_segments=nseq)
        ctx.has_output("MaxIndex") and ctx.set_output(
            "MaxIndex", jnp.zeros((nseq,) + data.shape[1:], jnp.int32)
        )
    elif ptype == "LAST":
        off = x.last_level()
        out = jnp.take(data, jnp.maximum(off[1:] - 1, 0), axis=0)
    elif ptype == "FIRST":
        off = x.last_level()
        out = jnp.take(data, off[:-1], axis=0)
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    ctx.set_output("Out", out)


@register_op("sequence_softmax", inputs=("X",),
             infer_shape=infer_same_shape)
def _sequence_softmax(ctx):
    x = ctx.input("X")
    assert isinstance(x, LoDArray)
    ids, nseq = _seg_ids(x)
    data = x.data.reshape(-1)
    mx = jax.ops.segment_max(data, ids, num_segments=nseq)
    shifted = data - mx[ids]
    e = jnp.exp(shifted)
    denom = jax.ops.segment_sum(e, ids, num_segments=nseq)
    out = e / denom[ids]
    # padding rows (ids == nseq would be OOB; they index garbage via clip)
    valid = ids < nseq
    out = jnp.where(valid, out, 0.0)
    ctx.set_output("Out", LoDArray(out.reshape(x.data.shape), x.lod))


def _temporal_concat_pair(a: LoDArray, b: LoDArray) -> LoDArray:
    """Concat sequence i of ``a`` with sequence i of ``b`` along time
    (reference: operators/sequence_concat_op.cc axis=0).  Packed-row
    re-interleave with static shapes: output row r maps to a source row
    in [A; B] computed from the offset tables."""
    a_off = a.last_level().astype(jnp.int32)
    b_off = b.last_level().astype(jnp.int32)
    na = a.data.shape[0]
    n_out = na + b.data.shape[0]
    out_off = a_off + b_off
    seq = row_segment_ids(out_off, n_out)
    seq = jnp.clip(seq, 0, a_off.shape[0] - 2)
    pos = jnp.arange(n_out, dtype=jnp.int32) - out_off[seq]
    a_len = a_off[seq + 1] - a_off[seq]
    src = jnp.where(pos < a_len,
                    a_off[seq] + pos,
                    na + b_off[seq] + (pos - a_len))
    both = jnp.concatenate([a.data, b.data], axis=0)
    out = jnp.take(both, jnp.clip(src, 0, n_out - 1), axis=0)
    lod = a.lod[:-1] + (out_off,) if len(a.lod) == len(b.lod) else (out_off,)
    return LoDArray(out, lod)


def _temporal_concat_padded(a, la, b, lb):
    """Padded ragged temporal concat: out[s] = a[s, :la[s]] ++ b[s, :lb[s]],
    zero-padded to Ta+Tb (the SeqVal twin of the packed path above)."""
    ta, tb = a.shape[1], b.shape[1]
    t = jnp.arange(ta + tb, dtype=jnp.int32)[None, :]      # (1, Tout)
    la = la.reshape(-1, 1).astype(jnp.int32)
    lb = lb.reshape(-1, 1).astype(jnp.int32)
    rows = jnp.arange(a.shape[0])[:, None]
    ga = a[rows, jnp.clip(t, 0, ta - 1)]
    gb = b[rows, jnp.clip(t - la, 0, tb - 1)]
    feat_shape = (1,) * (a.ndim - 2)
    from_a = (t < la).reshape((a.shape[0], ta + tb) + feat_shape)
    valid = (t < la + lb).reshape(from_a.shape)
    return jnp.where(from_a, ga, gb) * valid.astype(a.dtype)


@register_op("sequence_concat", inputs=("X", "Length"),
             infer_shape=_infer_sequence_concat_shape)
def _sequence_concat(ctx):
    """Concat same-LoD inputs: axis=1 joins features, axis=0 joins each
    pair of sequences along *time* (reference: operators/
    sequence_concat_op.cc both modes).  axis=0 accepts packed LoD
    inputs or padded (B, T, ...) inputs with optional per-input Length
    vectors (absent = full length)."""
    xs = ctx.inputs("X")
    axis = ctx.attr("axis", 0)
    if axis == 1:
        out = jnp.concatenate([unwrap(v) for v in xs], axis=1)
        ctx.set_output("Out", rewrap(xs[0], out))
        return
    if isinstance(xs[0], LoDArray):
        acc = xs[0]
        for nxt in xs[1:]:
            acc = _temporal_concat_pair(acc, nxt)
        ctx.set_output("Out", acc)
        return
    lens = ([unwrap(v) for v in ctx.inputs("Length")]
            if ctx.has_input("Length") else
            [jnp.full((x.shape[0],), x.shape[1], jnp.int32) for x in xs])
    acc, lacc = unwrap(xs[0]), lens[0]
    for nxt, ln in zip(xs[1:], lens[1:]):
        acc = _temporal_concat_padded(acc, lacc, unwrap(nxt), ln)
        lacc = lacc.reshape(-1) + ln.reshape(-1)
    ctx.set_output("Out", acc)


@register_op("seq_expand", inputs=("X", "Y"), diff_inputs=("X",))
def _seq_expand(ctx):
    """Expand X's rows so each input row/sequence repeats to match Y's
    LoD (reference: operators/seq_expand_op.cc)."""
    x = ctx.input("X")
    y = ctx.input("Y")
    assert isinstance(y, LoDArray)
    y_off = y.last_level()
    n_out = y.data.shape[0]
    ids = row_segment_ids(y_off, n_out)
    xd = unwrap(x)
    out = jnp.take(xd, jnp.clip(ids, 0, xd.shape[0] - 1), axis=0)
    ctx.set_output("Out", LoDArray(out, y.lod))


@register_op("lod_reset", inputs=("X", "TargetLoD"),
             infer_shape=infer_same_shape)
def _lod_reset(ctx):
    x = ctx.input("X")
    data = unwrap(x)
    if ctx.has_input("TargetLoD"):
        target = unwrap(ctx.input("TargetLoD")).astype(jnp.int32)
    else:
        target = jnp.asarray(ctx.attr("target_lod"), jnp.int32)
    ctx.set_output("Out", LoDArray(data, (target,)))


@register_op("padded_sequence_pool", inputs=("X", "Length"),
             infer_shape=_infer_drop_time_shape)
def _padded_sequence_pool(ctx):
    """Masked pooling over padded (B, T, D) sequences with lengths (B,)
    — the dense-layout twin of sequence_pool for the v2 facade."""
    x = unwrap(ctx.input("X"))          # (B, T, D) or (B, T)
    lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    ptype = ctx.attr("pooltype", "AVERAGE").upper()
    ptype = {"AVG": "AVERAGE"}.get(ptype, ptype)
    B, T = x.shape[0], x.shape[1]
    mask = (jnp.arange(T)[None, :] < lens[:, None])  # (B, T)
    if ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape(-1, 1, *([1] * (x.ndim - 2))), axis=1)[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        out = _masked_pool(x, mask, ptype, axis=1)
    ctx.set_output("Out", out)


def _masked_pool(x, mask, ptype, axis):
    """Pool ``x`` over ``axis`` under a boolean mask (same shape as x up
    to trailing feature dims)."""
    ptype = {"AVG": "AVERAGE"}.get(ptype, ptype)
    m = mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim)).astype(x.dtype)
    n = jnp.maximum(jnp.sum(m, axis=axis), 1.0)
    if ptype == "SUM":
        return jnp.sum(x * m, axis=axis)
    if ptype == "AVERAGE":
        return jnp.sum(x * m, axis=axis) / n
    if ptype == "SQRT":
        return jnp.sum(x * m, axis=axis) / jnp.sqrt(n)
    if ptype == "MAX":
        neg = jnp.asarray(-1e9, x.dtype)
        return jnp.max(jnp.where(m.astype(bool), x, neg), axis=axis)
    raise ValueError(ptype)


@register_op("padded_subseq_pool", inputs=("X", "Length", "SubLength"),
             diff_inputs=("X",),
             infer_shape=_infer_drop_subseq_time_shape)
def _padded_subseq_pool(ctx):
    """Pooling over a padded 2-level nested sequence (reference:
    gserver/layers/SequencePoolLayer.cpp with trans_type="seq"/"non-seq"
    over a nested input).  X (B, S, T, D), Length (B,) = #subsequences,
    SubLength (B, S) = steps per subsequence.

    agg="seq"  -> pool each subsequence:  (B, S, D)  (a plain sequence
                  whose lengths are Length)
    agg="none" -> pool every inner step:  (B, D)
    """
    x = unwrap(ctx.input("X"))                    # (B, S, T, ...)
    outer = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    sub = unwrap(ctx.input("SubLength")).astype(jnp.int32)  # (B, S)
    ptype = ctx.attr("pooltype", "AVERAGE").upper()
    agg = ctx.attr("agg", "seq")
    B, S, T = x.shape[0], x.shape[1], x.shape[2]
    # inner mask: step t of subseq s is real iff t < sub[b,s] AND s < outer[b]
    s_real = (jnp.arange(S)[None, :] < outer[:, None])          # (B, S)
    t_mask = (jnp.arange(T)[None, None, :] < sub[:, :, None])   # (B, S, T)
    mask = jnp.logical_and(t_mask, s_real[:, :, None])
    if agg == "seq":
        out = _masked_pool(x, mask, ptype, axis=2)              # (B, S, ...)
        ctx.set_output("Out", out)
    else:
        flat = x.reshape((B, S * T) + x.shape[3:])
        out = _masked_pool(flat, mask.reshape(B, S * T), ptype, axis=1)
        ctx.set_output("Out", out)


@register_op("subseq_flatten", inputs=("X", "Length", "SubLength"),
             outputs=("Out", "OutLength"), diff_inputs=("X",))
def _subseq_flatten(ctx):
    """Flatten a padded nested sequence (B, S, T, ...) to the packed
    plain sequence view (B, S*T, ...) the reference's outer
    sequenceStartPositions expose: real inner steps compacted to the
    front (stable), lengths = total real steps per sample."""
    x = unwrap(ctx.input("X"))
    outer = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    sub = unwrap(ctx.input("SubLength")).astype(jnp.int32)
    B, S, T = x.shape[0], x.shape[1], x.shape[2]
    s_real = (jnp.arange(S)[None, :] < outer[:, None])
    mask = jnp.logical_and(
        jnp.arange(T)[None, None, :] < sub[:, :, None],
        s_real[:, :, None]).reshape(B, S * T)
    # stable argsort of (not real) puts real steps first, in order
    perm = jnp.argsort(~mask, axis=1, stable=True)
    flat = x.reshape((B, S * T) + x.shape[3:])
    out = jnp.take_along_axis(
        flat, perm.reshape((B, S * T) + (1,) * (flat.ndim - 2)), axis=1)
    ctx.set_output("Out", out)
    ctx.set_output("OutLength", jnp.sum(mask.astype(jnp.int32), axis=1))


@register_op("padded_sequence_multi_slice",
             inputs=("X", "Length", "Starts", "Ends"),
             outputs=("Out", "OutLength", "OutSubLength"),
             diff_inputs=("X",))
def _padded_sequence_multi_slice(ctx):
    """K slices out of each sequence (reference:
    gserver/layers/SeqSliceLayer.cpp — starts/ends are (B, K), each row
    selects K windows, and the output is K sequences per input, i.e. a
    nested sequence).  X (B, T, D) -> Out (B, K, T, D) with
    OutSubLength (B, K) = clamped end-start and OutLength (B,) = K."""
    x = unwrap(ctx.input("X"))
    lens = unwrap(ctx.input("Length")).reshape(x.shape[0], -1)[:, 0] \
        if unwrap(ctx.input("Length")).ndim > 1 else \
        unwrap(ctx.input("Length")).reshape(-1)
    lens = lens.astype(jnp.int32)
    B, T = x.shape[0], x.shape[1]
    if ctx.has_input("Starts"):
        starts = unwrap(ctx.input("Starts")).astype(jnp.int32)
    else:
        starts = None
    if ctx.has_input("Ends"):
        ends = unwrap(ctx.input("Ends")).astype(jnp.int32)
    else:
        ends = None
    if starts is None:
        starts = jnp.zeros_like(ends)
    if ends is None:
        ends = jnp.broadcast_to(lens[:, None], starts.shape)
    K = starts.shape[1]
    starts = jnp.clip(starts, 0, lens[:, None])
    ends = jnp.clip(ends, starts, lens[:, None])
    sub_len = ends - starts                                   # (B, K)
    t = jnp.arange(T)[None, None, :]                          # (1, 1, T)
    idx = jnp.clip(starts[:, :, None] + t, 0, T - 1)          # (B, K, T)
    gathered = jnp.take_along_axis(
        x[:, None], idx.reshape(B, K, T, *([1] * (x.ndim - 2))), axis=2)
    mask = (t < sub_len[:, :, None]).reshape(
        (B, K, T) + (1,) * (x.ndim - 2))
    ctx.set_output("Out", jnp.where(mask, gathered, 0))
    ctx.set_output("OutLength", jnp.full((B,), K, jnp.int32))
    ctx.set_output("OutSubLength", sub_len)


@register_op("padded_subseq_slice",
             inputs=("X", "SubLength", "Starts", "Ends"),
             outputs=("Out", "OutSubLength"), diff_inputs=("X",))
def _padded_subseq_slice(ctx):
    """Per-subsequence window slice of a padded nested sequence
    (reference: SeqSliceLayer over a nested input — each subsequence s
    of sample b yields its [starts[b,s], ends[b,s]) window, re-packed
    to the front).  X (B, S, T, D), SubLength (B, S)."""
    x = unwrap(ctx.input("X"))
    sub = unwrap(ctx.input("SubLength")).astype(jnp.int32)   # (B, S)
    B, S, T = x.shape[0], x.shape[1], x.shape[2]
    starts = (unwrap(ctx.input("Starts")).astype(jnp.int32)
              if ctx.has_input("Starts") else jnp.zeros_like(sub))
    ends = (unwrap(ctx.input("Ends")).astype(jnp.int32)
            if ctx.has_input("Ends") else sub)
    # feeders may bucket-pad the starts/ends step dim past S
    starts = starts.reshape(B, -1)[:, :S]
    ends = ends.reshape(B, -1)[:, :S]
    starts = jnp.clip(starts, 0, sub)
    ends = jnp.clip(ends, starts, sub)
    t = jnp.arange(T)[None, None, :]
    idx = jnp.clip(starts[:, :, None] + t, 0, T - 1)          # (B, S, T)
    gathered = jnp.take_along_axis(
        x, idx.reshape((B, S, T) + (1,) * (x.ndim - 3)), axis=2)
    new_len = ends - starts
    mask = (t < new_len[:, :, None]).reshape(
        (B, S, T) + (1,) * (x.ndim - 3))
    ctx.set_output("Out", jnp.where(mask, gathered, 0))
    ctx.set_output("OutSubLength", new_len)


@register_op("padded_sequence_stride_pool", inputs=("X", "Length"),
             outputs=("Out", "OutLength"), diff_inputs=("X",),
             infer_shape=_infer_stride_pool_shape)
def _padded_sequence_stride_pool(ctx):
    """Strided sequence pooling (reference: SequencePoolLayer stride_ —
    pool each window of ``stride`` steps; output is a shorter sequence
    of ceil(len/stride) window-pools)."""
    x = unwrap(ctx.input("X"))          # (B, T, ...)
    lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    ptype = ctx.attr("pooltype", "AVERAGE").upper()
    stride = int(ctx.attr("stride"))
    B, T = x.shape[0], x.shape[1]
    W = -(-T // stride)                 # windows
    pad = W * stride - T
    if pad:
        x = jnp.pad(x, [(0, 0), (0, pad)] + [(0, 0)] * (x.ndim - 2))
    xw = x.reshape((B, W, stride) + x.shape[2:])
    tidx = jnp.arange(W * stride).reshape(W, stride)
    mask = (tidx[None] < lens[:, None, None])       # (B, W, stride)
    ctx.set_output("Out", _masked_pool(xw, mask, ptype, axis=2))
    ctx.set_output("OutLength", -(-jnp.maximum(lens, 0) // stride))


@register_op("padded_sequence_max_index", inputs=("X", "Length"),
             stop_gradient=True, infer_shape=_infer_drop_time_shape)
def _padded_sequence_max_index(ctx):
    """Max pooling returning the argmax step index per feature
    (reference: MaxPoolingType(output_max_index=True),
    gserver/layers/MaxLayer.cpp IVector output)."""
    x = unwrap(ctx.input("X"))          # (B, T, D)
    lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    mask = (jnp.arange(x.shape[1])[None, :] < lens[:, None])
    m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    neg = jnp.asarray(-1e9, x.dtype)
    idx = jnp.argmax(jnp.where(m, x, neg), axis=1)
    ctx.set_output("Out", idx.astype(jnp.float32))


def _window_reverse(x, lens):
    """Gather-reverse each row of padded (B, T, ...) inside its valid
    window; zeros beyond.  Involution: applying twice restores order.
    → (reversed_x, src_index_map, valid_mask)."""
    T = x.shape[1]
    lens = lens.reshape(-1).astype(jnp.int32)
    t = jnp.arange(T, dtype=jnp.int32)
    src = jnp.clip(lens[:, None] - 1 - t[None, :], 0, T - 1)   # (B, T)
    valid = (t[None, :] < lens[:, None])
    idx = src.reshape(src.shape + (1,) * (x.ndim - 2))
    mask = valid.reshape(valid.shape + (1,) * (x.ndim - 2))
    out = jnp.take_along_axis(x, idx, axis=1) * mask.astype(x.dtype)
    return out, src, valid


def _make_rnn_seq_infer(mult, gate_slots, hidden_slots):
    """Fused-RNN shape rule: ``Input`` carries ``mult`` pre-projected
    gates per hidden unit, so hidden-sized outputs are Input with the
    last dim divided by ``mult`` and gate-sized outputs mirror Input.
    Backfill-only (never overwrites builder-stamped shapes), propagates
    lod_level — the registry-audit ratchet's lstm/gru family."""

    def infer(op, block):
        ins = op.inputs.get("Input", [])
        if len(ins) != 1 or not ins[0]:
            raise SkipInferShape
        xv = block.find_var(ins[0])
        if xv is None or xv.shape is None or not xv.shape:
            raise SkipInferShape
        last = xv.shape[-1]
        if last >= 0 and last % mult:
            raise ValueError(
                f"{op.type}: Input last dim {last} must carry {mult} "
                f"packed gates per hidden unit")
        hid = tuple(xv.shape[:-1]) + (last // mult if last >= 0 else -1,)
        hit = False
        targets = ([(s, tuple(xv.shape)) for s in gate_slots]
                   + [(s, hid) for s in hidden_slots])
        for slot, shape in targets:
            outs = op.outputs.get(slot, [])
            if len(outs) != 1 or not outs[0]:
                continue
            ov = block.find_var(outs[0])
            if ov is None:
                continue
            hit = True
            if ov.shape is None:
                ov.shape = shape
            if ov.lod_level == 0 and xv.lod_level:
                ov.lod_level = xv.lod_level
        if not hit:
            raise SkipInferShape

    return infer


@register_op("lstm",
             inputs=("Input", "H0", "C0", "Weight", "Bias", "Length"),
             outputs=("Hidden", "Cell", "BatchGate", "BatchCellPreAct"),
             diff_inputs=("Input", "H0", "C0", "Weight", "Bias"),
             infer_shape=_make_rnn_seq_infer(
                 4, ("BatchGate",),
                 ("Hidden", "Cell", "BatchCellPreAct")))
def _lstm(ctx):
    """Fused LSTM over a padded batch-major tensor.

    Reference: operators/lstm_op.cc runs gate-matmuls per LoD batch via
    sequence2batch; here Input is (batch, time, 4*hidden) pre-projected
    gate activations (the reference's layout: input already multiplied
    by W_x in a `mul` op), Weight is the recurrent (hidden, 4*hidden),
    Bias (1, 4*hidden [+ 3*hidden peephole]).  Lowering = lax.scan over
    time with one (batch, hidden) x (hidden, 4*hidden) MXU matmul per
    step; padding handled by a length mask if Input is a LoDArray.
    """
    x_in = ctx.input("Input")
    is_lod = isinstance(x_in, LoDArray)
    if is_lod:
        # Packed LoD rows -> padded (S, Tmax, 4H) where Tmax = N (the
        # static bound; offsets are traced values).  Padding sits after
        # each sequence's end, so garbage steps never contaminate valid
        # outputs; valid rows are re-gathered into packed layout below.
        # Callers with many sequences should pre-pad (the fast path).
        off = x_in.last_level().astype(jnp.int32)
        data = x_in.data                       # (N, 4H)
        N = data.shape[0]
        S = off.shape[0] - 1
        # the pad-out below materializes (S, N, 4H): quadratic in the
        # sequence count because N (total rows) is the only static
        # Tmax bound when offsets are traced.  Guard against the
        # silent OOM/perf cliff instead of allocating tens of GB.
        import os as _os

        limit = int(_os.environ.get("PADDLE_TPU_LOD_LSTM_PAD_LIMIT",
                                    1 << 30))
        if S * N * data.shape[-1] > limit:
            raise ValueError(
                f"LoD lstm: padding {S} sequences of {N} packed rows "
                f"would materialize a {S}x{N}x{data.shape[-1]} tensor "
                f"({S * N * data.shape[-1] * 4 / 1e9:.1f} GB f32). "
                "Pre-pad the input to (batch, Tmax, 4H) (the fast "
                "path), split the batch, or raise "
                "PADDLE_TPU_LOD_LSTM_PAD_LIMIT.")
        t_idx = jnp.arange(N, dtype=jnp.int32)
        lens = off[1:] - off[:-1]
        lod_reverse = bool(ctx.attr("is_reverse", False))
        # ragged reversal happens inside each valid window at pad time
        src_t = (lens[:, None] - 1 - t_idx[None, :]) if lod_reverse \
            else t_idx[None, :]
        gather_idx = jnp.clip(off[:-1, None] + src_t, 0, N - 1)
        valid = (t_idx[None, :] < lens[:, None])
        x_pad = jnp.take(data, gather_idx.reshape(-1), axis=0).reshape(
            S, N, data.shape[-1])
        x_pad = x_pad * valid[:, :, None].astype(data.dtype)
        x_in = x_pad
    x = unwrap(x_in)  # (B, T, 4H)
    B, T, H4 = x.shape
    H = H4 // 4
    w = unwrap(ctx.input("Weight"))  # (H, 4H)
    bias = unwrap(ctx.input("Bias")) if ctx.has_input("Bias") else None
    use_peepholes = ctx.attr("use_peepholes", False) and bias is not None and bias.shape[-1] == 7 * H
    b_gate = bias[..., : 4 * H].reshape(1, 4 * H) if bias is not None else 0.0

    # initial carry in x.dtype: explicit f32 H0/C0 under amp must match
    # the step's pinned carry dtype
    h0 = (unwrap(ctx.input("H0")).astype(x.dtype) if ctx.has_input("H0")
          else jnp.zeros((B, H), x.dtype))
    c0 = (unwrap(ctx.input("C0")).astype(x.dtype) if ctx.has_input("C0")
          else jnp.zeros((B, H), x.dtype))

    gate_act = _act_fn(ctx.attr("gate_activation", "sigmoid"))
    cell_act = _act_fn(ctx.attr("cell_activation", "tanh"))
    cand_act = _act_fn(ctx.attr("candidate_activation", "tanh"))

    if use_peepholes:
        w_ic = bias[..., 4 * H : 5 * H].reshape(1, H)
        w_fc = bias[..., 5 * H : 6 * H].reshape(1, H)
        w_oc = bias[..., 6 * H : 7 * H].reshape(1, H)

    def step(carry, xt):
        h, c = carry
        gates = xt + jnp.dot(h, w, preferred_element_type=jnp.float32).astype(x.dtype) + b_gate
        i, f, ct_, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = gate_act(i + w_ic * c)
            f = gate_act(f + w_fc * c)
        else:
            i = gate_act(i)
            f = gate_act(f)
        cand = cand_act(ct_)
        c_new = f * c + i * cand
        o = gate_act(o + w_oc * c_new) if use_peepholes else gate_act(o)
        h_new = o * cell_act(c_new)
        # keep the carry dtype stable: under amp the f32 master bias
        # promotes the gate math to f32 while h0/c0 are bf16
        h_new = h_new.astype(x.dtype)
        c_new = c_new.astype(x.dtype)
        return (h_new, c_new), (h_new, c_new)

    # padded + reversed + lengths known: reverse INSIDE each row's
    # valid window (the reference's LoD reverse semantics) instead of
    # flipping the whole padded axis through the padding
    win_src = None
    if (ctx.attr("is_reverse", False) and not is_lod
            and ctx.has_input("Length")):
        _lens_arr = unwrap(ctx.input("Length"))
        x, win_src, _valid = _window_reverse(x, _lens_arr)

    xs = jnp.swapaxes(x, 0, 1)  # (T, B, 4H)
    # LoD input already reverses inside each valid window at pad time
    whole_reverse = (ctx.attr("is_reverse", False) and not is_lod
                     and win_src is None)
    if whole_reverse:
        xs = xs[::-1]

    from paddle_tpu import pallas as pk

    default_acts = (ctx.attr("gate_activation", "sigmoid") == "sigmoid"
                    and ctx.attr("cell_activation", "tanh") == "tanh"
                    and ctx.attr("candidate_activation", "tanh") == "tanh")
    if default_acts and not use_peepholes and pk.use_lstm(B, H):
        from paddle_tpu.pallas import lstm as pk_lstm

        bias_vec = (b_gate if bias is not None
                    else jnp.zeros((1, 4 * H), x.dtype))
        hs, cs = pk_lstm.lstm_seq(
            xs, w, bias_vec, h0, c0, pk.interpret_mode())
    else:
        (_, _), (hs, cs) = lax.scan(step, (h0, c0), xs)
    if whole_reverse:
        hs, cs = hs[::-1], cs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1)  # (B, T, H)
    cell = jnp.swapaxes(cs, 0, 1)
    if win_src is not None:
        # un-reverse: the window map is an involution; re-zero padding
        hidden, _, _ = _window_reverse(hidden, _lens_arr)
        cell, _, _ = _window_reverse(cell, _lens_arr)
    if is_lod:
        # re-gather valid steps into packed rows, same lod as the input;
        # under is_reverse padded position p holds original time
        # len-1-p, so the regather maps back through the same flip
        seq = jnp.clip(row_segment_ids(off, N), 0, S - 1)
        t = jnp.arange(N, dtype=jnp.int32) - off[seq]
        if lod_reverse:
            t = lens[seq] - 1 - t
        hidden = LoDArray(hidden[seq, t], ctx.input("Input").lod)
        cell = LoDArray(cell[seq, t], ctx.input("Input").lod)
    ctx.set_output("Hidden", hidden)
    ctx.set_output("Cell", cell)
    if ctx.has_output("BatchGate"):
        ctx.set_output("BatchGate", ctx.input("Input") if is_lod else x)
    if ctx.has_output("BatchCellPreAct"):
        ctx.set_output("BatchCellPreAct", cell)


@register_op("gru",
             inputs=("Input", "H0", "Weight", "Bias", "Length"),
             outputs=("Hidden", "BatchGate", "BatchResetHiddenPrev", "BatchHidden"),
             diff_inputs=("Input", "H0", "Weight", "Bias"),
             infer_shape=_make_rnn_seq_infer(
                 3, ("BatchGate",),
                 ("Hidden", "BatchResetHiddenPrev", "BatchHidden")))
def _gru(ctx):
    """Fused GRU (reference: operators/gru_op.cc).  Input (B, T, 3H) of
    pre-projected gates; Weight packs W_rz (H, 2H) and W_c (H, H)."""
    x = unwrap(ctx.input("Input"))
    B, T, H3 = x.shape
    H = H3 // 3
    w = unwrap(ctx.input("Weight"))  # (H, 3H): [:, :2H]=update/reset, [:, 2H:]=candidate
    w_rz = w[:, : 2 * H]
    w_c = w[:, 2 * H :]
    bias = unwrap(ctx.input("Bias")).reshape(1, 3 * H) if ctx.has_input("Bias") else jnp.zeros((1, 3 * H), x.dtype)
    h0 = (unwrap(ctx.input("H0")).astype(x.dtype) if ctx.has_input("H0")
          else jnp.zeros((B, H), x.dtype))  # match the pinned carry dtype
    gate_act = _act_fn(ctx.attr("gate_activation", "sigmoid"))
    cand_act = _act_fn(ctx.attr("activation", "tanh"))

    def step(h, xt):
        uz = xt[:, : 2 * H] + jnp.dot(h, w_rz, preferred_element_type=jnp.float32).astype(x.dtype) + bias[:, : 2 * H]
        u, r = jnp.split(gate_act(uz), 2, axis=-1)
        c = cand_act(xt[:, 2 * H :] + jnp.dot(r * h, w_c, preferred_element_type=jnp.float32).astype(x.dtype) + bias[:, 2 * H :])
        h_new = (u * h + (1 - u) * c).astype(x.dtype)  # stable carry under amp
        return h_new, h_new

    win_src = None
    if ctx.attr("is_reverse", False) and ctx.has_input("Length"):
        _lens_arr = unwrap(ctx.input("Length"))
        x, win_src, _valid = _window_reverse(x, _lens_arr)
    xs = jnp.swapaxes(x, 0, 1)
    whole_reverse = ctx.attr("is_reverse", False) and win_src is None
    if whole_reverse:
        xs = xs[::-1]
    _, hs = lax.scan(step, h0, xs)
    if whole_reverse:
        hs = hs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1)
    if win_src is not None:
        hidden, _, _ = _window_reverse(hidden, _lens_arr)
    ctx.set_output("Hidden", hidden)
    for slot in ("BatchGate", "BatchResetHiddenPrev", "BatchHidden"):
        if ctx.has_output(slot):
            ctx.set_output(slot, hidden)


def _act_fn(name):
    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda v: v,
    }[name]


@register_op("expand_as_steps", inputs=("X", "Y", "XLength"),
             diff_inputs=("X",))
def _expand_as_steps(ctx):
    """Broadcast a per-sequence vector X (B, D) to every step of the
    padded sequence Y (B, T, ...) -> (B, T, D) (reference analog:
    gserver ExpandLayer over LoD; here the batch is padded dense)."""
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    poison = None
    if x.ndim == 3:
        # a length-1 sequence is dense data in the reference's contract
        # (ExpandLayer.h: "sequence data where the length of each
        # sequence is one" — it CHECK-fails otherwise).  Inside jit we
        # cannot branch on data, so longer sequences poison the output
        # with NaN, which the finite gates downstream turn loud.
        if ctx.has_input("XLength"):
            xlen = unwrap(ctx.input("XLength")).reshape(-1)
            poison = jnp.max(xlen) > 1
        x = x[:, 0]
    t = y.shape[1]
    out = jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[-1]))
    if poison is not None:
        out = jnp.where(poison, jnp.nan, out)
    ctx.set_output("Out", out)


@register_op("expand_to_subseq", inputs=("X", "Y"), diff_inputs=("X",))
def _expand_to_subseq(ctx):
    """Expand into a padded nested sequence Y (B, S, T, ...) (reference:
    gserver/layers/ExpandLayer.cpp with subSequenceStartPositions).
    level="seq": X (B, S, D), step s broadcast over subsequence s's
    inner steps; level="non-seq": X (B, D) broadcast over every inner
    step."""
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    B, S, T = y.shape[0], y.shape[1], y.shape[2]
    if ctx.attr("level", "non-seq") == "seq":
        # x's padded step count need not equal S (feeders bucket-pad);
        # align it — steps past the real subsequence count are padding
        if x.shape[1] >= S:
            x = x[:, :S]
        else:
            x = jnp.pad(x, [(0, 0), (0, S - x.shape[1]), (0, 0)])
        out = jnp.broadcast_to(x[:, :, None, :], (B, S, T, x.shape[-1]))
    else:
        if x.ndim == 3:
            x = x[:, 0]
        out = jnp.broadcast_to(x[:, None, None, :], (B, S, T, x.shape[-1]))
    ctx.set_output("Out", out)


@register_op("context_project", inputs=("X", "Length"),
             infer_shape=_infer_context_project_shape)
def _context_project(ctx):
    """Sliding-window concat over time (reference: function/
    ContextProjectionOp.cpp; v1 context_projection).  X (B, T, D) ->
    (B, T, D * context_length): position t gets steps
    [t+start, t+start+len) with zero padding past boundaries.  Pure
    shifts + concat — XLA fuses it into the consumer matmul.

    With Length, steps at or past each row's length are zeroed FIRST,
    so windows crossing a short row's end see zeros (the reference's
    sequence-boundary zero padding) instead of pad embeddings."""
    x = unwrap(ctx.input("X"))
    if ctx.has_input("Length"):
        _lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
        _t = jnp.arange(x.shape[1], dtype=jnp.int32)
        _valid = (_t[None, :] < _lens[:, None])
        x = x * _valid.reshape(_valid.shape + (1,) * (x.ndim - 2)
                               ).astype(x.dtype)
    ctx_len = int(ctx.attr("context_length"))
    start = int(ctx.attr("context_start", -(ctx_len // 2)))
    B, T = x.shape[0], x.shape[1]
    slabs = []
    for k in range(ctx_len):
        shift = start + k
        if shift == 0:
            slabs.append(x)
        elif shift > 0:
            pad = jnp.zeros((B, min(shift, T)) + x.shape[2:], x.dtype)
            slabs.append(jnp.concatenate([x[:, shift:], pad], axis=1))
        else:
            pad = jnp.zeros((B, min(-shift, T)) + x.shape[2:], x.dtype)
            slabs.append(jnp.concatenate([pad, x[:, :shift]], axis=1))
    ctx.set_output("Out", jnp.concatenate(slabs, axis=-1))


@register_op("padded_sequence_softmax", inputs=("X", "Length"),
             diff_inputs=("X",), infer_shape=infer_same_shape)
def _padded_sequence_softmax(ctx):
    """Softmax over the time dim of a padded (B, T) or (B, T, 1) score
    tensor, masking steps >= Length (the padded-batch analog of the
    LoD sequence_softmax op; reference: operators/sequence_softmax_op.cc)."""
    x = unwrap(ctx.input("X"))
    lens = unwrap(ctx.input("Length")).reshape(-1)
    squeeze = x.ndim == 3
    s = x[..., 0] if squeeze else x                    # (B, T)
    t = s.shape[1]
    valid = jnp.arange(t)[None, :] < lens[:, None]
    s = jnp.where(valid, s, -1e9)
    out = jax.nn.softmax(s.astype(jnp.float32), axis=1).astype(x.dtype)
    out = jnp.where(valid, out, 0.0)
    ctx.set_output("Out", out[..., None] if squeeze else out)


@register_op("padded_sequence_slice",
             inputs=("X", "Length", "Offset", "SliceLen"),
             outputs=("Out", "OutLength"), diff_inputs=("X",))
def _padded_sequence_slice(ctx):
    """Per-row window [offset, offset+slice_len) of a padded (B, T, ...)
    batch, re-packed to the front (the padded analog of
    operators/sequence_slice_op.cc; v1 SequenceSliceLayer/
    SubSequenceLayer semantics)."""
    x = unwrap(ctx.input("X"))
    lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    off = unwrap(ctx.input("Offset")).reshape(-1).astype(jnp.int32)
    sl = unwrap(ctx.input("SliceLen")).reshape(-1).astype(jnp.int32)
    T = x.shape[1]
    idx = jnp.arange(T, dtype=jnp.int32)[None, :] + off[:, None]
    gathered = jnp.take_along_axis(
        x, jnp.clip(idx, 0, T - 1).reshape(idx.shape + (1,) * (x.ndim - 2)),
        axis=1) if x.ndim > 2 else jnp.take_along_axis(
        x, jnp.clip(idx, 0, T - 1), axis=1)
    new_len = jnp.clip(jnp.minimum(sl, lens - off), 0, T)
    valid = jnp.arange(T)[None, :] < new_len[:, None]
    vmask = valid.reshape(valid.shape + (1,) * (x.ndim - 2))
    ctx.set_output("Out", jnp.where(vmask, gathered, 0))
    ctx.set_output("OutLength", new_len)


@register_op("sub_nested_seq",
             inputs=("X", "Lengths", "SubLengths", "Selected"),
             outputs=("Out", "OutLengths", "OutSubLengths"))
def _sub_nested_seq(ctx):
    """Select sub-sequences of a 2-level nested sequence by per-sample
    indices (reference: operators/../gserver SubNestedSequenceLayer —
    the beam-search training selection).  X: (B, S, T, d) padded;
    Selected: (B, k) indices into the S axis."""
    x = unwrap(ctx.input("X"))
    lengths = unwrap(ctx.input("Lengths"))
    sub_lengths = unwrap(ctx.input("SubLengths"))
    sel = unwrap(ctx.input("Selected")).astype(jnp.int32)
    B, k = sel.shape
    sel_c = jnp.clip(sel, 0, x.shape[1] - 1)
    out = jnp.take_along_axis(
        x, sel_c.reshape(B, k, *([1] * (x.ndim - 2))), axis=1)
    out_sub = jnp.take_along_axis(sub_lengths, sel_c, axis=1)
    # rows whose index is out of range contribute empty seqs; negative
    # ids are the reference's pad/terminator convention (-1 = no pick)
    valid = (sel >= 0) & (sel < lengths[:, None])
    out_sub = jnp.where(valid, out_sub, 0).astype(jnp.int32)
    ctx.set_output("Out", out)
    ctx.set_output("OutLengths",
                   jnp.sum(valid, axis=1).astype(jnp.int32))
    ctx.set_output("OutSubLengths", out_sub)


@register_op("mask_padded_subseq_scores",
             inputs=("X", "Length", "SubLength"),
             infer_shape=_infer_subseq_mask_flatten_shape)
def _mask_padded_subseq_scores(ctx):
    """Mask a padded nested score tensor (B, S, T) to -1e9 on padding
    (rows past Length, inner steps past SubLength) and flatten to
    (B, S*T) — the padded-beam frame cross_entropy_over_beam consumes
    (candidate slot c's parent beam row is c // T, which only holds in
    the *padded*, non-compacted layout)."""
    x = unwrap(ctx.input("X"))
    if x.ndim == 4 and x.shape[-1] == 1:
        x = x[..., 0]
    lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    sub = unwrap(ctx.input("SubLength")).astype(jnp.int32)   # (B, S)
    B, S, T = x.shape
    row_ok = jnp.arange(S)[None, :] < lens[:, None]          # (B, S)
    step_ok = jnp.arange(T)[None, None, :] < sub[:, :, None]  # (B, S, T)
    ok = row_ok[:, :, None] & step_ok
    out = jnp.where(ok, x, jnp.asarray(-1e9, x.dtype))
    ctx.set_output("Out", out.reshape(B, S * T))


@register_op("mask_padded_scores", inputs=("X", "Length"),
             infer_shape=infer_same_shape)
def _mask_padded_scores(ctx):
    """Set scores past each sequence's length to -inf so top-k/argmax
    never select padding steps (KmaxSeqScoreLayer's per-sequence
    semantics over the padded dense layout)."""
    x = unwrap(ctx.input("X"))                   # (B, T)
    lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    mask = jnp.arange(x.shape[1])[None, :] < lens[:, None]
    # large-but-finite (not -inf): keeps downstream reductions and
    # central-difference grad checks NaN-free
    ctx.set_output("Out", jnp.where(mask, x, jnp.asarray(-1e30, x.dtype)))


@register_op("padded_sequence_reverse", inputs=("X", "Length"),
             infer_shape=infer_same_shape)
def _padded_sequence_reverse(ctx):
    """Reverse each row of a padded (B, T, ...) tensor inside its valid
    window (reference: the LoD reverse semantics of reversed recurrent
    layers — gserver/layers/RecurrentLayer.cpp backward-direction
    sequence walk).  Without Length, flips the whole time axis.  The
    map is an involution, so the same op undoes itself."""
    x = unwrap(ctx.input("X"))
    if not ctx.has_input("Length"):
        ctx.set_output("Out", jnp.flip(x, axis=1))
        return
    out, _, _ = _window_reverse(x, unwrap(ctx.input("Length")))
    ctx.set_output("Out", out)
