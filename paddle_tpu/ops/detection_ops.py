"""Detection ops: prior boxes, box coding, multiclass NMS, SSD loss.

Reference capability: the v1 detection stack —
gserver/layers/PriorBox.cpp, MultiBoxLossLayer.cpp,
DetectionOutputLayer.cpp (+ DetectionUtil.cpp NMS/encode helpers).

TPU-native designs (all static-shape, everything batched):
  - prior_box: closed-form anchor grid, computed in-graph (constant-
    folded by XLA).
  - box_coder: center-size encode/decode, vectorized.
  - multiclass_nms: fixed-iteration suppression — top-k candidates,
    then `keep_top_k` rounds of select-max + IoU-mask — instead of the
    reference's data-dependent greedy loop; outputs are padded with
    class -1 (the LoD-free equivalent of the reference's variable-size
    detection lists).
  - ssd_loss: per-prior argmax IoU matching + hard negative mining with
    a static 3:1 ratio via top-k over masked losses (the reference's
    MultiBoxLossLayer semantics without host-side sorting).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.common import unwrap
from paddle_tpu.registry import register_op


def expand_aspect_ratios(aspect_ratios, flip):
    """The op's dedup rule, shared with the layer so declared shapes
    match emitted shapes: 1.0 first, then each new ar (+ 1/ar if flip),
    duplicates dropped."""
    ars = [1.0]
    for ar in aspect_ratios or []:
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    return ars


def prior_count(min_sizes, max_sizes, aspect_ratios, flip):
    """Priors per cell, exactly as _prior_box emits them."""
    ars = expand_aspect_ratios(aspect_ratios, flip)
    n_max = min(len(max_sizes or []), len(min_sizes))
    return len(min_sizes) * len(ars) + n_max


def _iou(a, b):
    """a (M,4), b (N,4) corner boxes -> (M,N) IoU."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0.0) * jnp.clip(a[:, 3] - a[:, 1], 0.0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0.0) * jnp.clip(b[:, 3] - b[:, 1], 0.0)
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


@register_op("prior_box", inputs=("Input", "Image"),
             outputs=("Boxes", "Variances"), stop_gradient=True)
def _prior_box(ctx):
    """SSD anchor generation (reference: gserver/layers/PriorBox.cpp).
    Input (N,C,H,W) feature map, Image (N,C,IH,IW); emits (H, W, P, 4)
    normalized corner boxes + matching variances."""
    feat = unwrap(ctx.input("Input"))
    img = unwrap(ctx.input("Image"))
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", [])]
    ars = expand_aspect_ratios(ctx.attr("aspect_ratios", []),
                               ctx.attr("flip", True))
    variances = [float(v) for v in ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    offset = float(ctx.attr("offset", 0.5))
    step_w = float(ctx.attr("step_w", 0.0)) or IW / W
    step_h = float(ctx.attr("step_h", 0.0)) or IH / H

    whs = []
    for k, ms in enumerate(min_sizes):
        whs.append((ms, ms))
        for ar in ars[1:]:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if k < len(max_sizes):
            s = np.sqrt(ms * max_sizes[k])
            whs.append((s, s))
    whs = np.asarray(whs, np.float32)  # (P, 2) in pixels
    P = whs.shape[0]

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cx = jnp.broadcast_to(cx[None, :, None], (H, W, P))
    cy = jnp.broadcast_to(cy[:, None, None], (H, W, P))
    w = jnp.broadcast_to(jnp.asarray(whs[:, 0]), (H, W, P))
    h = jnp.broadcast_to(jnp.asarray(whs[:, 1]), (H, W, P))
    boxes = jnp.stack([(cx - w / 2) / IW, (cy - h / 2) / IH,
                       (cx + w / 2) / IW, (cy + h / 2) / IH], axis=-1)
    if ctx.attr("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, P, 4))
    ctx.set_output("Boxes", boxes)
    ctx.set_output("Variances", var)


def _encode_center_size(prior, prior_var, target):
    """corner-form target (…,M,4) vs prior (M,4) -> offsets."""
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = (prior[:, 0] + prior[:, 2]) / 2
    pcy = (prior[:, 1] + prior[:, 3]) / 2
    tw = jnp.maximum(target[..., 2] - target[..., 0], 1e-10)
    th = jnp.maximum(target[..., 3] - target[..., 1], 1e-10)
    tcx = (target[..., 0] + target[..., 2]) / 2
    tcy = (target[..., 1] + target[..., 3]) / 2
    return jnp.stack([
        (tcx - pcx) / pw / prior_var[:, 0],
        (tcy - pcy) / ph / prior_var[:, 1],
        jnp.log(tw / pw) / prior_var[:, 2],
        jnp.log(th / ph) / prior_var[:, 3],
    ], axis=-1)


def _decode_center_size(prior, prior_var, code):
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = (prior[:, 0] + prior[:, 2]) / 2
    pcy = (prior[:, 1] + prior[:, 3]) / 2
    cx = code[..., 0] * prior_var[:, 0] * pw + pcx
    cy = code[..., 1] * prior_var[:, 1] * ph + pcy
    w = jnp.exp(code[..., 2] * prior_var[:, 2]) * pw
    h = jnp.exp(code[..., 3] * prior_var[:, 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register_op("box_coder", inputs=("PriorBox", "PriorBoxVar", "TargetBox"),
             outputs=("OutputBox",), stop_gradient=True)
def _box_coder(ctx):
    """Encode/decode between corner boxes and prior-relative offsets
    (reference: DetectionUtil.cpp encodeBBox/decodeBBox)."""
    prior = unwrap(ctx.input("PriorBox")).reshape(-1, 4)
    pvar = unwrap(ctx.input("PriorBoxVar")).reshape(-1, 4)
    target = unwrap(ctx.input("TargetBox"))
    if ctx.attr("code_type", "encode_center_size") == "encode_center_size":
        out = _encode_center_size(prior, pvar, target)
    else:
        out = _decode_center_size(prior, pvar, target)
    ctx.set_output("OutputBox", out)


def _nms_single(boxes, scores, score_threshold, nms_threshold, keep,
                iou=None):
    """boxes (M,4), scores (M,) -> (keep,) indices (or -1) by greedy NMS
    with a fixed iteration count.  Pass a precomputed MxM ``iou`` when
    running per-class over shared boxes."""
    M = boxes.shape[0]
    if iou is None:
        iou = _iou(boxes, boxes)
    alive = scores > score_threshold

    def body(carry, _):
        alive, = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        # suppress overlaps of the winner (and the winner itself)
        suppress = (iou[best] > nms_threshold) | (jnp.arange(M) == best)
        alive = alive & (~suppress | ~ok)
        return (alive,), jnp.where(ok, best, -1)

    _, picks = lax.scan(body, (alive,), None, length=keep)
    return picks


@register_op("multiclass_nms", inputs=("BBoxes", "Scores"),
             outputs=("Out",), stop_gradient=True)
def _multiclass_nms(ctx):
    """Detection output (reference: DetectionOutputLayer.cpp +
    DetectionUtil.cpp applyNMSFast): per-class NMS then cross-class
    top-k.  Scores (B, C, M), BBoxes (M, 4) or (B, M, 4) decoded corner
    boxes.  Out: (B, keep_top_k, 6) rows [label, score, x1,y1,x2,y2],
    padded with label -1."""
    scores = unwrap(ctx.input("Scores")).astype(jnp.float32)
    bboxes = unwrap(ctx.input("BBoxes")).astype(jnp.float32)
    B, C, M = scores.shape
    if bboxes.ndim == 2:
        bboxes = jnp.broadcast_to(bboxes[None], (B, M, 4))
    st = float(ctx.attr("score_threshold", 0.01))
    nt = float(ctx.attr("nms_threshold", 0.45))
    per_class = int(ctx.attr("nms_top_k", 64))
    keep_top_k = int(ctx.attr("keep_top_k", 16))
    background = int(ctx.attr("background_label", 0))

    def one_image(sc, bx):
        iou = _iou(bx, bx)  # shared across classes
        rows = []
        for c in range(C):
            if c == background:
                continue
            picks = _nms_single(bx, sc[c], st, nt, min(per_class, M),
                                iou=iou)
            ok = picks >= 0
            idx = jnp.maximum(picks, 0)
            rows.append(jnp.concatenate([
                jnp.where(ok, float(c), -1.0)[:, None],
                jnp.where(ok, sc[c][idx], 0.0)[:, None],
                jnp.where(ok[:, None], bx[idx], 0.0),
            ], axis=1))
        allrows = jnp.concatenate(rows, axis=0)
        order = jnp.argsort(-jnp.where(allrows[:, 0] >= 0,
                                       allrows[:, 1], -jnp.inf))
        return allrows[order[:keep_top_k]]

    ctx.set_output("Out", jax.vmap(one_image)(scores, bboxes))


@register_op("ssd_loss", inputs=("Loc", "Conf", "PriorBox", "PriorBoxVar",
                                 "GtBox", "GtLabel"),
             outputs=("Loss",), diff_inputs=("Loc", "Conf"))
def _ssd_loss(ctx):
    """MultiBox loss (reference: gserver/layers/MultiBoxLossLayer.cpp):
    per-prior argmax-IoU matching against padded GT (label -1 = pad),
    smooth-L1 localization on positives, softmax CE on class with hard
    negative mining at a static neg:pos ratio."""
    loc = unwrap(ctx.input("Loc")).astype(jnp.float32)       # (B, M, 4)
    conf = unwrap(ctx.input("Conf")).astype(jnp.float32)     # (B, M, C)
    M_ = loc.shape[1]
    # priors are shared across the batch; accept (M,4), (H,W,P,4), or a
    # batch-broadcast (B,M,4) feed and canonicalize to (M,4)
    prior = unwrap(ctx.input("PriorBox")).reshape(-1, 4)
    pvar = unwrap(ctx.input("PriorBoxVar")).reshape(-1, 4)
    if prior.shape[0] != M_:
        prior = prior.reshape(-1, M_, 4)[0]
        pvar = pvar.reshape(-1, M_, 4)[0]
    gt = unwrap(ctx.input("GtBox")).astype(jnp.float32)      # (B, G, 4)
    # labels may arrive as the real-valued column of a packed gt record
    # (the v1 flat label layout); they index class rows, so integerize
    gtl = unwrap(ctx.input("GtLabel")).reshape(
        gt.shape[0], -1).astype(jnp.int32)  # (B, G)
    overlap_t = float(ctx.attr("overlap_threshold", 0.5))
    neg_ratio = float(ctx.attr("neg_pos_ratio", 3.0))
    background = int(ctx.attr("background_label", 0))
    loc_w = float(ctx.attr("loc_loss_weight", 1.0))
    conf_w = float(ctx.attr("conf_loss_weight", 1.0))
    B, M, _ = loc.shape
    G = gt.shape[1]

    def one(loc_i, conf_i, gt_i, gtl_i):
        valid_gt = gtl_i >= 0                                # (G,)
        iou = _iou(prior, gt_i)                              # (M, G)
        iou = jnp.where(valid_gt[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)                    # (M,)
        best_iou = jnp.max(iou, axis=1)
        pos = best_iou > overlap_t                           # (M,)
        matched_box = gt_i[best_gt]                          # (M, 4)
        matched_lab = jnp.where(pos, gtl_i[best_gt], background)

        # localization: smooth L1 on encoded offsets, positives only
        target = _encode_center_size(prior, pvar, matched_box)
        d = loc_i - target
        ad = jnp.abs(d)
        sl1 = jnp.sum(jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5), axis=1)
        n_pos = jnp.maximum(jnp.sum(pos), 1)
        loc_loss = jnp.sum(jnp.where(pos, sl1, 0.0)) / n_pos

        # confidence: CE everywhere; hard-negative mine via top-k
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        ce = -jnp.take_along_axis(logp, matched_lab[:, None], axis=1)[:, 0]
        bg_ce = -logp[:, background]
        neg_cand = jnp.where(pos, -jnp.inf, bg_ce)
        n_neg = jnp.minimum(
            (neg_ratio * n_pos).astype(jnp.int32), M)
        thresh = jnp.sort(neg_cand)[::-1][jnp.maximum(n_neg - 1, 0)]
        neg = (~pos) & (neg_cand >= thresh) & (n_neg > 0)
        conf_loss = (jnp.sum(jnp.where(pos, ce, 0.0)) +
                     jnp.sum(jnp.where(neg, ce, 0.0))) / n_pos
        return loc_w * loc_loss + conf_w * conf_loss

    loss = jax.vmap(one)(loc, conf, gt, gtl)
    ctx.set_output("Loss", loss[:, None])
