"""cudnn-named op aliases (reference: operators/conv_cudnn_op.cc etc. —
separate registrations of the same math bound to cuDNN kernels; on XLA
there is exactly one lowering, so aliases share it).  Imported LAST so
every target exists."""

from __future__ import annotations

from paddle_tpu.registry import OpRegistry, register_op


def _alias_op(alias: str, target: str, inputs, outputs=("Out",)):
    info = OpRegistry.get(target)
    register_op(alias, inputs=inputs, outputs=outputs,
                diff_inputs=info.diff_inputs,
                infer_shape=info.infer_shape)(info.lower)


_alias_op("conv2d_cudnn", "conv2d", ("Input", "Filter"), ("Output",))
_alias_op("conv3d_cudnn", "conv3d", ("Input", "Filter"), ("Output",))
_alias_op("conv2d_transpose_cudnn", "conv2d_transpose",
          ("Input", "Filter"), ("Output",))
_alias_op("conv3d_transpose_cudnn", "conv3d_transpose",
          ("Input", "Filter"), ("Output",))
_alias_op("pool2d_cudnn", "pool2d", ("X",))
_alias_op("pool3d_cudnn", "pool3d", ("X",))
