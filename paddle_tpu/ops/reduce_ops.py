"""Reduce ops (reference: operators/reduce_op.cc, mean_op.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.lod import unwrap
from paddle_tpu.ops.common import unary
from paddle_tpu.registry import SkipInferShape, register_op


def _reduce_io_vars(op, block):
    xs, outs = op.input("X"), op.output("Out")
    if len(xs) != 1 or len(outs) != 1 or not xs[0] or not outs[0]:
        raise SkipInferShape
    xv, ov = block.find_var(xs[0]), block.find_var(outs[0])
    if xv is None or ov is None or xv.shape is None:
        raise SkipInferShape
    return xv, ov


def _infer_scalar_shape(op, block):
    """mean / l1_norm collapse X to a rank-0 scalar."""
    _, ov = _reduce_io_vars(op, block)
    if ov.shape is None:
        ov.shape = ()


def _infer_reduce_shape(op, block):
    """reduce_{sum,mean,max,min}: drop (or keep as 1) the reduced dim,
    mirroring the lowering's axis semantics."""
    xv, ov = _reduce_io_vars(op, block)
    if ov.shape is not None:
        return
    keep = op.attr("keep_dim", False)
    if op.attr("reduce_all", False):
        ov.shape = (1,) * len(xv.shape) if keep else ()
        return
    dim = op.attr("dim", 0)
    if not isinstance(dim, int):
        raise SkipInferShape
    ndim = len(xv.shape)
    if not -ndim <= dim < ndim:
        raise ValueError(f"dim {dim} out of range for shape {xv.shape}")
    dim %= ndim
    shape = list(xv.shape)
    if keep:
        shape[dim] = 1
    else:
        del shape[dim]
    ov.shape = tuple(shape)


@register_op("mean", inputs=("X",), infer_shape=_infer_scalar_shape)
def _mean(ctx):
    x = unwrap(ctx.input("X"))
    ctx.set_output("Out", jnp.mean(x).reshape(()))


def _reg_reduce(name, fn):
    @register_op(name, inputs=("X",), infer_shape=_infer_reduce_shape)
    def _red(ctx, fn=fn):
        x = unwrap(ctx.input("X"))
        dim = ctx.attr("dim", 0)
        keep = ctx.attr("keep_dim", False)
        if ctx.attr("reduce_all", False):
            out = fn(x)
            if keep:
                out = out.reshape((1,) * x.ndim)
            ctx.set_output("Out", out)
            return
        ctx.set_output("Out", fn(x, axis=dim, keepdims=keep))


for _n, _f in [
    ("reduce_sum", jnp.sum),
    ("reduce_mean", jnp.mean),
    ("reduce_max", jnp.max),
    ("reduce_min", jnp.min),
]:
    _reg_reduce(_n, _f)


@register_op("l1_norm", inputs=("X",), infer_shape=_infer_scalar_shape)
def _l1_norm(ctx):
    unary(ctx, lambda x: jnp.sum(jnp.abs(x)).reshape(()))
