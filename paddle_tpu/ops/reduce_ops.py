"""Reduce ops (reference: operators/reduce_op.cc, mean_op.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.lod import unwrap
from paddle_tpu.ops.common import unary
from paddle_tpu.registry import register_op


@register_op("mean", inputs=("X",))
def _mean(ctx):
    x = unwrap(ctx.input("X"))
    ctx.set_output("Out", jnp.mean(x).reshape(()))


def _reg_reduce(name, fn):
    @register_op(name, inputs=("X",))
    def _red(ctx, fn=fn):
        x = unwrap(ctx.input("X"))
        dim = ctx.attr("dim", 0)
        keep = ctx.attr("keep_dim", False)
        if ctx.attr("reduce_all", False):
            out = fn(x)
            if keep:
                out = out.reshape((1,) * x.ndim)
            ctx.set_output("Out", out)
            return
        ctx.set_output("Out", fn(x, axis=dim, keepdims=keep))


for _n, _f in [
    ("reduce_sum", jnp.sum),
    ("reduce_mean", jnp.mean),
    ("reduce_max", jnp.max),
    ("reduce_min", jnp.min),
]:
    _reg_reduce(_n, _f)


@register_op("l1_norm", inputs=("X",))
def _l1_norm(ctx):
    unary(ctx, lambda x: jnp.sum(jnp.abs(x)).reshape(()))
