"""Control-flow-adjacent ops (reference: operators/{is_empty,increment,
array ops}).  Structured while/cond lowering lives with the layers that
build sub-blocks; these are the leaf utilities."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import register_op


@register_op("is_empty", inputs=("X",), stop_gradient=True)
def _is_empty(ctx):
    x = unwrap(ctx.input("X"))
    ctx.set_output("Out", jnp.asarray(x.size == 0))


@register_op("multiplex", inputs=("Ids", "X"), diff_inputs=("X",))
def _multiplex(ctx):
    ids = unwrap(ctx.input("Ids")).astype(jnp.int32).reshape(-1)
    xs = jnp.stack([unwrap(v) for v in ctx.inputs("X")])  # (K, N, D)
    rows = jnp.arange(ids.shape[0])
    ctx.set_output("Out", xs[ids, rows])
