"""Control-flow ops.

Reference: operators/{while_op.cc:35,92, recurrent_op.cc,
conditional_block_op.cc, tensor_array_read_write_op.cc,
lod_array_length_op.cc, increment, is_empty}.

TPU inversion (SURVEY.md §7): the reference interprets sub-blocks with
nested Executors and per-iteration step scopes; here a sub-block is
*traced into the parent XLA program* as a ``lax.while_loop`` /
``lax.scan`` / ``lax.cond`` region.  Loop state = the sub-block's
written vars that were initialized outside the loop; everything else is
a per-iteration temp.  ``recurrent`` (StaticRNN) uses lax.scan so the
whole RNN is reverse-differentiable via the standard vjp replay —
there is no RecurrentGradientMachine equivalent to hand-maintain.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import (
    LowerContext,
    OpRegistry,
    SkipInferShape,
    register_op,
)
from paddle_tpu.tensor_array import TensorArray


def _run_sub_block(sub_block, values, executor_ctx):
    for op_ in sub_block.ops:
        info = OpRegistry.get(op_.type)
        info.lower(LowerContext(op_, values, rng=None, executor_ctx=executor_ctx))


@register_op("while", inputs=("X", "Condition"), outputs=("Out", "StepScopes"),
             stop_gradient=True)
def _while(ctx):
    """lax.while_loop over the sub-block.  Carried state: sub-block
    written vars that exist (were initialized) before the loop, plus the
    condition.  Not differentiable (use ``recurrent`` for trainable
    recurrences) — matching XLA's while semantics."""
    sub = ctx.attr("sub_block")
    cond_name = ctx.op.input("Condition")[0]
    outer = ctx.values
    written = []
    for op_ in sub.ops:
        for n in op_.output_arg_names:
            if n:
                written.append(n)
    carry_names = [cond_name] + [
        n for n in dict.fromkeys(written) if n in outer and n != cond_name
    ]

    def cond_fn(carry):
        return jnp.reshape(unwrap(carry[cond_name]), ()).astype(bool)

    def body_fn(carry):
        values = dict(outer)
        values.update(carry)
        _run_sub_block(sub, values, ctx.executor_ctx)
        return {n: values[n] for n in carry_names}

    init = {n: outer[n] for n in carry_names}
    final = lax.while_loop(cond_fn, body_fn, init)
    for n, v in final.items():
        outer[n] = v


@register_op("recurrent",
             inputs=("Inputs", "InitStates", "Params"),
             outputs=("Outputs", "FinalStates"))
def _recurrent(ctx):
    """StaticRNN as lax.scan (reference: operators/recurrent_op.cc runs
    the step block once per time step with linked memories).

    attrs: sub_block, state_names (memory var names read in the block),
    state_update_names (vars holding each memory's new value),
    step_input_names (per-step slice var names, aligned with Inputs),
    step_output_names, reverse.  Sequence inputs are batch-major
    (B, T, ...); each scan step runs the sub-block on (B, ...) slices —
    full-batch MXU work per step.  Differentiable via vjp replay (the
    whole scan is traced, jax handles the backward scan)."""
    sub = ctx.attr("sub_block")
    state_names = ctx.attr("state_names")
    state_update_names = ctx.attr("state_update_names")
    step_input_names = ctx.attr("step_input_names")
    step_output_names = ctx.attr("step_output_names")
    reverse = ctx.attr("reverse", False)
    outer = ctx.values

    seqs = [unwrap(v) for v in ctx.inputs("Inputs")]
    xs = tuple(jnp.moveaxis(s, 1, 0) for s in seqs)  # (T, B, ...)
    init_states = tuple(unwrap(v) for v in ctx.inputs("InitStates"))

    def step(states, xts):
        values = dict(outer)
        for n, v in zip(state_names, states):
            values[n] = v
        for n, v in zip(step_input_names, xts):
            values[n] = v
        _run_sub_block(sub, values, ctx.executor_ctx)
        new_states = tuple(values[n] for n in state_update_names)
        outs = tuple(values[n] for n in step_output_names)
        return new_states, outs

    final_states, outs = lax.scan(step, init_states, xs, reverse=reverse)
    ctx.set_outputs("Outputs", [jnp.moveaxis(o, 0, 1) for o in outs])
    if ctx.has_output("FinalStates"):
        ctx.set_outputs("FinalStates", list(final_states))


@register_op("conditional_block", inputs=("Cond", "X"), outputs=("Out", "Scope"))
def _conditional_block(ctx):
    """lax.cond over the sub-block given a scalar bool condition.  The
    false branch passes through the outputs' pre-loop values, so each
    Out var must be initialized before the op (the reference instead
    skips execution and leaves vars untouched — same observable
    semantics)."""
    sub = ctx.attr("sub_block")
    cond = jnp.reshape(unwrap(ctx.inputs("Cond")[0]), ()).astype(bool)
    out_names = [n for n in ctx.op.output("Out") if n]
    outer = ctx.values

    def true_fn(init):
        values = dict(outer)
        values.update(init)
        _run_sub_block(sub, values, ctx.executor_ctx)
        return {n: values[n] for n in out_names}

    def false_fn(init):
        return init

    init = {n: outer[n] for n in out_names}
    final = lax.cond(cond, true_fn, false_fn, init)
    for n, v in final.items():
        outer[n] = v


# --- tensor arrays ---------------------------------------------------------


@register_op("create_array", inputs=(), stop_gradient=True)
def _create_array(ctx):
    shape = tuple(ctx.attr("elem_shape"))
    cap = ctx.attr("capacity", 64)
    from paddle_tpu.ops.common import jnp_dtype

    ctx.set_output("Out", TensorArray.create(cap, shape, jnp_dtype(ctx.attr("dtype", "float32"))))


@register_op("write_to_array", inputs=("X", "I", "Array"))
def _write_to_array(ctx):
    arr = ctx.input("Array")
    ctx.set_output("Out", arr.write(unwrap(ctx.input("I")), unwrap(ctx.input("X"))))


@register_op("read_from_array", inputs=("X", "I"))
def _read_from_array(ctx):
    arr = ctx.input("X")
    ctx.set_output("Out", arr.read(unwrap(ctx.input("I"))))


@register_op("lod_array_length", inputs=("X",), stop_gradient=True)
def _lod_array_length(ctx):
    ctx.set_output("Out", ctx.input("X").length.reshape(1).astype(jnp.int64))


@register_op("max_sequence_len", inputs=("RankTable",), stop_gradient=True)
def _max_sequence_len(ctx):
    x = ctx.input("RankTable")
    from paddle_tpu.lod import LoDArray, LoDRankTable

    if isinstance(x, LoDRankTable):
        ctx.set_output("Out", jnp.max(x.lengths).reshape(()))
    elif isinstance(x, LoDArray):
        ctx.set_output("Out", jnp.max(x.seq_lens()).reshape(()))
    else:
        ctx.set_output("Out", jnp.asarray(unwrap(x).shape[1], jnp.int32))


@register_op("select_where", inputs=("Cond", "X", "Y"), diff_inputs=("X", "Y"))
def _select_where(ctx):
    """Row-wise select: out[i] = cond[i] ? x[i] : y[i] (the IfElse merge;
    reference analog: operators/merge_lod_tensor_op via mask)."""
    cond = unwrap(ctx.inputs("Cond")[0]).astype(bool)
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    while cond.ndim < x.ndim:
        cond = cond[..., None] if cond.ndim else cond.reshape((1,))
    ctx.set_output("Out", jnp.where(cond, x, y))


@register_op("is_empty", inputs=("X",), stop_gradient=True)
def _is_empty(ctx):
    x = unwrap(ctx.input("X"))
    ctx.set_output("Out", jnp.asarray(x.size == 0))


def _infer_multiplex_shape(op, block):
    # Out picks one row per index from the stacked candidates: it
    # mirrors any single candidate's shape
    xs = op.inputs.get("X", [])
    outs = op.outputs.get("Out", [])
    if not xs or not xs[0] or len(outs) != 1 or not outs[0]:
        raise SkipInferShape
    xv, ov = block.find_var(xs[0]), block.find_var(outs[0])
    if xv is None or ov is None or xv.shape is None:
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(xv.shape)


@register_op("multiplex", inputs=("Ids", "X"), diff_inputs=("X",),
             infer_shape=_infer_multiplex_shape)
def _multiplex(ctx):
    ids = unwrap(ctx.input("Ids")).astype(jnp.int32).reshape(-1)
    xs = jnp.stack([unwrap(v) for v in ctx.inputs("X")])  # (K, N, D)
    rows = jnp.arange(ids.shape[0])
    ctx.set_output("Out", xs[ids, rows])
