"""Beam-search ops.

Reference: operators/beam_search_op.cc (one expansion/pruning step over
a LoD candidate structure) and operators/beam_search_decode_op.cc
(backtrack the step-wise selections into full sentences).

TPU design: the reference keeps a ragged LoD beam state and prunes rows
per step.  Here the beam state is dense (batch, beam) and a step is
``top_k`` over the (batch, beam*vocab) score matrix — fixed shapes, one
fused XLA kernel, no host round trips.  Finished beams are kept live
and extended with end_id at zero cost, which matches the reference's
"pruned" beams contributing nothing further.  The whole decode loop
(see paddle_tpu.decoding.beam_search) is a lax.scan; these ops expose
the step/decode pieces for program-IR parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import register_op

NEG_INF = -1.0e9


@register_op("beam_search", inputs=("pre_ids", "pre_scores", "scores"),
             outputs=("selected_ids", "selected_scores", "parent_idx"),
             stop_gradient=True)
def _beam_search(ctx):
    pre_ids = unwrap(ctx.input("pre_ids")).astype(jnp.int32)     # (B, K)
    pre_scores = unwrap(ctx.input("pre_scores"))                 # (B, K)
    scores = unwrap(ctx.input("scores"))                         # (B, K, V)
    end_id = int(ctx.attr("end_id", 0))
    beam_size = int(ctx.attr("beam_size", pre_ids.shape[1]))
    B, K, V = scores.shape
    logp = jax.nn.log_softmax(scores.astype(jnp.float32), axis=-1)
    finished = pre_ids == end_id
    eos_only = jnp.full((B, K, V), NEG_INF).at[:, :, end_id].set(0.0)
    logp = jnp.where(finished[..., None], eos_only, logp)
    total = pre_scores[..., None] + logp                         # (B, K, V)
    top_scores, top_idx = lax.top_k(total.reshape(B, K * V), beam_size)
    ctx.set_output("selected_ids", (top_idx % V).astype(jnp.int64))
    ctx.set_output("selected_scores", top_scores)
    ctx.set_output("parent_idx", (top_idx // V).astype(jnp.int64))


@register_op("beam_search_decode", inputs=("Ids", "ParentIdx", "Scores"),
             outputs=("SentenceIds", "SentenceScores"), stop_gradient=True)
def _beam_search_decode(ctx):
    ids = unwrap(ctx.input("Ids")).astype(jnp.int32)             # (T, B, K)
    parents = unwrap(ctx.input("ParentIdx")).astype(jnp.int32)   # (T, B, K)
    scores = unwrap(ctx.input("Scores"))                         # (T, B, K)
    T, B, K = ids.shape

    def backtrack(ptr, tb):
        tok_t, bp_t = tb
        tok = jnp.take_along_axis(tok_t, ptr, axis=1)
        return jnp.take_along_axis(bp_t, ptr, axis=1), tok

    init_ptr = jnp.tile(jnp.arange(K, dtype=jnp.int32), (B, 1))
    _, seq_rev = lax.scan(backtrack, init_ptr, (ids, parents), reverse=True)
    ctx.set_output("SentenceIds", jnp.moveaxis(seq_rev, 0, 2).astype(jnp.int64))
    ctx.set_output("SentenceScores",
                   scores[-1] if T else jnp.zeros((B, K), scores.dtype))
