"""CTC loss + hierarchical sigmoid + factorization machine ops.

Reference: the v1 gserver capability set — `CTCLayer`/`WarpCTCLayer`
(gserver/layers/CTCLayer.cpp, WarpCTCLayer.cpp over
cuda/hl_warpctc_wrap.cc), `HierarchicalSigmoidLayer`
(gserver/layers/HierarchicalSigmoidLayer.cpp), and
`FactorizationMachineLayer` (gserver/layers/FactorizationMachineLayer.cpp).

TPU-native designs:
  - CTC: the log-space alpha recursion as one `lax.scan` over time with
    static (B, 2S+1) state — no warp kernels; the gradient is plain
    autodiff through the scan (exact, same as warpctc's analytic grad).
  - HSigmoid: complete-binary-tree path codes are bit arithmetic on the
    label id, so the whole loss is a handful of gathers + a masked
    logistic sum — O(B * log V) dense compute, MXU-friendly.
  - FM: the classic (sum_xw)^2 - sum(x^2 w^2) identity — two matmuls.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.common import unwrap
from paddle_tpu.registry import register_op

NEG_INF = -1e30


def _ctc_loss_batch(logits, logit_lens, labels, label_lens, blank):
    """logits (B,T,C) raw; labels (B,S) int32; returns (B,) -logp."""
    B, T, C = logits.shape
    S = labels.shape[1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # extended label sequence l' = [blank, l1, blank, l2, ..., blank]
    L = 2 * S + 1
    ext = jnp.full((B, L), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    ext_len = 2 * label_lens.astype(jnp.int32) + 1

    # can we skip from s-2 to s? only if ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], 1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    def emit(t):
        # log p(ext_s at time t) for every s: gather along class axis
        return jnp.take_along_axis(logp[:, t, :], ext, axis=1)  # (B, L)

    alpha0 = jnp.full((B, L), NEG_INF, jnp.float32)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_lens > 0,
                  jnp.take_along_axis(logp[:, 0, :],
                                      ext[:, 1:2], axis=1)[:, 0],
                  NEG_INF))

    def step(alpha, t):
        prev1 = jnp.concatenate(
            [jnp.full((B, 1), NEG_INF, jnp.float32), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate(
            [jnp.full((B, 2), NEG_INF, jnp.float32), alpha[:, :-2]], 1)
        prev2 = jnp.where(can_skip, prev2, NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        new = merged + emit(t)
        # freeze past each sequence's end so short sequences read their
        # final alpha at t = len-1
        active = (t < logit_lens)[:, None]
        new = jnp.where(active, new, alpha)
        return new, None

    alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # p(labels) = alpha[len'-1] + alpha[len'-2]; for an empty label
    # (len'=1) there is only the all-blank path — no second term
    last = jnp.take_along_axis(alphaT, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alphaT, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    return -jnp.where(ext_len > 1, jnp.logaddexp(last, last2), last)


@register_op("warpctc",
             inputs=("Logits", "Label", "LogitsLength", "LabelLength"),
             outputs=("Loss",), diff_inputs=("Logits",))
def _warpctc(ctx):
    """CTC negative log-likelihood over padded (B, T, C) logits
    (reference: WarpCTCLayer semantics; `blank` attr as in hl_warpctc).
    Differentiable by construction — jax.vjp through the scan gives the
    exact warpctc gradient."""
    logits = unwrap(ctx.input("Logits"))
    labels = unwrap(ctx.input("Label"))
    B, T, _ = logits.shape
    if ctx.has_input("LogitsLength"):
        logit_lens = unwrap(ctx.input("LogitsLength")).reshape(-1).astype(jnp.int32)
    else:
        logit_lens = jnp.full((B,), T, jnp.int32)
    if ctx.has_input("LabelLength"):
        label_lens = unwrap(ctx.input("LabelLength")).reshape(-1).astype(jnp.int32)
    else:
        label_lens = jnp.full((B,), labels.shape[1], jnp.int32)
    blank = int(ctx.attr("blank", 0))
    norm = bool(ctx.attr("norm_by_times", False))
    loss = _ctc_loss_batch(logits, logit_lens, labels, label_lens, blank)
    if norm:
        loss = loss / jnp.maximum(logit_lens.astype(jnp.float32), 1.0)
    ctx.set_output("Loss", loss[:, None])


@register_op("hierarchical_sigmoid", inputs=("X", "W", "Bias", "Label"),
             outputs=("Cost",), diff_inputs=("X", "W", "Bias"))
def _hsigmoid(ctx):
    """Complete-binary-tree hierarchical sigmoid (reference:
    gserver/layers/HierarchicalSigmoidLayer.cpp: num_classes-1 inner
    nodes, left branch = code bit 0).  Tree layout matches the
    reference's implicit heap order: internal node k has children
    2k+1 / 2k+2; class c sits at leaf (num_classes - 1 + c)."""
    x = unwrap(ctx.input("X")).astype(jnp.float32)          # (B, D)
    w = unwrap(ctx.input("W")).astype(jnp.float32)          # (V-1, D)
    label = unwrap(ctx.input("Label")).reshape(-1)          # (B,)
    num_classes = w.shape[0] + 1
    depth = int(np.ceil(np.log2(max(num_classes, 2))))

    # walk up from the leaf: node ids and branch directions, static depth
    node = label.astype(jnp.int32) + (num_classes - 1)
    scores = jnp.zeros(label.shape, jnp.float32)
    logits_all = x @ w.T                                    # (B, V-1)
    if ctx.has_input("Bias"):
        logits_all = logits_all + unwrap(ctx.input("Bias")).reshape(-1)
    for _ in range(depth):
        parent = (node - 1) // 2
        is_right = (node % 2) == 0          # child 2k+2 -> right
        valid = node > 0
        logit = jnp.take_along_axis(
            logits_all, jnp.maximum(parent, 0)[:, None], axis=1)[:, 0]
        # p(branch) = sigmoid(+/- logit); sum log-probs along the path
        z = jnp.where(is_right, -logit, logit)
        step_cost = jax.nn.softplus(-z)     # -log sigmoid(z)
        scores = scores + jnp.where(valid, step_cost, 0.0)
        node = jnp.maximum(parent, 0)
    ctx.set_output("Cost", scores[:, None])


@register_op("factorization_machine", inputs=("X", "W"),
             outputs=("Out",), diff_inputs=("X", "W"))
def _factorization_machine(ctx):
    """Second-order FM interaction term (reference:
    gserver/layers/FactorizationMachineLayer.cpp): out =
    0.5 * sum_k[(x @ W)_k^2 - (x^2 @ W^2)_k]."""
    x = unwrap(ctx.input("X")).astype(jnp.float32)   # (B, D)
    w = unwrap(ctx.input("W")).astype(jnp.float32)   # (D, K)
    s = x @ w                                        # (B, K)
    s2 = (x * x) @ (w * w)                           # (B, K)
    ctx.set_output("Out", 0.5 * jnp.sum(s * s - s2, axis=1, keepdims=True))
