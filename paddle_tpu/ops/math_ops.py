"""Dense math ops.

Reference: paddle/operators/{mul,matmul,elementwise_*,sum,scale,sign,
clip,clip_by_norm,cos_sim,squared_l2_norm,squared_l2_distance,cast,
logical_*,compare}_op.cc — all lowered to jnp/lax so the MXU gets
large fused matmuls instead of per-op kernel launches.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.ops.common import broadcast_to_x, elementwise, unary
from paddle_tpu.registry import SkipInferShape, infer_same_shape, register_op


def _dim_known(d) -> bool:
    return d is not None and d >= 0


def _static_numel(shape):
    """Product of dims, or None if any is dynamic."""
    n = 1
    for d in shape:
        if not _dim_known(d):
            return None
        n *= d
    return n


def _infer_mul_shape(op, block):
    """mul flattens X to 2-D at x_num_col_dims and Y at y_num_col_dims
    (reference: operators/mul_op.cc InferShape): Out keeps X's leading
    dims and Y's trailing dims.  Validates the contracted extents when
    both are static; backfills Out's shape when missing."""
    xv = block.find_var(op.input("X")[0]) if op.input("X") else None
    yv = block.find_var(op.input("Y")[0]) if op.input("Y") else None
    ov = block.find_var(op.output("Out")[0]) if op.output("Out") else None
    if xv is None or yv is None or ov is None:
        raise SkipInferShape
    if xv.shape is None or yv.shape is None:
        raise SkipInferShape
    xn = op.attr("x_num_col_dims", 1)
    yn = op.attr("y_num_col_dims", 1)
    if not (0 < xn <= len(xv.shape) and 0 < yn <= len(yv.shape)):
        raise ValueError(
            f"num_col_dims ({xn}, {yn}) out of range for shapes "
            f"{xv.shape} x {yv.shape}")
    k_x = _static_numel(xv.shape[xn:])
    k_y = _static_numel(yv.shape[:yn])
    if k_x is not None and k_y is not None and k_x != k_y:
        raise ValueError(
            f"contracted extents differ: X{list(xv.shape)} flattened at "
            f"{xn} gives K={k_x}, Y{list(yv.shape)} flattened at {yn} "
            f"gives K={k_y}")
    if ov.shape is None:
        ov.shape = tuple(xv.shape[:xn]) + tuple(yv.shape[yn:])


def _infer_matmul_shape(op, block):
    """Batched matmul: Out is (batch..., M, N) after transpose attrs.
    Validates the inner extents when static; backfills Out's shape."""
    xv = block.find_var(op.input("X")[0]) if op.input("X") else None
    yv = block.find_var(op.input("Y")[0]) if op.input("Y") else None
    ov = block.find_var(op.output("Out")[0]) if op.output("Out") else None
    if xv is None or yv is None or ov is None:
        raise SkipInferShape
    if xv.shape is None or yv.shape is None:
        raise SkipInferShape
    xs, ys = list(xv.shape), list(yv.shape)
    if len(xs) < 2 or len(ys) < 2:
        raise SkipInferShape  # 1-D operands follow numpy promotion rules
    if op.attr("transpose_X", False):
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if op.attr("transpose_Y", False):
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if _dim_known(xs[-1]) and _dim_known(ys[-2]) and xs[-1] != ys[-2]:
        raise ValueError(
            f"inner extents differ: {xv.shape} @ {yv.shape} "
            f"(K={xs[-1]} vs {ys[-2]})")
    if ov.shape is None:
        # numpy-style broadcast over the leading batch dims
        xb, yb = xs[:-2], ys[:-2]
        if len(xb) < len(yb):
            xb = [1] * (len(yb) - len(xb)) + xb
        else:
            yb = [1] * (len(xb) - len(yb)) + yb
        batch = []
        for a, b in zip(xb, yb):
            if a == 1:
                batch.append(b)
            elif b == 1:
                batch.append(a)
            elif not _dim_known(a) or not _dim_known(b):
                batch.append(-1)
            elif a == b:
                batch.append(a)
            else:
                raise ValueError(
                    f"batch dims do not broadcast: {xv.shape} @ {yv.shape}")
        ov.shape = tuple(batch) + (xs[-2], ys[-1])


def _infer_sum_shape(op, block):
    """sum's Out mirrors the first X operand with a known shape."""
    outs = op.output("Out")
    if len(outs) != 1 or not outs[0]:
        raise SkipInferShape
    ov = block.find_var(outs[0])
    if ov is None:
        raise SkipInferShape
    for name in op.input("X"):
        xv = block.find_var(name) if name else None
        if xv is not None and xv.shape is not None:
            if ov.shape is None:
                ov.shape = tuple(xv.shape)
            if ov.lod_level == 0 and xv.lod_level:
                ov.lod_level = xv.lod_level
            return
    raise SkipInferShape


def _pref():
    from paddle_tpu import amp

    return amp.preferred_acc()


def _flatten2d(x, num_col_dims):
    lead = 1
    for s in x.shape[:num_col_dims]:
        lead *= s
    rest = 1
    for s in x.shape[num_col_dims:]:
        rest *= s
    return jnp.reshape(x, (lead, rest))


@register_op("mul", inputs=("X", "Y"), infer_shape=_infer_mul_shape)
def _mul(ctx):
    """Flattening matmul (reference: operators/mul_op.cc): X flattened to
    2-D at x_num_col_dims, Y at y_num_col_dims."""
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    xn = ctx.attr("x_num_col_dims", 1)
    yn = ctx.attr("y_num_col_dims", 1)
    from paddle_tpu import amp

    out_dt = amp.out_dtype(x)
    x2, y2 = amp.cast_operands(_flatten2d(x, xn), _flatten2d(y, yn))
    out = None
    from paddle_tpu import pallas as pk

    if pk.use_matmul():
        from paddle_tpu.pallas import matmul as pk_mm

        m, k = x2.shape
        n = y2.shape[1]
        if pk_mm.fits(m, k, n):
            out = pk.pallas_matmul(x2, y2, interpret=pk.interpret_mode()).astype(out_dt)
    if out is None:
        out = jnp.dot(x2, y2, preferred_element_type=_pref()).astype(out_dt)
    out_shape = x.shape[:xn] + y.shape[yn:]
    ctx.set_output("Out", rewrap(ctx.input("X"), jnp.reshape(out, out_shape)))


@register_op("matmul", inputs=("X", "Y"), infer_shape=_infer_matmul_shape)
def _matmul(ctx):
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    from paddle_tpu import amp

    out_dt = amp.out_dtype(x)
    x, y = amp.cast_operands(x, y)
    out = jnp.matmul(x, y, preferred_element_type=_pref()).astype(out_dt)
    ctx.set_output("Out", out)


for name, fn in [
    ("elementwise_add", jnp.add),
    ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply),
    ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum),
    ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
]:
    # Out mirrors X: the reference broadcast rule aligns Y's dims to a
    # run of X's, so X's shape is always the output shape
    register_op(name, inputs=("X", "Y"), infer_shape=infer_same_shape)(
        functools.partial(lambda ctx, f: elementwise(ctx, f), f=fn))


@register_op("sum", inputs=("X",), infer_shape=_infer_sum_shape)
def _sum(ctx):
    from paddle_tpu.sparse import SparseGrad, concat_sparse

    raw = ctx.inputs("X")
    if all(isinstance(v, SparseGrad) for v in raw):
        # Sum of SelectedRows = row concatenation (reference:
        # operators/sum_op.h SelectedRows branch) — stays sparse.
        ctx.set_output("Out", concat_sparse(raw))
        return
    xs = [unwrap(v) for v in raw]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    template = next((v for v in raw if not isinstance(v, SparseGrad)), raw[0])
    ctx.set_output("Out", rewrap(template, out))


@register_op("scale", inputs=("X",), infer_shape=infer_same_shape)
def _scale(ctx):
    s = ctx.attr("scale", 1.0)
    b = ctx.attr("bias", 0.0)
    unary(ctx, lambda x: x * jnp.asarray(s, x.dtype) + jnp.asarray(b, x.dtype))


@register_op("sign", inputs=("X",), stop_gradient=True,
             infer_shape=infer_same_shape)
def _sign(ctx):
    unary(ctx, jnp.sign)


@register_op("clip", inputs=("X",), infer_shape=infer_same_shape)
def _clip(ctx):
    lo, hi = ctx.attr("min"), ctx.attr("max")
    unary(ctx, lambda x: jnp.clip(x, lo, hi))


@register_op("clip_by_norm", inputs=("X",), infer_shape=infer_same_shape)
def _clip_by_norm(ctx):
    max_norm = ctx.attr("max_norm")
    def f(x):
        norm = jnp.sqrt(jnp.sum(jnp.square(x)))
        scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
        return x * scale
    unary(ctx, f)


def _slot_var(op, block, slot, inputs=True, need_shape=False):
    names = (op.inputs if inputs else op.outputs).get(slot, [])
    if len(names) != 1 or not names[0]:
        raise SkipInferShape
    v = block.find_var(names[0])
    if v is None or (need_shape and v.shape is None):
        raise SkipInferShape
    return v


def _set_shape(v, shape):
    if v.shape is None:
        v.shape = tuple(int(s) for s in shape)


def _infer_squared_l2_norm_shape(op, block):
    _slot_var(op, block, "X", need_shape=True)
    _set_shape(_slot_var(op, block, "Out", inputs=False), (1,))


def _infer_squared_l2_distance_shape(op, block):
    xv = _slot_var(op, block, "X", need_shape=True)
    _set_shape(_slot_var(op, block, "sub_result", inputs=False), xv.shape)
    _set_shape(_slot_var(op, block, "Out", inputs=False),
               (xv.shape[0], 1))


def _infer_cos_sim_shape(op, block):
    # size-K form (Y holds K stacked vectors of X's width) yields K
    # similarities per row; the plain form yields one
    xv = _slot_var(op, block, "X", need_shape=True)
    yv = _slot_var(op, block, "Y", need_shape=True)
    if not xv.shape or not yv.shape or not xv.shape[-1]:
        raise SkipInferShape
    k = (1 if yv.shape[-1] == xv.shape[-1]
         else yv.shape[-1] // xv.shape[-1])
    _set_shape(_slot_var(op, block, "Out", inputs=False),
               tuple(xv.shape[:-1]) + (k,))
    _set_shape(_slot_var(op, block, "XNorm", inputs=False),
               tuple(xv.shape[:-1]) + (1,))
    _set_shape(_slot_var(op, block, "YNorm", inputs=False),
               tuple(yv.shape[:-1]) + (k if k > 1 else 1,))


def _infer_bilinear_shape(op, block):
    xv = _slot_var(op, block, "X", need_shape=True)
    wv = _slot_var(op, block, "Weight", need_shape=True)
    _set_shape(_slot_var(op, block, "Out", inputs=False),
               (xv.shape[0], wv.shape[0]))


@register_op("squared_l2_norm", inputs=("X",),
             infer_shape=_infer_squared_l2_norm_shape)
def _squared_l2_norm(ctx):
    unary(ctx, lambda x: jnp.sum(jnp.square(x)).reshape(1))


@register_op("squared_l2_distance", inputs=("X", "Y"), outputs=("sub_result", "Out"),
             infer_shape=_infer_squared_l2_distance_shape)
def _squared_l2_distance(ctx):
    x = unwrap(ctx.input("X"))
    y = broadcast_to_x(x, ctx.input("Y"), 0)
    sub = x - y
    ctx.set_output("sub_result", sub)
    ctx.set_output("Out", jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim))).reshape(-1, 1))


@register_op("cos_sim", inputs=("X", "Y"), outputs=("Out", "XNorm", "YNorm"),
             infer_shape=_infer_cos_sim_shape)
def _cos_sim(ctx):
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    if y.shape[-1] != x.shape[-1]:
        # reference CosSimLayer size>1: Y holds K stacked vectors of
        # X's width; output is the K similarities (gserver
        # CosSimLayer.cpp with config size = K)
        k = y.shape[-1] // x.shape[-1]
        y = y.reshape(y.shape[:-1] + (k, x.shape[-1]))
        x = x[..., None, :]
        xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1))
        yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1))
        out = jnp.sum(x * y, axis=-1) / (xn * yn + 1e-12)
        ctx.set_output("Out", out)
        ctx.set_output("XNorm", xn)
        ctx.set_output("YNorm", yn)
        return
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    ctx.set_output("Out", out)
    ctx.set_output("XNorm", xn)
    ctx.set_output("YNorm", yn)


def _register_compare(name, fn):
    @register_op(name, inputs=("X", "Y"), stop_gradient=True,
                 infer_shape=infer_same_shape)
    def _cmp(ctx, fn=fn):
        x = ctx.input("X")
        y = ctx.input("Y")
        out = fn(unwrap(x), broadcast_to_x(x, y, ctx.attr("axis", -1)))
        ctx.set_output("Out", rewrap(x, out))


for name, fn in [
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    _register_compare(name, fn)


@register_op("logical_not", inputs=("X",), stop_gradient=True,
             infer_shape=infer_same_shape)
def _logical_not(ctx):
    unary(ctx, jnp.logical_not)


@register_op("minus", inputs=("X", "Y"), infer_shape=infer_same_shape)
def _minus(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", rewrap(x, unwrap(x) - unwrap(ctx.input("Y"))))


@register_op("bilinear_tensor_product", inputs=("X", "Y", "Weight", "Bias"),
             infer_shape=_infer_bilinear_shape)
def _bilinear_tensor_product(ctx):
    x = unwrap(ctx.input("X"))  # (B, M)
    y = unwrap(ctx.input("Y"))  # (B, N)
    w = unwrap(ctx.input("Weight"))  # (K, M, N)
    out = jnp.einsum("bm,kmn,bn->bk", x, w, y)
    if ctx.has_input("Bias"):
        out = out + unwrap(ctx.input("Bias"))
    ctx.set_output("Out", out)
