"""Dense math ops.

Reference: paddle/operators/{mul,matmul,elementwise_*,sum,scale,sign,
clip,clip_by_norm,cos_sim,squared_l2_norm,squared_l2_distance,cast,
logical_*,compare}_op.cc — all lowered to jnp/lax so the MXU gets
large fused matmuls instead of per-op kernel launches.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.ops.common import broadcast_to_x, elementwise, unary
from paddle_tpu.registry import register_op


def _pref():
    from paddle_tpu import amp

    return amp.preferred_acc()


def _flatten2d(x, num_col_dims):
    lead = 1
    for s in x.shape[:num_col_dims]:
        lead *= s
    rest = 1
    for s in x.shape[num_col_dims:]:
        rest *= s
    return jnp.reshape(x, (lead, rest))


@register_op("mul", inputs=("X", "Y"))
def _mul(ctx):
    """Flattening matmul (reference: operators/mul_op.cc): X flattened to
    2-D at x_num_col_dims, Y at y_num_col_dims."""
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    xn = ctx.attr("x_num_col_dims", 1)
    yn = ctx.attr("y_num_col_dims", 1)
    from paddle_tpu import amp

    out_dt = amp.out_dtype(x)
    x2, y2 = amp.cast_operands(_flatten2d(x, xn), _flatten2d(y, yn))
    out = None
    from paddle_tpu import pallas as pk

    if pk.use_matmul():
        from paddle_tpu.pallas import matmul as pk_mm

        m, k = x2.shape
        n = y2.shape[1]
        if pk_mm.fits(m, k, n):
            out = pk.pallas_matmul(x2, y2, interpret=pk.interpret_mode()).astype(out_dt)
    if out is None:
        out = jnp.dot(x2, y2, preferred_element_type=_pref()).astype(out_dt)
    out_shape = x.shape[:xn] + y.shape[yn:]
    ctx.set_output("Out", rewrap(ctx.input("X"), jnp.reshape(out, out_shape)))


@register_op("matmul", inputs=("X", "Y"))
def _matmul(ctx):
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    from paddle_tpu import amp

    out_dt = amp.out_dtype(x)
    x, y = amp.cast_operands(x, y)
    out = jnp.matmul(x, y, preferred_element_type=_pref()).astype(out_dt)
    ctx.set_output("Out", out)


for name, fn in [
    ("elementwise_add", jnp.add),
    ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply),
    ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum),
    ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
]:
    register_op(name, inputs=("X", "Y"))(functools.partial(lambda ctx, f: elementwise(ctx, f), f=fn))


@register_op("sum", inputs=("X",))
def _sum(ctx):
    from paddle_tpu.sparse import SparseGrad, concat_sparse

    raw = ctx.inputs("X")
    if all(isinstance(v, SparseGrad) for v in raw):
        # Sum of SelectedRows = row concatenation (reference:
        # operators/sum_op.h SelectedRows branch) — stays sparse.
        ctx.set_output("Out", concat_sparse(raw))
        return
    xs = [unwrap(v) for v in raw]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    template = next((v for v in raw if not isinstance(v, SparseGrad)), raw[0])
    ctx.set_output("Out", rewrap(template, out))


@register_op("scale", inputs=("X",))
def _scale(ctx):
    s = ctx.attr("scale", 1.0)
    b = ctx.attr("bias", 0.0)
    unary(ctx, lambda x: x * jnp.asarray(s, x.dtype) + jnp.asarray(b, x.dtype))


@register_op("sign", inputs=("X",), stop_gradient=True)
def _sign(ctx):
    unary(ctx, jnp.sign)


@register_op("clip", inputs=("X",))
def _clip(ctx):
    lo, hi = ctx.attr("min"), ctx.attr("max")
    unary(ctx, lambda x: jnp.clip(x, lo, hi))


@register_op("clip_by_norm", inputs=("X",))
def _clip_by_norm(ctx):
    max_norm = ctx.attr("max_norm")
    def f(x):
        norm = jnp.sqrt(jnp.sum(jnp.square(x)))
        scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
        return x * scale
    unary(ctx, f)


@register_op("squared_l2_norm", inputs=("X",))
def _squared_l2_norm(ctx):
    unary(ctx, lambda x: jnp.sum(jnp.square(x)).reshape(1))


@register_op("squared_l2_distance", inputs=("X", "Y"), outputs=("sub_result", "Out"))
def _squared_l2_distance(ctx):
    x = unwrap(ctx.input("X"))
    y = broadcast_to_x(x, ctx.input("Y"), 0)
    sub = x - y
    ctx.set_output("sub_result", sub)
    ctx.set_output("Out", jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim))).reshape(-1, 1))


@register_op("cos_sim", inputs=("X", "Y"), outputs=("Out", "XNorm", "YNorm"))
def _cos_sim(ctx):
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    if y.shape[-1] != x.shape[-1]:
        # reference CosSimLayer size>1: Y holds K stacked vectors of
        # X's width; output is the K similarities (gserver
        # CosSimLayer.cpp with config size = K)
        k = y.shape[-1] // x.shape[-1]
        y = y.reshape(y.shape[:-1] + (k, x.shape[-1]))
        x = x[..., None, :]
        xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1))
        yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1))
        out = jnp.sum(x * y, axis=-1) / (xn * yn + 1e-12)
        ctx.set_output("Out", out)
        ctx.set_output("XNorm", xn)
        ctx.set_output("YNorm", yn)
        return
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    ctx.set_output("Out", out)
    ctx.set_output("XNorm", xn)
    ctx.set_output("YNorm", yn)


def _register_compare(name, fn):
    @register_op(name, inputs=("X", "Y"), stop_gradient=True)
    def _cmp(ctx, fn=fn):
        x = ctx.input("X")
        y = ctx.input("Y")
        out = fn(unwrap(x), broadcast_to_x(x, y, ctx.attr("axis", -1)))
        ctx.set_output("Out", rewrap(x, out))


for name, fn in [
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    _register_compare(name, fn)


@register_op("logical_not", inputs=("X",), stop_gradient=True)
def _logical_not(ctx):
    unary(ctx, jnp.logical_not)


@register_op("minus", inputs=("X", "Y"))
def _minus(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", rewrap(x, unwrap(x) - unwrap(ctx.input("Y"))))


@register_op("bilinear_tensor_product", inputs=("X", "Y", "Weight", "Bias"))
def _bilinear_tensor_product(ctx):
    x = unwrap(ctx.input("X"))  # (B, M)
    y = unwrap(ctx.input("Y"))  # (B, N)
    w = unwrap(ctx.input("Weight"))  # (K, M, N)
    out = jnp.einsum("bm,kmn,bn->bk", x, w, y)
    if ctx.has_input("Bias"):
        out = out + unwrap(ctx.input("Bias"))
    ctx.set_output("Out", out)
