"""Tensor creation / movement ops.

Reference: paddle/operators/{fill_constant,fill_zeros_like,assign,cast,
uniform_random,gaussian_random,increment,concat,split,reshape,transpose,
expand,gather,scatter,fill_constant_batch_size_like,...}_op.cc
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.lod import LoDArray, rewrap, unwrap
from paddle_tpu.ops.common import jnp_dtype, unary
from paddle_tpu.registry import SkipInferShape, infer_same_shape, register_op


# ---------------------------------------------------------------------------
# infer_shape rules (registry-audit ratchet: tensor-movement / gather
# family).  Same contract as the conv/pool rules in nn_ops.py: backfill
# missing output metadata, SkipInferShape when statically unknowable.
# ---------------------------------------------------------------------------


def _shape_var(block, name):
    v = block.find_var(name) if name else None
    if v is None:
        raise SkipInferShape
    return v


def _one_in_out(op, block, in_slot="X", out_slot="Out"):
    ins = op.inputs.get(in_slot, [])
    outs = op.outputs.get(out_slot, [])
    if len(ins) != 1 or len(outs) != 1:
        raise SkipInferShape
    xv, ov = _shape_var(block, ins[0]), _shape_var(block, outs[0])
    if xv.shape is None:
        raise SkipInferShape
    return xv, ov


def _infer_concat_shape(op, block):
    ins = op.inputs.get("X", [])
    outs = op.outputs.get("Out", [])
    if not ins or len(outs) != 1:
        raise SkipInferShape
    xvs = [_shape_var(block, n) for n in ins]
    ov = _shape_var(block, outs[0])
    if any(v.shape is None for v in xvs):
        raise SkipInferShape
    axis = op.attr("axis", 0) % max(1, len(xvs[0].shape))
    base = list(xvs[0].shape)
    if axis >= len(base):
        raise SkipInferShape
    dims = [v.shape[axis] if axis < len(v.shape) else -1 for v in xvs]
    base[axis] = -1 if any(d < 0 for d in dims) else sum(dims)
    if ov.shape is None:
        ov.shape = tuple(base)
    if ov.lod_level == 0 and xvs[0].lod_level:
        ov.lod_level = xvs[0].lod_level


def _infer_split_shape(op, block):
    ins = op.inputs.get("X", [])
    outs = op.outputs.get("Out", [])
    if len(ins) != 1 or not outs:
        raise SkipInferShape
    xv = _shape_var(block, ins[0])
    if xv.shape is None or not xv.shape:
        raise SkipInferShape
    axis = op.attr("axis", 0) % len(xv.shape)
    sections = op.attr("sections", None)
    if sections and len(sections) != len(outs):
        raise SkipInferShape
    for i, name in enumerate(outs):
        ov = _shape_var(block, name)
        if ov.shape is not None:
            continue
        if sections:
            d = int(sections[i])
        elif xv.shape[axis] >= 0:
            d = xv.shape[axis] // max(1, len(outs))
        else:
            d = -1
        shape = list(xv.shape)
        shape[axis] = d
        ov.shape = tuple(shape)


def _infer_reshape_shape(op, block):
    xv, ov = _one_in_out(op, block)
    if ov.shape is not None:
        return
    shape = [int(s) for s in (op.attr("shape", ()) or ())]
    if not shape:
        raise SkipInferShape
    shape = [xv.shape[i] if s == 0 and i < len(xv.shape) else s
             for i, s in enumerate(shape)]
    if shape.count(-1) == 1 and all(d >= 0 for d in xv.shape):
        total = 1
        for d in xv.shape:
            total *= d
        known = 1
        for d in shape:
            if d > 0:
                known *= d
        if known > 0 and total % known == 0:
            shape[shape.index(-1)] = total // known
    ov.shape = tuple(shape)


def _infer_transpose_shape(op, block):
    xv, ov = _one_in_out(op, block)
    perm = op.attr("axis", None)
    if not perm or len(perm) != len(xv.shape):
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(xv.shape[int(p)] for p in perm)


def _infer_expand_shape(op, block):
    xv, ov = _one_in_out(op, block)
    times = op.attr("expand_times", None)
    # only the matched-rank tile; rank-promoting tiles stay dynamic
    if not times or len(times) != len(xv.shape):
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(d * int(t) if d >= 0 else -1
                         for d, t in zip(xv.shape, times))


def _infer_gather_shape(op, block):
    xv, ov = _one_in_out(op, block)
    idxs = op.inputs.get("Index", [])
    if len(idxs) != 1:
        raise SkipInferShape
    iv = _shape_var(block, idxs[0])
    if iv.shape is None:
        raise SkipInferShape
    if ov.shape is None:
        # jnp.take(x, idx, axis=0): idx dims replace x's leading dim
        ov.shape = tuple(iv.shape) + tuple(xv.shape[1:])


def _infer_scatter_shape(op, block):
    rv, ov = _one_in_out(op, block, "Ref", "Out")
    if ov.shape is None:
        ov.shape = tuple(rv.shape)


def _infer_shape_op_shape(op, block):
    xv, ov = _one_in_out(op, block, "Input", "Out")
    if ov.shape is None:
        ov.shape = (len(xv.shape),)


def _infer_one_hot_shape(op, block):
    xv, ov = _one_in_out(op, block)
    depth = op.attr("depth", None)
    if not depth:
        raise SkipInferShape
    if ov.shape is None:
        shape = tuple(xv.shape)
        if shape and shape[-1] == 1:   # trailing id dim is squeezed
            shape = shape[:-1]
        ov.shape = shape + (int(depth),)


def _infer_attr_shape(op, block):
    # source ops (no tensor inputs) whose static shape IS their "shape"
    # attribute: fill_constant, uniform_random, gaussian_random, ...
    outs = op.outputs.get("Out", [])
    if len(outs) != 1:
        raise SkipInferShape
    ov = _shape_var(block, outs[0])
    shape = op.attr("shape", None)
    if not shape:
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(int(s) for s in shape)


def _infer_fill_bsl_shape(op, block):
    xv, ov = _one_in_out(op, block, in_slot="Input")
    shape = list(op.attr("shape", None) or [])
    in_idx = int(op.attr("input_dim_idx", 0) or 0)
    out_idx = int(op.attr("output_dim_idx", 0) or 0)
    if (not shape or in_idx >= len(xv.shape) or out_idx >= len(shape)):
        raise SkipInferShape
    shape[out_idx] = xv.shape[in_idx]
    if ov.shape is None:
        ov.shape = tuple(int(s) for s in shape)


def _infer_lookup_table_shape(op, block):
    # Ids (..., 1) int64 against W (V, D) -> Out (..., D); Out rides
    # Ids' LoD (sequence embedding keeps the sequence structure)
    ws = op.inputs.get("W", [])
    ids = op.inputs.get("Ids", [])
    outs = op.outputs.get("Out", [])
    if len(ws) != 1 or len(ids) != 1 or len(outs) != 1:
        raise SkipInferShape
    wv = _shape_var(block, ws[0])
    iv = _shape_var(block, ids[0])
    ov = _shape_var(block, outs[0])
    if wv.shape is None or iv.shape is None:
        raise SkipInferShape
    base = tuple(iv.shape)
    if base and base[-1] == 1:
        base = base[:-1]
    if ov.shape is None:
        ov.shape = base + (wv.shape[-1],)
    if ov.lod_level == 0 and iv.lod_level:
        ov.lod_level = iv.lod_level


@register_op("fill_constant", inputs=(), stop_gradient=True,
             infer_shape=_infer_attr_shape)
def _fill_constant(ctx):
    shape = tuple(ctx.attr("shape", ()))
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    value = ctx.attr("value", 0.0)
    ctx.set_output("Out", jnp.full(shape, value, dtype=dtype))


@register_op("fill_constant_batch_size_like", inputs=("Input",), stop_gradient=True,
             infer_shape=_infer_fill_bsl_shape)
def _fill_constant_bsl(ctx):
    ref = unwrap(ctx.input("Input"))
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    ctx.set_output("Out", jnp.full(tuple(shape), ctx.attr("value", 0.0), dtype=dtype))


@register_op("fill_zeros_like", inputs=("X",), stop_gradient=True, infer_shape=infer_same_shape)
def _fill_zeros_like(ctx):
    unary(ctx, jnp.zeros_like)


@register_op("assign", inputs=("X",), infer_shape=infer_same_shape)
def _assign(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("cast", inputs=("X",), infer_shape=infer_same_shape)
def _cast(ctx):
    dtype = jnp_dtype(ctx.attr("out_dtype", ctx.attr("dtype", "float32")))
    unary(ctx, lambda x: x.astype(dtype))


@register_op("uniform_random", inputs=(), stop_gradient=True,
             infer_shape=_infer_attr_shape)
def _uniform_random(ctx):
    shape = tuple(ctx.attr("shape"))
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    seed = ctx.attr("seed", 0)
    key = jax.random.key(seed) if seed else ctx.rng()
    ctx.set_output("Out", jax.random.uniform(key, shape, dtype=jnp.float32, minval=lo, maxval=hi).astype(dtype))


@register_op("gaussian_random", inputs=(), stop_gradient=True,
             infer_shape=_infer_attr_shape)
def _gaussian_random(ctx):
    shape = tuple(ctx.attr("shape"))
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    seed = ctx.attr("seed", 0)
    key = jax.random.key(seed) if seed else ctx.rng()
    ctx.set_output("Out", (jax.random.normal(key, shape) * std + mean).astype(dtype))


@register_op("increment", inputs=("X",), stop_gradient=True, infer_shape=infer_same_shape)
def _increment(ctx):
    step = ctx.attr("step", 1.0)
    unary(ctx, lambda x: x + jnp.asarray(step, x.dtype))


@register_op("concat", inputs=("X",), infer_shape=_infer_concat_shape)
def _concat(ctx):
    xs = ctx.inputs("X")
    axis = ctx.attr("axis", 0)
    datas = [unwrap(x) for x in xs]
    ctx.set_output("Out", rewrap(xs[0], jnp.concatenate(datas, axis=axis)))


@register_op("split", inputs=("X",), infer_shape=_infer_split_shape)
def _split(ctx):
    x = unwrap(ctx.input("X"))
    axis = ctx.attr("axis", 0)
    num = ctx.attr("num", 0)
    sections = ctx.attr("sections", None)
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num, axis=axis)
    ctx.set_outputs("Out", parts)


@register_op("reshape", inputs=("X",), infer_shape=_infer_reshape_shape)
def _reshape(ctx):
    x = unwrap(ctx.input("X"))
    shape = list(ctx.attr("shape"))
    # one -1 wildcard and 0 = copy-input-dim, as in the reference
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    ctx.set_output("Out", jnp.reshape(x, shape))


@register_op("transpose", inputs=("X",),
             infer_shape=_infer_transpose_shape)
def _transpose(ctx):
    x = unwrap(ctx.input("X"))
    ctx.set_output("Out", jnp.transpose(x, ctx.attr("axis")))


@register_op("expand", inputs=("X",), infer_shape=_infer_expand_shape)
def _expand(ctx):
    x = unwrap(ctx.input("X"))
    times = ctx.attr("expand_times")
    ctx.set_output("Out", jnp.tile(x, times))


@register_op("gather", inputs=("X", "Index"), diff_inputs=("X",),
             infer_shape=_infer_gather_shape)
def _gather(ctx):
    x = unwrap(ctx.input("X"))
    idx = unwrap(ctx.input("Index")).astype(jnp.int32)
    ctx.set_output("Out", jnp.take(x, idx, axis=0))


@register_op("scatter", inputs=("Ref", "Index", "Updates"),
             diff_inputs=("Ref", "Updates"),
             infer_shape=_infer_scatter_shape)
def _scatter(ctx):
    ref = unwrap(ctx.input("Ref"))
    idx = unwrap(ctx.input("Index")).astype(jnp.int32)
    upd = unwrap(ctx.input("Updates"))
    ctx.set_output("Out", ref.at[idx].set(upd))


def _lookup_table_grad_lower(ctx):
    """W@GRAD for lookup_table (reference: operators/lookup_table_op.cc
    LookupTableGradKernel).  With ``is_sparse`` the cotangent is kept as
    a static-shape SelectedRows (`paddle_tpu.sparse.SparseGrad`) — the
    (N, D) looked-up rows plus their indices — so no (vocab, D) dense
    gradient is ever built; otherwise a dense scatter-add."""
    from paddle_tpu.sparse import SparseGrad

    gname = ctx.op.outputs.get("W@GRAD", [""])[0]
    if not gname:
        return
    fwd_inputs = ctx.op.attr("__fwd_inputs__")
    fwd_attrs = ctx.op.attr("__fwd_attrs__")
    w = unwrap(ctx.values[fwd_inputs["W"][0]])
    ids_data = unwrap(ctx.values[fwd_inputs["Ids"][0]]).astype(jnp.int32)
    flat = ids_data[..., 0] if ids_data.shape[-1] == 1 else ids_data
    g = unwrap(ctx.input("Out@GRAD"))
    rows = flat.reshape(-1)
    vals = g.reshape(-1, g.shape[-1])
    padding_idx = fwd_attrs.get("padding_idx")
    if padding_idx is not None and padding_idx >= 0:
        vals = vals * (rows != padding_idx)[:, None].astype(vals.dtype)
    if fwd_attrs.get("is_sparse"):
        ctx.values[gname] = SparseGrad(rows, vals, w.shape[0])
    else:
        ctx.values[gname] = jnp.zeros_like(w).at[rows].add(vals.astype(w.dtype))


@register_op("lookup_table", inputs=("W", "Ids"), diff_inputs=("W",),
             grad_lower=_lookup_table_grad_lower,
             infer_shape=_infer_lookup_table_shape)
def _lookup_table(ctx):
    """Embedding lookup (reference: operators/lookup_table_op.cc).  Ids of
    shape (..., 1) int64; gradient w.r.t. W is a SelectedRows-style
    (rows, values) pair when ``is_sparse`` else a dense scatter-add."""
    w = unwrap(ctx.input("W"))
    ids = ctx.input("Ids")
    ids_data = unwrap(ids).astype(jnp.int32)
    squeeze = ids_data.shape[-1] == 1
    flat = ids_data[..., 0] if squeeze else ids_data
    padding_idx = ctx.attr("padding_idx", None)
    out = None
    from paddle_tpu import pallas as pk

    if pk.use_gather() and flat.ndim == 1:
        from paddle_tpu.pallas import embedding as pk_emb

        if pk_emb.fits(flat.shape[0], w.shape[1]):
            out = pk.pallas_gather_rows(w, flat, interpret=pk.interpret_mode())
    if out is None:
        out = jnp.take(w, flat, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (flat != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    ctx.set_output("Out", rewrap(ids, out))


@register_op("shape", inputs=("Input",), stop_gradient=True,
             infer_shape=_infer_shape_op_shape)
def _shape(ctx):
    x = unwrap(ctx.input("Input"))
    ctx.set_output("Out", jnp.asarray(x.shape, dtype=jnp.int32))


@register_op("slice_tensor", inputs=("X",))
def _slice_tensor(ctx):
    x = unwrap(ctx.input("X"))
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    sl = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[ax] = slice(st, en)
    ctx.set_output("Out", x[tuple(sl)])


@register_op("one_hot", inputs=("X",), stop_gradient=True,
             infer_shape=_infer_one_hot_shape)
def _one_hot(ctx):
    x = unwrap(ctx.input("X")).astype(jnp.int32)
    if x.ndim and x.shape[-1] == 1:
        x = x[..., 0]
    depth = ctx.attr("depth")
    ctx.set_output("Out", jax.nn.one_hot(x, depth, dtype=jnp.float32))


@register_op("reverse", inputs=("X",), infer_shape=infer_same_shape)
def _reverse(ctx):
    """Flip along `axis` (reference capability: RotateLayer's flip half;
    fluid gained a reverse op in later versions)."""
    x = unwrap(ctx.input("X"))
    axis = ctx.attr("axis", 0)
    ctx.set_output("Out", rewrap(ctx.input("X"), jnp.flip(x, axis=axis)))
