"""Loss ops.

Reference: paddle/operators/{cross_entropy,softmax_with_cross_entropy,
sigmoid_cross_entropy_with_logits,smooth_l1_loss,huber_loss,hinge_loss,
rank_loss,margin_rank_loss,log_loss,squared_l2_distance}_op.cc
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.registry import SkipInferShape, register_op


def _infer_mirror(in_slot, *out_slots):
    """Each named output mirrors the single ``in_slot`` input."""

    def infer(op, block):
        ins = op.inputs.get(in_slot, [])
        if len(ins) != 1 or not ins[0]:
            raise SkipInferShape
        xv = block.find_var(ins[0])
        if xv is None or xv.shape is None:
            raise SkipInferShape
        hit = False
        for slot in out_slots:
            outs = op.outputs.get(slot, [])
            if len(outs) != 1 or not outs[0]:
                continue
            ov = block.find_var(outs[0])
            if ov is None:
                continue
            hit = True
            if ov.shape is None:
                ov.shape = tuple(xv.shape)
            if ov.lod_level == 0 and xv.lod_level:
                ov.lod_level = xv.lod_level
        if not hit:
            raise SkipInferShape

    return infer


def _infer_rowwise(in_slot, out_slot, mirror=()):
    """``out_slot`` is a per-row (N, 1) loss column, N taken from the
    leading dim of ``in_slot`` (first entry for list slots); any
    ``mirror`` outputs copy the input shape wholesale."""

    def infer(op, block):
        ins = op.inputs.get(in_slot, [])
        if not ins or not ins[0]:
            raise SkipInferShape
        xv = block.find_var(ins[0])
        if xv is None or xv.shape is None or not len(xv.shape):
            raise SkipInferShape
        outs = op.outputs.get(out_slot, [])
        if len(outs) != 1 or not outs[0]:
            raise SkipInferShape
        ov = block.find_var(outs[0])
        if ov is None:
            raise SkipInferShape
        if ov.shape is None:
            ov.shape = (xv.shape[0], 1)
        for slot in mirror:
            m_outs = op.outputs.get(slot, [])
            if len(m_outs) != 1 or not m_outs[0]:
                continue
            mv = block.find_var(m_outs[0])
            if mv is not None and mv.shape is None:
                mv.shape = tuple(xv.shape)

    return infer


def _take_label_prob(x, label):
    """x: (N, D) probs; label: (N, 1) or (N,) int -> (N, 1)."""
    lab = label.astype(jnp.int32)
    if lab.ndim == 2 and lab.shape[-1] == 1:
        lab = lab[:, 0]
    picked = jnp.take_along_axis(x, lab[:, None], axis=1)
    return picked


@register_op("cross_entropy", inputs=("X", "Label"), outputs=("Y",),
             diff_inputs=("X",), infer_shape=_infer_rowwise("X", "Y"))
def _cross_entropy(ctx):
    """-log p[label] over a probability input (reference:
    operators/cross_entropy_op.cc; soft_label supported)."""
    x = unwrap(ctx.input("X")).astype(jnp.float32)
    label = unwrap(ctx.input("Label"))
    eps = 1e-12
    if ctx.attr("soft_label", False):
        y = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        y = -jnp.log(_take_label_prob(x, label) + eps)
    ctx.set_output("Y", rewrap(ctx.input("X"), y))


@register_op("softmax_with_cross_entropy", inputs=("Logits", "Label"),
             outputs=("Softmax", "Loss"), diff_inputs=("Logits",),
             infer_shape=_infer_rowwise("Logits", "Loss", mirror=("Softmax",)))
def _softmax_with_cross_entropy(ctx):
    logits = unwrap(ctx.input("Logits")).astype(jnp.float32)
    label = unwrap(ctx.input("Label"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    ctx.set_output("Softmax", jnp.exp(logp))
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        loss = -_take_label_prob(logp, label)
    ctx.set_output("Loss", loss)


@register_op("sigmoid_cross_entropy_with_logits", inputs=("X", "Label"),
             diff_inputs=("X",), infer_shape=_infer_mirror("X", "Out"))
def _sigmoid_ce(ctx):
    x = unwrap(ctx.input("X"))
    label = unwrap(ctx.input("Label")).astype(x.dtype)
    # max(x,0) - x*z + log(1+exp(-|x|)), numerically stable
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctx.set_output("Out", loss)


@register_op("smooth_l1_loss", inputs=("X", "Y", "InsideWeight", "OutsideWeight"),
             outputs=("Diff", "Out"), diff_inputs=("X", "Y"),
             infer_shape=_infer_rowwise("X", "Out", mirror=("Diff",)))
def _smooth_l1(ctx):
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    sigma = ctx.attr("sigma", 1.0)
    sigma2 = sigma * sigma
    diff = x - y
    if ctx.has_input("InsideWeight"):
        diff = diff * unwrap(ctx.input("InsideWeight"))
    ctx.set_output("Diff", diff)
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * jnp.square(diff), ad - 0.5 / sigma2)
    if ctx.has_input("OutsideWeight"):
        loss = loss * unwrap(ctx.input("OutsideWeight"))
    ctx.set_output("Out", jnp.sum(loss, axis=tuple(range(1, loss.ndim))).reshape(-1, 1))


@register_op("huber_loss", inputs=("X", "Y"), outputs=("Residual", "Out"),
             diff_inputs=("X", "Y"),
             infer_shape=_infer_mirror("X", "Residual", "Out"))
def _huber(ctx):
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y"))
    delta = ctx.attr("delta", 1.0)
    r = y - x
    ctx.set_output("Residual", r)
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * jnp.square(r), delta * (ar - 0.5 * delta))
    ctx.set_output("Out", out)


@register_op("hinge_loss", inputs=("Logits", "Labels"), outputs=("Loss",),
             diff_inputs=("Logits",),
             infer_shape=_infer_mirror("Logits", "Loss"))
def _hinge(ctx):
    logits = unwrap(ctx.input("Logits"))
    labels = unwrap(ctx.input("Labels")).astype(logits.dtype)
    ctx.set_output("Loss", jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0))


@register_op("rank_loss", inputs=("Label", "Left", "Right"), outputs=("Out",),
             diff_inputs=("Left", "Right"),
             infer_shape=_infer_mirror("Left", "Out"))
def _rank_loss(ctx):
    label = unwrap(ctx.input("Label"))
    left = unwrap(ctx.input("Left"))
    right = unwrap(ctx.input("Right"))
    d = left - right
    ctx.set_output("Out", jnp.log1p(jnp.exp(d)) - label * d)


@register_op("margin_rank_loss", inputs=("Label", "X1", "X2"),
             outputs=("Out", "Activated"), diff_inputs=("X1", "X2"),
             infer_shape=_infer_mirror("X1", "Out", "Activated"))
def _margin_rank_loss(ctx):
    label = unwrap(ctx.input("Label"))
    x1 = unwrap(ctx.input("X1"))
    x2 = unwrap(ctx.input("X2"))
    margin = ctx.attr("margin", 0.0)
    raw = -label * (x1 - x2) + margin
    act = (raw > 0).astype(x1.dtype)
    ctx.set_output("Activated", act)
    ctx.set_output("Out", act * raw)


@register_op("log_loss", inputs=("Predicted", "Labels"), outputs=("Loss",),
             diff_inputs=("Predicted",),
             infer_shape=_infer_mirror("Predicted", "Loss"))
def _log_loss(ctx):
    p = unwrap(ctx.input("Predicted"))
    l = unwrap(ctx.input("Labels"))
    eps = ctx.attr("epsilon", 1e-4)
    ctx.set_output("Loss", -l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps))


@register_op("modified_huber_loss", inputs=("X", "Y"),
             outputs=("IntermediateVal", "Out"), diff_inputs=("X",),
             infer_shape=_infer_mirror("X", "IntermediateVal", "Out"))
def _modified_huber(ctx):
    x = unwrap(ctx.input("X"))
    y = unwrap(ctx.input("Y")).astype(x.dtype)
    z = (2.0 * y - 1.0) * x
    ctx.set_output("IntermediateVal", z)
    out = jnp.where(z < -1.0, -4.0 * z, jnp.square(jnp.maximum(1.0 - z, 0.0)))
    ctx.set_output("Out", out)


@register_op("padded_sequence_cross_entropy", inputs=("X", "Label", "Length"),
             diff_inputs=("X",), infer_shape=_infer_rowwise("X", "Out"))
def _padded_sequence_cross_entropy(ctx):
    """Per-sequence mean NLL over a padded (B, T, V) probability tensor
    with (B, T) integer labels, masking steps >= Length — the padded
    analog of per-step cross_entropy over a LoD sequence (reference:
    operators/cross_entropy_op.cc applied per step of a dynamic RNN)."""
    x = unwrap(ctx.input("X")).astype(jnp.float32)
    label = unwrap(ctx.input("Label"))
    B, T = label.shape[0], label.shape[1]
    if ctx.has_input("Length"):
        lens = unwrap(ctx.input("Length")).reshape(-1)
    else:
        lens = jnp.full((B,), T, jnp.int32)
    p = jnp.take_along_axis(x, label[..., None].astype(jnp.int32), axis=-1)[..., 0]
    nll = -jnp.log(jnp.maximum(p, 1e-12))                 # (B, T)
    valid = jnp.arange(T)[None, :] < lens[:, None]
    per_seq = (jnp.sum(jnp.where(valid, nll, 0.0), axis=1)
               / jnp.maximum(lens.astype(jnp.float32), 1.0))
    ctx.set_output("Out", per_seq[:, None])


def _lambda_positions(y, o, lens, T):
    """Sort each row's first ``lens`` entries by true score desc.
    Returns (order, y_sorted, o_sorted, valid_positions)."""
    valid = jnp.arange(T)[None, :] < lens[:, None]
    key = jnp.where(valid, y, -jnp.inf)
    order = jnp.argsort(-key, axis=1)                     # (B, T)
    ys = jnp.take_along_axis(y, order, axis=1)
    os_ = jnp.take_along_axis(o, order, axis=1)
    return order, ys, os_, valid


def _lambda_max_dcg(ys, lens, k):
    pos = jnp.arange(ys.shape[1])[None, :]
    k_eff = jnp.minimum(lens, k)[:, None]
    disc = 1.0 / jnp.log(pos + 2.0)
    gain = jnp.power(2.0, ys) - 1.0
    return jnp.sum(jnp.where(pos < k_eff, gain * disc, 0.0), axis=1)


def _lambda_cost_grad_lower(ctx):
    """Hand-defined LambdaRank gradients (reference: gserver/layers/
    CostLayer.cpp LambdaCost::calcGrad) — NOT the gradient of the NDCG
    forward, by design."""
    from paddle_tpu.lod import LoDArray

    fwd_in = ctx.op.attr("__fwd_inputs__")
    fwd_at = ctx.op.attr("__fwd_attrs__")
    score_v = ctx.values[fwd_in["Score"][0]]
    label_v = ctx.values[fwd_in["Label"][0]]
    o = unwrap(score_v).astype(jnp.float32)
    y = unwrap(label_v).astype(jnp.float32)
    squeeze = o.ndim == 3
    if squeeze:
        o = o[..., 0]
    if y.ndim == 3:
        y = y[..., 0]
    B, T = o.shape
    if fwd_in.get("Length"):
        lens = unwrap(ctx.values[fwd_in["Length"][0]]).reshape(-1).astype(jnp.int32)
    else:
        lens = jnp.full((B,), T, jnp.int32)
    k = int(fwd_at.get("NDCG_num", 5))
    mss = int(fwd_at.get("max_sort_size", -1))
    gout = unwrap(ctx.input("Out@GRAD")).reshape(B, 1).astype(jnp.float32)

    order, ys, os_, _valid = _lambda_positions(y, o, lens, T)
    max_dcg = jnp.maximum(_lambda_max_dcg(ys, lens, k), 1e-12)   # (B,)

    pos = jnp.arange(T)
    p = pos[:, None]                                      # i (row)
    q = pos[None, :]                                      # j (col)
    sort_size = lens if mss < 0 else jnp.minimum(lens, mss)      # (B,)
    pair_ok = ((p < q)[None]
               & (q[None] < lens[:, None, None])
               & (p[None] < sort_size[:, None, None]))    # (B, T, T)
    disc_p = 1.0 / jnp.log(p + 2.0)
    disc_q = 1.0 / jnp.log(q + 2.0)
    gain = jnp.power(2.0, ys)                             # (B, T)
    gdif = gain[:, :, None] - gain[:, None, :]
    dcg_dif = jnp.where((q[None] < sort_size[:, None, None]),
                        gdif * (disc_p - disc_q)[None],
                        gdif * disc_p[None])
    lam = -jnp.abs(dcg_dif) / (1.0 + jnp.exp(
        os_[:, :, None] - os_[:, None, :]))
    lam = jnp.where(pair_ok, lam, 0.0)
    g_sorted = (jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)) \
        / max_dcg[:, None]                                # (B, T)
    # unsort back to original positions
    grad = jnp.zeros_like(g_sorted)
    grad = jnp.put_along_axis(grad, order, g_sorted, axis=1,
                              inplace=False)
    grad = grad * gout                                    # chain outer grad
    if squeeze:
        grad = grad[..., None]
    gname = ctx.op.outputs.get("Score@GRAD", [None])[0]
    if gname:
        from paddle_tpu.lod import rewrap as _rw

        ctx.values[gname] = _rw(score_v, grad.astype(unwrap(score_v).dtype))


@register_op("lambda_cost", inputs=("Score", "Label", "Length"),
             outputs=("Out",), diff_inputs=("Score",),
             grad_lower=_lambda_cost_grad_lower,
             infer_shape=_infer_rowwise("Score", "Out"))
def _lambda_cost(ctx):
    """LambdaRank listwise cost (reference: gserver/layers/CostLayer.cpp
    LambdaCost; v1 lambda_cost).  Forward emits NDCG@k per list (what
    the reference layer reports); backward is the hand-defined lambda
    gradient above.  Score/Label: padded (B, T[, 1]); Length: (B,)."""
    o = unwrap(ctx.input("Score")).astype(jnp.float32)
    y = unwrap(ctx.input("Label")).astype(jnp.float32)
    if o.ndim == 3:
        o = o[..., 0]
    if y.ndim == 3:
        y = y[..., 0]
    B, T = o.shape
    if ctx.has_input("Length"):
        lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    else:
        lens = jnp.full((B,), T, jnp.int32)
    k = int(ctx.attr("NDCG_num", 5))

    valid = jnp.arange(T)[None, :] < lens[:, None]
    # DCG of the model ranking, maxDCG of the ideal ranking
    order_o = jnp.argsort(-jnp.where(valid, o, -jnp.inf), axis=1)
    y_by_o = jnp.take_along_axis(y, order_o, axis=1)
    dcg = _lambda_max_dcg(y_by_o, lens, k)
    _, ys, _, _ = _lambda_positions(y, o, lens, T)
    max_dcg = jnp.maximum(_lambda_max_dcg(ys, lens, k), 1e-12)
    ctx.set_output("Out", (dcg / max_dcg)[:, None])


@register_op("cross_entropy_over_beam", inputs=("Scores", "Ids", "Golds"),
             outputs=("Out",), diff_inputs=("Scores",),
             infer_shape=_infer_rowwise("Scores", "Out"))
def _cross_entropy_over_beam(ctx):
    """Cross entropy over beam expansions, globally normalized over all
    expanded paths (reference: gserver/layers/CrossEntropyOverBeam.cpp
    CostForOneSequence — calValidExpandStep / constructTotalExpansion /
    globallyNormalizedScore).

    Inputs per expansion step i (lists, one entry per step):
      Scores: (B, N_i) candidate scores; for i >= 1 the candidate axis
        is laid out as k_{i-1} parent blocks of C_i = N_i / k_{i-1}
        candidates each, so candidate c's parent beam slot is c // C_i.
      Ids:    (B, k_i) candidate indices selected into the beam
        (kmax output), -1 padded.  Required when there is more than
        one step — the path set is defined by the beam.
      Golds:  (B, 1) gold candidate index at that step.

    Reference semantics reproduced exactly:
      - the valid expansion L per sample is the first step whose beam
        does not contain the gold (all steps when it never falls off);
      - the softmax runs once over the scores of all paths alive in
        expansion L, where a path's score is the SUM of its selected
        candidates' scores along its ancestry (anc below);
      - if the gold fell off the beam it joins as an extra path
        (goldAsExtraPath_); cost = -log p(gold path).
    """
    scores = [unwrap(v).astype(jnp.float32) for v in ctx.inputs("Scores")]
    scores = [s[..., 0] if s.ndim == 3 else s for s in scores]
    golds = [unwrap(v).reshape(-1).astype(jnp.int32)
             for v in ctx.inputs("Golds")]
    ids_named = [n for n in ctx.op.inputs.get("Ids", []) if n]
    ids = [unwrap(ctx.values[n]).astype(jnp.int32) for n in ids_named]
    E = len(scores)
    B = scores[0].shape[0]
    if E > 1 and len(ids) != E:
        raise ValueError(
            "cross_entropy_over_beam: multi-step beams need the Ids "
            "input (one (B, k) selected-candidate tensor per step) to "
            "define the expanded path set (reference "
            "CrossEntropyOverBeam.cpp constructTotalExpansion)")
    if E == 1 and not ids:
        # beam == all candidates: one softmax over the single expansion
        logp = jax.nn.log_softmax(scores[0], axis=-1)
        nll = -jnp.take_along_axis(logp, golds[0][:, None], axis=1)[:, 0]
        ctx.set_output("Out", nll[:, None])
        return

    NEG = jnp.float32(-1e30)

    def one(sample_scores, sample_ids, sample_golds):
        # per-sample; unrolled over the static step count
        active = jnp.bool_(True)       # gold survived all earlier beams
        gold_sum = jnp.float32(0.0)    # gold path score so far
        cost = jnp.float32(0.0)
        anc_prev = None                # (k_{i-1},) path score per slot
        for i in range(E):
            s, g = sample_scores[i], sample_golds[i]
            sid = sample_ids[i]
            valid = sid >= 0
            cand = jnp.where(valid, sid, 0)
            if anc_prev is None:
                anc = s[cand]
            else:
                cpp = s.shape[0] // anc_prev.shape[0]
                anc = anc_prev[cand // cpp] + s[cand]
            anc = jnp.where(valid, anc, NEG)
            gold_sum = gold_sum + s[g]
            found = jnp.any(valid & (cand == g))
            # expansion L = first not-found step, else the last step
            is_last = active & (~found | jnp.bool_(i == E - 1))
            paths = jnp.concatenate(
                [anc, jnp.where(found, NEG, gold_sum)[None]])
            lse = jax.scipy.special.logsumexp(paths)
            cost = cost + jnp.where(is_last, lse - gold_sum, 0.0)
            active = active & found
            anc_prev = anc
        return cost

    nll = jax.vmap(one)(scores, ids, golds)
    ctx.set_output("Out", nll[:, None])
