"""LoD structure ops: rank table, tensor<->array, RNN memory plumbing.

Reference: operators/lod_rank_table_op.cc, lod_tensor_to_array_op.cc,
array_to_lod_tensor_op.cc, shrink_rnn_memory_op.cc,
rnn_memory_helper_op.cc, split_lod_tensor_op.cc, merge_lod_tensor_op.cc
— the machinery behind fluid's length-sorted dynamic RNN
(python/paddle/v2/fluid/layers/control_flow.py).

TPU design: the reference physically regroups ragged rows into
per-timestep tensors of *shrinking* batch size.  Under a static-shape
compiler we keep a fixed (max_len, n_seq, D) batch-major buffer ordered
by the rank table (longest sequence first) and *mask* instead of
shrinking: ``shrink_rnn_memory`` zero-masks retired rows rather than
slicing them off, which preserves the observable semantics (retired
sequences stop contributing) while every step stays one fixed-shape MXU
matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import LoDArray, LoDRankTable, row_segment_ids, unwrap
from paddle_tpu.registry import register_op
from paddle_tpu.tensor_array import TensorArray


@register_op("lod_rank_table", inputs=("X",), stop_gradient=True)
def _lod_rank_table(ctx):
    x = ctx.input("X")
    assert isinstance(x, LoDArray), "lod_rank_table needs a LoD input"
    level = int(ctx.attr("level", 0))
    off = x.lod[level]
    lens = off[1:] - off[:-1]
    # stable descending sort by length (reference keeps input order for ties)
    order = jnp.argsort(-lens, stable=True).astype(jnp.int32)
    ctx.set_output("Out", LoDRankTable(order, lens[order], x.last_level(),
                                       src_rows=x.data.shape[0]))


def _batch_major(x: LoDArray, table: LoDRankTable, max_len=None):
    """Packed rows -> (max_len, n_seq, D) ordered by rank table.

    ``max_len`` bounds the time dimension statically; without it the
    only safe static bound is the total packed row count (a single
    sequence could own every row), so callers that know their bucketed
    max length should pass it (lod_tensor_to_array's max_len attr) to
    keep downstream scans O(max_len), not O(total_rows)."""
    data = x.data
    off = x.last_level()
    nseq = off.shape[0] - 1
    max_len = int(max_len) if max_len else data.shape[0]
    ids = row_segment_ids(off, data.shape[0])          # seq id per row
    pos = jnp.arange(data.shape[0], dtype=jnp.int32) - jnp.take(
        off, jnp.minimum(ids, nseq - 1))               # step within sequence
    # rank of each sequence: inverse permutation of table.index
    rank_of = jnp.zeros(nseq, jnp.int32).at[table.index].set(
        jnp.arange(nseq, dtype=jnp.int32))
    col = jnp.take(rank_of, jnp.minimum(ids, nseq - 1))
    # Steps at/beyond max_len are explicitly truncated (bucketing
    # contract); without the pos bound they would alias into the
    # sentinel slot and corrupt other rows.
    valid = (ids < nseq) & (pos < max_len)
    flat_idx = jnp.where(valid, pos * nseq + col, max_len * nseq)
    buf = jnp.zeros((max_len * nseq + 1,) + data.shape[1:], data.dtype)
    buf = buf.at[flat_idx].set(jnp.where(
        valid.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0))
    return buf[:-1].reshape((max_len, nseq) + data.shape[1:])


@register_op("lod_tensor_to_array", inputs=("X", "RankTable"))
def _lod_tensor_to_array(ctx):
    x = ctx.input("X")
    table = ctx.input("RankTable")
    assert isinstance(x, LoDArray) and isinstance(table, LoDRankTable)
    max_len = ctx.attr("max_len")
    bm = _batch_major(x, table, max_len=max_len)
    size = jnp.max(table.lengths).astype(jnp.int32)
    if max_len:
        # Keep the scan bound consistent with the (possibly truncated)
        # time dimension.
        size = jnp.minimum(size, jnp.int32(int(max_len)))
    ctx.set_output("Out", TensorArray(bm, size))


@register_op("array_to_lod_tensor", inputs=("X", "RankTable"))
def _array_to_lod_tensor(ctx):
    ta = ctx.input("X")
    table = ctx.input("RankTable")
    bm = ta.stack                                     # (max_len, n_seq, D)
    max_len, nseq = bm.shape[0], bm.shape[1]
    off = table.offsets
    total = bm.shape[0] * nseq
    ids = row_segment_ids(off, total)                 # dest seq per packed row
    pos = jnp.arange(total, dtype=jnp.int32) - jnp.take(
        off, jnp.minimum(ids, nseq - 1))
    rank_of = jnp.zeros(nseq, jnp.int32).at[table.index].set(
        jnp.arange(nseq, dtype=jnp.int32))
    col = jnp.take(rank_of, jnp.minimum(ids, nseq - 1))
    valid = ids < nseq
    src = jnp.where(valid, pos * nseq + col, 0)
    flat = bm.reshape((total,) + bm.shape[2:])
    rows = jnp.take(flat, jnp.minimum(src, total - 1), axis=0)
    rows = jnp.where(
        valid.reshape((-1,) + (1,) * (rows.ndim - 1)), rows, 0)
    # restore the source packed buffer size (rows beyond off[-1] are the
    # zero padding the original tensor carried)
    n_rows = ctx.attr("max_rows", table.src_rows) or total
    ctx.set_output("Out", LoDArray(rows[:n_rows], (off,)))


@register_op("shrink_rnn_memory", inputs=("X", "RankTable", "I"))
def _shrink_rnn_memory(ctx):
    """Zero-mask memory rows of sequences that ended before step I
    (reference slices the first k rows off; see module docstring)."""
    x = unwrap(ctx.input("X"))                        # (n_seq, D) rank-ordered
    table = ctx.input("RankTable")
    i = jnp.reshape(unwrap(ctx.input("I")), ()).astype(jnp.int32)
    alive = (table.lengths > i).astype(x.dtype)       # rank-ordered, desc
    ctx.set_output("Out", x * alive.reshape((-1,) + (1,) * (x.ndim - 1)))


@register_op("rnn_memory_helper", inputs=("X",))
def _rnn_memory_helper(ctx):
    # identity plumbing var for memory hand-off between steps
    ctx.set_output("Out", ctx.input("X"))


@register_op("split_lod_tensor", inputs=("X", "Mask"),
             outputs=("OutTrue", "OutFalse"), diff_inputs=("X",))
def _split_lod_tensor(ctx):
    """Mask-split rows (reference physically partitions; we zero-mask the
    complementary rows so both outputs keep the static shape)."""
    x = unwrap(ctx.input("X"))
    mask = unwrap(ctx.input("Mask")).astype(bool).reshape(-1)
    m = mask.reshape((-1,) + (1,) * (x.ndim - 1))
    ctx.set_output("OutTrue", jnp.where(m, x, 0))
    ctx.set_output("OutFalse", jnp.where(m, 0, x))


@register_op("merge_lod_tensor", inputs=("X", "Mask", "InTrue", "InFalse"),
             diff_inputs=("InTrue", "InFalse"))
def _merge_lod_tensor(ctx):
    t = unwrap(ctx.input("InTrue"))
    f = unwrap(ctx.input("InFalse"))
    mask = unwrap(ctx.input("Mask")).astype(bool).reshape(-1)
    m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
    ctx.set_output("Out", jnp.where(m, t, f))
