"""NN layer ops: conv, pool, norm, dropout, softmax.

Reference: paddle/operators/{conv,pool,batch_norm,dropout,softmax,lrn,
conv_transpose,maxout}_op.cc.  All NCHW (the reference layout); XLA's
layout assignment maps them onto the MXU/VPU natively, so no cudnn-style
per-op algorithm choice exists here — the whole block fuses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.registry import (SkipInferShape, infer_same_shape,
                                 register_op)


def _pref():
    from paddle_tpu import amp

    return amp.preferred_acc()


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


# ---------------------------------------------------------------------------
# infer_shape rules (registry-audit ratchet: conv/pool family).  Same
# contract as the elementwise/matmul rules in math_ops.py: backfill
# missing output metadata, SkipInferShape when statically unknowable,
# ValueError only for shapes the lowering would also reject.
# ---------------------------------------------------------------------------


def _io_vars(op, block, in_slot, out_slot):
    ins = op.inputs.get(in_slot, [])
    outs = op.outputs.get(out_slot, [])
    if len(ins) != 1 or len(outs) != 1 or not ins[0] or not outs[0]:
        raise SkipInferShape
    xv = block.find_var(ins[0])
    ov = block.find_var(outs[0])
    if xv is None or ov is None or xv.shape is None:
        raise SkipInferShape
    return xv, ov


def _conv_extent(size, k, p, s, d=1):
    if size < 0:
        return -1
    out = (size + 2 * p - ((k - 1) * d + 1)) // s + 1
    if out < 1:
        raise ValueError(
            f"conv/pool output extent {out} < 1 (input {size}, kernel {k}, "
            f"pad {p}, stride {s}, dilation {d})")
    return out


def _nd(op, name, default, n):
    v = op.attr(name, default)
    v = tuple(v) if isinstance(v, (list, tuple)) else (v,) * n
    if len(v) != n:
        raise SkipInferShape
    return v


def _make_conv_infer(spatial: int, transpose: bool = False):
    def infer(op, block):
        xv, ov = _io_vars(op, block, "Input", "Output")
        fs = op.inputs.get("Filter", [])
        wv = block.find_var(fs[0]) if len(fs) == 1 and fs[0] else None
        if (wv is None or wv.shape is None or ov.shape is not None
                or len(xv.shape) != 2 + spatial
                or len(wv.shape) != 2 + spatial):
            if ov.shape is not None:
                return
            raise SkipInferShape
        ones = (1,) * spatial
        zeros = (0,) * spatial
        strides = _nd(op, "strides", ones, spatial)
        pads = _nd(op, "paddings", zeros, spatial)
        dils = _nd(op, "dilations", ones, spatial)
        if transpose:
            # filter (Cin, Cout, *k).  Match what lax.conv_transpose
            # with transpose_kernel=True actually emits:
            # (in-1)*s + 2p - (k-1)*d + 1 (verified empirically across
            # stride/pad/dilation combos).  NB the layer builder stamps
            # the Paddle-paper convention ((in-1)*s - 2p + (k-1)*d + 1)
            # at build time — the two agree exactly when
            # p == (k-1)*d/2 (every shipped config); this rule only
            # backfills missing metadata, so built programs keep the
            # builder's value.
            out_c = wv.shape[1]

            def _t_extent(i):
                size = xv.shape[2 + i]
                if size < 0:
                    return -1
                out = (size - 1) * strides[i] + 2 * pads[i] \
                    - (wv.shape[2 + i] - 1) * dils[i] + 1
                if out < 1:
                    raise ValueError(
                        f"conv_transpose output extent {out} < 1 "
                        f"(input {size}, kernel {wv.shape[2 + i]}, "
                        f"pad {pads[i]}, stride {strides[i]}, "
                        f"dilation {dils[i]})")
                return out

            sp = tuple(_t_extent(i) for i in range(spatial))
        else:
            out_c = wv.shape[0]
            sp = tuple(_conv_extent(xv.shape[2 + i], wv.shape[2 + i],
                                    pads[i], strides[i], dils[i])
                       for i in range(spatial))
        ov.shape = (xv.shape[0], out_c) + sp

    return infer


def _make_pool_infer(spatial: int, out_slot: str = "Out",
                     default_strides=None, also: tuple = ()):
    def infer(op, block):
        xv, ov = _io_vars(op, block, "X", out_slot)
        if len(xv.shape) != 2 + spatial:
            raise SkipInferShape
        if ov.shape is None:
            if op.attr("global_pooling", False):
                sp = (1,) * spatial
            else:
                ks = _nd(op, "ksize", (2,) * spatial, spatial)
                st_default = (ks if default_strides == "ksize"
                              else default_strides or (1,) * spatial)
                st = _nd(op, "strides", st_default, spatial)
                pd = _nd(op, "paddings", (0,) * spatial, spatial)
                ceil = op.attr("ceil_mode", False)
                sp = []
                for i in range(spatial):
                    size = xv.shape[2 + i]
                    if size < 0:
                        sp.append(-1)
                        continue
                    from paddle_tpu.layers.nn import pool_out_extent

                    sp.append(pool_out_extent(size, ks[i], pd[i], st[i],
                                              ceil_mode=ceil))
                sp = tuple(sp)
            ov.shape = tuple(xv.shape[:2]) + sp
        for slot in also:   # e.g. the with_index Mask mirrors Out
            extra = op.outputs.get(slot, [])
            if len(extra) == 1 and extra[0]:
                ev = block.find_var(extra[0])
                if ev is not None and ev.shape is None:
                    ev.shape = tuple(ov.shape)

    return infer


def _infer_mirror_x(*out_slots, in_slot="X"):
    """Every named output mirrors the (single) ``in_slot`` input."""

    def infer(op, block):
        ins = op.inputs.get(in_slot, [])
        if len(ins) != 1 or not ins[0]:
            raise SkipInferShape
        xv = block.find_var(ins[0])
        if xv is None or xv.shape is None:
            raise SkipInferShape
        hit = False
        for slot in out_slots:
            outs = op.outputs.get(slot, [])
            if len(outs) != 1 or not outs[0]:
                continue
            ov = block.find_var(outs[0])
            if ov is None:
                continue
            hit = True
            if ov.shape is None:
                ov.shape = tuple(xv.shape)
            if ov.lod_level == 0 and xv.lod_level:
                ov.lod_level = xv.lod_level
        if not hit:
            raise SkipInferShape

    return infer


def _infer_batch_norm_shape(op, block):
    xv, ov = _io_vars(op, block, "X", "Y")
    if ov.shape is None:
        ov.shape = tuple(xv.shape)
    if len(xv.shape) < 2:
        return
    c = xv.shape[1]
    if c < 0:
        return
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        outs = op.outputs.get(slot, [])
        if len(outs) == 1 and outs[0]:
            sv = block.find_var(outs[0])
            if sv is not None and sv.shape is None:
                sv.shape = (c,)


def _infer_maxout_shape(op, block):
    xv, ov = _io_vars(op, block, "X", "Out")
    if ov.shape is not None or len(xv.shape) != 4:
        raise SkipInferShape
    groups = op.attr("groups", None)
    if not groups:
        raise SkipInferShape
    n, c, h, w = xv.shape
    if c >= 0 and c % groups != 0:
        raise ValueError(f"maxout: channels {c} not divisible by "
                         f"groups {groups}")
    ov.shape = (n, c // groups if c >= 0 else -1, h, w)


def _infer_pad_shape(op, block):
    xv, ov = _io_vars(op, block, "X", "Out")
    if ov.shape is not None:
        return
    paddings = op.attr("paddings", None)
    if not paddings or len(paddings) != 2 * len(xv.shape):
        raise SkipInferShape
    ov.shape = tuple(
        -1 if d < 0 else d + paddings[2 * i] + paddings[2 * i + 1]
        for i, d in enumerate(xv.shape))


def _infer_bilinear_shape(op, block):
    xv, ov = _io_vars(op, block, "X", "Out")
    if ov.shape is not None or len(xv.shape) != 4:
        raise SkipInferShape
    oh, ow = op.attr("out_h", None), op.attr("out_w", None)
    if not oh or not ow:
        raise SkipInferShape
    ov.shape = (xv.shape[0], xv.shape[1], int(oh), int(ow))


@register_op("conv2d", inputs=("Input", "Filter"), outputs=("Output",),
             infer_shape=_make_conv_infer(2))
def _conv2d(ctx):
    """NCHW conv, filter (O, I/groups, H, W), groups supported
    (reference: operators/conv_op.cc)."""
    from paddle_tpu import amp

    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = _pair(ctx.attr("strides", (1, 1)))
    pads = _pair(ctx.attr("paddings", (0, 0)))
    dilations = _pair(ctx.attr("dilations", (1, 1)))
    groups = ctx.attr("groups", 1)
    out_dt = amp.out_dtype(x)
    x, w = amp.cast_operands(x, w)
    from paddle_tpu import pallas as pk

    if (groups == 1 and dilations == (1, 1) and pads[0] == pads[1]
            and strides[0] == strides[1] and pk.use_conv2d(
                x.shape[0], x.shape[2], x.shape[3], x.shape[1], w.shape[0],
                w.shape[2], w.shape[3], strides[0], pads[0])):
        from paddle_tpu.pallas.conv import conv2d_nhwc

        out = conv2d_nhwc(
            jnp.transpose(x, (0, 2, 3, 1)),
            jnp.transpose(w, (2, 3, 1, 0)).astype(x.dtype), pads[0],
            pk.interpret_mode())
        ctx.set_output("Output",
                       jnp.transpose(out, (0, 3, 1, 2)).astype(out_dt))
        return
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=_pref(),
    ).astype(out_dt)
    ctx.set_output("Output", out)


@register_op("conv3d", inputs=("Input", "Filter"), outputs=("Output",),
             infer_shape=_make_conv_infer(3))
def _conv3d(ctx):
    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = tuple(ctx.attr("strides", (1, 1, 1)))
    pads = tuple(ctx.attr("paddings", (0, 0, 0)))
    dilations = tuple(ctx.attr("dilations", (1, 1, 1)))
    groups = ctx.attr("groups", 1)
    out = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        preferred_element_type=_pref(),
    ).astype(x.dtype)
    ctx.set_output("Output", out)


@register_op("conv2d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             infer_shape=_make_conv_infer(2, transpose=True))
def _conv2d_transpose(ctx):
    """Gradient-of-conv as a forward op (reference:
    operators/conv_transpose_op.cc).  Filter layout (I, O, H, W)."""
    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = _pair(ctx.attr("strides", (1, 1)))
    pads = _pair(ctx.attr("paddings", (0, 0)))
    dilations = _pair(ctx.attr("dilations", (1, 1)))
    # paddle filter layout (Cin, Cout, H, W) is the OIHW layout of the
    # forward conv being transposed, which is exactly what
    # transpose_kernel=True expects (it swaps I/O and flips spatials);
    # declaring it IOHW only type-checked when Cin == Cout
    out = lax.conv_transpose(
        x,
        w,
        strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        transpose_kernel=True,
    ).astype(x.dtype)
    ctx.set_output("Output", out)


@register_op("pool2d", inputs=("X",), infer_shape=_make_pool_infer(2))
def _pool2d(ctx):
    x = unwrap(ctx.input("X"))
    ptype = ctx.attr("pooling_type", "max")
    ksize = _pair(ctx.attr("ksize", (2, 2)))
    strides = _pair(ctx.attr("strides", (1, 1)))
    pads = _pair(ctx.attr("paddings", (0, 0)))
    if ctx.attr("global_pooling", False):
        ksize = x.shape[2:4]
        strides = (1, 1)
        pads = (0, 0)
    # ceil_mode (reference: config_parser cnn_output_size with
    # caffe_mode=False, the v1 img_pool default): output extent uses
    # ceil, implemented as extra high-side padding; windows there are
    # clipped to the real image exactly like the reference loop bounds
    # (Matrix.cpp avgPoolForward hend=min(.., imgSize)), because the
    # extra cells are -inf for max and excluded from avg counts below
    extra = (0, 0)
    if ctx.attr("ceil_mode", False):
        from paddle_tpu.layers.nn import pool_extra_padding

        extra = (pool_extra_padding(x.shape[2], ksize[0], pads[0], strides[0]),
                 pool_extra_padding(x.shape[3], ksize[1], pads[1], strides[1]))
    window = (1, 1) + ksize
    strides4 = (1, 1) + strides
    padding = ((0, 0), (0, 0), (pads[0], pads[0] + extra[0]),
               (pads[1], pads[1] + extra[1]))
    # max/sum windows are separable: two 1-D passes do kh+kw work per
    # output instead of kh*kw (a 32x32 stride-1 pool drops from 1024 to
    # 64 ops/element — the XLA CPU backend at low opt levels does not
    # perform this rewrite itself).  Only worth it for LARGE windows:
    # for the common 2x2/3x3 pools the split doubles the backward's
    # select-and-scatter passes (measured +8% on the GoogLeNet step)
    # while saving almost nothing forward.
    separable = ksize[0] > 1 and ksize[1] > 1 and ksize[0] * ksize[1] >= 32

    def _sep(v, init, op):
        h = lax.reduce_window(v, init, op, (1, 1, ksize[0], 1),
                              (1, 1, strides[0], 1),
                              ((0, 0), (0, 0), padding[2], (0, 0)))
        return lax.reduce_window(h, init, op, (1, 1, 1, ksize[1]),
                                 (1, 1, 1, strides[1]),
                                 ((0, 0), (0, 0), (0, 0), padding[3]))

    if ptype == "max":
        init = -jnp.inf
        if separable:
            out = _sep(x, init, lax.max)
        else:
            out = lax.reduce_window(x, init, lax.max, window, strides4,
                                    padding)
    else:
        xf = x.astype(jnp.float32)
        summed = (_sep(xf, 0.0, lax.add) if separable else
                  lax.reduce_window(xf, 0.0, lax.add, window, strides4,
                                    padding))
        if ctx.attr("exclusive", False):
            ones = jnp.ones_like(x, dtype=jnp.float32)
            counts = (_sep(ones, 0.0, lax.add) if separable else
                      lax.reduce_window(ones, 0.0, lax.add, window,
                                        strides4, padding))
            out = (summed / counts).astype(x.dtype)
        else:
            out = (summed / (ksize[0] * ksize[1])).astype(x.dtype)
    ctx.set_output("Out", out)


@register_op("batch_norm",
             inputs=("X", "Scale", "Bias", "Mean", "Variance", "Length"),
             outputs=("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"),
             diff_inputs=("X", "Scale", "Bias"),
             infer_shape=_infer_batch_norm_shape)
def _batch_norm(ctx):
    """Training/inference BN over NCHW channel axis 1 (reference:
    operators/batch_norm_op.cc).  MeanOut/VarianceOut are the running
    statistics (written back to the same persistable vars, functionally)."""
    x = unwrap(ctx.input("X"))
    scale = unwrap(ctx.input("Scale"))
    bias = unwrap(ctx.input("Bias"))
    mean = unwrap(ctx.input("Mean"))
    var = unwrap(ctx.input("Variance"))
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    is_test = ctx.attr("is_test", False)
    layout = ctx.attr("data_layout", "NCHW")
    seq_mode = ctx.has_input("Length") and x.ndim == 3
    # padded sequence frames (B, T, C): channel is the LAST axis
    c_axis = (x.ndim - 1 if (seq_mode or layout != "NCHW") else 1)
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if is_test:
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, var
        new_mean, new_var = mean, var
    elif seq_mode:
        # statistics over the REAL frames only (the reference's LoD
        # rows carry no padding — gserver BatchNormBaseLayer sees
        # packed frames)
        _lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
        _valid = (jnp.arange(x.shape[1])[None, :] < _lens[:, None]
                  ).astype(jnp.float32)[:, :, None]           # (B, T, 1)
        n = jnp.maximum(jnp.sum(_valid), 1.0)
        xf = x.astype(jnp.float32) * _valid
        use_mean = jnp.sum(xf, axis=(0, 1)) / n
        use_var = (jnp.sum(jnp.square(xf), axis=(0, 1)) / n
                   - jnp.square(use_mean))
        saved_mean, saved_var = use_mean, use_var
        new_mean = momentum * mean + (1 - momentum) * use_mean
        new_var = momentum * var + (1 - momentum) * use_var
    else:
        # f32-accumulated statistics regardless of activation dtype (the
        # convert fuses into the reduction, so bf16 activations are read
        # once, not materialized in f32)
        use_mean = jnp.mean(x, axis=red_axes, dtype=jnp.float32)
        use_var = (jnp.mean(jnp.square(x.astype(jnp.float32)), axis=red_axes)
                   - jnp.square(use_mean))
        saved_mean, saved_var = use_mean, use_var
        new_mean = momentum * mean + (1 - momentum) * use_mean
        new_var = momentum * var + (1 - momentum) * use_var

    inv = lax.rsqrt(use_var + eps)
    _seq_valid = None
    if seq_mode:
        # preserve the zero-padding invariant downstream ops rely on
        _lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
        _seq_valid = (jnp.arange(x.shape[1])[None, :] < _lens[:, None]
                      )[:, :, None]
    if x.dtype == jnp.bfloat16:
        # normalize in bf16 (stats stay f32): halves the HBM traffic of
        # the normalize pass, measured +6% on the ResNet-50 train step.
        # Fold the per-channel affine in f32 first so the bf16 rounding
        # happens once, and the per-element work is one mul + one add.
        a = (scale.astype(jnp.float32) * inv)
        b = bias.astype(jnp.float32) - use_mean * a
        y = x * a.astype(x.dtype).reshape(bshape) \
            + b.astype(x.dtype).reshape(bshape)
        if _seq_valid is not None:
            y = y * _seq_valid.astype(y.dtype)
        ctx.set_output("Y", y)
    else:
        xf = x.astype(jnp.float32)
        y = (xf - use_mean.reshape(bshape)) * inv.reshape(bshape)
        y = y * scale.reshape(bshape) + bias.reshape(bshape)
        if _seq_valid is not None:
            y = y * _seq_valid
        ctx.set_output("Y", y.astype(x.dtype))
    ctx.set_output("MeanOut", new_mean)
    ctx.set_output("VarianceOut", new_var)
    ctx.set_output("SavedMean", saved_mean)
    ctx.set_output("SavedVariance", saved_var)


def _dropout_grad_lower(ctx):
    """d(out)/d(x) = mask (already scaled)."""
    gout = ctx.input("Out@GRAD")
    mask = ctx.values[ctx.op.attr("__fwd_outputs__")["Mask"][0]]
    gname = ctx.op.outputs["X@GRAD"][0]
    from paddle_tpu.lod import LoDArray

    g = unwrap(gout) * mask
    ctx.values[gname] = rewrap(gout, g)


@register_op("dropout", inputs=("X",), outputs=("Out", "Mask"),
             infer_shape=_infer_mirror_x("Out", "Mask"),
             grad_lower=_dropout_grad_lower)
def _dropout(ctx):
    x = ctx.input("X")
    xd = unwrap(x)
    p = ctx.attr("dropout_prob", 0.5)
    if ctx.attr("is_test", False):
        ctx.set_output("Out", x)
        ctx.set_output("Mask", jnp.ones_like(xd))
        return
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, xd.shape)
    # inverted dropout: scale at train time
    mask = keep.astype(xd.dtype) / jnp.asarray(1.0 - p, xd.dtype)
    ctx.set_output("Out", rewrap(x, xd * mask))
    ctx.set_output("Mask", mask)


@register_op("softmax", inputs=("X",), infer_shape=infer_same_shape)
def _softmax(ctx):
    unary_in = ctx.input("X")
    x = unwrap(unary_in)
    from paddle_tpu import pallas as pk

    if x.ndim == 2 and pk.use_softmax(x.shape[0], x.shape[1]):
        ctx.set_output("Out", rewrap(
            unary_in, pk.pallas_softmax(x, interpret=pk.interpret_mode())))
        return
    ctx.set_output("Out", rewrap(unary_in, jax.nn.softmax(x, axis=-1)))


@register_op("lrn", inputs=("X",), outputs=("Out", "MidOut"),
             infer_shape=_infer_mirror_x("Out", "MidOut"))
def _lrn(ctx):
    """Local response norm across channels (reference: operators/lrn_op.cc)."""
    x = unwrap(ctx.input("X"))
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(x.astype(jnp.float32))
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i : i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    ctx.set_output("MidOut", mid)
    ctx.set_output("Out", (x / jnp.power(mid, beta)).astype(x.dtype))


@register_op("maxout", inputs=("X",), infer_shape=_infer_maxout_shape)
def _maxout(ctx):
    x = unwrap(ctx.input("X"))
    groups = ctx.attr("groups")
    n, c, h, w = x.shape
    ctx.set_output("Out", jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2))


@register_op("pad", inputs=("X",), infer_shape=_infer_pad_shape)
def _pad(ctx):
    x = unwrap(ctx.input("X"))
    paddings = ctx.attr("paddings")
    val = ctx.attr("pad_value", 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output("Out", jnp.pad(x, cfg, constant_values=val))


@register_op("crop", inputs=("X", "Y"))
def _crop(ctx):
    """Crop X to a target shape from ``axis`` onward (reference:
    operators/crop_op.cc + CropLayer axis semantics: dims before
    ``axis`` are kept whole; offsets default to 0)."""
    x = unwrap(ctx.input("X"))
    axis = ctx.attr("axis", 0)
    offsets = list(ctx.attr("offsets") or [])
    if ctx.has_input("Y"):
        tgt = list(unwrap(ctx.input("Y")).shape)
        if len(tgt) == x.ndim:
            shape = tgt[axis:]
        else:
            shape = tgt
    else:
        shape = list(ctx.attr("shape"))
        if len(shape) == x.ndim:
            axis, shape = 0, shape
    if len(offsets) == x.ndim:
        axis = 0
    if not offsets:
        offsets = [0] * len(shape)
    if len(offsets) != len(shape):
        raise ValueError(
            f"crop: offsets rank {len(offsets)} != target rank "
            f"{len(shape)} (axis={axis}); silent truncation would crop "
            "the wrong dimensions")
    sl = [slice(None)] * axis + [
        slice(o, o + s) for o, s in zip(offsets, shape)]
    ctx.set_output("Out", x[tuple(sl)])


@register_op("conv3d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             infer_shape=_make_conv_infer(3, transpose=True))
def _conv3d_transpose(ctx):
    """3-D transposed conv (reference: operators/conv_transpose_op.cc
    3-D registration).  Filter layout (I, O, D, H, W)."""
    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = tuple(ctx.attr("strides", (1, 1, 1)))
    pads = tuple(ctx.attr("paddings", (0, 0, 0)))
    dilations = tuple(ctx.attr("dilations", (1, 1, 1)))
    # (Cin, Cout, D, H, W) = the forward conv's OIDHW; see the 2-D twin
    out = lax.conv_transpose(
        x, w, strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        transpose_kernel=True,
    ).astype(x.dtype)
    ctx.set_output("Output", out)


@register_op("bilinear_interp", inputs=("X",),
             infer_shape=_infer_bilinear_shape)
def _bilinear_interp(ctx):
    """Bilinear resize over NCHW spatial dims (reference:
    operators/bilinear_interp_op.cc / BilinearInterpLayer)."""
    x = unwrap(ctx.input("X"))
    oh = ctx.attr("out_h")
    ow = ctx.attr("out_w")
    n, c = x.shape[0], x.shape[1]
    out = jax.image.resize(x.astype(jnp.float32), (n, c, oh, ow),
                           method="bilinear").astype(x.dtype)
    ctx.set_output("Out", out)
