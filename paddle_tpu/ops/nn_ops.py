"""NN layer ops: conv, pool, norm, dropout, softmax.

Reference: paddle/operators/{conv,pool,batch_norm,dropout,softmax,lrn,
conv_transpose,maxout}_op.cc.  All NCHW (the reference layout); XLA's
layout assignment maps them onto the MXU/VPU natively, so no cudnn-style
per-op algorithm choice exists here — the whole block fuses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.registry import register_op


def _pref():
    from paddle_tpu import amp

    return amp.preferred_acc()


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


@register_op("conv2d", inputs=("Input", "Filter"), outputs=("Output",))
def _conv2d(ctx):
    """NCHW conv, filter (O, I/groups, H, W), groups supported
    (reference: operators/conv_op.cc)."""
    from paddle_tpu import amp

    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = _pair(ctx.attr("strides", (1, 1)))
    pads = _pair(ctx.attr("paddings", (0, 0)))
    dilations = _pair(ctx.attr("dilations", (1, 1)))
    groups = ctx.attr("groups", 1)
    out_dt = amp.out_dtype(x)
    x, w = amp.cast_operands(x, w)
    from paddle_tpu import pallas as pk

    if (groups == 1 and dilations == (1, 1) and pads[0] == pads[1]
            and strides[0] == strides[1] and pk.use_conv2d(
                x.shape[0], x.shape[2], x.shape[3], x.shape[1], w.shape[0],
                w.shape[2], w.shape[3], strides[0], pads[0])):
        from paddle_tpu.pallas.conv import conv2d_nhwc

        out = conv2d_nhwc(
            jnp.transpose(x, (0, 2, 3, 1)),
            jnp.transpose(w, (2, 3, 1, 0)).astype(x.dtype), pads[0],
            pk.interpret_mode())
        ctx.set_output("Output",
                       jnp.transpose(out, (0, 3, 1, 2)).astype(out_dt))
        return
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=_pref(),
    ).astype(out_dt)
    ctx.set_output("Output", out)


@register_op("conv3d", inputs=("Input", "Filter"), outputs=("Output",))
def _conv3d(ctx):
    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = tuple(ctx.attr("strides", (1, 1, 1)))
    pads = tuple(ctx.attr("paddings", (0, 0, 0)))
    dilations = tuple(ctx.attr("dilations", (1, 1, 1)))
    groups = ctx.attr("groups", 1)
    out = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        preferred_element_type=_pref(),
    ).astype(x.dtype)
    ctx.set_output("Output", out)


@register_op("conv2d_transpose", inputs=("Input", "Filter"), outputs=("Output",))
def _conv2d_transpose(ctx):
    """Gradient-of-conv as a forward op (reference:
    operators/conv_transpose_op.cc).  Filter layout (I, O, H, W)."""
    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = _pair(ctx.attr("strides", (1, 1)))
    pads = _pair(ctx.attr("paddings", (0, 0)))
    dilations = _pair(ctx.attr("dilations", (1, 1)))
    # paddle filter layout (Cin, Cout, H, W) is the OIHW layout of the
    # forward conv being transposed, which is exactly what
    # transpose_kernel=True expects (it swaps I/O and flips spatials);
    # declaring it IOHW only type-checked when Cin == Cout
    out = lax.conv_transpose(
        x,
        w,
        strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        transpose_kernel=True,
    ).astype(x.dtype)
    ctx.set_output("Output", out)


@register_op("pool2d", inputs=("X",))
def _pool2d(ctx):
    x = unwrap(ctx.input("X"))
    ptype = ctx.attr("pooling_type", "max")
    ksize = _pair(ctx.attr("ksize", (2, 2)))
    strides = _pair(ctx.attr("strides", (1, 1)))
    pads = _pair(ctx.attr("paddings", (0, 0)))
    if ctx.attr("global_pooling", False):
        ksize = x.shape[2:4]
        strides = (1, 1)
        pads = (0, 0)
    # ceil_mode (reference: config_parser cnn_output_size with
    # caffe_mode=False, the v1 img_pool default): output extent uses
    # ceil, implemented as extra high-side padding; windows there are
    # clipped to the real image exactly like the reference loop bounds
    # (Matrix.cpp avgPoolForward hend=min(.., imgSize)), because the
    # extra cells are -inf for max and excluded from avg counts below
    extra = (0, 0)
    if ctx.attr("ceil_mode", False):
        from paddle_tpu.layers.nn import pool_extra_padding

        extra = (pool_extra_padding(x.shape[2], ksize[0], pads[0], strides[0]),
                 pool_extra_padding(x.shape[3], ksize[1], pads[1], strides[1]))
    window = (1, 1) + ksize
    strides4 = (1, 1) + strides
    padding = ((0, 0), (0, 0), (pads[0], pads[0] + extra[0]),
               (pads[1], pads[1] + extra[1]))
    # max/sum windows are separable: two 1-D passes do kh+kw work per
    # output instead of kh*kw (a 32x32 stride-1 pool drops from 1024 to
    # 64 ops/element — the XLA CPU backend at low opt levels does not
    # perform this rewrite itself).  Only worth it for LARGE windows:
    # for the common 2x2/3x3 pools the split doubles the backward's
    # select-and-scatter passes (measured +8% on the GoogLeNet step)
    # while saving almost nothing forward.
    separable = ksize[0] > 1 and ksize[1] > 1 and ksize[0] * ksize[1] >= 32

    def _sep(v, init, op):
        h = lax.reduce_window(v, init, op, (1, 1, ksize[0], 1),
                              (1, 1, strides[0], 1),
                              ((0, 0), (0, 0), padding[2], (0, 0)))
        return lax.reduce_window(h, init, op, (1, 1, 1, ksize[1]),
                                 (1, 1, 1, strides[1]),
                                 ((0, 0), (0, 0), (0, 0), padding[3]))

    if ptype == "max":
        init = -jnp.inf
        if separable:
            out = _sep(x, init, lax.max)
        else:
            out = lax.reduce_window(x, init, lax.max, window, strides4,
                                    padding)
    else:
        xf = x.astype(jnp.float32)
        summed = (_sep(xf, 0.0, lax.add) if separable else
                  lax.reduce_window(xf, 0.0, lax.add, window, strides4,
                                    padding))
        if ctx.attr("exclusive", False):
            ones = jnp.ones_like(x, dtype=jnp.float32)
            counts = (_sep(ones, 0.0, lax.add) if separable else
                      lax.reduce_window(ones, 0.0, lax.add, window,
                                        strides4, padding))
            out = (summed / counts).astype(x.dtype)
        else:
            out = (summed / (ksize[0] * ksize[1])).astype(x.dtype)
    ctx.set_output("Out", out)


@register_op("batch_norm",
             inputs=("X", "Scale", "Bias", "Mean", "Variance", "Length"),
             outputs=("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"),
             diff_inputs=("X", "Scale", "Bias"))
def _batch_norm(ctx):
    """Training/inference BN over NCHW channel axis 1 (reference:
    operators/batch_norm_op.cc).  MeanOut/VarianceOut are the running
    statistics (written back to the same persistable vars, functionally)."""
    x = unwrap(ctx.input("X"))
    scale = unwrap(ctx.input("Scale"))
    bias = unwrap(ctx.input("Bias"))
    mean = unwrap(ctx.input("Mean"))
    var = unwrap(ctx.input("Variance"))
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    is_test = ctx.attr("is_test", False)
    layout = ctx.attr("data_layout", "NCHW")
    seq_mode = ctx.has_input("Length") and x.ndim == 3
    # padded sequence frames (B, T, C): channel is the LAST axis
    c_axis = (x.ndim - 1 if (seq_mode or layout != "NCHW") else 1)
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if is_test:
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, var
        new_mean, new_var = mean, var
    elif seq_mode:
        # statistics over the REAL frames only (the reference's LoD
        # rows carry no padding — gserver BatchNormBaseLayer sees
        # packed frames)
        _lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
        _valid = (jnp.arange(x.shape[1])[None, :] < _lens[:, None]
                  ).astype(jnp.float32)[:, :, None]           # (B, T, 1)
        n = jnp.maximum(jnp.sum(_valid), 1.0)
        xf = x.astype(jnp.float32) * _valid
        use_mean = jnp.sum(xf, axis=(0, 1)) / n
        use_var = (jnp.sum(jnp.square(xf), axis=(0, 1)) / n
                   - jnp.square(use_mean))
        saved_mean, saved_var = use_mean, use_var
        new_mean = momentum * mean + (1 - momentum) * use_mean
        new_var = momentum * var + (1 - momentum) * use_var
    else:
        # f32-accumulated statistics regardless of activation dtype (the
        # convert fuses into the reduction, so bf16 activations are read
        # once, not materialized in f32)
        use_mean = jnp.mean(x, axis=red_axes, dtype=jnp.float32)
        use_var = (jnp.mean(jnp.square(x.astype(jnp.float32)), axis=red_axes)
                   - jnp.square(use_mean))
        saved_mean, saved_var = use_mean, use_var
        new_mean = momentum * mean + (1 - momentum) * use_mean
        new_var = momentum * var + (1 - momentum) * use_var

    inv = lax.rsqrt(use_var + eps)
    _seq_valid = None
    if seq_mode:
        # preserve the zero-padding invariant downstream ops rely on
        _lens = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
        _seq_valid = (jnp.arange(x.shape[1])[None, :] < _lens[:, None]
                      )[:, :, None]
    if x.dtype == jnp.bfloat16:
        # normalize in bf16 (stats stay f32): halves the HBM traffic of
        # the normalize pass, measured +6% on the ResNet-50 train step.
        # Fold the per-channel affine in f32 first so the bf16 rounding
        # happens once, and the per-element work is one mul + one add.
        a = (scale.astype(jnp.float32) * inv)
        b = bias.astype(jnp.float32) - use_mean * a
        y = x * a.astype(x.dtype).reshape(bshape) \
            + b.astype(x.dtype).reshape(bshape)
        if _seq_valid is not None:
            y = y * _seq_valid.astype(y.dtype)
        ctx.set_output("Y", y)
    else:
        xf = x.astype(jnp.float32)
        y = (xf - use_mean.reshape(bshape)) * inv.reshape(bshape)
        y = y * scale.reshape(bshape) + bias.reshape(bshape)
        if _seq_valid is not None:
            y = y * _seq_valid
        ctx.set_output("Y", y.astype(x.dtype))
    ctx.set_output("MeanOut", new_mean)
    ctx.set_output("VarianceOut", new_var)
    ctx.set_output("SavedMean", saved_mean)
    ctx.set_output("SavedVariance", saved_var)


def _dropout_grad_lower(ctx):
    """d(out)/d(x) = mask (already scaled)."""
    gout = ctx.input("Out@GRAD")
    mask = ctx.values[ctx.op.attr("__fwd_outputs__")["Mask"][0]]
    gname = ctx.op.outputs["X@GRAD"][0]
    from paddle_tpu.lod import LoDArray

    g = unwrap(gout) * mask
    ctx.values[gname] = rewrap(gout, g)


@register_op("dropout", inputs=("X",), outputs=("Out", "Mask"),
             grad_lower=_dropout_grad_lower)
def _dropout(ctx):
    x = ctx.input("X")
    xd = unwrap(x)
    p = ctx.attr("dropout_prob", 0.5)
    if ctx.attr("is_test", False):
        ctx.set_output("Out", x)
        ctx.set_output("Mask", jnp.ones_like(xd))
        return
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, xd.shape)
    # inverted dropout: scale at train time
    mask = keep.astype(xd.dtype) / jnp.asarray(1.0 - p, xd.dtype)
    ctx.set_output("Out", rewrap(x, xd * mask))
    ctx.set_output("Mask", mask)


@register_op("softmax", inputs=("X",))
def _softmax(ctx):
    unary_in = ctx.input("X")
    x = unwrap(unary_in)
    from paddle_tpu import pallas as pk

    if x.ndim == 2 and pk.use_softmax(x.shape[0], x.shape[1]):
        ctx.set_output("Out", rewrap(
            unary_in, pk.pallas_softmax(x, interpret=pk.interpret_mode())))
        return
    ctx.set_output("Out", rewrap(unary_in, jax.nn.softmax(x, axis=-1)))


@register_op("lrn", inputs=("X",), outputs=("Out", "MidOut"))
def _lrn(ctx):
    """Local response norm across channels (reference: operators/lrn_op.cc)."""
    x = unwrap(ctx.input("X"))
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(x.astype(jnp.float32))
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i : i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    ctx.set_output("MidOut", mid)
    ctx.set_output("Out", (x / jnp.power(mid, beta)).astype(x.dtype))


@register_op("maxout", inputs=("X",))
def _maxout(ctx):
    x = unwrap(ctx.input("X"))
    groups = ctx.attr("groups")
    n, c, h, w = x.shape
    ctx.set_output("Out", jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2))


@register_op("pad", inputs=("X",))
def _pad(ctx):
    x = unwrap(ctx.input("X"))
    paddings = ctx.attr("paddings")
    val = ctx.attr("pad_value", 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output("Out", jnp.pad(x, cfg, constant_values=val))


@register_op("crop", inputs=("X", "Y"))
def _crop(ctx):
    """Crop X to a target shape from ``axis`` onward (reference:
    operators/crop_op.cc + CropLayer axis semantics: dims before
    ``axis`` are kept whole; offsets default to 0)."""
    x = unwrap(ctx.input("X"))
    axis = ctx.attr("axis", 0)
    offsets = list(ctx.attr("offsets") or [])
    if ctx.has_input("Y"):
        tgt = list(unwrap(ctx.input("Y")).shape)
        if len(tgt) == x.ndim:
            shape = tgt[axis:]
        else:
            shape = tgt
    else:
        shape = list(ctx.attr("shape"))
        if len(shape) == x.ndim:
            axis, shape = 0, shape
    if len(offsets) == x.ndim:
        axis = 0
    if not offsets:
        offsets = [0] * len(shape)
    if len(offsets) != len(shape):
        raise ValueError(
            f"crop: offsets rank {len(offsets)} != target rank "
            f"{len(shape)} (axis={axis}); silent truncation would crop "
            "the wrong dimensions")
    sl = [slice(None)] * axis + [
        slice(o, o + s) for o, s in zip(offsets, shape)]
    ctx.set_output("Out", x[tuple(sl)])


@register_op("conv3d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",))
def _conv3d_transpose(ctx):
    """3-D transposed conv (reference: operators/conv_transpose_op.cc
    3-D registration).  Filter layout (I, O, D, H, W)."""
    x = unwrap(ctx.input("Input"))
    w = unwrap(ctx.input("Filter"))
    strides = tuple(ctx.attr("strides", (1, 1, 1)))
    pads = tuple(ctx.attr("paddings", (0, 0, 0)))
    dilations = tuple(ctx.attr("dilations", (1, 1, 1)))
    # (Cin, Cout, D, H, W) = the forward conv's OIDHW; see the 2-D twin
    out = lax.conv_transpose(
        x, w, strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        transpose_kernel=True,
    ).astype(x.dtype)
    ctx.set_output("Output", out)


@register_op("bilinear_interp", inputs=("X",))
def _bilinear_interp(ctx):
    """Bilinear resize over NCHW spatial dims (reference:
    operators/bilinear_interp_op.cc / BilinearInterpLayer)."""
    x = unwrap(ctx.input("X"))
    oh = ctx.attr("out_h")
    ow = ctx.attr("out_w")
    n, c = x.shape[0], x.shape[1]
    out = jax.image.resize(x.astype(jnp.float32), (n, c, oh, ow),
                           method="bilinear").astype(x.dtype)
    ctx.set_output("Out", out)
