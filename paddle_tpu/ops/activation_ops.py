"""Activation ops (reference: operators/activation_op.cc registers the
sigmoid/relu/tanh/... family; gradients here come from jax.vjp of the
forward lowering instead of hand-written ActivationGradKernels)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.common import unary
from paddle_tpu.registry import infer_same_shape, register_op


def _reg(name, fn):
    @register_op(name, inputs=("X",), infer_shape=infer_same_shape)
    def _act(ctx, fn=fn):
        unary(ctx, lambda x: _apply(ctx, fn, x))


def _apply(ctx, fn, x):
    try:
        return fn(x, ctx)
    except TypeError:
        return fn(x)


_SIMPLE = {
    "sigmoid": jax.nn.sigmoid,
    "logsigmoid": jax.nn.log_sigmoid,
    "exp": jnp.exp,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "sqrt": jnp.sqrt,
    "abs": jnp.abs,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "round": jnp.round,
    "reciprocal": lambda x: 1.0 / x,
    "log": jnp.log,
    "square": jnp.square,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "tanh_shrink": lambda x: x - jnp.tanh(x),
}

for _n, _f in _SIMPLE.items():
    _reg(_n, _f)

_WITH_ATTRS = {
    "leaky_relu": lambda x, ctx: jnp.where(x >= 0, x, x * ctx.attr("alpha", 0.02)),
    "elu": lambda x, ctx: jnp.where(x >= 0, x, ctx.attr("alpha", 1.0) * (jnp.exp(x) - 1)),
    "relu6": lambda x, ctx: jnp.clip(x, 0.0, ctx.attr("threshold", 6.0)),
    "pow": lambda x, ctx: jnp.power(x, ctx.attr("factor", 1.0)),
    "stanh": lambda x, ctx: ctx.attr("scale_b", 1.7159) * jnp.tanh(ctx.attr("scale_a", 2.0 / 3.0) * x),
    "brelu": lambda x, ctx: jnp.clip(x, ctx.attr("t_min", 0.0), ctx.attr("t_max", 24.0)),
    "soft_relu": lambda x, ctx: jnp.log1p(jnp.exp(jnp.clip(x, -ctx.attr("threshold", 40.0), ctx.attr("threshold", 40.0)))),
    "softshrink": lambda x, ctx: jnp.where(
        x > ctx.attr("lambda", 0.5), x - ctx.attr("lambda", 0.5),
        jnp.where(x < -ctx.attr("lambda", 0.5), x + ctx.attr("lambda", 0.5), 0.0)
    ),
    "hard_shrink": lambda x, ctx: jnp.where(jnp.abs(x) > ctx.attr("threshold", 0.5), x, 0.0),
    "thresholded_relu": lambda x, ctx: jnp.where(x > ctx.attr("threshold", 1.0), x, 0.0),
    "hard_sigmoid": lambda x, ctx: jnp.clip(
        ctx.attr("slope", 0.2) * x + ctx.attr("offset", 0.5), 0.0, 1.0
    ),
    "swish": lambda x, ctx: x * jax.nn.sigmoid(ctx.attr("beta", 1.0) * x),
}

for _n, _f in _WITH_ATTRS.items():
    _reg(_n, _f)


@register_op("prelu", inputs=("X", "Alpha"), infer_shape=infer_same_shape)
def _prelu(ctx):
    from paddle_tpu.lod import rewrap, unwrap

    x = ctx.input("X")
    alpha = unwrap(ctx.input("Alpha"))
    xd = unwrap(x)
    ctx.set_output("Out", rewrap(x, jnp.where(xd >= 0, xd, alpha * xd)))
