"""Pipelined transformer-stack op: pp-axis GPipe schedule as one op.

Reference capability analog: ParallelNeuralNetwork's per-layer device
placement (gserver/gradientmachines/ParallelNeuralNetwork.h:34,61-63)
— re-designed TPU-first: the L identical blocks' parameters are
stacked (L, ...) and sharded over the mesh's ``pp`` axis; the lowering
runs the GPipe microbatch schedule (parallel/pipeline.py) inside
``shard_map``, composing with dp (batch) and sp (ring attention) axes
of the same mesh.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.registry import register_op

_PARAM_SLOTS = ("QKVW", "ProjW", "FF1W", "FF1B", "FF2W", "FF2B",
                "LN1S", "LN1B", "LN2S", "LN2B")


def _ln(h, s, b, eps=1e-5):
    hf = h.astype(jnp.float32)
    m = hf.mean(-1, keepdims=True)
    v = ((hf - m) ** 2).mean(-1, keepdims=True)
    return ((hf - m) / jnp.sqrt(v + eps) * s + b).astype(h.dtype)


def _make_block_fn(num_heads: int, causal: bool, sp_axis):
    from paddle_tpu.parallel.ring_attention import (
        local_attention, ring_attention)

    def block(p, h):
        qkvw, projw, ff1w, ff1b, ff2w, ff2b, ln1s, ln1b, ln2s, ln2b = p
        Bm, S, d = h.shape
        hd = d // num_heads
        hn = _ln(h, ln1s, ln1b)
        qkv = hn @ qkvw  # (Bm, S, 3d)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(Bm, S, num_heads, hd).transpose(0, 2, 1, 3)
                   for t in (q, k, v))
        if sp_axis is not None:
            att = ring_attention(q, k, v, axis_name=sp_axis, causal=causal)
        else:
            att = local_attention(q, k, v, causal=causal)
        att = att.transpose(0, 2, 1, 3).reshape(Bm, S, d) @ projw
        h = h + att
        hn2 = _ln(h, ln2s, ln2b)
        f = jnp.maximum(hn2 @ ff1w + ff1b[None, None], 0.0) @ ff2w
        return h + f + ff2b[None, None]

    return block


@register_op("transformer_pipeline_blocks",
             inputs=("X",) + _PARAM_SLOTS, outputs=("Out",))
def _transformer_pipeline_blocks(ctx):
    from paddle_tpu.parallel import strategy as strat
    from paddle_tpu.parallel.pipeline import gpipe

    x = unwrap(ctx.input("X"))
    params = tuple(unwrap(ctx.input(s)) for s in _PARAM_SLOTS)
    num_heads = ctx.attr("num_heads")
    causal = ctx.attr("causal", True)
    n_microbatch = ctx.attr("n_microbatch", 1)

    s = strat.current_strategy()
    pp = getattr(s, "pp_axis", None) if s is not None else None
    sp = getattr(s, "sp_axis", None) if s is not None else None
    mesh = s.mesh if s is not None else None
    block = _make_block_fn(num_heads, causal, sp if pp is not None else None)
    if pp is None:
        # unsharded / no pipeline axis: run the same stacked block scan
        out = gpipe(block, params, x, mesh=None, pp_axis=None,
                    n_microbatch=n_microbatch)
    else:
        out = gpipe(block, params, x, mesh=mesh, pp_axis=pp,
                    n_microbatch=n_microbatch,
                    batch_axis=getattr(s, "dp_axis", None), sp_axis=sp)
    ctx.set_output("Out", rewrap(ctx.input("X"), out))
