"""IO and misc structural ops: feed/fetch, save/load, fill, cond.

Reference: operators/feed_op.cc, fetch_op.cc, save_op.cc, load_op.cc
(tensor serialization with a version header), fill_op.cc, cond_op.cc.

TPU design: feed/fetch are pure plumbing — the executor binds feeds and
fetches around the compiled block, so in-graph they lower to identity.
``save`` uses an ordered io_callback (the XLA-sanctioned side-effect
escape hatch) writing the same single-tensor file format io.py uses;
``load`` reads at trace time and embeds the value as a device constant,
which is exactly the semantics of running a load op once before the
step loop.  The legacy ``cond`` op (scatter subset rows to two
sub-nets, run, merge) becomes: run both sub-blocks dense over the full
batch, then a row-wise where — branch-divergence-free, the way SIMD
hardware wants it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import register_op


@register_op("feed", inputs=("X",), stop_gradient=True)
def _feed(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("fetch", inputs=("X",), stop_gradient=True)
def _fetch(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("fill", inputs=(), stop_gradient=True)
def _fill(ctx):
    shape = tuple(int(s) for s in ctx.attr("shape", []))
    dtype = jnp.dtype(ctx.attr("dtype", "float32"))
    raw = ctx.attr("data", None)
    if raw is not None:
        ctx.set_output("Out", jnp.asarray(raw, dtype).reshape(shape))
    else:
        ctx.set_output("Out", jnp.full(shape, ctx.attr("value", 0.0), dtype))


@register_op("save", inputs=("X",), outputs=(), stop_gradient=True)
def _save(ctx):
    from paddle_tpu.io import serialize_tensor_bytes

    path = ctx.attr("file_path")
    overwrite = bool(ctx.attr("overwrite", True))

    def host_write(arr):
        import os

        if not overwrite and os.path.exists(path):
            raise IOError(f"save op: {path} exists and overwrite=False")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(serialize_tensor_bytes(arr))

    io_callback(host_write, None, unwrap(ctx.input("X")), ordered=True)


@register_op("load", inputs=(), stop_gradient=True)
def _load(ctx):
    from paddle_tpu.io import deserialize_tensor_bytes

    path = ctx.attr("file_path")
    with open(path, "rb") as f:
        arr = deserialize_tensor_bytes(f.read())
    ctx.set_output("Out", jnp.asarray(arr))


@register_op("cond", inputs=("Cond", "Xs"), outputs=("Outs", "IndexTensors"))
def _cond(ctx):
    """Legacy two-branch cond (reference: operators/cond_op.cc): rows
    where Cond is true flow through the true sub-block, the rest through
    the false sub-block; outputs merge row-wise."""
    from paddle_tpu.ops.control_flow_ops import _run_sub_block

    mask = unwrap(ctx.input("Cond")).astype(bool).reshape(-1)
    true_block = ctx.attr("true_block")
    false_block = ctx.attr("false_block")
    out_names = [n for n in ctx.op.output("Outs") if n]
    outer = ctx.values

    def run(block):
        values = dict(outer)
        _run_sub_block(block, values, ctx.executor_ctx)
        return [values[n] for n in out_names]

    t_outs, f_outs = run(true_block), run(false_block)
    for n, t, f in zip(out_names, t_outs, f_outs):
        t, f = unwrap(t), unwrap(f)
        m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
        outer[n] = jnp.where(m, t, f)


@register_op("print", inputs=("X",), stop_gradient=True)
def _print(ctx):
    """Host-side value printing mid-program (reference: the v1
    PrintLayer; fluid later added a Print op) via ordered io_callback;
    lowers to identity on the value path."""
    x = unwrap(ctx.input("X"))
    message = ctx.attr("message", "")

    def host_print(arr):
        import numpy as np

        print(f"[print {message}]", np.asarray(arr), flush=True)
        return np.int32(0)

    io_callback(host_print, jnp.zeros((), jnp.int32), x, ordered=True)
    ctx.set_output("Out", ctx.input("X"))
