"""IO and misc structural ops: feed/fetch, save/load, fill, cond.

Reference: operators/feed_op.cc, fetch_op.cc, save_op.cc, load_op.cc
(tensor serialization with a version header), fill_op.cc, cond_op.cc.

TPU design: feed/fetch are pure plumbing — the executor binds feeds and
fetches around the compiled block, so in-graph they lower to identity.
``save`` uses an ordered io_callback (the XLA-sanctioned side-effect
escape hatch) writing the same single-tensor file format io.py uses;
``load`` reads at trace time and embeds the value as a device constant,
which is exactly the semantics of running a load op once before the
step loop.  The legacy ``cond`` op (scatter subset rows to two
sub-nets, run, merge) becomes: run both sub-blocks dense over the full
batch, then a row-wise where — branch-divergence-free, the way SIMD
hardware wants it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import SkipInferShape, register_op


@register_op("feed", inputs=("X",), stop_gradient=True)
def _feed(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("fetch", inputs=("X",), stop_gradient=True)
def _fetch(ctx):
    ctx.set_output("Out", ctx.input("X"))


def _infer_fill_shape(op, block):
    outs = op.outputs.get("Out", [])
    if len(outs) != 1 or not outs[0]:
        raise SkipInferShape
    ov = block.find_var(outs[0])
    shape = op.attr("shape", None)
    if ov is None or not shape:
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(int(s) for s in shape)


@register_op("fill", inputs=(), stop_gradient=True,
             infer_shape=_infer_fill_shape)
def _fill(ctx):
    shape = tuple(int(s) for s in ctx.attr("shape", []))
    dtype = jnp.dtype(ctx.attr("dtype", "float32"))
    raw = ctx.attr("data", None)
    if raw is not None:
        ctx.set_output("Out", jnp.asarray(raw, dtype).reshape(shape))
    else:
        ctx.set_output("Out", jnp.full(shape, ctx.attr("value", 0.0), dtype))


@register_op("save", inputs=("X",), outputs=(), stop_gradient=True)
def _save(ctx):
    from paddle_tpu.io import serialize_tensor_bytes

    path = ctx.attr("file_path")
    overwrite = bool(ctx.attr("overwrite", True))

    def host_write(arr):
        import os

        if not overwrite and os.path.exists(path):
            raise IOError(f"save op: {path} exists and overwrite=False")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(serialize_tensor_bytes(arr))

    io_callback(host_write, None, unwrap(ctx.input("X")), ordered=True)


@register_op("load", inputs=(), stop_gradient=True)
def _load(ctx):
    from paddle_tpu.io import deserialize_tensor_bytes

    path = ctx.attr("file_path")
    with open(path, "rb") as f:
        arr = deserialize_tensor_bytes(f.read())
    ctx.set_output("Out", jnp.asarray(arr))


@register_op("cond", inputs=("Cond", "Xs"), outputs=("Outs", "IndexTensors"))
def _cond(ctx):
    """Legacy two-branch cond (reference: operators/cond_op.cc): rows
    where Cond is true flow through the true sub-block, the rest through
    the false sub-block; outputs merge row-wise."""
    from paddle_tpu.ops.control_flow_ops import _run_sub_block

    mask = unwrap(ctx.input("Cond")).astype(bool).reshape(-1)
    true_block = ctx.attr("true_block")
    false_block = ctx.attr("false_block")
    out_names = [n for n in ctx.op.output("Outs") if n]
    outer = ctx.values

    def run(block):
        values = dict(outer)
        _run_sub_block(block, values, ctx.executor_ctx)
        return [values[n] for n in out_names]

    t_outs, f_outs = run(true_block), run(false_block)
    for n, t, f in zip(out_names, t_outs, f_outs):
        t, f = unwrap(t), unwrap(f)
        m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
        outer[n] = jnp.where(m, t, f)


@register_op("print", inputs=("X",), stop_gradient=True)
def _print(ctx):
    """Host-side value printing mid-program (reference: the v1
    PrintLayer; fluid later added a Print op) via ordered io_callback;
    lowers to identity on the value path."""
    x = unwrap(ctx.input("X"))
    message = ctx.attr("message", "")

    def host_print(arr):
        import numpy as np

        print(f"[print {message}]", np.asarray(arr), flush=True)
        return np.int32(0)

    io_callback(host_print, jnp.zeros((), jnp.int32), x, ordered=True)
    ctx.set_output("Out", ctx.input("X"))


def _grad_printer_grad_lower(ctx):
    """Print the incoming gradient host-side, pass it through unchanged
    (reference: GradientPrinter in gserver/evaluators/Evaluator.cpp —
    evaluated over the *grad* argument of its input layer)."""
    import numpy as np

    gout = ctx.input("Out@GRAD")
    message = ctx.op.attr("__fwd_attrs__", {}).get("message", "")

    def host_print(arr):
        print(f"[grad {message}]", np.asarray(arr), flush=True)
        return np.int32(0)

    io_callback(host_print, jnp.zeros((), jnp.int32), unwrap(gout),
                ordered=True)
    ctx.values[ctx.op.outputs["X@GRAD"][0]] = gout


@register_op("grad_printer", inputs=("X",),
             grad_lower=_grad_printer_grad_lower)
def _grad_printer(ctx):
    """Identity on the value path; prints its *gradient* during the
    backward pass (reference: gradient_printer_evaluator,
    gserver/evaluators/Evaluator.cpp:1120 ValuePrinter over grads)."""
    ctx.set_output("Out", ctx.input("X"))


# (scope_id, realpath) pairs whose result_file was already truncated
# this evaluation — see seq_text_printer
_SEQTEXT_TRUNCATED = set()


@register_op("seq_text_printer", inputs=("X", "Id"), stop_gradient=True)
def _seq_text_printer(ctx):
    """Write id sequences as dictionary-translated text lines to
    result_file (reference: seqtext_printer_evaluator,
    gserver/evaluators/Evaluator.cpp SequenceTextPrinter).  Each line is
    ``id \\t tokens...`` — the Id input when given, else the sequence
    index (reference evalImp: ``os_ << (hasId ? sampleIds[i] : i)``)."""
    from paddle_tpu.lod import LoDArray

    x = ctx.input("X")
    sample_id = ctx.input("Id") if ctx.op.inputs.get("Id") else None
    result_file = ctx.attr("result_file")
    dict_file = ctx.attr("dict_file", None)
    delimited = ctx.attr("delimited", True)

    words = None
    if dict_file:
        with open(dict_file) as f:
            words = [line.rstrip("\n") for line in f]
    sep = " " if (delimited is None or delimited) else ""

    def fmt(ids):
        toks = [(words[i] if words and 0 <= i < len(words) else str(i))
                for i in ids]
        return sep.join(toks)

    # reference SequenceTextPrinter truncates once per evaluation
    # (init opens the ofstream); anchor "evaluation" to the executor
    # Scope ACTIVE AT WRITE TIME (not trace time — the shape-keyed jit
    # cache can replay one lowering under many scopes), held by weakref
    # so a recycled id() of a collected Scope can never collide
    import os as _os

    real_path = _os.path.realpath(result_file)

    def host_write(data, lengths, ids_arr):
        import weakref

        import numpy as np

        import paddle_tpu.executor as _executor_mod

        scope = (_executor_mod._scope_stack[-1]
                 if _executor_mod._scope_stack else None)
        trunc_key = (weakref.ref(scope) if scope is not None else None,
                     real_path)
        data = np.asarray(data)
        lengths = np.asarray(lengths)
        ids_arr = np.asarray(ids_arr)
        lines = []
        row = 0
        for k, L in enumerate(lengths):
            L = int(L)
            seq = data[row:row + L].reshape(-1).astype(np.int64)
            row += L
            # reference evalImp always writes an id column: the Id
            # input when given, else the sequence index
            sid = int(ids_arr.reshape(-1)[k]) if ids_arr.size else k
            lines.append(f"{sid}\t" + fmt(seq.tolist()))
        mode = "a" if trunc_key in _SEQTEXT_TRUNCATED else "w"
        _SEQTEXT_TRUNCATED.add(trunc_key)
        # prune dead-scope keys so the set stays bounded
        dead = [k for k in _SEQTEXT_TRUNCATED
                if k[0] is not None and k[0]() is None]
        _SEQTEXT_TRUNCATED.difference_update(dead)
        with open(result_file, mode) as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        return np.int32(0)

    if isinstance(x, LoDArray):
        data, lengths = x.data, x.seq_lens()
    else:
        # dense (N, W): each row is one sample of W tokens
        xv = unwrap(x)
        data = xv.reshape(xv.shape[0], -1)
        lengths = jnp.ones((xv.shape[0],), jnp.int32)
    ids_val = (unwrap(sample_id).astype(jnp.int64)
               if sample_id is not None else jnp.zeros((0,), jnp.int64))
    io_callback(host_write, jnp.zeros((), jnp.int32),
                data.astype(jnp.int64), lengths, ids_val, ordered=True)
    ctx.set_output("Out", ctx.input("X"))


@register_op("segment_rng_key", inputs=(), stop_gradient=True)
def _segment_rng_key(ctx):
    """PRNG key for one rematerialization segment
    (fluid.recompute_scope): the forward segment AND its backward
    recompute both derive randomness from this single value, so
    dropout masks replay identically across the recompute."""
    ctx.set_output("Out", ctx.rng())


@register_op("recompute_segment_grad", inputs=("X", "OutGrad", "SegKey"),
             stop_gradient=True)
def _recompute_segment_grad(ctx):
    """Backward of a rematerialization segment: re-derive the
    segment's forward from its external inputs (instead of reading
    saved intermediates) and apply jax.vjp — activations inside the
    segment are never live across the forward->backward span, the
    jax.checkpoint memory/FLOPs trade expressed at the program level
    where this framework's per-op AD lives."""
    from paddle_tpu.registry import RngState

    seg_ops = ctx.attr("__seg_ops__")
    ext_in = list(ctx.attr("__seg_inputs__"))
    ext_out = list(ctx.attr("__seg_outputs__"))
    key_names = ctx.op.inputs.get("SegKey") or []
    key = (ctx.values.get(key_names[0]) if key_names else None)

    def fwd(*in_vals):
        local = dict(zip(ext_in, in_vals))
        from paddle_tpu.executor import _segment_op_rng
        from paddle_tpu.registry import LowerContext, OpRegistry

        for op in seg_ops:
            # per-op folded key: identical to the forward pass even
            # though this replay may run a pruned (loss-relevant-only)
            # subset of the segment
            op_rng = (_segment_op_rng(key, op) if key is not None
                      else None)
            OpRegistry.get(op.type).lower(
                LowerContext(op, local, rng=op_rng,
                             executor_ctx=ctx.executor_ctx))
        return tuple(local[n] for n in ext_out)

    primals = tuple(ctx.values[n] for n in ext_in)
    outs, vjp = jax.vjp(fwd, *primals)
    gnames = ctx.op.inputs.get("OutGrad") or []
    cts = []
    for o, gn in zip(outs, gnames):
        if gn and gn in ctx.values:
            cts.append(ctx.values[gn])
        else:
            cts.append(jax.tree_util.tree_map(jnp.zeros_like, o))
    gins = vjp(tuple(cts))
    for name, g in zip(ctx.op.outputs.get("X@GRAD", []), gins):
        if name:
            ctx.values[name] = g
