"""Attention + normalization ops for the transformer/long-context path.

The reference predates transformers (its attention is the seq2seq
additive attention built from existing ops — see
python/paddle/v2/fluid/tests/book/test_machine_translation.py-era
models); a TPU-native framework makes fused scaled-dot-product
attention a first-class op so that (a) XLA lowers it onto the MXU as
two big batched matmuls and (b) under a sequence-parallel strategy it
switches to ring attention over the mesh's ``sp`` axis
(paddle_tpu/parallel/ring_attention.py) — the long-context scaling
story the reference's LoD batching cannot provide.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.registry import SkipInferShape, register_op


def _infer_layer_norm_shape(op, block):
    # Y mirrors X; Mean/Variance keep the leading (un-normalized) axes
    xs = op.inputs.get("X", [])
    ys = op.outputs.get("Y", [])
    if len(xs) != 1 or len(ys) != 1 or not xs[0] or not ys[0]:
        raise SkipInferShape
    xv, yv = block.find_var(xs[0]), block.find_var(ys[0])
    if xv is None or yv is None or xv.shape is None:
        raise SkipInferShape
    if yv.shape is None:
        yv.shape = tuple(xv.shape)
    if yv.lod_level == 0 and xv.lod_level:
        yv.lod_level = xv.lod_level
    begin = int(op.attr("begin_norm_axis", 1) or 1)
    if not 0 < begin <= len(xv.shape):
        raise SkipInferShape
    for slot in ("Mean", "Variance"):
        names = op.outputs.get(slot, [])
        if len(names) == 1 and names[0]:
            sv = block.find_var(names[0])
            if sv is not None and sv.shape is None:
                sv.shape = tuple(xv.shape[:begin])


@register_op("layer_norm", inputs=("X", "Scale", "Bias"),
             outputs=("Y", "Mean", "Variance"),
             infer_shape=_infer_layer_norm_shape)
def _layer_norm(ctx):
    x = unwrap(ctx.input("X"))
    eps = ctx.attr("epsilon", 1e-5)
    begin = ctx.attr("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=axes, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=axes, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    if ctx.has_input("Scale"):
        scale = unwrap(ctx.input("Scale")).astype(jnp.float32)
        y = y * scale.reshape(x.shape[begin:])
    if ctx.has_input("Bias"):
        bias = unwrap(ctx.input("Bias")).astype(jnp.float32)
        y = y + bias.reshape(x.shape[begin:])
    ctx.set_output("Y", rewrap(ctx.input("X"), y.astype(x.dtype)))
    ctx.set_output("Mean", mean.squeeze(axes))
    ctx.set_output("Variance", var.squeeze(axes))


def _infer_sdpa_shape(op, block):
    # Out mirrors Q: (B, S, H, D) in, (B, S, H, D) out
    qs = op.inputs.get("Q", [])
    outs = op.outputs.get("Out", [])
    if len(qs) != 1 or len(outs) != 1 or not qs[0] or not outs[0]:
        raise SkipInferShape
    qv, ov = block.find_var(qs[0]), block.find_var(outs[0])
    if qv is None or ov is None or qv.shape is None:
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(qv.shape)
    if ov.lod_level == 0 and qv.lod_level:
        ov.lod_level = qv.lod_level


@register_op("scaled_dot_product_attention", inputs=("Q", "K", "V"),
             infer_shape=_infer_sdpa_shape)
def _sdp_attention(ctx):
    """Q,K,V: (B, S, H, D) -> Out (B, S, H, D).

    Under a strategy whose mesh has a sequence-parallel axis, lowers to
    ring attention (K/V rotating over ICI via ppermute with online
    softmax); otherwise a plain fused attention that XLA maps to two
    batched MXU matmuls.
    """
    from paddle_tpu.parallel import strategy as strat
    from paddle_tpu.parallel.ring_attention import (
        local_attention, ring_attention_sharded)

    q = unwrap(ctx.input("Q"))
    k = unwrap(ctx.input("K"))
    v = unwrap(ctx.input("V"))
    causal = ctx.attr("causal", False)
    # (B, S, H, D) -> (B, H, S, D)
    qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    s = strat.current_strategy()
    sp = getattr(s, "sp_axis", None) if s is not None else None
    if sp is not None:
        out = ring_attention_sharded(
            s.mesh, sp, qt, kt, vt, causal=causal,
            batch_axis=getattr(s, "dp_axis", None),
            head_axis=getattr(s, "tp_axis", None))
    else:
        from paddle_tpu import pallas as pk

        B, H, S, D = qt.shape
        Sk = kt.shape[2]
        if pk.use_flash_attention(B * H, S, Sk, D):
            out = pk.pallas_flash_attention(
                qt.reshape(B * H, S, D), kt.reshape(B * H, Sk, D),
                vt.reshape(B * H, Sk, D), causal, None,
                pk.interpret_mode()).reshape(B, H, S, D)
        else:
            out = local_attention(qt, kt, vt, causal=causal)
    ctx.set_output("Out", rewrap(ctx.input("Q"), out.transpose(0, 2, 1, 3)))
