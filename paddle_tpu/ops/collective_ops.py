"""Collective ops.

The reference exposes NCCL collectives as ops
(operators/nccl_op.cc:19-100) driven by an explicit Communicator.  On
TPU there is no communicator object: when the Executor compiles a block
under a sharded strategy, XLA inserts the collectives implied by the
sharding annotations (psum for data-parallel grads, etc.) and routes
them over ICI.  These explicit ops exist for programs that want manual
collectives inside ``shard_map``-style lowering (parallel.Strategy
spmd mode); under single-device compilation they are identity/no-ops,
matching nccl semantics on one rank.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.registry import register_op


def _axis(ctx):
    return ctx.attr("axis_name", "dp")


@register_op("all_reduce", inputs=("X",))
def _all_reduce(ctx):
    x = ctx.input("X")
    red = ctx.attr("reduction", "sum")
    try:
        if red == "sum":
            out = lax.psum(unwrap(x), _axis(ctx))
        elif red == "mean":
            out = lax.pmean(unwrap(x), _axis(ctx))
        elif red == "max":
            out = lax.pmax(unwrap(x), _axis(ctx))
        elif red == "min":
            out = lax.pmin(unwrap(x), _axis(ctx))
        else:
            raise ValueError(red)
    except NameError:
        out = unwrap(x)  # single-device / unsharded compilation
    ctx.set_output("Out", rewrap(x, out))


# nccl-style aliases for the reference op names (operators/nccl_op.cc:
# ncclInit/ncclAllReduce/ncclReduce/ncclBcast).  On TPU there is no
# communicator object to initialize — GSPMD compiles the collective
# into the program — so ncclInit is a no-op marker and reduce/bcast
# map to psum (every replica gets the result; the reference's
# root-only semantics have no SPMD analog) and a root-broadcast.
@register_op("ncclInit", inputs=(), outputs=(), stop_gradient=True)
def _nccl_init(ctx):
    pass


@register_op("ncclAllReduce", inputs=("X",))
def _nccl_all_reduce(ctx):
    x = ctx.input("X")
    try:
        out = lax.psum(unwrap(x), _axis(ctx))
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))


@register_op("ncclReduce", inputs=("X",))
def _nccl_reduce(ctx):
    x = ctx.input("X")
    try:
        out = lax.psum(unwrap(x), _axis(ctx))
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))


@register_op("ncclBcast", inputs=("X",))
def _nccl_bcast(ctx):
    """Root's value to every replica (root attr, default 0)."""
    x = ctx.input("X")
    root = int(ctx.attr("root", 0))
    try:
        ax = _axis(ctx)
        idx = lax.axis_index(ax)
        v = unwrap(x)
        out = lax.psum(jnp.where(idx == root, v, jnp.zeros_like(v)), ax)
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))


@register_op("broadcast", inputs=("X",))
def _broadcast(ctx):
    # Under SPMD every replica already holds the value; identity.
    ctx.set_output("Out", ctx.input("X"))


@register_op("all_gather", inputs=("X",))
def _all_gather(ctx):
    x = ctx.input("X")
    try:
        out = lax.all_gather(unwrap(x), _axis(ctx), tiled=True)
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))


@register_op("reduce_scatter", inputs=("X",))
def _reduce_scatter(ctx):
    x = ctx.input("X")
    try:
        out = lax.psum_scatter(unwrap(x), _axis(ctx), tiled=True)
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))


# ---------------------------------------------------------------------------
# send / recv: the fluid distributed ops (reference: operators/send_op.cc:30,
# recv_op.cc:45 — send ships a gradient to a parameter server over gRPC;
# recv ran the optimizer sub-program server-side and returned the fresh
# parameter).  Here the server IS the optimizer (native/pserver_service.cc
# runs the C-ABI optimizer per parameter), so send maps to the GRAD RPC
# and recv to GET, both via ordered io_callbacks (the XLA side-effect
# escape hatch) against a process-wide client.
# ---------------------------------------------------------------------------

_PSERVER_CLIENT = [None]


def set_pserver_client(client):
    """Install the process-wide PServerClient used by send/recv ops
    (the fluid analog of the reference's gRPC channel setup)."""
    _PSERVER_CLIENT[0] = client


def _client():
    c = _PSERVER_CLIENT[0]
    if c is None:
        raise RuntimeError(
            "send/recv ops need a pserver: call "
            "paddle_tpu.ops.collective_ops.set_pserver_client(...) first")
    return c


@register_op("send", inputs=("X",), outputs=(), stop_gradient=True)
def _send(ctx):
    from jax.experimental import io_callback
    import numpy as np

    name = ctx.attr("param_name")

    def host_send(arr):
        _client().send_grad(name, np.asarray(arr))
        return np.int32(0)

    io_callback(host_send, jnp.zeros((), jnp.int32),
                unwrap(ctx.input("X")), ordered=True)


@register_op("recv", inputs=("X",), stop_gradient=True)
def _recv(ctx):
    from jax.experimental import io_callback
    import numpy as np

    name = ctx.attr("param_name")
    x = unwrap(ctx.input("X"))  # shape/dtype template (the local copy)

    def host_recv(template):
        v = _client().get_param(name).astype(np.float32)
        return v.reshape(np.asarray(template).shape)

    out = io_callback(host_recv, jax.ShapeDtypeStruct(x.shape, jnp.float32),
                      x, ordered=True)
    ctx.set_output("Out", out.astype(x.dtype))
