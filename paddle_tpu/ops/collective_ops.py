"""Collective ops.

The reference exposes NCCL collectives as ops
(operators/nccl_op.cc:19-100) driven by an explicit Communicator.  On
TPU there is no communicator object: when the Executor compiles a block
under a sharded strategy, XLA inserts the collectives implied by the
sharding annotations (psum for data-parallel grads, etc.) and routes
them over ICI.  These explicit ops exist for programs that want manual
collectives inside ``shard_map``-style lowering (parallel.Strategy
spmd mode); under single-device compilation they are identity/no-ops,
matching nccl semantics on one rank.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import rewrap, unwrap
from paddle_tpu.registry import register_op


def _axis(ctx):
    return ctx.attr("axis_name", "dp")


@register_op("all_reduce", inputs=("X",))
def _all_reduce(ctx):
    x = ctx.input("X")
    red = ctx.attr("reduction", "sum")
    try:
        if red == "sum":
            out = lax.psum(unwrap(x), _axis(ctx))
        elif red == "mean":
            out = lax.pmean(unwrap(x), _axis(ctx))
        elif red == "max":
            out = lax.pmax(unwrap(x), _axis(ctx))
        elif red == "min":
            out = lax.pmin(unwrap(x), _axis(ctx))
        else:
            raise ValueError(red)
    except NameError:
        out = unwrap(x)  # single-device / unsharded compilation
    ctx.set_output("Out", rewrap(x, out))


# nccl-style aliases for the reference op names
@register_op("ncclAllReduce", inputs=("X",))
def _nccl_all_reduce(ctx):
    x = ctx.input("X")
    try:
        out = lax.psum(unwrap(x), _axis(ctx))
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))


@register_op("broadcast", inputs=("X",))
def _broadcast(ctx):
    # Under SPMD every replica already holds the value; identity.
    ctx.set_output("Out", ctx.input("X"))


@register_op("all_gather", inputs=("X",))
def _all_gather(ctx):
    x = ctx.input("X")
    try:
        out = lax.all_gather(unwrap(x), _axis(ctx), tiled=True)
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))


@register_op("reduce_scatter", inputs=("X",))
def _reduce_scatter(ctx):
    x = ctx.input("X")
    try:
        out = lax.psum_scatter(unwrap(x), _axis(ctx), tiled=True)
    except NameError:
        out = unwrap(x)
    ctx.set_output("Out", rewrap(x, out))
