"""Shared helpers for op lowerings."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from paddle_tpu.lod import LoDArray, unwrap, rewrap


def jnp_dtype(name: str):
    return {"bfloat16": jnp.bfloat16}.get(name, np.dtype(name))


def broadcast_to_x(x, y, axis: int = -1):
    """Reference elementwise broadcast rule
    (paddle/operators/elementwise_op_function.h): Y's dims align to a
    contiguous run of X's dims starting at ``axis`` (-1 = trailing)."""
    x_ = unwrap(x)
    y_ = unwrap(y)
    if x_.shape == y_.shape:
        return y_
    # the default axis aligns Y's ORIGINAL rank to X's trailing dims
    # (reference operators/elementwise_op.h: axis = x.ndim - y.ndim,
    # computed before the trailing-1 trim), so (B,1) against (B,D)
    # anchors at axis 0, not at the feature dim
    if axis == -1:
        axis = x_.ndim - y_.ndim
    # trim trailing 1s from y (reference trims them before matching)
    yshape = list(y_.shape)
    while yshape and yshape[-1] == 1 and len(yshape) > 1:
        yshape = yshape[:-1]
    full = [1] * x_.ndim
    for i, s in enumerate(yshape):
        full[axis + i] = s
    return jnp.reshape(y_, full)


def elementwise(ctx, fn):
    x = ctx.input("X")
    y = ctx.input("Y")
    axis = ctx.attr("axis", -1)
    out = fn(unwrap(x), broadcast_to_x(x, y, axis))
    ctx.set_output("Out", rewrap(x, out))


def unary(ctx, fn, slot_in="X", slot_out="Out"):
    x = ctx.input(slot_in)
    ctx.set_output(slot_out, rewrap(x, fn(unwrap(x))))
