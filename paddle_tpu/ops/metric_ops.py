"""Metric ops (reference: operators/{accuracy,top_k,auc,precision_recall}_op.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import SkipInferShape, register_op


def _infer_top_k_shape(op, block):
    # Out/Indices: X with the last dim replaced by k
    ins = op.inputs.get("X", [])
    if len(ins) != 1 or not ins[0]:
        raise SkipInferShape
    xv = block.find_var(ins[0])
    if xv is None or xv.shape is None or not xv.shape:
        raise SkipInferShape
    shape = tuple(xv.shape[:-1]) + (int(op.attr("k", 1)),)
    for slot in ("Out", "Indices"):
        outs = op.outputs.get(slot, [])
        if len(outs) == 1 and outs[0]:
            ov = block.find_var(outs[0])
            if ov is not None and ov.shape is None:
                ov.shape = shape


@register_op("top_k", inputs=("X",), outputs=("Out", "Indices"), stop_gradient=True,
             infer_shape=_infer_top_k_shape)
def _top_k(ctx):
    x = unwrap(ctx.input("X"))
    k = ctx.attr("k", 1)
    vals, idx = lax.top_k(x, k)
    ctx.set_output("Out", vals)
    ctx.set_output("Indices", idx.astype(jnp.int64))


def _infer_accuracy_shape(op, block):
    # Accuracy is the (1,) batch mean; Correct/Total are scalar counts
    hit = False
    for slot, shape in (("Accuracy", (1,)), ("Correct", ()),
                        ("Total", ())):
        names = op.outputs.get(slot, [])
        if len(names) != 1 or not names[0]:
            continue
        v = block.find_var(names[0])
        if v is None:
            continue
        hit = True
        if v.shape is None:
            v.shape = shape
    if not hit:
        raise SkipInferShape


@register_op("accuracy", inputs=("Out", "Indices", "Label"),
             outputs=("Accuracy", "Correct", "Total"), stop_gradient=True,
             infer_shape=_infer_accuracy_shape)
def _accuracy(ctx):
    """Top-k accuracy given top_k's outputs (reference:
    operators/accuracy_op.cc)."""
    idx = unwrap(ctx.input("Indices"))
    label = unwrap(ctx.input("Label")).astype(idx.dtype)
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[:, :1]
    else:
        label = label.reshape(-1, 1)
    hit = jnp.any(idx == label, axis=1)
    n = idx.shape[0]
    correct = jnp.sum(hit.astype(jnp.int32))
    ctx.set_output("Correct", correct)
    ctx.set_output("Total", jnp.asarray(n, jnp.int32))
    ctx.set_output("Accuracy", (correct / n).astype(jnp.float32).reshape(1))


@register_op("auc", inputs=("Out", "Indices", "Label"), outputs=("AUC",),
             stop_gradient=True)
def _auc(ctx):
    """Single-batch ROC-AUC estimate via thresholded trapezoid rule
    (reference: operators/auc_op.cc)."""
    probs = unwrap(ctx.input("Out"))
    label = unwrap(ctx.input("Label")).reshape(-1)
    score = probs[:, -1] if probs.ndim == 2 else probs.reshape(-1)
    num_t = ctx.attr("num_thresholds", 200)
    thresholds = jnp.linspace(0.0, 1.0, num_t)
    pred = score[None, :] >= thresholds[:, None]
    pos = (label > 0)[None, :]
    tp = jnp.sum(pred & pos, axis=1).astype(jnp.float32)
    fp = jnp.sum(pred & ~pos, axis=1).astype(jnp.float32)
    p_total = jnp.maximum(jnp.sum(pos), 1)
    n_total = jnp.maximum(jnp.sum(~pos), 1)
    tpr = tp / p_total
    fpr = fp / n_total
    auc = -jnp.trapezoid(tpr, fpr)
    ctx.set_output("AUC", auc.reshape(1))


@register_op("chunk_eval", inputs=("Inference", "Label"),
             outputs=("Precision", "Recall", "F1-Score", "NumInferChunks",
                      "NumLabelChunks", "NumCorrectChunks"),
             stop_gradient=True)
def _chunk_eval(ctx):
    """Chunk-level precision/recall/F1 for sequence tagging (reference:
    operators/chunk_eval_op.cc; schemes IOB/IOE/IOBES/plain).

    Jittable reformulation: a predicted chunk [s, e] of type t counts as
    correct iff the label tags are identical over [s, e] and the label
    sequence starts a chunk at s and ends one at e — no host-side span
    lists, just boundary masks + segment mins."""
    import jax

    inf = unwrap(ctx.input("Inference")).astype(jnp.int32).reshape(-1)
    lab = unwrap(ctx.input("Label")).astype(jnp.int32).reshape(-1)
    scheme = ctx.attr("chunk_scheme", "IOB")
    num_types = int(ctx.attr("num_chunk_types", 1))
    x = ctx.input("Inference")
    from paddle_tpu.lod import LoDArray, row_segment_ids

    n = inf.shape[0]
    if isinstance(x, LoDArray):
        seq_id = row_segment_ids(x.last_level(), n)
        nseq = x.num_sequences()
    else:
        seq_id = jnp.zeros(n, jnp.int32)
        nseq = 1

    n_tag = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    # outside tag = num_types * n_tag (the reference's "other")
    outside = num_types * n_tag

    def masks(tags):
        inside = tags < outside
        ttype = jnp.where(inside, tags // n_tag, -1)
        tpos = jnp.where(inside, tags % n_tag, -1)
        prev = jnp.concatenate([jnp.full((1,), -2, jnp.int32), tags[:-1]])
        prev_type = jnp.where(prev >= 0, prev // n_tag, -1)
        prev_in = (prev >= 0) & (prev < outside)
        first = jnp.concatenate(
            [jnp.ones((1,), bool),
             seq_id[1:] != seq_id[:-1]]) if n > 1 else jnp.ones((1,), bool)
        nxt = jnp.concatenate([tags[1:], jnp.full((1,), -2, jnp.int32)])
        nxt_type = jnp.where(nxt >= 0, nxt // n_tag, -1)
        nxt_in = (nxt >= 0) & (nxt < outside)
        last = jnp.concatenate(
            [seq_id[1:] != seq_id[:-1],
             jnp.ones((1,), bool)]) if n > 1 else jnp.ones((1,), bool)
        if scheme == "IOB":        # tag 0 = B, 1 = I
            start = inside & ((tpos == 0) | first | ~prev_in
                              | (prev_type != ttype))
            end = inside & (last | ~nxt_in | (nxt_type != ttype)
                            | (nxt % n_tag == 0))
        elif scheme == "IOE":      # tag 0 = I, 1 = E
            start = inside & (first | ~prev_in | (prev_type != ttype)
                              | (prev % n_tag == 1))
            end = inside & ((tpos == 1) | last | ~nxt_in
                            | (nxt_type != ttype))
        elif scheme == "IOBES":    # 0=B 1=I 2=E 3=S
            start = inside & ((tpos == 0) | (tpos == 3))
            end = inside & ((tpos == 2) | (tpos == 3))
        else:                      # plain: every maximal same-type run
            start = inside & (first | (prev != tags))
            end = inside & (last | (nxt != tags))
        return inside, ttype, start, end

    inf_inside, inf_type, inf_start, inf_end = masks(inf)
    _, lab_type, lab_start, lab_end = masks(lab)

    num_inf = jnp.sum(inf_start)
    num_lab = jnp.sum(lab_start)

    # chunk id per position from inference starts; positions before the
    # first start get id 0 but are excluded via the inside mask at starts
    chunk_id = jnp.cumsum(inf_start.astype(jnp.int32)) - 1
    eq = (inf == lab)
    # min over each inference chunk of tag equality; only positions that
    # actually lie inside an inference chunk participate (trailing
    # outside tags carry the previous chunk's id, and malformed leading
    # inside tags have chunk_id -1 — both must not poison the min)
    in_chunk = inf_inside & (chunk_id >= 0)
    n_chunks_cap = n
    all_eq = jax.ops.segment_min(
        jnp.where(in_chunk, eq, True).astype(jnp.int32),
        jnp.maximum(chunk_id, 0), num_segments=n_chunks_cap)
    # a chunk is correct if: starts aligned + types equal + tags equal
    # throughout + ends aligned (end position of inference chunk also
    # ends a label chunk)
    end_ok = jax.ops.segment_min(
        jnp.where(inf_end & in_chunk, lab_end, True).astype(jnp.int32),
        jnp.maximum(chunk_id, 0), num_segments=n_chunks_cap)
    per_start = (inf_start & lab_start & (inf_type == lab_type))
    chunk_ok = jnp.take(all_eq * end_ok, jnp.maximum(chunk_id, 0))
    num_correct = jnp.sum(per_start & (chunk_ok > 0))

    p = num_correct / jnp.maximum(num_inf, 1)
    r = num_correct / jnp.maximum(num_lab, 1)
    f1 = 2 * p * r / jnp.maximum(p + r, 1e-12)
    ctx.set_output("Precision", p.astype(jnp.float32).reshape(1))
    ctx.set_output("Recall", r.astype(jnp.float32).reshape(1))
    ctx.set_output("F1-Score", f1.astype(jnp.float32).reshape(1))
    ctx.set_output("NumInferChunks", num_inf.astype(jnp.int64).reshape(1))
    ctx.set_output("NumLabelChunks", num_lab.astype(jnp.int64).reshape(1))
    ctx.set_output("NumCorrectChunks", num_correct.astype(jnp.int64).reshape(1))


@register_op("positive_negative_pair", inputs=("Score", "Label", "QueryID"),
             outputs=("PositivePair", "NegativePair", "NeutralPair"),
             stop_gradient=True)
def _positive_negative_pair(ctx):
    """Query-grouped ranking pair stats (reference:
    operators/positive_negative_pair_op.cc): over pairs (i, j) in the
    same query with different labels — positive if the score order
    matches the label order, neutral on score ties."""
    score = unwrap(ctx.input("Score")).reshape(-1)
    label = unwrap(ctx.input("Label")).reshape(-1).astype(score.dtype)
    qid = unwrap(ctx.input("QueryID")).reshape(-1)
    n = score.shape[0]

    def counts_for_rows(s_blk, l_blk, q_blk, row0, blk):
        # (blk, n) pairwise slab — peak memory O(blk * n), not O(n^2)
        rows = row0 + jnp.arange(blk)
        upper = rows[:, None] < jnp.arange(n)[None, :]
        valid = (q_blk[:, None] == qid[None, :]) & upper & (
            l_blk[:, None] != label[None, :])
        s_cmp = jnp.sign(s_blk[:, None] - score[None, :])
        l_cmp = jnp.sign(l_blk[:, None] - label[None, :])
        pos = jnp.sum(valid & (s_cmp == l_cmp) & (s_cmp != 0))
        neu = jnp.sum(valid & (s_cmp == 0))
        return pos, neu, jnp.sum(valid)

    if n == 0:
        zero = jnp.zeros(1, jnp.float32)
        ctx.set_output("PositivePair", zero)
        ctx.set_output("NegativePair", zero)
        ctx.set_output("NeutralPair", zero)
        return
    blk = min(n, 1024)
    n_blocks = -(-n // blk)
    if n_blocks == 1:
        pos, neu, tot = counts_for_rows(score, label, qid, 0, n)
    else:
        pad = n_blocks * blk - n
        # pad with qid = -1 rows: they match no real query, count nothing
        sp = jnp.pad(score, (0, pad))
        lp = jnp.pad(label, (0, pad))
        qp = jnp.pad(qid, (0, pad), constant_values=-1)

        def body(i, acc):
            s_blk = lax.dynamic_slice_in_dim(sp, i * blk, blk)
            l_blk = lax.dynamic_slice_in_dim(lp, i * blk, blk)
            q_blk = lax.dynamic_slice_in_dim(qp, i * blk, blk)
            p, u, t = counts_for_rows(s_blk, l_blk, q_blk, i * blk, blk)
            return acc[0] + p, acc[1] + u, acc[2] + t

        zero = jnp.asarray(0, jnp.int32)
        pos, neu, tot = lax.fori_loop(0, n_blocks, body, (zero, zero, zero))
    neg = tot - pos - neu
    ctx.set_output("PositivePair", pos.astype(jnp.float32).reshape(1))
    ctx.set_output("NegativePair", neg.astype(jnp.float32).reshape(1))
    ctx.set_output("NeutralPair", neu.astype(jnp.float32).reshape(1))
