"""Metric ops (reference: operators/{accuracy,top_k,auc,precision_recall}_op.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import unwrap
from paddle_tpu.registry import register_op


@register_op("top_k", inputs=("X",), outputs=("Out", "Indices"), stop_gradient=True)
def _top_k(ctx):
    x = unwrap(ctx.input("X"))
    k = ctx.attr("k", 1)
    vals, idx = lax.top_k(x, k)
    ctx.set_output("Out", vals)
    ctx.set_output("Indices", idx.astype(jnp.int64))


@register_op("accuracy", inputs=("Out", "Indices", "Label"),
             outputs=("Accuracy", "Correct", "Total"), stop_gradient=True)
def _accuracy(ctx):
    """Top-k accuracy given top_k's outputs (reference:
    operators/accuracy_op.cc)."""
    idx = unwrap(ctx.input("Indices"))
    label = unwrap(ctx.input("Label")).astype(idx.dtype)
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[:, :1]
    else:
        label = label.reshape(-1, 1)
    hit = jnp.any(idx == label, axis=1)
    n = idx.shape[0]
    correct = jnp.sum(hit.astype(jnp.int32))
    ctx.set_output("Correct", correct)
    ctx.set_output("Total", jnp.asarray(n, jnp.int32))
    ctx.set_output("Accuracy", (correct / n).astype(jnp.float32).reshape(1))


@register_op("auc", inputs=("Out", "Indices", "Label"), outputs=("AUC",),
             stop_gradient=True)
def _auc(ctx):
    """Single-batch ROC-AUC estimate via thresholded trapezoid rule
    (reference: operators/auc_op.cc)."""
    probs = unwrap(ctx.input("Out"))
    label = unwrap(ctx.input("Label")).reshape(-1)
    score = probs[:, -1] if probs.ndim == 2 else probs.reshape(-1)
    num_t = ctx.attr("num_thresholds", 200)
    thresholds = jnp.linspace(0.0, 1.0, num_t)
    pred = score[None, :] >= thresholds[:, None]
    pos = (label > 0)[None, :]
    tp = jnp.sum(pred & pos, axis=1).astype(jnp.float32)
    fp = jnp.sum(pred & ~pos, axis=1).astype(jnp.float32)
    p_total = jnp.maximum(jnp.sum(pos), 1)
    n_total = jnp.maximum(jnp.sum(~pos), 1)
    tpr = tp / p_total
    fpr = fp / n_total
    auc = -jnp.trapezoid(tpr, fpr)
    ctx.set_output("AUC", auc.reshape(1))
