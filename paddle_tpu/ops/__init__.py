"""Op library: every op registers a JAX lowering rule.

Importing this package registers the full op set (reference inventory:
paddle/operators/, 176 registrations — see SURVEY.md §2.2)."""

from paddle_tpu.ops import tensor_ops  # noqa: F401
from paddle_tpu.ops import math_ops  # noqa: F401
from paddle_tpu.ops import activation_ops  # noqa: F401
from paddle_tpu.ops import nn_ops  # noqa: F401
from paddle_tpu.ops import nn_extra_ops  # noqa: F401
from paddle_tpu.ops import loss_ops  # noqa: F401
from paddle_tpu.ops import reduce_ops  # noqa: F401
from paddle_tpu.ops import optimizer_ops  # noqa: F401
from paddle_tpu.ops import metric_ops  # noqa: F401
from paddle_tpu.ops import sequence_ops  # noqa: F401
from paddle_tpu.ops import control_flow_ops  # noqa: F401
from paddle_tpu.ops import collective_ops  # noqa: F401
from paddle_tpu.ops import lod_ops  # noqa: F401
from paddle_tpu.ops import rnn_unit_ops  # noqa: F401
from paddle_tpu.ops import beam_ops  # noqa: F401
from paddle_tpu.ops import io_ops  # noqa: F401
from paddle_tpu.ops import attention_ops  # noqa: F401
from paddle_tpu.ops import pipeline_ops  # noqa: F401
from paddle_tpu.ops import ctc_ops  # noqa: F401
from paddle_tpu.ops import detection_ops  # noqa: F401
from paddle_tpu.ops import aliases  # noqa: F401  (must be last)
