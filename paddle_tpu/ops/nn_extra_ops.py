"""Second-wave NN ops.

Reference: operators/{nce,linear_chain_crf,crf_decoding,roi_pool,
row_conv,conv_shift,pool_with_index,unpool,pool3d,sampling_id,norm,
precision_recall}_op.cc.

LoD deviation: the CRF pair operates on padded (B, T, D) emissions plus
a Length vector (the TPU layout) rather than packed LoD rows; the
DataFeeder/layers adapt.  Forward/viterbi recursions are lax.scan over
time — compiled, not per-sequence host loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.lod import LoDArray, rewrap, row_segment_ids, unwrap
from paddle_tpu.ops.nn_ops import _make_pool_infer
from paddle_tpu.registry import SkipInferShape, infer_same_shape, register_op

NEG_INF = -1e9


@register_op("nce", inputs=("Input", "Label", "Weight", "Bias", "SampleWeight"),
             outputs=("Cost", "SampleLogits", "SampleLabels"),
             diff_inputs=("Input", "Weight", "Bias"))
def _nce(ctx):
    """Noise-contrastive estimation (reference: operators/nce_op.cc;
    legacy gserver/layers/NCELayer).  Shares negative samples across the
    batch (drawn uniformly per step), binary logistic loss."""
    x = unwrap(ctx.input("Input"))           # (B, D)
    label = unwrap(ctx.input("Label")).astype(jnp.int32)  # (B, T)
    if label.ndim == 1:
        label = label[:, None]
    w = unwrap(ctx.input("Weight"))          # (V, D)
    num_neg = ctx.attr("num_neg_samples", 10)
    V = ctx.attr("num_total_classes", w.shape[0])
    B = x.shape[0]
    num_true = label.shape[1]

    samples = jax.random.randint(ctx.rng(), (num_neg,), 0, V)
    ids = jnp.concatenate([label, jnp.tile(samples[None], (B, 1))], axis=1)
    logits = jnp.einsum("bd,bkd->bk", x, w[ids])
    if ctx.has_input("Bias"):
        logits = logits + unwrap(ctx.input("Bias"))[ids]
    labels01 = jnp.concatenate(
        [jnp.ones((B, num_true)), jnp.zeros((B, num_neg))], axis=1)
    # sigmoid CE with noise prior q = 1/V (uniform sampler)
    logq = jnp.log(jnp.asarray(num_neg / V, jnp.float32))
    adj = logits - logq
    loss = jnp.maximum(adj, 0) - adj * labels01 + jnp.log1p(jnp.exp(-jnp.abs(adj)))
    ctx.set_output("Cost", jnp.sum(loss, axis=1, keepdims=True))
    ctx.set_output("SampleLogits", logits)
    ctx.set_output("SampleLabels", ids)


def _crf_norm_scan(emission, transition, length):
    """log-partition per sequence. emission (B,T,D) f32, transition
    (D+2, D): row 0 start, row 1 end, rows 2.. pairwise. length (B,)."""
    B, T, D = emission.shape
    start = transition[0]
    end = transition[1]
    pair = transition[2:]                    # (D, D) pair[i, j]: i -> j

    alpha0 = start[None, :] + emission[:, 0]  # (B, D)

    def step(alpha, inputs):
        e_t, t_idx = inputs                   # (B, D), scalar
        # logsumexp_i alpha_i + pair[i, j] + e_j
        s = alpha[:, :, None] + pair[None, :, :]
        new = jax.scipy.special.logsumexp(s, axis=1) + e_t
        valid = (t_idx < length)[:, None]
        alpha = jnp.where(valid, new, alpha)
        return alpha, alpha

    ts = jnp.arange(1, T)
    alpha, _ = lax.scan(step, alpha0, (jnp.moveaxis(emission[:, 1:], 1, 0), ts))
    return jax.scipy.special.logsumexp(alpha + end[None, :], axis=1), alpha0


def _crf_path_score(emission, transition, label, length):
    B, T, D = emission.shape
    start = transition[0]
    end = transition[1]
    pair = transition[2:]
    lab = label.astype(jnp.int32)
    if lab.ndim == 3:
        lab = lab[..., 0]
    t_range = jnp.arange(T)[None, :]
    mask = (t_range < length[:, None]).astype(jnp.float32)
    emit = jnp.take_along_axis(emission, lab[..., None], axis=2)[..., 0]
    score = jnp.sum(emit * mask, axis=1)
    score = score + start[lab[:, 0]]
    trans = pair[lab[:, :-1], lab[:, 1:]]     # (B, T-1)
    score = score + jnp.sum(trans * mask[:, 1:], axis=1)
    last_idx = jnp.maximum(length - 1, 0)
    last_tag = jnp.take_along_axis(lab, last_idx[:, None], axis=1)[:, 0]
    return score + end[last_tag]


@register_op("linear_chain_crf",
             inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("LogLikelihood", "Alpha", "EmissionExps", "TransitionExps"),
             diff_inputs=("Emission", "Transition"))
def _linear_chain_crf(ctx):
    em = unwrap(ctx.input("Emission")).astype(jnp.float32)  # (B,T,D)
    tr = unwrap(ctx.input("Transition")).astype(jnp.float32)
    label = unwrap(ctx.input("Label"))
    if ctx.has_input("Length"):
        length = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((em.shape[0],), em.shape[1], jnp.int32)
    logz, alpha0 = _crf_norm_scan(em, tr, length)
    score = _crf_path_score(em, tr, label, length)
    ll = (score - logz)[:, None]
    ctx.set_output("LogLikelihood", -ll)  # reference emits negative LL as cost
    ctx.set_output("Alpha", alpha0)
    ctx.set_output("EmissionExps", jnp.exp(em))
    ctx.set_output("TransitionExps", jnp.exp(tr))


@register_op("crf_decoding", inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("ViterbiPath",), stop_gradient=True)
def _crf_decoding(ctx):
    em = unwrap(ctx.input("Emission")).astype(jnp.float32)
    tr = unwrap(ctx.input("Transition")).astype(jnp.float32)
    B, T, D = em.shape
    if ctx.has_input("Length"):
        length = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((B,), T, jnp.int32)
    start, end, pair = tr[0], tr[1], tr[2:]

    delta0 = start[None, :] + em[:, 0]

    def fwd(delta, inputs):
        e_t, t_idx = inputs
        s = delta[:, :, None] + pair[None]
        best = jnp.max(s, axis=1) + e_t
        arg = jnp.argmax(s, axis=1).astype(jnp.int32)
        valid = (t_idx < length)[:, None]
        new_delta = jnp.where(valid, best, delta)
        return new_delta, arg

    ts = jnp.arange(1, T)
    delta, backs = lax.scan(fwd, delta0, (jnp.moveaxis(em[:, 1:], 1, 0), ts))
    last = jnp.argmax(delta + end[None], axis=1).astype(jnp.int32)

    def bwd(tag, inputs):
        back_t, t_idx = inputs
        prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
        # only follow the pointer for steps inside the sequence
        tag_new = jnp.where(t_idx < length, prev, tag)
        return tag_new, tag_new

    _, path_rev = lax.scan(bwd, last, (backs[::-1], ts[::-1]))
    path = jnp.concatenate([path_rev[::-1].T, last[:, None]], axis=1)  # (B,T)
    ctx.set_output("ViterbiPath", path)


@register_op("roi_pool", inputs=("X", "ROIs"), outputs=("Out", "Argmax"),
             diff_inputs=("X",))
def _roi_pool(ctx):
    """Max-pool fixed bins over regions (reference: operators/roi_pool_op.cc).
    ROIs: (R, 5) [batch_idx, x1, y1, x2, y2]."""
    x = unwrap(ctx.input("X"))        # (B, C, H, W)
    rois = unwrap(ctx.input("ROIs")).astype(jnp.float32)
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    scale = ctx.attr("spatial_scale", 1.0)
    B, C, H, W = x.shape

    ys = jnp.arange(H, dtype=jnp.float32)
    xs_ = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * scale, roi[2] * scale, roi[3] * scale, roi[4] * scale
        rh = jnp.maximum(y2 - y1 + 1, 1.0) / ph
        rw = jnp.maximum(x2 - x1 + 1, 1.0) / pw
        fmap = x[b]                    # (C, H, W)

        def bin_val(i, j):
            ys_lo = y1 + i * rh
            ys_hi = y1 + (i + 1) * rh
            xs_lo = x1 + j * rw
            xs_hi = x1 + (j + 1) * rw
            m = ((ys >= jnp.floor(ys_lo)) & (ys < jnp.ceil(ys_hi)))[:, None] & \
                ((xs_ >= jnp.floor(xs_lo)) & (xs_ < jnp.ceil(xs_hi)))[None, :]
            masked = jnp.where(m[None], fmap, NEG_INF)
            return jnp.max(masked, axis=(1, 2))

        grid = jnp.stack([jnp.stack([bin_val(i, j) for j in range(pw)], -1)
                          for i in range(ph)], -2)   # (C, ph, pw)
        return grid

    out = jax.vmap(one_roi)(rois)     # (R, C, ph, pw)
    ctx.set_output("Out", out.astype(x.dtype))
    if ctx.has_output("Argmax"):
        ctx.set_output("Argmax", jnp.zeros(out.shape, jnp.int32))


@register_op("row_conv", inputs=("X", "Filter"), diff_inputs=("X", "Filter"),
             infer_shape=infer_same_shape)
def _row_conv(ctx):
    """Lookahead row convolution (reference: operators/row_conv_op.cc):
    out[t] = sum_{i=0..k-1} w[i] * x[t+i], over (B, T, D) input."""
    x = unwrap(ctx.input("X"))
    w = unwrap(ctx.input("Filter"))    # (k, D)
    k = w.shape[0]
    B, T, D = x.shape
    pad = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(pad[:, i:i + T] * w[i][None, None, :] for i in range(k))
    ctx.set_output("Out", out)


@register_op("conv_shift", inputs=("X", "Y"), diff_inputs=("X", "Y"),
             infer_shape=infer_same_shape)
def _conv_shift(ctx):
    """Circular correlation (reference: operators/conv_shift_op.cc):
    out[b, i] = sum_j x[b, (i + j - M/2) mod N] * y[b, j]."""
    x = unwrap(ctx.input("X"))  # (B, N)
    y = unwrap(ctx.input("Y"))  # (B, M), M odd
    B, N = x.shape
    M = y.shape[1]
    half = M // 2
    idx = (jnp.arange(N)[:, None] + jnp.arange(M)[None, :] - half) % N  # (N, M)
    ctx.set_output("Out", jnp.einsum("bnm,bm->bn", x[:, idx], y))


@register_op("max_pool2d_with_index", inputs=("X",), outputs=("Out", "Mask"),
             infer_shape=_make_pool_infer(2, default_strides=(2, 2),
                                          also=("Mask",)))
def _max_pool2d_with_index(ctx):
    x = unwrap(ctx.input("X"))
    ks = tuple(ctx.attr("ksize", (2, 2)))
    st = tuple(ctx.attr("strides", (2, 2)))
    pd = tuple(ctx.attr("paddings", (0, 0)))
    if ctx.attr("global_pooling", False):
        ks, st, pd = x.shape[2:4], (1, 1), (0, 0)
    B, C, H, W = x.shape
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=ks, window_strides=st,
        padding=[(pd[0], pd[0]), (pd[1], pd[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    OH, OW = patches.shape[2], patches.shape[3]
    patches = patches.reshape(B, C, ks[0] * ks[1], OH, OW)
    out = jnp.max(patches, axis=2)
    within = jnp.argmax(patches, axis=2).astype(jnp.int32)  # window-local idx
    # convert to global flat H*W index, matching the reference Mask
    oy = jnp.arange(OH)[:, None] * st[0] - pd[0]
    ox = jnp.arange(OW)[None, :] * st[1] - pd[1]
    wy = within // ks[1]
    wx = within % ks[1]
    gy = jnp.clip(oy[None, None] + wy, 0, H - 1)
    gx = jnp.clip(ox[None, None] + wx, 0, W - 1)
    ctx.set_output("Out", out)
    ctx.set_output("Mask", gy * W + gx)


@register_op("unpool", inputs=("X", "Indices"), diff_inputs=("X",))
def _unpool(ctx):
    """Max-unpool via the Mask indices (reference: operators/unpool_op.cc)."""
    x = unwrap(ctx.input("X"))           # (B, C, h, w)
    idx = unwrap(ctx.input("Indices")).astype(jnp.int32)
    ks = tuple(ctx.attr("ksize", (2, 2)))
    st = tuple(ctx.attr("strides", ks))
    B, C, h, w = x.shape
    H = (h - 1) * st[0] + ks[0]
    W = (w - 1) * st[1] + ks[1]
    flat = jnp.zeros((B, C, H * W), x.dtype)
    out = flat.at[
        jnp.arange(B)[:, None, None],
        jnp.arange(C)[None, :, None],
        idx.reshape(B, C, -1),
    ].add(x.reshape(B, C, -1))
    ctx.set_output("Out", out.reshape(B, C, H, W))


@register_op("pool3d", inputs=("X",), infer_shape=_make_pool_infer(3))
def _pool3d(ctx):
    x = unwrap(ctx.input("X"))
    ks = tuple(ctx.attr("ksize", (2, 2, 2)))
    st = tuple(ctx.attr("strides", (1, 1, 1)))
    pd = tuple(ctx.attr("paddings", (0, 0, 0)))
    if ctx.attr("global_pooling", False):
        ks, st, pd = x.shape[2:5], (1, 1, 1), (0, 0, 0)
    extra = (0, 0, 0)
    if ctx.attr("ceil_mode", False):
        from paddle_tpu.layers.nn import pool_extra_padding

        extra = tuple(pool_extra_padding(x.shape[2 + i], ks[i], pd[i], st[i])
                      for i in range(3))
    window = (1, 1) + ks
    strides = (1, 1) + st
    padding = ((0, 0), (0, 0)) + tuple(
        (p, p + e) for p, e in zip(pd, extra))
    if ctx.attr("pooling_type", "max") == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)
    else:
        s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, window,
                              strides, padding)
        if ctx.attr("exclusive", False):
            ones = jnp.ones_like(x, dtype=jnp.float32)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                       padding)
            out = (s / counts).astype(x.dtype)
        else:
            out = (s / (ks[0] * ks[1] * ks[2])).astype(x.dtype)
    ctx.set_output("Out", out)


def _infer_sampling_id_shape(op, block):
    # categorical over the last (class) axis: (B, C) probs -> (B,) ids
    xs = op.inputs.get("X", [])
    outs = op.outputs.get("Out", [])
    if len(xs) != 1 or len(outs) != 1 or not xs[0] or not outs[0]:
        raise SkipInferShape
    xv, ov = block.find_var(xs[0]), block.find_var(outs[0])
    if xv is None or ov is None or xv.shape is None:
        raise SkipInferShape
    if ov.shape is None:
        ov.shape = tuple(xv.shape[:-1])


@register_op("sampling_id", inputs=("X",), stop_gradient=True,
             infer_shape=_infer_sampling_id_shape)
def _sampling_id(ctx):
    probs = unwrap(ctx.input("X"))
    ctx.set_output("Out", jax.random.categorical(
        ctx.rng(), jnp.log(probs + 1e-12), axis=-1).astype(jnp.int64))


@register_op("norm", inputs=("X", "Scale"), diff_inputs=("X", "Scale"),
             infer_shape=infer_same_shape)
def _norm(ctx):
    """Cross-channel L2 norm + per-channel scale (reference:
    operators/norm_op.cc, the SSD NormLayer)."""
    x = unwrap(ctx.input("X"))  # (B, C, H, W)
    scale = unwrap(ctx.input("Scale")).reshape(1, -1, 1, 1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    ctx.set_output("Out", x / norm * scale)


@register_op("precision_recall", inputs=("MaxProbs", "Indices", "Labels", "Weights"),
             outputs=("BatchMetrics", "AccumMetrics", "AccumStatesInfo"),
             stop_gradient=True)
def _precision_recall(ctx):
    """Macro/micro precision-recall-F1 over a batch (reference:
    operators/precision_recall_op.cc)."""
    idx = unwrap(ctx.input("Indices")).reshape(-1).astype(jnp.int32)
    labels = unwrap(ctx.input("Labels")).reshape(-1).astype(jnp.int32)
    C = ctx.attr("class_number")
    pred_oh = jax.nn.one_hot(idx, C)
    lab_oh = jax.nn.one_hot(labels, C)
    tp = jnp.sum(pred_oh * lab_oh, axis=0)
    fp = jnp.sum(pred_oh * (1 - lab_oh), axis=0)
    fn = jnp.sum((1 - pred_oh) * lab_oh, axis=0)
    prec = tp / jnp.maximum(tp + fp, 1e-12)
    rec = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    tp_s, fp_s, fn_s = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    mprec = tp_s / jnp.maximum(tp_s + fp_s, 1e-12)
    mrec = tp_s / jnp.maximum(tp_s + fn_s, 1e-12)
    mf1 = 2 * mprec * mrec / jnp.maximum(mprec + mrec, 1e-12)
    micro = jnp.stack([mprec, mrec, mf1])
    metrics = jnp.concatenate([macro, micro])
    ctx.set_output("BatchMetrics", metrics)
    ctx.set_output("AccumMetrics", metrics)
    ctx.set_output("AccumStatesInfo", jnp.stack([tp, fp, fn], axis=1))


@register_op("sequence_conv", inputs=("X", "Filter", "PaddingData"),
             diff_inputs=("X", "Filter"))
def _sequence_conv(ctx):
    """Context-window projection over packed LoD rows with per-sequence
    boundary masking (reference: operators/sequence_conv_op.cc +
    math/context_project.h)."""
    x = ctx.input("X")
    assert isinstance(x, LoDArray), "sequence_conv needs LoD input"
    w = unwrap(ctx.input("Filter"))          # (ctx_len * D, M)
    ctx_len = ctx.attr("contextLength", 3)
    ctx_start = ctx.attr("contextStart", -(ctx_len // 2))
    data = x.data                            # (N, D)
    N, D = data.shape
    off = x.last_level()
    ids = row_segment_ids(off, N)
    cols = []
    rows_idx = jnp.arange(N)
    for i in range(ctx_len):
        shift = ctx_start + i
        src = jnp.clip(rows_idx + shift, 0, N - 1)
        col = data[src]
        # zero out rows that crossed a sequence boundary
        same_seq = (ids[src] == ids) & ((rows_idx + shift >= 0) & (rows_idx + shift < N))
        cols.append(jnp.where(same_seq[:, None], col, 0.0))
    ctx_mat = jnp.concatenate(cols, axis=1)  # (N, ctx_len*D)
    out = jnp.dot(ctx_mat, w)
    ctx.set_output("Out", LoDArray(out, x.lod))


@register_op("sequence_slice", inputs=("X", "Offset", "Length"),
             diff_inputs=("X",))
def _sequence_slice(ctx):
    """Slice each sequence [offset, offset+length) — rows re-packed with
    a fresh LoD (reference: operators/sequence_slice_op.cc).  Keeps the
    packed buffer size (static shapes); invalid rows zeroed."""
    x = ctx.input("X")
    assert isinstance(x, LoDArray)
    offset = unwrap(ctx.input("Offset")).reshape(-1).astype(jnp.int32)
    length = unwrap(ctx.input("Length")).reshape(-1).astype(jnp.int32)
    off = x.last_level()
    N = x.data.shape[0]
    ids = row_segment_ids(off, N)
    # position of each row within its sequence
    pos = jnp.arange(N, dtype=jnp.int32) - off[:-1][jnp.clip(ids, 0, off.shape[0] - 2)]
    keep = (pos >= offset[jnp.clip(ids, 0, offset.shape[0] - 1)]) & (
        pos < offset[jnp.clip(ids, 0, offset.shape[0] - 1)]
        + length[jnp.clip(ids, 0, length.shape[0] - 1)])
    # stable-compact kept rows to the front
    order = jnp.argsort(jnp.where(keep, jnp.arange(N), N + jnp.arange(N)))
    new_data = jnp.where(keep[order][:, None], x.data[order], 0.0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(length)])
    ctx.set_output("Out", LoDArray(new_data, (new_off,)))


@register_op("max_pool3d_with_index", inputs=("X",), outputs=("Out", "Mask"),
             infer_shape=_make_pool_infer(3, default_strides="ksize",
                                          also=("Mask",)))
def _max_pool3d_with_index(ctx):
    """3-D max pool emitting global flat D*H*W argmax indices
    (reference: operators/pool_with_index_op.cc, 3-D registration)."""
    x = unwrap(ctx.input("X"))
    ks = tuple(ctx.attr("ksize", (2, 2, 2)))
    st = tuple(ctx.attr("strides", ks))
    pd = tuple(ctx.attr("paddings", (0, 0, 0)))
    if ctx.attr("global_pooling", False):
        ks, st, pd = x.shape[2:5], (1, 1, 1), (0, 0, 0)
    B, C, D, H, W = x.shape
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=ks, window_strides=st,
        padding=[(p, p) for p in pd],
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    OD, OH, OW = patches.shape[2:5]
    patches = patches.reshape(B, C, ks[0] * ks[1] * ks[2], OD, OH, OW)
    out = jnp.max(patches, axis=2)
    within = jnp.argmax(patches, axis=2).astype(jnp.int32)
    od = jnp.arange(OD)[:, None, None] * st[0] - pd[0]
    oh = jnp.arange(OH)[None, :, None] * st[1] - pd[1]
    ow = jnp.arange(OW)[None, None, :] * st[2] - pd[2]
    wd = within // (ks[1] * ks[2])
    wh = (within // ks[2]) % ks[1]
    ww = within % ks[2]
    gd = jnp.clip(od[None, None] + wd, 0, D - 1)
    gh = jnp.clip(oh[None, None] + wh, 0, H - 1)
    gw = jnp.clip(ow[None, None] + ww, 0, W - 1)
    ctx.set_output("Out", out)
    ctx.set_output("Mask", (gd * H + gh) * W + gw)


@register_op("block_expand", inputs=("X",), outputs=("Out", "OutLength"))
def _block_expand(ctx):
    """im2col to sequence steps (reference: gserver BlockExpandLayer /
    function/BlockExpandOp.cpp): (B, C, H, W) -> (B, S, C*bh*bw) where
    S = output positions, each step one block.  OutLength (optional
    slot) is the per-sample step count (all S — block positions are
    dense), making the result a well-formed padded sequence."""
    x = unwrap(ctx.input("X"))
    bh, bw = int(ctx.attr("block_y")), int(ctx.attr("block_x"))
    sh = int(ctx.attr("stride_y", bh))
    sw = int(ctx.attr("stride_x", bw))
    ph = int(ctx.attr("padding_y", 0))
    pw = int(ctx.attr("padding_x", 0))
    # the reference includes partial edge blocks (ceil output count:
    # BlockExpandLayer.cpp outputH = 1 + (2p + img - block + s - 1)/s);
    # pad bottom/right so the patch extractor emits exactly that many
    H, W = x.shape[2], x.shape[3]
    oh = (2 * ph + H - bh + sh - 1) // sh + 1
    ow = (2 * pw + W - bw + sw - 1) // sw + 1
    eh = max(0, (oh - 1) * sh + bh - H - 2 * ph)
    ew = max(0, (ow - 1) * sw + bw - W - 2 * pw)
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=(bh, bw), window_strides=(sh, sw),
        padding=[(ph, ph + eh), (pw, pw + ew)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    B, CKK, OH, OW = patches.shape
    ctx.set_output("Out",
                   jnp.moveaxis(patches.reshape(B, CKK, OH * OW), 1, 2))
    ctx.set_output("OutLength", jnp.full((B,), OH * OW, jnp.int32))


@register_op("scale_sub_region_mask", inputs=("X", "Indices"))
def _scale_sub_region_mask(ctx):
    """Scale the per-sample (C, H, W) subregion given by Indices
    (B, 6) = [c0, c1, h0, h1, w0, w1], 1-based inclusive (reference:
    gserver/layers/ScaleSubRegionLayer.cpp) — lowered as an iota mask
    so the region stays dynamic per sample with static shapes."""
    x = unwrap(ctx.input("X"))
    idx = unwrap(ctx.input("Indices")).astype(jnp.int32)
    value = ctx.attr("value", 1.0)
    B, C, H, W = x.shape
    c = lax.broadcasted_iota(jnp.int32, (B, C, H, W), 1)
    h = lax.broadcasted_iota(jnp.int32, (B, C, H, W), 2)
    w = lax.broadcasted_iota(jnp.int32, (B, C, H, W), 3)
    r = idx.reshape(B, 6, 1, 1, 1)
    mask = ((c >= r[:, 0] - 1) & (c <= r[:, 1] - 1) &
            (h >= r[:, 2] - 1) & (h <= r[:, 3] - 1) &
            (w >= r[:, 4] - 1) & (w <= r[:, 5] - 1))
    ctx.set_output("Out", jnp.where(mask, x * value, x))
