"""Parameter initializers (reference: python/paddle/v2/fluid/initializer.py
— Constant/Uniform/Normal/Xavier/MSRA).  Each appends an init op to the
startup program's global block."""

from __future__ import annotations

import math


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": self.value},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self.low,
                "max": self.high,
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 3:
        receptive = 1
        for s in shape[2:]:
            receptive *= s
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    else:
        fan_in = fan_out = shape[0] if shape else 1
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
