"""In-graph sequence decoding: greedy + beam search.

The reference implements beam search twice: RecurrentGradientMachine's
path-expansion generator (gserver/gradientmachines/
RecurrentGradientMachine.cpp:964,1439) and the fluid beam_search +
beam_search_decode ops over LoD tensor arrays (operators/
beam_search_op.cc, beam_search_decode_op.cc), both host-side and
pointer-chasing.  On TPU the whole decode is one compiled program:
dense (batch, beam) state, ``lax.scan`` over max_len steps, top-k
pruning on the joint (beam x vocab) scores, and backpointer stacks
that are re-walked in-graph at the end (the beam_search_decode
equivalent).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e9


class BeamState(NamedTuple):
    tokens: jnp.ndarray       # (B, K) current token per beam
    log_probs: jnp.ndarray    # (B, K) cumulative scores
    finished: jnp.ndarray     # (B, K) bool
    state: object             # model state pytree, leaves (B, K, ...)


def _gather_beams(tree, idx):
    """Select beams: tree leaves (B, K, ...), idx (B, K) int."""
    def g(x):
        return jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return jax.tree_util.tree_map(g, tree)


def beam_search(
    step_fn: Callable,
    init_state,
    batch_size: int,
    beam_size: int,
    vocab_size: int,
    bos_id: int,
    eos_id: int,
    max_len: int,
    length_penalty: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run beam search; returns (sequences (B, K, max_len), scores (B, K)),
    best beam first.

    ``step_fn(tokens, state) -> (log_probs, new_state)``: tokens (B, K)
    int32, log_probs (B, K, V); state leaves are (B, K, ...).
    """
    B, K, V = batch_size, beam_size, vocab_size

    init_tokens = jnp.full((B, K), bos_id, jnp.int32)
    # only beam 0 is live initially (others would duplicate it)
    init_lp = jnp.tile(jnp.asarray([0.0] + [NEG_INF] * (K - 1)), (B, 1))
    init = BeamState(init_tokens, init_lp, jnp.zeros((B, K), bool), init_state)

    def step(carry, _):
        bs = carry
        logp, new_state = step_fn(bs.tokens, bs.state)  # (B, K, V)
        logp = jax.nn.log_softmax(logp.astype(jnp.float32), axis=-1)
        # finished beams only extend with EOS at no cost
        eos_only = jnp.full((B, K, V), NEG_INF).at[:, :, eos_id].set(0.0)
        logp = jnp.where(bs.finished[..., None], eos_only, logp)
        total = bs.log_probs[..., None] + logp          # (B, K, V)
        flat = total.reshape(B, K * V)
        top_scores, top_idx = lax.top_k(flat, K)        # (B, K)
        beam_idx = top_idx // V
        tok_idx = (top_idx % V).astype(jnp.int32)
        new_finished = jnp.take_along_axis(bs.finished, beam_idx, axis=1) | (
            tok_idx == eos_id)
        sel_state = _gather_beams(new_state, beam_idx)
        nbs = BeamState(tok_idx, top_scores, new_finished, sel_state)
        return nbs, (tok_idx, beam_idx)

    final, (toks, backptrs) = lax.scan(step, init, None, length=max_len)
    # toks/backptrs: (T, B, K).  Re-walk backpointers (beam_search_decode).
    def backtrack(carry, tb):
        ptr = carry  # (B, K) which beam at t+1 each output row follows
        tok_t, bp_t = tb
        tok = jnp.take_along_axis(tok_t, ptr, axis=1)
        new_ptr = jnp.take_along_axis(bp_t, ptr, axis=1)
        return new_ptr, tok

    init_ptr = jnp.tile(jnp.arange(K, dtype=jnp.int32), (B, 1))
    _, seq_rev = lax.scan(backtrack, init_ptr, (toks, backptrs), reverse=True)
    sequences = jnp.moveaxis(seq_rev, 0, 2)  # (B, K, T)

    scores = final.log_probs
    if length_penalty > 0:
        lengths = jnp.sum(
            jnp.cumsum((sequences == eos_id).astype(jnp.int32), axis=-1) == 0,
            axis=-1) + 1.0
        scores = scores / jnp.power(lengths, length_penalty)
    order = jnp.argsort(-scores, axis=1)
    sequences = jnp.take_along_axis(sequences, order[..., None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return sequences, scores


def greedy_search(step_fn, init_state, batch_size, bos_id, eos_id, max_len):
    """Greedy decode: step_fn(tokens (B,), state) -> (logits (B, V), state)."""
    B = batch_size

    def step(carry, _):
        tokens, state, finished = carry
        logits, new_state = step_fn(tokens, state)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, eos_id, nxt)
        return (nxt, new_state, finished | (nxt == eos_id)), nxt

    init = (jnp.full((B,), bos_id, jnp.int32), init_state, jnp.zeros((B,), bool))
    _, out = lax.scan(step, init, None, length=max_len)
    return jnp.moveaxis(out, 0, 1)  # (B, T)
