"""Multi-host SPMD: process initialization and ICI×DCN mesh layout.

The reference scales across hosts with MPI/NCCL process groups plus its
parameter-server RPC fabric (SURVEY §2.5: MultiGradientMachine +
pserver/LightNetwork, gRPC send/recv).  The TPU-native replacement is
jax.distributed: every host runs the SAME program, `initialize()`
enrolls it in the cluster, and `jax.devices()` then spans every chip in
the pod — after which the existing strategies (`DataParallelStrategy`,
`HybridParallelStrategy`) and the executor's jit-with-shardings path
work unchanged, with XLA routing collectives over ICI within a slice
and DCN across slices.

Mesh layout rule (the scaling-book recipe): DCN-spanning axes must be
OUTERMOST and carry only bandwidth-light collectives (data-parallel
gradient psum), while model axes (tp/sp/pp) stay inside a slice on
ICI.  `make_hybrid_mesh` encodes exactly that split.

The pserver/master/coord C++ services (paddle_tpu/native) remain the
DCN control plane — dataset sharding, failure detection, checkpoints,
async/sparse parameter service — matching SURVEY §7's division of
labor: gradients ride ICI collectives, bookkeeping rides RPC.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

import jax
from jax.sharding import Mesh

_initialized = [False]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> int:
    """Enroll this host in the cluster (idempotent).

    On Cloud TPU pods every argument auto-detects from the metadata
    server; elsewhere pass them explicitly or via PADDLE_TPU_COORD /
    PADDLE_TPU_NPROC / PADDLE_TPU_PROC_ID (the same rendezvous triplet
    the reference passes to mpirun/paddle pserver --port,--num_hosts).
    Single-process runs (num_processes in (None, 1) with no
    coordinator) skip initialization entirely.  Returns the process
    index."""
    coordinator_address = coordinator_address or os.environ.get(
        "PADDLE_TPU_COORD")
    if num_processes is None and os.environ.get("PADDLE_TPU_NPROC"):
        num_processes = int(os.environ["PADDLE_TPU_NPROC"])
    if process_id is None and os.environ.get("PADDLE_TPU_PROC_ID"):
        process_id = int(os.environ["PADDLE_TPU_PROC_ID"])
    if _initialized[0]:
        wants_cluster = (coordinator_address is not None
                         or (num_processes or 1) > 1)
        if wants_cluster and _initialized[0] == "local":
            raise RuntimeError(
                "initialize() was already called without a coordinator "
                "(single-host no-op); a later multi-host initialize "
                f"(coordinator={coordinator_address!r}, "
                f"num_processes={num_processes}) cannot take effect — "
                "call the coordinated initialize() first in this process")
        return jax.process_index()
    if coordinator_address is None and (num_processes or 1) == 1:
        # single host: nothing to rendezvous
        _initialized[0] = "local"
        return 0
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)
    _initialized[0] = "distributed"
    return jax.process_index()


def make_hybrid_mesh(ici_axes: Dict[str, int],
                     dcn_axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Mesh whose ``dcn_axes`` (outermost) span slices over DCN and
    whose ``ici_axes`` stay within a slice on ICI.

    make_hybrid_mesh({"tp": 4, "sp": 2}, {"dp": 4}) on a 4-slice pod
    of 8-chip slices yields a ("dp", "tp", "sp") mesh where only the
    dp gradient psum crosses DCN.  On a single slice (or the virtual
    CPU mesh) the DCN axes simply become leading axes of the local
    device grid, so the same model code runs everywhere."""
    dcn_axes = dcn_axes or {}
    names = tuple(dcn_axes) + tuple(ici_axes)
    sizes = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    n = int(np.prod(sizes))
    if jax.process_count() > 1 and dcn_axes:
        from jax.experimental import mesh_utils

        # per-axis factorization: each mesh axis is (dcn part) x (ici
        # part); dcn axes are ici-size 1 and vice versa
        ici_shape = (1,) * len(dcn_axes) + tuple(ici_axes.values())
        dcn_shape = tuple(dcn_axes.values()) + (1,) * len(ici_axes)
        devs = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=ici_shape, dcn_mesh_shape=dcn_shape)
        return Mesh(devs, names)
    from paddle_tpu.parallel.strategy import make_mesh

    devices = jax.devices()
    assert len(devices) >= n, (
        f"mesh needs {n} devices, have {len(devices)}")
    return make_mesh({**dcn_axes, **ici_axes})
