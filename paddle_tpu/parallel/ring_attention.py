"""Ring attention: sequence/context parallelism over a mesh axis.

The reference (PaddlePaddle v0.11.0) predates sequence parallelism —
its long-sequence story is LoD ragged batching (framework/lod_tensor.h).
A TPU-native framework must scale *sequence length* across chips, so
this module implements ring attention (Liu et al. 2023 style): Q stays
resident, K/V blocks rotate around the mesh axis via ``lax.ppermute``
over ICI, and softmax is accumulated online (flash-attention style
running max/sum), so no chip ever materializes the full S x S score
matrix or the full K/V.

Differentiable: the loop is a ``lax.scan`` and ``ppermute`` has a
well-defined transpose, so ``jax.grad`` through a ``shard_map``-wrapped
call yields the ring-parallel backward pass automatically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _online_block(q, k, v, bias, m, l, acc, scale):
    """One flash-style block update.  q:(B,H,Sq,D) k,v:(B,H,Sk,D);
    m,l:(B,H,Sq) running max / normalizer; acc:(B,H,Sq,D)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard: fully-masked rows have m_new == -inf; keep exp args finite
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - m_safe)
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _use_flash_chunks(B, H, S, D) -> bool:
    from paddle_tpu import pallas as pk
    from paddle_tpu.pallas import flash_attention as fa

    if pk.mode() == "off" or not fa.fits(B, H, S, D):
        return False
    if pk.mode() == "on":
        return True
    return pk._auto_ok() and S >= 1024


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Attention over sequence shards.  Call inside ``shard_map`` (or
    ``shard_map``-style manual SPMD) with the sequence dim of q/k/v
    sharded over ``axis_name``.

    q, k, v: (B, H, S_local, D); returns (B, H, S_local, D).
    ``causal`` masks by *global* position, computed from the shard index.

    Per-shard chunk math: when the local shapes fit, each (q_local,
    kv_chunk) block runs the Pallas flash kernel (no S_local x S_chunk
    score tensor in HBM) and chunks merge in log-sum-exp space; causal
    masking resolves at the ring level — chunks strictly ahead of this
    shard skip their FLOPs entirely, the diagonal chunk runs the
    kernel's causal mask, earlier chunks run unmasked.  Shapes the
    kernel rejects fall back to the jnp online-softmax block.
    """
    n = (lax.axis_size(axis_name) if hasattr(lax, "axis_size")
         else lax.psum(1, axis_name))  # pre-0.4.38 spelling
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    if scale is None:
        scale = D ** -0.5

    if _use_flash_chunks(B, H, S, D):
        return _ring_attention_flash(q, k, v, axis_name, causal, scale,
                                     n, idx)

    qf = q.astype(jnp.float32)
    m0 = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    a0 = jnp.zeros((B, H, S, D), jnp.float32)

    q_pos = idx * S + jnp.arange(S)

    def step(carry, t):
        k_cur, v_cur, m, l, acc = carry
        # chunk currently held arrived from shard (idx - t) mod n
        src = (idx - t) % n
        bias = None
        if causal:
            k_pos = src * S + jnp.arange(S)
            mask = q_pos[:, None] >= k_pos[None, :]
            bias = jnp.where(mask, 0.0, -jnp.inf)[None, None]
        m, l, acc = _online_block(qf, k_cur.astype(jnp.float32),
                                  v_cur, bias, m, l, acc, scale)
        # rotate K/V to the next shard around the ring (ICI neighbours)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    (k, v, m, l, acc), _ = lax.scan(step, (k, v, m0, l0, a0),
                                    jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name, causal, scale, n, idx):
    """Ring attention with the Pallas flash kernel as the per-chunk
    block: chunk results (normalized out, lse) merge in log-sum-exp
    space, which is exact and keeps the backward pass flowing through
    the kernel's custom vjp plus elementwise merge algebra."""
    from paddle_tpu import pallas as pk
    from paddle_tpu.pallas.flash_attention import flash_attention_with_lse

    B, H, S, D = q.shape
    q3 = q.reshape(B * H, S, D)
    interp = pk.interpret_mode()

    o0 = jnp.zeros((B * H, S, D), jnp.float32)
    lse0 = jnp.full((B * H, S), -jnp.inf, jnp.float32)

    def step(carry, t):
        k_cur, v_cur, o, lse = carry
        src = (idx - t) % n
        k3 = k_cur.reshape(B * H, S, D)
        v3 = v_cur.reshape(B * H, S, D)

        def full(_):
            return flash_attention_with_lse(q3, k3, v3, False, scale,
                                            interp)

        def diag(_):
            return flash_attention_with_lse(q3, k3, v3, True, scale,
                                            interp)

        def skip(_):
            return (jnp.zeros_like(q3), jnp.full((B * H, S), -jnp.inf,
                                                 jnp.float32))

        if causal:
            # 0: src < idx (full), 1: src == idx (diagonal), 2: skip
            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            out_c, lse_c = lax.switch(branch, [full, diag, skip], None)
        else:
            out_c, lse_c = full(None)

        lse_new = jnp.logaddexp(lse, lse_c)
        safe = jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)
        w_old = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(lse - safe))
        w_new = jnp.where(jnp.isneginf(lse_c), 0.0, jnp.exp(lse_c - safe))
        o = o * w_old[..., None] + out_c.astype(jnp.float32) \
            * w_new[..., None]

        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o, lse_new), None

    (_, _, o, lse), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    return o.reshape(B, H, S, D).astype(q.dtype)


def local_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Single-device reference path, same signature semantics
    ((B, H, S, D) in, (B, H, S, D) out)."""
    B, H, S, D = q.shape
    if scale is None:
        scale = D ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def ring_attention_sharded(mesh, sp_axis: str, q, k, v,
                           causal: bool = False,
                           batch_axis: Optional[str] = None,
                           head_axis: Optional[str] = None):
    """``shard_map``-wrapped ring attention usable from inside ``jit``.

    q, k, v are logically-global (B, H, S, D) arrays; the sequence dim
    is sharded over ``sp_axis``, batch over ``batch_axis`` (dp), heads
    over ``head_axis`` (tp) when given.  GSPMD composes this region
    with the surrounding program's shardings.
    """
    spec = P(batch_axis, head_axis, sp_axis, None)
    fn = functools.partial(ring_attention, axis_name=sp_axis, causal=causal)
    from paddle_tpu.parallel.compat import shard_map as _shard_map

    mapped = _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return mapped(q, k, v)
