"""SPMD parallelism: meshes, shardings, strategies.

Replaces the reference's three distributed backends (MultiGradientMachine
threads, NCCL ops, C++/Go parameter servers — SURVEY.md §2.5) with the
TPU-native design: one compiled program, sharded over a
``jax.sharding.Mesh``; XLA inserts psum/all_gather over ICI.
"""

from paddle_tpu.parallel.strategy import (
    DataParallelStrategy,
    HybridParallelStrategy,
    Strategy,
    TensorParallelStrategy,
    current_strategy,
    make_mesh,
    strategy_scope,
)
from paddle_tpu.parallel.ring_attention import (
    local_attention,
    ring_attention,
    ring_attention_sharded,
)
from paddle_tpu.parallel.multihost import initialize, make_hybrid_mesh
