"""jax version compat for the parallel package."""

from __future__ import annotations

import jax


def shard_map(fn, *, mesh, in_specs, out_specs):
    """``jax.shard_map(..., check_vma=False)`` with fallback to the
    pre-0.4.38 spelling (``jax.experimental.shard_map.shard_map`` with
    ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm  # pragma: no cover

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)
