"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh
axis, the scaling-book recipe done with ``shard_map`` + ``lax.scan`` +
``lax.ppermute``.

The reference's closest capability is layer-placement model parallelism
(ParallelNeuralNetwork.h:34,61-63: per-layer deviceId dispatch across
threads).  The TPU-native version: identical layer blocks' parameters
are *stacked* on a leading dim and sharded over the ``pp`` axis, so
each chip holds a contiguous stage of layers; activations hop stages
over ICI via ppermute while microbatches stream through, and the whole
schedule — bubbles included — is one compiled XLA program.
Reverse-mode AD through scan+ppermute yields the 1F1B-ish backward
schedule automatically.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _pipeline_local(layer_fn, stacked_params, x_mb, n_microbatch):
    """No-pp fallback: scan microbatches through all layers locally."""

    def through_layers(h):
        def body(h, p):
            return layer_fn(p, h), None

        h, _ = lax.scan(body, h, stacked_params)
        return h

    return lax.map(through_layers, x_mb)


def gpipe(layer_fn: Callable, stacked_params, x, *, mesh, pp_axis: str,
          n_microbatch: int, batch_axis: Optional[str] = None,
          sp_axis: Optional[str] = None):
    """Run ``x`` through L stacked layers, pipelined over ``pp_axis``.

    layer_fn(params_i, h) -> h   (one transformer block, pure jnp; may
        use ``sp_axis`` collectives, e.g. ring attention, when given)
    stacked_params: pytree of (L, ...) arrays, L = total layers.
    x: (B, S, ...) global activations; microbatched on dim 0.

    Microbatch membership contract: rows are assigned round-robin (row
    r lands in microbatch ``r % n_microbatch``), not in contiguous
    chunks as canonical GPipe slices them; the inverse mapping restores
    row order on output.  Per-row layer_fns are unaffected, but any
    batch-coupled computation inside layer_fn (e.g. batch statistics)
    sees different groupings than a contiguous split would produce.

    n_microbatch must divide the batch; the pp axis size must divide L.
    """
    B = x.shape[0]
    assert B % n_microbatch == 0, (B, n_microbatch)
    # Split the batch with the dp-sharded factor MAJOR: (B,..) P(dp,..)
    # -> (B/M, M, ..) keeps dp on dim 0 without data movement, and the
    # swapaxes to microbatch-major is a free dim permutation for GSPMD.
    # Reshaping directly to (M, B/M, ..) would land dp on the microbatch
    # dim and force an involuntary full rematerialization at the
    # shard_map boundary (each microbatch is just a batch partition, so
    # the interleaved assignment is semantically equivalent; the inverse
    # mapping below restores the original row order exactly).
    x_mb = x.reshape((B // n_microbatch, n_microbatch) + x.shape[1:]
                     ).swapaxes(0, 1)

    def un_mb(out):
        return out.swapaxes(0, 1).reshape((B,) + x.shape[1:])

    if mesh is None or pp_axis is None:
        out = _pipeline_local(layer_fn, stacked_params, x_mb, n_microbatch)
        return un_mb(out)

    n_stages = mesh.shape[pp_axis]

    def run(params_local, x_loc):
        # params_local: (L/pp, ...) slices; x_loc: (M, Bm_loc, S_loc, ...)
        s_idx = lax.axis_index(pp_axis)
        M = x_loc.shape[0]
        T = M + n_stages - 1

        def stage_body(h):
            def body(h, p):
                return layer_fn(p, h), None

            h, _ = lax.scan(body, h, params_local)
            return h

        mb_shape = x_loc.shape[1:]
        out0 = jnp.zeros((M,) + mb_shape, x_loc.dtype)
        recv0 = jnp.zeros(mb_shape, x_loc.dtype)

        def step(carry, t):
            recv, out = carry
            # stage 0 injects microbatch t (clamped; masked later)
            inject = x_loc[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where(s_idx == 0, inject, recv)
            y = stage_body(h_in)
            # last stage writes finished microbatch t-(S-1)
            w = t - (n_stages - 1)
            valid = jnp.logical_and(s_idx == n_stages - 1,
                                    jnp.logical_and(w >= 0, w < M))
            upd = jnp.where(valid, y, out[jnp.clip(w, 0, M - 1)])
            out = lax.dynamic_update_index_in_dim(
                out, upd, jnp.clip(w, 0, M - 1), 0)
            # hand y to the next stage (no wraparound: last stage's
            # output leaves the ring via the out buffer)
            perm = [(i, i + 1) for i in range(n_stages - 1)]
            recv_next = lax.ppermute(y, pp_axis, perm)
            return (recv_next, out), None

        (recv, out), _ = lax.scan(step, (recv0, out0), jnp.arange(T))
        # replicate the result over pp (only last stage holds it)
        mask = (s_idx == n_stages - 1).astype(out.dtype)
        return lax.psum(out * mask, pp_axis)

    pspec = jax.tree.map(lambda _: P(pp_axis), stacked_params)
    xspec = P(None, batch_axis, sp_axis) if x_mb.ndim >= 3 else P(None, batch_axis)
    from paddle_tpu.parallel.compat import shard_map as _shard_map

    mapped = _shard_map(run, mesh=mesh, in_specs=(pspec, xspec),
                        out_specs=xspec)
    out = mapped(stacked_params, x_mb)
    return un_mb(out)
