"""Parallel strategies: sharding rules the Executor applies at compile
time.

Data parallel (reference equivalents: MultiGradientMachine
gserver/gradientmachines/MultiGradientMachine.h:30-80, ncclAllReduce
operators/nccl_op.cu.cc:41-78, sync pserver pserver/ParameterServer2.h):
shard every feed's batch dim over the mesh, replicate parameters, and
let XLA turn the (replicated-out) gradient contractions into psum over
ICI.  No gradient-merge thread, no parameter server: the collective is
inside the step program.

Tensor parallel (no reference equivalent — the closest is per-layer
device placement in ParallelNeuralNetwork.h:34): parameters carry a
``dist_spec`` (a PartitionSpec-shaped tuple set via
``ParamAttr(shard=...)``); XLA/GSPMD propagates the sharding through
the matmuls and inserts the all-reduce/all-gather where row/column
parallel layers meet.

Sequence parallel: the strategy exposes ``sp_axis``; feeds with a
sequence dim shard it, and the ``scaled_dot_product_attention`` op
lowers to ring attention over that axis
(paddle_tpu/parallel/ring_attention.py).
"""

from __future__ import annotations

import contextlib
import re
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# --- current-strategy scope (read by op lowerings at trace time) -----------

_current: list = [None]


def current_strategy():
    return _current[-1]


@contextlib.contextmanager
def strategy_scope(s):
    _current.append(s)
    try:
        yield
    finally:
        _current.pop()


def make_mesh(axis_sizes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}; devices default to all."""
    devices = devices if devices is not None else jax.devices()
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    n = int(np.prod(sizes))
    arr = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


class Strategy:
    """Base: everything replicated (single-program, multi-chip copies)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def state_spec(self, name: str, var) -> P:
        return P()

    def feed_spec(self, name: str, var) -> P:
        return P()

    def jit_shardings(self, block, state_names: Sequence[str],
                      feed_names: Sequence[str], uses_rng: bool = False,
                      out_state_names: Optional[Sequence[str]] = None):
        state_sh = {
            n: NamedSharding(self.mesh, self.state_spec(n, block.find_var(n)))
            for n in state_names
        }
        out_state_sh = {
            n: NamedSharding(self.mesh, self.state_spec(n, block.find_var(n)))
            for n in (out_state_names if out_state_names is not None else state_names)
        }
        feed_sh = {
            n: NamedSharding(self.mesh, self.feed_spec(n, block.find_var(n)))
            for n in feed_names
        }
        replicated = NamedSharding(self.mesh, P())
        # positional: (state, feeds[, seed]); outputs (fetches, state)
        in_sh = [state_sh, feed_sh]
        if uses_rng:
            in_sh.append(replicated)
        return {
            "in_shardings": tuple(in_sh),
            "out_shardings": (None, out_state_sh),
        }


class DataParallelStrategy(Strategy):
    """Shard feed batch dim over ``axis``; replicate state."""

    def __init__(self, mesh: Mesh, axis: str = "dp"):
        super().__init__(mesh)
        self.axis = axis
        self.dp_axis = axis

    def feed_spec(self, name: str, var) -> P:
        from paddle_tpu.lod import LoDArray  # noqa: F401

        if var is not None and var.lod_level > 0:
            # ragged packed rows don't shard on batch yet: replicate
            return P()
        return P(self.axis)


def _spec_from_dist(dist_spec) -> P:
    return P(*dist_spec) if dist_spec is not None else None


class HybridParallelStrategy(Strategy):
    """Multi-axis SPMD: dp x tp x sp (x ep via ShardedEmbedding) on one
    mesh — the scaling-book recipe: annotate, let GSPMD insert the
    collectives, ICI carries them.

    - ``dp_axis``: feeds shard dim 0.
    - ``tp_axis``: parameters shard per their ``dist_spec`` (from
      ``ParamAttr(shard=...)``); optimizer accumulators inherit the
      spec of the parameter whose name prefixes theirs.
    - ``sp_axis``: feeds listed in ``seq_feeds`` (or all rank>=2 feeds
      when ``shard_all_seq``) shard dim 1; the attention op switches to
      ring attention over this axis.
    - ``feed_specs``: explicit per-feed PartitionSpec overrides.
    """

    def __init__(self, mesh: Mesh, dp_axis: Optional[str] = "dp",
                 tp_axis: Optional[str] = None, sp_axis: Optional[str] = None,
                 pp_axis: Optional[str] = None,
                 feed_specs: Optional[Dict[str, P]] = None,
                 seq_feeds: Sequence[str] = (), shard_all_seq: bool = False,
                 param_rules: Sequence = ()):
        super().__init__(mesh)
        axes = set(mesh.axis_names)
        for a in (dp_axis, tp_axis, sp_axis, pp_axis):
            assert a is None or a in axes, f"axis {a!r} not in mesh {axes}"
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.sp_axis = sp_axis
        self.pp_axis = pp_axis
        self.feed_specs = dict(feed_specs or {})
        self.seq_feeds = set(seq_feeds)
        self.shard_all_seq = shard_all_seq
        # (regex, spec-tuple) fallbacks for params without dist_spec
        self.param_rules = [(re.compile(p), s) for p, s in param_rules]

    def _param_spec(self, name: str, var) -> Optional[P]:
        ds = getattr(var, "dist_spec", None) if var is not None else None
        if ds is not None:
            return _spec_from_dist(ds)
        for rx, spec in self.param_rules:
            if rx.search(name):
                return P(*spec)
        return None

    def state_spec(self, name: str, var) -> P:
        spec = self._param_spec(name, var)
        if spec is not None:
            return spec
        # optimizer accumulators (e.g. "<param>_velocity_0") inherit the
        # parameter's sharding so optimizer math stays local to the shard
        block = var.block if var is not None else None
        if block is not None:
            shape = var.shape
            for pname, pvar in block.vars.items():
                if pname != name and name.startswith(pname) and (
                        getattr(pvar, "dist_spec", None) is not None
                        and tuple(pvar.shape or ()) == tuple(shape or ())):
                    return _spec_from_dist(pvar.dist_spec)
        return P()

    def feed_spec(self, name: str, var) -> P:
        if name in self.feed_specs:
            return self.feed_specs[name]
        if var is not None and var.lod_level > 0:
            return P()
        # positional: dim 0 = batch (dp), dim 1 = sequence (sp); a None
        # dp axis must still hold the batch slot so sp lands on dim 1
        ndim = var.ndim if var is not None and var.shape is not None else None
        if self.sp_axis is not None and ndim is not None and ndim >= 2 and (
                self.shard_all_seq or name in self.seq_feeds):
            return P(self.dp_axis, self.sp_axis)
        if self.dp_axis is not None:
            return P(self.dp_axis)
        return P()


class TensorParallelStrategy(HybridParallelStrategy):
    """Pure TP (optionally + dp): params shard via dist_spec over
    ``axis``; activations follow by propagation."""

    def __init__(self, mesh: Mesh, axis: str = "tp",
                 dp_axis: Optional[str] = None, **kw):
        super().__init__(mesh, dp_axis=dp_axis, tp_axis=axis, **kw)
