"""Parallel strategies: sharding rules the Executor applies at compile
time.

Data parallel (reference equivalents: MultiGradientMachine
gserver/gradientmachines/MultiGradientMachine.h:30-80, ncclAllReduce
operators/nccl_op.cu.cc:41-78, sync pserver pserver/ParameterServer2.h):
shard every feed's batch dim over the mesh, replicate parameters, and
let XLA turn the (replicated-out) gradient contractions into psum over
ICI.  No gradient-merge thread, no parameter server: the collective is
inside the step program.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_sizes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}; devices default to all."""
    devices = devices if devices is not None else jax.devices()
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    n = int(np.prod(sizes))
    arr = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


class Strategy:
    """Base: everything replicated (single-program, multi-chip copies)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def state_spec(self, name: str, var) -> P:
        return P()

    def feed_spec(self, name: str, var) -> P:
        return P()

    def jit_shardings(self, block, state_names: Sequence[str],
                      feed_names: Sequence[str], uses_rng: bool = False,
                      out_state_names: Optional[Sequence[str]] = None):
        state_sh = {
            n: NamedSharding(self.mesh, self.state_spec(n, block.find_var(n)))
            for n in state_names
        }
        out_state_sh = {
            n: NamedSharding(self.mesh, self.state_spec(n, block.find_var(n)))
            for n in (out_state_names if out_state_names is not None else state_names)
        }
        feed_sh = {
            n: NamedSharding(self.mesh, self.feed_spec(n, block.find_var(n)))
            for n in feed_names
        }
        replicated = NamedSharding(self.mesh, P())
        # positional: (state, feeds[, seed]); outputs (fetches, state)
        in_sh = [state_sh, feed_sh]
        if uses_rng:
            in_sh.append(replicated)
        return {
            "in_shardings": tuple(in_sh),
            "out_shardings": (None, out_state_sh),
        }


class DataParallelStrategy(Strategy):
    """Shard feed batch dim over ``axis``; replicate state."""

    def __init__(self, mesh: Mesh, axis: str = "dp"):
        super().__init__(mesh)
        self.axis = axis

    def feed_spec(self, name: str, var) -> P:
        from paddle_tpu.lod import LoDArray  # noqa: F401

        if var is not None and var.lod_level > 0:
            # ragged packed rows don't shard on batch yet: replicate
            return P()
        return P(self.axis)
