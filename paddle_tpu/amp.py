"""Automatic mixed precision.

The reference era trained fp32 with an experimental fp16 path
(reference: paddle/math/float16.h, doc/design/float16.md).  On TPU the
native fast path is bfloat16 on the MXU with fp32 accumulation — no
loss scaling needed thanks to bf16's fp32-range exponent.  When
enabled, matmul/conv lowerings cast operands to bf16 and keep bf16
activations (halving HBM traffic); parameters, optimizer state and
gradients stay fp32 (master weights), because the cast's vjp restores
fp32 cotangents automatically.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

_STATE = {"enabled": False}


def enable(flag: bool = True):
    _STATE["enabled"] = bool(flag)


def is_enabled() -> bool:
    return _STATE["enabled"]


def compute_dtype():
    """bf16 when AMP is on, else None (keep operand dtype)."""
    return jnp.bfloat16 if _STATE["enabled"] else None


@contextlib.contextmanager
def amp_guard(flag: bool = True):
    old = _STATE["enabled"]
    _STATE["enabled"] = bool(flag)
    try:
        yield
    finally:
        _STATE["enabled"] = old


def cast_operands(*xs):
    dt = compute_dtype()
    if dt is None:
        return xs
    return tuple(x.astype(dt) if x.dtype in (jnp.float32, jnp.float64) else x
                 for x in xs)


def out_dtype(x):
    """Output dtype for a matmul/conv given input x (pre-cast)."""
    dt = compute_dtype()
    return dt if dt is not None and x.dtype in (jnp.float32, jnp.bfloat16) else x.dtype


def preferred_acc():
    """preferred_element_type for dot/conv.  None under AMP: bf16 in/out
    (MXU still accumulates fp32 internally); explicitly f32 otherwise.
    Keeping in/out dtypes uniform keeps jax's conv transpose rule happy."""
    return None if is_enabled() else jnp.float32
