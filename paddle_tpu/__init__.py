"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up rebuild of the capabilities of PaddlePaddle v0.11.0
(reference: /root/reference) designed for TPU hardware:

- **Program-as-data IR** (Program/Block/Operator/Variable), mirroring the
  semantics of the reference's fluid ``framework.proto`` / ``framework.py``
  (reference: python/paddle/v2/fluid/framework.py), but *lowered* rather
  than interpreted: the Executor traces whole blocks into XLA programs via
  JAX and caches compiled executables keyed by (block, feed shapes).
- **Ops as lowering rules**: every op registers a JAX lowering (and
  optionally a Pallas kernel) instead of per-place OpKernels
  (reference: paddle/framework/op_registry.h).
- **Autodiff on the IR**: ``append_backward`` inserts ``*_grad`` ops into
  the program (reference: paddle/framework/backward.cc); grad lowerings
  are derived from forward lowerings with ``jax.vjp`` unless a hand
  written rule is provided.
- **SPMD parallelism**: device meshes + shardings (``paddle_tpu.parallel``)
  replace the reference's NCCL ops / parameter server with XLA
  collectives over ICI.
"""

from paddle_tpu import framework
from paddle_tpu.framework import (
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    recompute_scope,
    CPUPlace,
    TPUPlace,
)
from paddle_tpu.executor import Executor, global_scope, scope_guard, Scope
from paddle_tpu.backward import append_backward
from paddle_tpu import ops  # registers the op library
from paddle_tpu import layers
from paddle_tpu import nets
from paddle_tpu import initializer
from paddle_tpu import optimizer
from paddle_tpu import regularizer
from paddle_tpu import io
from paddle_tpu import evaluator
from paddle_tpu import profiler
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.lod import LoDArray, create_lod_array
from paddle_tpu import parallel
from paddle_tpu import backward
from paddle_tpu import clip
from paddle_tpu import lr_scheduler
from paddle_tpu import net_drawer
from paddle_tpu import flags
from paddle_tpu import stat
from paddle_tpu import errors
from paddle_tpu import analysis
from paddle_tpu import observability

__version__ = "0.1.0"
