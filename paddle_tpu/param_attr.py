"""ParamAttr (reference: python/paddle/v2/fluid/param_attr.py)."""

from __future__ import annotations


class ParamAttr:
    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        gradient_clip=None,
        shard=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        # sharding hint: PartitionSpec-shaped tuple, one entry per dim
        # (mesh axis name or None), consumed by parallel strategies
        self.shard = tuple(shard) if shard is not None else None

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, dict):
            return ParamAttr(**arg)
        from paddle_tpu.initializer import Initializer

        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        from paddle_tpu.regularizer import WeightDecayRegularizer

        if isinstance(arg, WeightDecayRegularizer):
            # reference param_attr.py:47 — a bare regularizer means
            # "default attrs + this weight decay"
            return ParamAttr(regularizer=arg)
        if arg is True:
            # v1 bias_attr=True means "use a default bias"
            return ParamAttr()
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")
