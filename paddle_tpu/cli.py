#!/usr/bin/env python3
"""The `paddle` command (reference: paddle/scripts/submit_local.sh.in —
the shell wrapper exposing train / version / merge_model; plus the
TPU-era additions: pserver / master / coord service launchers).

Subcommands:
  paddle train --config=conf.py [--num_passes=N] [--save_dir=D] [--config_args=k=v,...]
  paddle version
  paddle merge_model --model_dir=DIR --out=OUT_DIR [--config_args=...]
      (reference `paddle merge_model` fused config+params into one
       binary for the C API; here: re-parse the v1 config, load the
       pass params, export a save_inference_model directory that
       capi/paddle_tpu_capi.h consumes)
  paddle compile --model_dir=DIR --out=DIR [--max_batch=N]
                 [--buckets=1,2,4] [--no-optimize] [--gen_config=SCRIPT]
                 [--smoke]
      (AOT serving artifacts — paddle_tpu/aot: run the serving warmup
       paths under export capture and serialize every bucket-ladder /
       decode-step executable into a versioned artifact directory that
       `paddle serve --artifacts=DIR` boots from without JIT compiling;
       --smoke is the self-contained export->boot->parity CI gate)
  paddle serve [--model_dir=DIR] [--port=N] [--replicas=N] [--max_batch=N]
               [--batch_timeout_ms=MS] [--warmup] [--artifacts=DIR]
               [--request_timeout=SECONDS] [--max_inflight=N]
               [--gen_config=SCRIPT] [--gen_pages=N] [--gen_page_size=N]
               [--gen_pages_per_seq=N] [--gen_slots=N] [--gen_queue=N]
               [--gen_max_tokens=N] [--beam_max=K] [--prefix_cache]
               [--prefix_cache_pages=N] [--spec_draft=ngram] [--spec_k=N]
      (HTTP JSON inference over a save_inference_model export —
       paddle_tpu/serving: bucketed request coalescing into power-of-two
       batch shapes + a pool of executor replicas; --warmup pre-compiles
       the bucket ladder; --request_timeout returns 504 on expiry,
       --max_inflight sheds load with 503 instead of piling up threads.
       --gen_config mounts POST /generate: token streaming over the
       paged-KV continuous-batching decode engine, paddle_tpu/decode —
       the script defines make_generator() -> (beam_gen, parameters)
       or make_decode_model() -> paged LM, see demos/seq2seq/
       gen_config.py; --beam_max enables beam search over CoW sibling
       slots, --prefix_cache shares prompt-prefix KV pages across
       requests, --spec_draft/--spec_k enable speculative decoding)
  paddle elastic --coord=HOST:PORT --checkpoint-dir=DIR [--job=NAME]
                 [--tasks=N] [--passes=P] [--worker-id=ID] ...
      (preemption-safe demo training worker —
       paddle_tpu/distributed/elastic.py; kill it mid-epoch and a
       relaunched worker resumes from the last committed checkpoint)
  paddle lint <program.json|config.py> [--level=...] [--strict] [--json]
      (static program verification — paddle_tpu/analysis; exits nonzero
       on error diagnostics.  --audit-registry checks op-metadata
       coverage against the checked-in baseline)
  paddle tune [--kernel=matmul,flash_attention,...] [--shapes=MxKxN;...]
              [--budget=N] [--reps=N] [--output=PATH] [--smoke]
      (Pallas kernel autotuner — paddle_tpu/pallas/tuning: measure tile
       configs over each kernel family's valid space and persist the
       winners into the checked-in tuning database that dispatch
       consults; --smoke runs tiny shapes in interpret mode)
  paddle stats [--json] [--run=script.py] [--file=telemetry.json]
               [--url=http://host:port] [--trace=out.json]
      (snapshot the telemetry registry — paddle_tpu/observability — as
       a human table or JSON; --run execs a fluid script first so its
       Executor.run counters show, --url scrapes a live `paddle serve`
       /stats endpoint, --file renders a bench telemetry artifact,
       --trace also exports the host event ring as Chrome-trace JSON)
  paddle pserver [--port=P] [--checkpoint=PATH] [--checkpoint_sec=S]
  paddle master [--port=P] [--lease_sec=S] [--failure_max=N]
  paddle coord  [--port=P]
"""

import os
import sys

# Honor JAX_PLATFORMS before any backend use: the axon TPU plugin
# registers itself as the default backend regardless of the env var, so
# `JAX_PLATFORMS=cpu paddle train ...` would silently hit the TPU
# tunnel (same dance as tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass


def _kv_args(argv):
    out = {}
    rest = []
    for a in argv:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            out[k] = v
        else:
            rest.append(a)
    return out, rest


def cmd_version(_):
    import jax

    import paddle_tpu

    print(f"paddle_tpu {paddle_tpu.__version__}")
    print(f"  jax {jax.__version__}; backend "
          f"{jax.default_backend()} x{jax.device_count()}")
    return 0


def _cwd_importable():
    # v1 config files import their own package tree relative to the
    # invocation directory (reference: `paddle train` ran from the
    # workdir with PYTHONPATH=.)
    if os.getcwd() not in sys.path:
        sys.path.insert(0, os.getcwd())


def cmd_train(argv):
    _cwd_importable()
    from paddle_tpu.trainer.trainer import main as trainer_main

    return trainer_main(argv)


def cmd_merge_model(argv):
    _cwd_importable()
    args, _ = _kv_args(argv)
    model_dir = args.get("model_dir")
    out = args.get("out")
    if not model_dir or not out:
        print("usage: paddle merge_model --model_dir=DIR --out=OUT_DIR",
              file=sys.stderr)
        return 2
    config = args.get("config") or os.path.join(model_dir, "trainer_config.py")
    from paddle_tpu.trainer.trainer import Trainer
    from paddle_tpu.trainer.config_parser import parse_config
    import paddle_tpu as fluid

    conf = parse_config(config, args.get("config_args", ""))
    t = Trainer(conf)
    t.load_parameters(model_dir)
    t.export_inference_model(out)
    print(f"merged model written to {out}")
    return 0


def _serve(make_server, argv, label):
    import signal
    import threading

    args, _ = _kv_args(argv)
    srv = make_server(args)
    print(f"{label} listening on {srv.address}", flush=True)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    signal.signal(signal.SIGINT, lambda *a: done.set())
    done.wait()
    srv.stop()
    return 0


def _load_generator(args, flags=()):
    """Build a paged-KV GenerationEngine from a --gen_config script.

    The script is exec'd and must define ``make_generator()`` returning
    ``(beam_gen, parameters)`` — a v1 ``beam_search`` spec plus trained
    parameters (see demos/seq2seq/gen_config.py) — or
    ``make_decode_model()`` returning a paged decoder-LM model (the
    path that supports prefix caching and speculative decoding).
    Page-pool geometry comes from the --gen_* flags; ``--beam_max=K``
    enables POST /generate ``{"beam": k}``; ``--prefix_cache`` /
    ``--spec_draft=ngram`` (or a ``make_draft_model()`` in the config)
    turn on prompt-prefix page reuse and speculative decoding for
    models that support them."""
    _cwd_importable()
    from paddle_tpu.decode import GenerationEngine

    path = args["gen_config"]
    glb = {"__file__": path, "__name__": "__paddle_serve_gen__"}
    with open(path) as f:
        exec(compile(f.read(), path, "exec"), glb)
    beam_max = int(args.get("beam_max", 0))
    spec_draft = None
    if "make_draft_model" in glb:
        spec_draft = glb["make_draft_model"]()
    elif args.get("spec_draft") == "ngram":
        from paddle_tpu.decode.spec import NgramDraft

        spec_draft = NgramDraft()
    if "make_decode_model" in glb:
        return GenerationEngine(
            glb["make_decode_model"](),
            max_slots=int(args.get("gen_slots", 8)),
            max_waiting=int(args.get("gen_queue", 64)),
            max_new_tokens=int(args.get("gen_max_tokens", 32)),
            prefix_cache="--prefix_cache" in flags,
            prefix_cache_pages=(int(args["prefix_cache_pages"])
                                if args.get("prefix_cache_pages") else None),
            spec_draft=spec_draft,
            spec_k=int(args.get("spec_k", 4)),
            beam_max=beam_max)
    if "make_generator" not in glb:
        raise RuntimeError(
            f"{path} defines no make_generator() -> (beam_gen, parameters) "
            "and no make_decode_model() -> paged decoder model")
    beam_gen, parameters = glb["make_generator"]()
    return GenerationEngine.for_seq2seq(
        beam_gen, parameters,
        num_pages=int(args.get("gen_pages", 64)),
        page_size=int(args.get("gen_page_size", 8)),
        pages_per_seq=int(args.get("gen_pages_per_seq", 2)),
        max_slots=int(args.get("gen_slots", 8)),
        max_waiting=int(args.get("gen_queue", 64)),
        max_new_tokens=(int(args["gen_max_tokens"])
                        if args.get("gen_max_tokens") else None),
        beam_max=beam_max)


def cmd_serve(argv):
    """paddle serve [--model_dir=DIR] [--port=N] [--replicas=N]
    [--max_batch=N] [--batch_timeout_ms=MS] [--warmup]
    [--artifacts=DIR] [--request_timeout=S] [--max_inflight=N]
    [--tenants=NAME:RATE[:BURST[:WEIGHT]],...] [--tenant_config=FILE]
    [--max_attempts=N] [--replica_heartbeat_ms=MS]
    [--dispatch_timeout=S] [--chaos=KIND[@N[:rIDX]]]
    [--gen_config=SCRIPT --gen_pages=N --gen_page_size=N
     --gen_pages_per_seq=N --gen_slots=N --gen_queue=N
     --gen_max_tokens=N --beam_max=K --prefix_cache
     --prefix_cache_pages=N --spec_draft=ngram --spec_k=N]
    — HTTP inference over a save_inference_model
    export (paddle_tpu/serving): concurrent requests coalesce into
    power-of-two batch buckets dispatched across a pool of executor
    replicas, with graceful-degradation bounds (504 on deadline expiry,
    503 on overload).  Replicas are supervised and self-healing:
    crashed or hung dispatches requeue their batch (up to
    --max_attempts per request) onto a respawned replica.
    --artifacts=DIR boots replicas from a `paddle compile` export:
    warmup deserializes the bucket ladder instead of JIT-compiling it
    (manifest mismatches fall back to JIT loudly — see
    aot_load_total{result} on /metrics).  --tenants
    gives each named tenant a token-bucket admission quota and a
    fair-queue weight ('*' entry templates unknown tenants;
    --tenant_config reads the same spec, one entry per line, from a
    file); --chaos arms a dev-only fault injector (die|raise|hang on
    the Nth dispatch).  With --gen_config, also mounts POST /generate —
    token streaming over the paged-KV continuous-batching decode
    engine (paddle_tpu/decode); --beam_max enables {"beam": k} beam
    search, --prefix_cache shares prompt-prefix KV pages across
    requests, --spec_draft/--spec_k turn on speculative decoding."""
    from paddle_tpu.serving import InferenceServer

    args, rest = _kv_args(argv)
    if not args.get("model_dir") and not args.get("gen_config"):
        print("usage: paddle serve [--model_dir=DIR] [--port=N] "
              "[--replicas=N] [--max_batch=N] [--batch_timeout_ms=MS] "
              "[--warmup] [--request_timeout=SECONDS] [--max_inflight=N] "
              "[--gen_config=SCRIPT ...] (need --model_dir and/or "
              "--gen_config)", file=sys.stderr)
        return 2
    def _tenant_spec(a):
        if a.get("tenant_config"):
            with open(a["tenant_config"]) as fh:
                entries = [ln.strip() for ln in fh
                           if ln.strip() and not ln.startswith("#")]
            return ",".join(entries)
        return a.get("tenants")

    return _serve(
        lambda a: InferenceServer(
            a.get("model_dir"), port=int(a.get("port", 0)),
            request_timeout=(float(a["request_timeout"])
                             if a.get("request_timeout") else None),
            max_inflight=(int(a["max_inflight"])
                          if a.get("max_inflight") else None),
            replicas=int(a.get("replicas", 1)),
            max_batch=int(a.get("max_batch", 8)),
            batch_timeout_ms=float(a.get("batch_timeout_ms", 0.0)),
            warmup="--warmup" in rest,
            tenants=_tenant_spec(a),
            max_attempts=int(a.get("max_attempts", 3)),
            replica_heartbeat_ms=float(a.get("replica_heartbeat_ms",
                                             1000.0)),
            dispatch_timeout=(float(a["dispatch_timeout"])
                              if a.get("dispatch_timeout") else None),
            chaos=a.get("chaos"),
            artifacts=a.get("artifacts"),
            generator=(_load_generator(a, rest) if a.get("gen_config")
                       else None)),
        argv, "inference server")


def cmd_compile(argv):
    """paddle compile --model_dir=DIR --out=DIR [--max_batch=N]
    [--buckets=1,2,4] [--no-optimize] [--gen_config=SCRIPT ...]
    [--smoke] — export AOT serving artifacts (paddle_tpu/aot): the
    bucket-ladder (and decode-step) executables a `paddle serve
    --warmup` boot would JIT-compile, serialized under a versioned
    manifest so `paddle serve --artifacts=DIR` boots without
    compiling.  --smoke runs the self-contained export->boot->parity
    gate CI uses."""
    from paddle_tpu.aot.compile_cli import main as compile_main

    return compile_main(argv)


def cmd_elastic(argv):
    """paddle elastic ... — preemption-safe demo training worker
    (paddle_tpu/distributed/elastic.py)."""
    from paddle_tpu.distributed.elastic import main as elastic_main

    return elastic_main(argv)


def cmd_pserver(argv):
    from paddle_tpu.distributed import ParameterServer

    return _serve(
        lambda a: ParameterServer(port=int(a.get("port", 0)),
                                  checkpoint_path=a.get("checkpoint", ""),
                                  checkpoint_sec=int(a.get("checkpoint_sec", 0))),
        argv, "pserver")


def cmd_master(argv):
    from paddle_tpu.distributed import MasterServer

    return _serve(
        lambda a: MasterServer(port=int(a.get("port", 0)),
                               lease_sec=int(a.get("lease_sec", 10)),
                               failure_max=int(a.get("failure_max", 3))),
        argv, "master")


def cmd_coord(argv):
    from paddle_tpu.distributed import CoordServer

    return _serve(lambda a: CoordServer(port=int(a.get("port", 0))),
                  argv, "coord")


def _lint_load(target, config_args=""):
    """Resolve a lint target to (program, feed_names|None, fetch_names|None).

    ``*.json``: a save_inference_model __model__.json (program + feed/
    fetch lists) or a bare Program.to_dict dump.  ``*.py``: a v1 trainer
    config (parsed and traced to a Program via Topology) or a fluid-style
    script that builds the default main program when exec'd.
    """
    import json

    from paddle_tpu import framework

    if target.endswith(".json"):
        with open(target) as f:
            meta = json.load(f)
        if "program" in meta:
            feeds = meta.get("feed_names")
            return (framework.Program.from_dict(meta["program"]),
                    set(feeds) if feeds is not None else None,
                    meta.get("fetch_names") or None)
        return framework.Program.from_dict(meta), None, None

    _cwd_importable()
    v1_err = None
    try:
        from paddle_tpu.trainer.config_parser import parse_config
        from paddle_tpu.v2.topology import Topology

        conf = parse_config(target, config_args)
        if conf.cost is not None:
            topo = Topology(conf.cost, extra_layers=conf.evaluators)
            fetches = [v.name for v in topo.output_vars]
            return topo.main_program, set(topo.feed_names()), fetches
    except Exception as e:
        v1_err = e  # remember; maybe it's a fluid script instead
    main, startup = framework.Program(), framework.Program()
    try:
        with framework.program_guard(main, startup):
            glb = {"__file__": target, "__name__": "__paddle_lint__"}
            with open(target) as f:
                exec(compile(f.read(), target, "exec"), glb)
    except Exception as e:
        if v1_err is not None:
            raise RuntimeError(
                f"not a v1 config ({type(v1_err).__name__}: {v1_err}) "
                f"nor a fluid script ({type(e).__name__}: {e})") from e
        raise
    if v1_err is not None and not any(b.ops for b in main.blocks):
        # exec "succeeded" but built nothing: the v1 parse error is the
        # real diagnostic, not a silent clean
        raise RuntimeError(
            f"v1 config parse failed: {type(v1_err).__name__}: {v1_err}")
    return main, None, None


def cmd_lint(argv):
    """paddle lint <program.json|config.py> [--level=warning] [--strict]
    [--json] [--fetch=a,b] [--feed=a,b] [--optimize]
    | paddle lint --audit-registry

    Run the static verifier (paddle_tpu/analysis) and print structured
    diagnostics.  Exit 1 when errors fire (or warnings, with --strict).

    ``--optimize`` additionally dry-runs the whole-program optimizer
    (analysis/optimize.py) over each target and prints its report —
    ops removed per pass, constant folds, CSE hits, and the
    donation-safety mask — then re-verifies the rewritten program
    (exit 1 if the optimizer output has any error, which the pipeline's
    internal verify-or-revert gate should make impossible).
    """
    import json as json_mod

    from paddle_tpu import analysis

    args, rest = _kv_args(argv)
    flags = {a for a in rest if a.startswith("--")}
    targets = [a for a in rest if not a.startswith("--")]
    as_json = "--json" in flags
    strict = "--strict" in flags
    do_optimize = "--optimize" in flags

    audit = "--audit-registry" in flags or bool(args.get("audit-registry"))
    diags = []
    if audit:
        diags.extend(analysis.audit_registry())
    if not targets and not audit:
        print("usage: paddle lint <program.json|config.py> "
              "[--level=error|warning|all] [--strict] [--json] "
              "[--fetch=a,b] [--feed=a,b] [--audit-registry]",
              file=sys.stderr)
        return 2

    level = args.get("level", "warning")
    if level not in ("error", "warning", "info", "all"):
        print(f"bad --level={level}; one of error|warning|info|all",
              file=sys.stderr)
        return 2
    unusable = False  # a bad target never downgrades to "clean"
    opt_reports = []  # (target, OptReport) pairs under --optimize
    for target in targets:
        if not os.path.exists(target):
            print(f"lint target not found: {target}", file=sys.stderr)
            unusable = True
            continue
        try:
            program, feeds, fetches = _lint_load(target,
                                                 args.get("config_args", ""))
        except Exception as e:
            print(f"cannot load lint target {target}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            unusable = True
            continue
        if not any(b.ops for b in program.blocks):
            # a target that builds zero ops was not actually analyzed —
            # reporting "clean" here would be a false negative
            print(f"lint target {target} built an empty program "
                  "(no ops); nothing to analyze", file=sys.stderr)
            unusable = True
            continue
        if args.get("feed"):
            feeds = set(args["feed"].split(","))
        if args.get("fetch"):
            fetches = args["fetch"].split(",")
        diags.extend(analysis.verify_program(
            program, feed_names=feeds, fetch_names=fetches, level=level))
        if do_optimize:
            optimized, report = analysis.optimize_program(
                program, feed_names=feeds, fetch_names=fetches)
            opt_reports.append((target, report))
            # the pipeline reverts any pass whose output fails error-
            # tier verification, so errors here mean a gate bug — fail
            # loudly rather than report a broken rewrite as clean
            diags.extend(analysis.verify_program(
                optimized, feed_names=feeds, fetch_names=fetches,
                level="error"))

    if as_json:
        doc = [d.to_dict() for d in diags]
        if do_optimize:
            doc = {"diagnostics": doc,
                   "optimize": {t: r.to_dict() for t, r in opt_reports}}
        print(json_mod.dumps(doc, indent=1))
    elif diags or not unusable:  # no "clean" claim if nothing was analyzed
        for target, report in opt_reports:
            print(f"== optimize: {target}")
            print(report.format())
        print(analysis.format_report(diags))
    if unusable:
        return 2
    bad = [d for d in diags if d.severity == analysis.Severity.ERROR
           or (strict and d.severity == analysis.Severity.WARNING)]
    return 1 if bad else 0


def cmd_stats(argv):
    """paddle stats [--json] [--run=script.py] [--file=artifact.json]
    [--url=http://host:port] [--trace=out.json]

    Dump the observability registry (paddle_tpu/observability): every
    counter/gauge/histogram the executor, serving, and trainer paths
    recorded, as a human table or JSON.  Sources, in precedence order:
    a live server's /stats endpoint (--url), a bench telemetry artifact
    (--file), or this process's registry (optionally after exec'ing a
    fluid script via --run so its Executor.run calls are measured).
    """
    import json as json_mod

    from paddle_tpu import observability as obs

    args, rest = _kv_args(argv)
    as_json = "--json" in rest
    if args.get("trace") and args.get("url"):
        print("--trace: host events live in the server process and are "
              "not exported over /stats; run paddle stats --trace "
              "in-process instead", file=sys.stderr)
        return 2
    if args.get("url"):
        import urllib.request

        url = args["url"].rstrip("/") + "/stats"
        with urllib.request.urlopen(url, timeout=30) as r:
            snap = json_mod.loads(r.read())
    elif args.get("file"):
        with open(args["file"]) as f:
            data = json_mod.load(f)
        # a bench telemetry artifact nests the registry under "metrics";
        # a raw snapshot dump IS the registry
        snap = data.get("metrics", data) or {}
    else:
        if args.get("run"):
            _cwd_importable()
            path = args["run"]
            glb = {"__file__": path, "__name__": "__paddle_stats__"}
            with open(path) as f:
                exec(compile(f.read(), path, "exec"), glb)
        snap = obs.snapshot()
    if as_json:
        print(json_mod.dumps(snap, indent=1, sort_keys=True))
    else:
        table = obs.format_snapshot(snap)
        print(table if table else
              "telemetry registry is empty (no metrics recorded)")
    if args.get("trace"):
        if args.get("file"):
            # a bench artifact embeds its run's Chrome trace — export
            # that, not this CLI process's (empty) event ring
            trace = data.get("events")
            if not trace:
                print(f"--trace: {args['file']} carries no embedded "
                      "host events", file=sys.stderr)
                return 2
            with open(args["trace"], "w") as f:
                json_mod.dump(trace, f)
        else:
            obs.export_chrome_trace(args["trace"])
        print(f"host events written to {args['trace']} "
              "(chrome://tracing)", file=sys.stderr)
    return 0


def cmd_tune(argv):
    from paddle_tpu.pallas.tuning.tune import main as tune_main

    return tune_main(argv)


COMMANDS = {
    "train": cmd_train,
    "version": cmd_version,
    "merge_model": cmd_merge_model,
    "compile": cmd_compile,
    "serve": cmd_serve,
    "lint": cmd_lint,
    "stats": cmd_stats,
    "tune": cmd_tune,
    "pserver": cmd_pserver,
    "master": cmd_master,
    "coord": cmd_coord,
    "elastic": cmd_elastic,
}


def main():
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(__doc__)
        return 0 if len(sys.argv) >= 2 else 2
    cmd = COMMANDS.get(sys.argv[1])
    if cmd is None:
        print(f"unknown command {sys.argv[1]!r}; "
              f"one of {sorted(COMMANDS)}", file=sys.stderr)
        return 2
    return cmd(sys.argv[2:])


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
