#!/usr/bin/env python3
"""The `paddle` command (reference: paddle/scripts/submit_local.sh.in —
the shell wrapper exposing train / version / merge_model; plus the
TPU-era additions: pserver / master / coord service launchers).

Subcommands:
  paddle train --config=conf.py [--num_passes=N] [--save_dir=D] [--config_args=k=v,...]
  paddle version
  paddle merge_model --model_dir=DIR --out=OUT_DIR [--config_args=...]
      (reference `paddle merge_model` fused config+params into one
       binary for the C API; here: re-parse the v1 config, load the
       pass params, export a save_inference_model directory that
       capi/paddle_tpu_capi.h consumes)
  paddle serve --model_dir=DIR [--port=N]
      (HTTP JSON inference over a save_inference_model export —
       paddle_tpu/serving.py)
  paddle pserver [--port=P] [--checkpoint=PATH] [--checkpoint_sec=S]
  paddle master [--port=P] [--lease_sec=S] [--failure_max=N]
  paddle coord  [--port=P]
"""

import os
import sys

# Honor JAX_PLATFORMS before any backend use: the axon TPU plugin
# registers itself as the default backend regardless of the env var, so
# `JAX_PLATFORMS=cpu paddle train ...` would silently hit the TPU
# tunnel (same dance as tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass


def _kv_args(argv):
    out = {}
    rest = []
    for a in argv:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            out[k] = v
        else:
            rest.append(a)
    return out, rest


def cmd_version(_):
    import jax

    import paddle_tpu

    print(f"paddle_tpu {paddle_tpu.__version__}")
    print(f"  jax {jax.__version__}; backend "
          f"{jax.default_backend()} x{jax.device_count()}")
    return 0


def _cwd_importable():
    # v1 config files import their own package tree relative to the
    # invocation directory (reference: `paddle train` ran from the
    # workdir with PYTHONPATH=.)
    if os.getcwd() not in sys.path:
        sys.path.insert(0, os.getcwd())


def cmd_train(argv):
    _cwd_importable()
    from paddle_tpu.trainer.trainer import main as trainer_main

    return trainer_main(argv)


def cmd_merge_model(argv):
    _cwd_importable()
    args, _ = _kv_args(argv)
    model_dir = args.get("model_dir")
    out = args.get("out")
    if not model_dir or not out:
        print("usage: paddle merge_model --model_dir=DIR --out=OUT_DIR",
              file=sys.stderr)
        return 2
    config = args.get("config") or os.path.join(model_dir, "trainer_config.py")
    from paddle_tpu.trainer.trainer import Trainer
    from paddle_tpu.trainer.config_parser import parse_config
    import paddle_tpu as fluid

    conf = parse_config(config, args.get("config_args", ""))
    t = Trainer(conf)
    t.load_parameters(model_dir)
    t.export_inference_model(out)
    print(f"merged model written to {out}")
    return 0


def _serve(make_server, argv, label):
    import signal
    import threading

    args, _ = _kv_args(argv)
    srv = make_server(args)
    print(f"{label} listening on {srv.address}", flush=True)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    signal.signal(signal.SIGINT, lambda *a: done.set())
    done.wait()
    srv.stop()
    return 0


def cmd_serve(argv):
    """paddle serve --model_dir=DIR [--port=N] — HTTP inference over a
    save_inference_model export (paddle_tpu/serving.py)."""
    from paddle_tpu.serving import InferenceServer

    args, _ = _kv_args(argv)
    if not args.get("model_dir"):
        print("usage: paddle serve --model_dir=DIR [--port=N]",
              file=sys.stderr)
        return 2
    return _serve(
        lambda a: InferenceServer(a["model_dir"],
                                  port=int(a.get("port", 0))),
        argv, "inference server")


def cmd_pserver(argv):
    from paddle_tpu.distributed import ParameterServer

    return _serve(
        lambda a: ParameterServer(port=int(a.get("port", 0)),
                                  checkpoint_path=a.get("checkpoint", ""),
                                  checkpoint_sec=int(a.get("checkpoint_sec", 0))),
        argv, "pserver")


def cmd_master(argv):
    from paddle_tpu.distributed import MasterServer

    return _serve(
        lambda a: MasterServer(port=int(a.get("port", 0)),
                               lease_sec=int(a.get("lease_sec", 10)),
                               failure_max=int(a.get("failure_max", 3))),
        argv, "master")


def cmd_coord(argv):
    from paddle_tpu.distributed import CoordServer

    return _serve(lambda a: CoordServer(port=int(a.get("port", 0))),
                  argv, "coord")


COMMANDS = {
    "train": cmd_train,
    "version": cmd_version,
    "merge_model": cmd_merge_model,
    "serve": cmd_serve,
    "pserver": cmd_pserver,
    "master": cmd_master,
    "coord": cmd_coord,
}


def main():
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(__doc__)
        return 0 if len(sys.argv) >= 2 else 2
    cmd = COMMANDS.get(sys.argv[1])
    if cmd is None:
        print(f"unknown command {sys.argv[1]!r}; "
              f"one of {sorted(COMMANDS)}", file=sys.stderr)
        return 2
    return cmd(sys.argv[2:])


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
