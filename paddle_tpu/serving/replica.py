"""Executor replica pool for the serving engine.

Each ``Replica`` owns its own ``Scope`` (its own parameter buffers,
freshly loaded from the export) and its own compiling ``Executor`` —
the same zero-shared-mutable-state cloning shape the C API proved with
``pd_machine_clone`` (capi multi_thread example, commit ``dc29a77``):
nothing is locked because nothing is shared.  The one deliberately
shared object is the parsed ``Program`` IR, which is read-only after
``BatchSpec`` propagation; sharing it keeps every replica's compile
cache and telemetry keyed by the *same* program fingerprint, and lets
the persistent XLA cache dedupe replicas 2..N's compiles.

Workers pull dispatch groups from the ``RequestQueue``: while replica A
is inside an XLA step, admission and batch formation continue and
replica B takes the next bucket — admission, batching, and device
dispatch overlap instead of serializing behind one lock.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

from paddle_tpu.serving.batching import (
    BatchSpec,
    PendingRequest,
    RequestQueue,
    _M_BATCH_ROWS,
    _M_UNBATCHED,
    bucket_ladder,
    coalesce,
    scatter,
)


class ModelBundle:
    """One parse of a save_inference_model export, shared by replicas.

    The Program IR is immutable after load (+ shape propagation); each
    replica loads its *own* copy of the parameters from the manifest.
    """

    def __init__(self, model_dir: str, optimize: bool = True):
        from paddle_tpu import io

        self.model_dir = model_dir
        self.program, feed_names, fetch_names, self.param_names = \
            io.read_inference_export(model_dir)
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.opt_report = None
        if optimize:
            # every replica serves the optimized program: the rewrite
            # runs ONCE here and the shared IR keeps all replicas on one
            # fingerprint (one compile-cache entry, one telemetry key).
            # The pipeline is parity-gated internally; any failure falls
            # back to the loaded program untouched.
            from paddle_tpu import analysis

            try:
                self.program, self.opt_report = analysis.optimize_program(
                    self.program, feed_names=set(self.feed_names),
                    fetch_names=self.fetch_names)
            except Exception:
                self.opt_report = None

    def batch_spec(self) -> BatchSpec:
        return BatchSpec.from_program(self.program, self.feed_names,
                                      self.fetch_names)

    def load_params_into(self, scope) -> None:
        from paddle_tpu import io

        for name in self.param_names:
            scope.set(name, io.load_exported_param(self.model_dir, name))


class Replica:
    """One worker clone: private Scope + private Executor."""

    def __init__(self, bundle: ModelBundle, index: int, place=None):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod

        self.index = index
        self.bundle = bundle
        self.scope = executor_mod.Scope()
        bundle.load_params_into(self.scope)
        self.exe = fluid.Executor(place if place is not None
                                  else fluid.TPUPlace())

    def run(self, feeds) -> list:
        # scope passed explicitly: scope_guard would mutate the
        # process-global scope stack from a worker thread
        return list(self.exe.run(self.bundle.program, feed=feeds,
                                 fetch_list=list(self.bundle.fetch_names),
                                 scope=self.scope))


class ReplicaPool:
    """N replicas pulling coalesced batches from one RequestQueue."""

    def __init__(self, bundle: ModelBundle, queue: RequestQueue,
                 spec: BatchSpec, replicas: int = 1, place=None):
        self.bundle = bundle
        self.queue = queue
        self.spec = spec
        self.replicas = [Replica(bundle, i, place)
                         for i in range(max(1, int(replicas)))]
        self._threads = [
            threading.Thread(target=self._worker, args=(rep,), daemon=True,
                             name=f"serving-replica-{rep.index}")
            for rep in self.replicas
        ]
        for t in self._threads:
            t.start()

    # -- lifecycle ----------------------------------------------------------

    def pause(self) -> None:
        """Stop workers from taking new batches (drain / maintenance /
        deterministic overload in tests).  In-flight batches finish;
        queued requests wait and expire against their deadlines."""
        self.queue.pause()

    def resume(self) -> None:
        self.queue.resume()

    def stop(self) -> None:
        self.queue.close()
        for t in self._threads:
            t.join(timeout=30)

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> int:
        """Pre-compile the bucket ladder on every replica with synthetic
        batches (zeros), so live traffic starts at cache-hit steady
        state.  Returns the number of (replica, bucket) programs run."""
        if not self.spec.batchable:
            return 0
        buckets = tuple(buckets or bucket_ladder(self.queue.max_batch))

        def _one(rep):
            for b in buckets:
                feeds = {
                    name: np.zeros((b,) + self.spec.row_shapes[name],
                                   dtype=self.spec.dtypes[name])
                    for name in self.spec.feed_names
                }
                rep.run(feeds)

        threads = [threading.Thread(target=_one, args=(rep,))
                   for rep in self.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return len(buckets) * len(self.replicas)

    # -- worker loop --------------------------------------------------------

    def _worker(self, rep: Replica) -> None:
        while True:
            batch = self.queue.take()
            if batch is None:
                return
            self._execute(rep, batch)

    def _execute(self, rep: Replica, batch: List[PendingRequest]) -> None:
        try:
            if len(batch) == 1 and not batch[0].batchable:
                # legacy exact-shape path: ragged/LoD/odd-shaped request.
                # Counted by reason so the ragged-gap closure (paged
                # decode taking these workloads) is measurable on
                # /metrics before/after.
                req = batch[0]
                _M_BATCH_ROWS.observe(req.rows, bucket="unbatched")
                _M_UNBATCHED.inc(reason=req.solo_reason)
                req.complete(rep.run(req.feeds))
                return
            feeds, rows, bucket = coalesce(batch, self.spec)
            _M_BATCH_ROWS.observe(rows, bucket=str(bucket))
            for req in batch:
                req.bucket = bucket
            outs = rep.run(feeds)
            scatter(batch, outs, bucket)
        except BaseException as exc:  # noqa: BLE001 - surfaced per waiter
            for req in batch:
                req.fail(exc)
