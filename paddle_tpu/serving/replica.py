"""Executor replica pool for the serving engine.

Each ``Replica`` owns its own ``Scope`` (its own parameter buffers,
freshly loaded from the export) and its own compiling ``Executor`` —
the same zero-shared-mutable-state cloning shape the C API proved with
``pd_machine_clone`` (capi multi_thread example, commit ``dc29a77``):
nothing is locked because nothing is shared.  The one deliberately
shared object is the parsed ``Program`` IR, which is read-only after
``BatchSpec`` propagation; sharing it keeps every replica's compile
cache and telemetry keyed by the *same* program fingerprint, and lets
the persistent XLA cache dedupe replicas 2..N's compiles.

Workers pull dispatch groups from the ``RequestQueue``: while replica A
is inside an XLA step, admission and batch formation continue and
replica B takes the next bucket — admission, batching, and device
dispatch overlap instead of serializing behind one lock.

Self-healing (PR 19) ports the lease/sweep shape of
``distributed/elastic.py`` into this pool:

- every worker stamps a **heartbeat** before each dispatch and holds an
  in-flight lease ``(batch, started_at)`` while inside ``Executor.run``;
- a **supervisor** thread sweeps those leases: a dispatch that outlives
  ``dispatch_timeout`` (a hung device / injected hang) or raises a
  non-request error marks the replica dead, **requeues** the in-flight
  batch, and schedules a replacement ``Replica`` (fresh Scope + fresh
  Executor) behind ``RetryPolicy`` backoff and a sliding-window
  restart-rate limit;
- requeued requests carry a bounded ``attempts`` counter (stamped at
  ``take()``): a request that keeps killing replicas is quarantined
  after ``max_attempts`` with a 503 ``retry_exhausted`` instead of
  grinding the pool down forever, and requeued work is redispatched
  *solo* so one poison row can't take innocent batchmates with it
  twice.

A replica marked dead while its thread is wedged becomes a **zombie**:
the thread is left to finish (or hang) on its own, and any completions
it produces later are harmless because ``PendingRequest.complete`` is
first-wins and the queue sweep skips ``done`` requests.

``FaultInjector`` is the test/chaos hook: arm it to make dispatch N
raise, hang, or hard-die, from ``tests/test_serving_selfheal.py`` and
``benchmark/serving_chaos_bench.py``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.distributed.retry import RetryPolicy
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.serving.batching import (
    BatchSpec,
    PendingRequest,
    RequestQueue,
    RetryExhausted,
    _M_BATCH_ROWS,
    _M_UNBATCHED,
    bucket_ladder,
    coalesce,
    scatter,
)

_M_RESTARTS = _metrics.counter(
    "serving_replica_restarts_total",
    "replicas respawned by the serving supervisor")
_M_DEATHS = _metrics.counter(
    "serving_replica_deaths_total",
    "replicas declared dead, labeled by cause (exception|hang|injected)")
_M_REQUEUED = _metrics.counter(
    "serving_requeued_total",
    "in-flight requests requeued after losing their replica")
_M_LIVE = _metrics.gauge(
    "serving_replicas_live", "replicas currently taking batches")
_M_TTR = _metrics.histogram(
    "serving_time_to_ready_seconds",
    "warmup() wall time until every replica's bucket ladder is "
    "compiled, labeled by boot source (aot = every program loaded "
    "from the artifact store, jit = every program traced+compiled, "
    "mixed = partial artifact coverage)",
    buckets=_metrics.COMPILE_TIME_BUCKETS)

#: Errors attributed to the *request* (malformed feed dict, bad dtype,
#: shape mismatch at scatter): fail the waiters, keep the replica.  An
#: executor that raises anything else has unknown internal state and is
#: replaced rather than trusted with the next batch.
_REQUEST_ERRORS = (KeyError, ValueError, TypeError)

#: Backoff between respawns of the same pool (attempt index = restarts
#: inside the sliding window), mirroring SUPERVISOR_POLICY's patience.
RESPAWN_POLICY = RetryPolicy(max_attempts=64, base_delay=0.05,
                             max_delay=2.0, jitter=0.25)


class ReplicaDied(RuntimeError):
    """Raised inside a worker by an injected hard death (the in-process
    stand-in for SIGKILL: the dispatch never returns a result)."""


class FaultInjector:
    """Deterministic dispatch-time fault hook for chaos tests/benches.

    ``kind``:

    - ``"raise"`` — dispatch raises ``RuntimeError`` (replica-fatal);
    - ``"die"``   — dispatch raises ``ReplicaDied``, modeling a worker
      killed mid-flight (no partial results, lease left dangling);
    - ``"hang"``  — dispatch sleeps ``hang_s`` seconds, modeling a
      wedged device; the supervisor must detect it via the lease.

    The fault fires on the ``nth`` armed dispatch (1-based, counted
    across the pool, or only on ``replica`` when given) and only while
    armed — pools arm the injector *after* warmup so compile traffic
    can't eat the fault.  One-shot by default (``repeat=False``).
    """

    def __init__(self, kind: str, nth: int = 1,
                 replica: Optional[int] = None, hang_s: float = 5.0,
                 repeat: bool = False, armed: bool = False):
        if kind not in ("raise", "die", "hang"):
            raise ValueError(f"unknown fault kind: {kind!r}")
        self.kind = kind
        self.nth = max(1, int(nth))
        self.replica = replica
        self.hang_s = float(hang_s)
        self.repeat = bool(repeat)
        self._armed = bool(armed)
        self._count = 0
        self._fired = 0
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: str) -> "FaultInjector":
        """Parse ``KIND[@N[:rIDX]]``, e.g. ``die@5`` (5th dispatch dies)
        or ``hang@3:r1`` (replica 1's 3rd armed dispatch hangs).
        Returns a disarmed injector; the server arms a ``--chaos``
        spec itself once construction (and warmup) is done."""
        kind, _, rest = spec.strip().partition("@")
        nth, replica = 1, None
        if rest:
            nth_s, _, rep_s = rest.partition(":")
            nth = int(nth_s or 1)
            if rep_s:
                replica = int(rep_s.lstrip("r"))
        return cls(kind, nth=nth, replica=replica)

    def arm(self) -> None:
        with self._lock:
            self._armed = True
            self._count = 0

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    @property
    def fired(self) -> int:
        return self._fired

    def before_dispatch(self, replica_index: int) -> None:
        with self._lock:
            if not self._armed:
                return
            if self.replica is not None and replica_index != self.replica:
                return
            self._count += 1
            if self._count != self.nth:
                return
            self._fired += 1
            if self.repeat:
                self._count = 0
            else:
                self._armed = False
        if self.kind == "hang":
            time.sleep(self.hang_s)
            return
        if self.kind == "die":
            raise ReplicaDied(
                f"injected death on replica {replica_index}")
        raise RuntimeError(
            f"injected dispatch failure on replica {replica_index}")


class ModelBundle:
    """One parse of a save_inference_model export, shared by replicas.

    The Program IR is immutable after load (+ shape propagation); each
    replica loads its *own* copy of the parameters from the manifest.
    """

    def __init__(self, model_dir: str, optimize: bool = True):
        from paddle_tpu import io

        self.model_dir = model_dir
        self.program, feed_names, fetch_names, self.param_names = \
            io.read_inference_export(model_dir)
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.opt_report = None
        if optimize:
            # every replica serves the optimized program: the rewrite
            # runs ONCE here and the shared IR keeps all replicas on one
            # fingerprint (one compile-cache entry, one telemetry key).
            # The pipeline is parity-gated internally; any failure falls
            # back to the loaded program untouched.
            from paddle_tpu import analysis

            try:
                self.program, self.opt_report = analysis.optimize_program(
                    self.program, feed_names=set(self.feed_names),
                    fetch_names=self.fetch_names)
            except Exception:
                self.opt_report = None

    def batch_spec(self) -> BatchSpec:
        return BatchSpec.from_program(self.program, self.feed_names,
                                      self.fetch_names)

    def load_params_into(self, scope) -> None:
        from paddle_tpu import io

        for name in self.param_names:
            scope.set(name, io.load_exported_param(self.model_dir, name))


class Replica:
    """One worker clone: private Scope + private Executor."""

    def __init__(self, bundle: ModelBundle, index: int, place=None,
                 fault: Optional[FaultInjector] = None, store=None):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod

        self.index = index
        self.bundle = bundle
        self.fault = fault
        self.scope = executor_mod.Scope()
        bundle.load_params_into(self.scope)
        self.exe = fluid.Executor(place if place is not None
                                  else fluid.TPUPlace())
        # artifact-booted replica: the executor consults this store at
        # every compile-cache miss before tracing (paddle_tpu/aot)
        self.exe.aot_store = store

    def run(self, feeds) -> list:
        if self.fault is not None:
            self.fault.before_dispatch(self.index)
        # scope passed explicitly: scope_guard would mutate the
        # process-global scope stack from a worker thread
        return list(self.exe.run(self.bundle.program, feed=feeds,
                                 fetch_list=list(self.bundle.fetch_names),
                                 scope=self.scope))


class ReplicaPool:
    """N supervised replicas pulling coalesced batches from one queue."""

    def __init__(self, bundle: ModelBundle, queue: RequestQueue,
                 spec: BatchSpec, replicas: int = 1, place=None,
                 fault: Optional[FaultInjector] = None,
                 max_attempts: int = 3, heartbeat: float = 1.0,
                 dispatch_timeout: Optional[float] = None,
                 respawn_policy: RetryPolicy = RESPAWN_POLICY,
                 max_restarts: int = 8, restart_window: float = 60.0,
                 supervise: bool = True, artifact_store=None):
        self.bundle = bundle
        self.queue = queue
        self.spec = spec
        self._place = place
        self.fault = fault
        self.artifact_store = artifact_store
        self.configured = max(1, int(replicas))
        self.max_attempts = max(1, int(max_attempts))
        self.heartbeat = max(0.01, float(heartbeat))
        # a dispatch is a single XLA step; anything resembling the
        # elastic lease TTL (heartbeat x N) past it is a wedged device,
        # floored so slow first compiles never read as hangs.
        self.dispatch_timeout = (float(dispatch_timeout)
                                 if dispatch_timeout
                                 else max(30.0, self.heartbeat * 30.0))
        self.respawn_policy = respawn_policy
        self.max_restarts = max(1, int(max_restarts))
        self.restart_window = float(restart_window)

        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._live: Dict[int, Replica] = {}
        self._threads: Dict[int, threading.Thread] = {}
        self._dead: set = set()
        self._inflight: Dict[int, Tuple[List[PendingRequest], float]] = {}
        self._beats: Dict[int, float] = {}
        self._next_index = 0
        self._pending_respawns = 0
        self._next_respawn_at = 0.0
        self._restarts: Deque[float] = collections.deque()
        self._restarts_total = 0
        self._budget_exhausted = False

        for _ in range(self.configured):
            rep = Replica(bundle, self._next_index, place, fault=fault,
                          store=artifact_store)
            self._next_index += 1
            self._spawn_worker(rep)
        _M_LIVE.set(len(self._live))

        self._supervisor_thread = None
        if supervise:
            self._supervisor_thread = threading.Thread(
                target=self._supervise, daemon=True,
                name="serving-supervisor")
            self._supervisor_thread.start()

    # -- introspection -------------------------------------------------------

    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._live.values())

    def info(self) -> dict:
        with self._lock:
            return {
                "configured": self.configured,
                "live": len(self._live),
                "dead": len(self._dead),
                "restarts": self._restarts_total,
                "pending_respawns": self._pending_respawns,
                "max_attempts": self.max_attempts,
                "heartbeat_s": self.heartbeat,
                "dispatch_timeout_s": self.dispatch_timeout,
                "restart_budget_exhausted": self._budget_exhausted,
            }

    def degraded_reasons(self) -> List[str]:
        """Why /health should say ``degraded`` (empty list = healthy)."""
        reasons = []
        with self._lock:
            live = len(self._live)
            if live < self.configured:
                reasons.append(f"replicas_down:{self.configured - live}")
            if live == 0:
                reasons.append("no_live_replicas")
            if self._budget_exhausted and self._pending_respawns:
                reasons.append("restart_budget_exhausted")
        return reasons

    # -- lifecycle ----------------------------------------------------------

    def pause(self) -> None:
        """Stop workers from taking new batches (drain / maintenance /
        deterministic overload in tests).  In-flight batches finish;
        queued requests wait and expire against their deadlines."""
        self.queue.pause()

    def resume(self) -> None:
        self.queue.resume()

    def stop(self) -> None:
        self._stopping.set()
        self.queue.close()
        with self._lock:
            threads = dict(self._threads)
            dead = set(self._dead)
        for idx, t in threads.items():
            # zombie threads (hung dispatch) are daemons: don't let one
            # wedge shutdown for its full hang
            t.join(timeout=1.0 if idx in dead else 30.0)
        if self._supervisor_thread is not None:
            self._supervisor_thread.join(timeout=5.0)

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> int:
        """Pre-compile the bucket ladder on every replica with synthetic
        batches (zeros), so live traffic starts at cache-hit steady
        state.  Returns the number of (replica, bucket) programs run.

        The wall time lands in ``serving_time_to_ready_seconds{boot=}``:
        ``aot`` when every program came out of the artifact store,
        ``jit`` when every one was traced+compiled, ``mixed`` for
        partial coverage — the before/after of ``paddle compile``."""
        if not self.spec.batchable:
            return 0
        buckets = tuple(buckets or bucket_ladder(self.queue.max_batch))
        reps = self.replicas
        t0 = time.monotonic()

        def _one(rep):
            for b in buckets:
                feeds = {
                    name: np.zeros((b,) + self.spec.row_shapes[name],
                                   dtype=self.spec.dtypes[name])
                    for name in self.spec.feed_names
                }
                rep.run(feeds)

        threads = [threading.Thread(target=_one, args=(rep,))
                   for rep in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _M_TTR.observe(time.monotonic() - t0, boot=self.boot_source())
        return len(buckets) * len(reps)

    def boot_source(self) -> str:
        """``aot`` / ``jit`` / ``mixed``: where the live replicas'
        compiled programs came from (their executors' compile counts)."""
        jit = aot = 0
        for rep in self.replicas:
            counts = getattr(rep.exe, "compile_counts", None) or {}
            jit += counts.get("jit", 0)
            aot += counts.get("aot", 0)
        if aot and not jit:
            return "aot"
        if jit and not aot:
            return "jit"
        return "mixed" if (jit and aot) else "jit"

    # -- worker loop --------------------------------------------------------

    def _spawn_worker(self, rep: Replica) -> None:
        t = threading.Thread(target=self._worker, args=(rep,), daemon=True,
                             name=f"serving-replica-{rep.index}")
        with self._lock:
            self._live[rep.index] = rep
            self._threads[rep.index] = t
            self._beats[rep.index] = time.monotonic()
        t.start()

    def _worker(self, rep: Replica) -> None:
        idx = rep.index
        while True:
            with self._lock:
                if idx in self._dead:
                    return
                self._beats[idx] = time.monotonic()
            batch = self.queue.take()
            if batch is None:
                return
            # a requeued request may have been completed by a zombie of
            # the replica that originally took it — don't run it twice
            batch = [r for r in batch if not r.done]
            if not batch:
                continue
            with self._lock:
                swept = idx in self._dead
                if not swept:
                    self._inflight[idx] = (batch, time.monotonic())
            if swept:
                # declared dead between take() and here: hand the work
                # back untouched (attempts were already stamped; the
                # requeue path tolerates that)
                self.queue.requeue(batch)
                return
            try:
                self._execute(rep, batch)
            except BaseException as exc:  # noqa: BLE001 - replica-fatal
                cause = ("injected" if isinstance(exc, ReplicaDied)
                         else "exception")
                self._mark_dead(idx, cause=cause, exc=exc)
                return
            finally:
                with self._lock:
                    self._inflight.pop(idx, None)
            with self._lock:
                if idx in self._dead:
                    # hang-swept while executing: our completions stand
                    # (first-wins) but a zombie takes no more work
                    return

    def _execute(self, rep: Replica, batch: List[PendingRequest]) -> None:
        try:
            if len(batch) == 1 and not batch[0].batchable:
                # legacy exact-shape path: ragged/LoD/odd-shaped request.
                # Counted by reason so the ragged-gap closure (paged
                # decode taking these workloads) is measurable on
                # /metrics before/after.
                req = batch[0]
                _M_BATCH_ROWS.observe(req.rows, bucket="unbatched")
                _M_UNBATCHED.inc(reason=req.solo_reason)
                req.complete(rep.run(req.feeds))
                return
            feeds, rows, bucket = coalesce(batch, self.spec)
            _M_BATCH_ROWS.observe(rows, bucket=str(bucket))
            for req in batch:
                req.bucket = bucket
            outs = rep.run(feeds)
            scatter(batch, outs, bucket)
        except _REQUEST_ERRORS as exc:
            # the request's fault, not the replica's: fail the waiters,
            # keep serving
            for req in batch:
                req.fail(exc)

    # -- supervision --------------------------------------------------------

    def _mark_dead(self, index: int, cause: str,
                   exc: Optional[BaseException] = None) -> None:
        with self._lock:
            rep = self._live.pop(index, None)
            if rep is None:
                return  # already swept by the other path
            batch, _ = self._inflight.pop(index, (None, 0.0))
            self._dead.add(index)
            self._beats.pop(index, None)
            self._pending_respawns += 1
            now = time.monotonic()
            streak = sum(1 for t in self._restarts
                         if now - t <= self.restart_window)
            self._next_respawn_at = max(
                self._next_respawn_at,
                now + self.respawn_policy.for_attempt(streak))
            live = len(self._live)
        _M_DEATHS.inc(cause=cause)
        _M_LIVE.set(live)
        if batch:
            self._requeue_batch(batch, exc)

    def _requeue_batch(self, batch: List[PendingRequest],
                       exc: Optional[BaseException]) -> None:
        retry: List[PendingRequest] = []
        for req in batch:
            if req.done:
                continue
            if req.attempts >= self.max_attempts:
                req.fail(RetryExhausted(
                    f"request quarantined after {req.attempts} dispatch "
                    f"attempts, each of which lost its replica "
                    f"(last error: {exc!r})"))
                continue
            # redispatch solo so a poison row can't take a second set of
            # innocent batchmates down with it
            req.batchable = False
            req.solo_reason = "requeued"
            retry.append(req)
        if retry:
            _M_REQUEUED.inc(len(retry))
            self.queue.requeue(retry)

    def _supervise(self) -> None:
        while not self._stopping.wait(min(self.heartbeat, 0.25)):
            now = time.monotonic()
            with self._lock:
                hung = [idx for idx, (_, t0) in self._inflight.items()
                        if idx in self._live
                        and now - t0 > self.dispatch_timeout]
            for idx in hung:
                self._mark_dead(
                    idx, cause="hang",
                    exc=TimeoutError(
                        f"replica {idx} dispatch exceeded "
                        f"{self.dispatch_timeout:.1f}s lease"))
            self._maybe_respawn()

    def _maybe_respawn(self) -> None:
        with self._lock:
            if self._pending_respawns <= 0 or self._stopping.is_set():
                return
            now = time.monotonic()
            while (self._restarts and
                   now - self._restarts[0] > self.restart_window):
                self._restarts.popleft()
            if len(self._restarts) >= self.max_restarts:
                self._budget_exhausted = True
                return
            self._budget_exhausted = False
            if now < self._next_respawn_at:
                return
            self._pending_respawns -= 1
            index = self._next_index
            self._next_index += 1
            self._restarts.append(now)
        try:
            rep = Replica(self.bundle, index, self._place, fault=self.fault,
                          store=self.artifact_store)
        except Exception:
            # params/device unavailable right now: put the slot back and
            # retry next sweep with more backoff
            with self._lock:
                self._pending_respawns += 1
                self._next_respawn_at = (
                    time.monotonic() +
                    self.respawn_policy.for_attempt(len(self._restarts)))
            return
        if self._stopping.is_set():
            return
        self._spawn_worker(rep)
        with self._lock:
            self._restarts_total += 1
            live = len(self._live)
        _M_RESTARTS.inc()
        _M_LIVE.set(live)
