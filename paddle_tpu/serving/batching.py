"""Bucketed request coalescing for the serving engine.

Continuous batching, the way a static-shape compiler wants it: concurrent
single-row (or few-row) ``/predict`` requests are coalesced into one
padded batch at a small set of power-of-two *bucket* shapes, so the
Executor's compile cache holds exactly one XLA program per
(program-fingerprint, bucket) key and steady-state traffic never
re-traces.  The scheduling shape follows the continuous/ragged-batch
ideas in "Ragged Paged Attention" (PAPERS.md): admission, batch
formation, and device dispatch overlap — a worker that frees up takes
whatever compatible requests are queued *right now* (no mandatory
linger), so light traffic keeps single-request latency and heavy
traffic amortizes dispatch across the batch.

Pieces:

- ``BatchSpec`` — the *bucketer's* static decision: does the loaded
  program admit row coalescing at all?  It trusts verifier shape
  metadata (``Variable.shape``/``lod_level``, backfilled by the op
  registry's ``infer_shape`` rules — paddle_tpu/analysis registry
  ratchet): every feed and every fetch must be batch-major
  (leading dim -1, static trailing dims, lod_level 0).  Programs that
  fail the test (ragged feeds, scalar/reduced fetches, LoD outputs)
  still serve — each request just executes solo, exactly as the
  pre-batching server did.
- ``PendingRequest`` — one waiter: converted feeds, row span, deadline,
  tenant id + dispatch-attempt counter (the self-healing pool requeues
  a dead replica's in-flight batch), and a completion event the HTTP
  handler blocks on.
- ``RequestQueue`` — the bounded coalescing queue replica workers pull
  from: ``take()`` groups compatible pending requests up to
  ``max_batch`` rows (optionally lingering ``batch_timeout`` seconds to
  fill a bucket) and expires requests whose deadline passed while
  queued.
- ``coalesce``/``scatter`` — pad rows up to the bucket (replicating the
  last real row, so padding can never create NaN/Inf out of thin air)
  and slice each fetch back to the right waiter.

Multi-tenancy (ISSUE 19): requests carry a tenant id and admission is
no longer one global pool.  ``TenantQuota`` is a per-tenant token
bucket (``rate`` tokens/s refill capped at ``burst`` — an idle tenant
can never bank more than its burst) and a fair-share ``weight``;
``TenantRegistry`` holds the configured tenants plus a ``"*"``
template for tenants first seen at runtime.  Over-quota submissions
raise ``TenantOverQuota`` (HTTP 429) at admission, and dequeue order
is weighted-fair: each request gets a virtual finish time
``vft = max(tenant_vft, queue_vclock) + rows / weight`` at submit, and
``take()`` serves in vft order — under saturation each tenant's
completed rate converges to its weight share, while a lone tenant
sees plain FIFO (zero scheduling overhead when there is no
contention).  Under sustained queue pressure (``shed_watermark``)
the queue sheds lowest-weight tenants first (``QueueShed``, HTTP 503)
before collapsing into shedding everyone at twice the watermark.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.pallas.tuning import bucket as _bucket

_M_QUEUE_WAIT = _metrics.histogram(
    "serving_queue_wait_seconds",
    "time a request spends queued before a replica takes it")
_M_BATCH_ROWS = _metrics.histogram(
    "serving_batch_size",
    "coalesced request rows per executed batch "
    "(label bucket = padded rows dispatched, 'unbatched' = solo path)",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0))
_M_UNBATCHED = _metrics.counter(
    "serving_unbatched_total",
    "solo-fallback dispatches by reason (the BatchSpec disabled() "
    "family: lod_feed/lod_fetch/not_batch_major/... when the model "
    "cannot batch at all, shape_mismatch when this request's shapes "
    "did not fit an otherwise batchable model, requeued when a "
    "replica death sent the request back for solo redispatch)")
_M_TENANT_DEPTH = _metrics.gauge(
    "serving_tenant_queue_depth",
    "queued requests per tenant (weighted-fair scheduling input)")

#: Tenant id used when a request names none (no X-Tenant header, no
#: "tenant" payload key).
DEFAULT_TENANT = "default"


class TenantOverQuota(RuntimeError):
    """The tenant's token bucket is empty — HTTP 429, their burst
    degrades *their* latency instead of starving other tenants."""

    def __init__(self, tenant: str, message: str):
        super().__init__(message)
        self.tenant = tenant


class QueueShed(RuntimeError):
    """Load-shedding admission refusal under sustained queue pressure
    (HTTP 503): ``reason`` is ``shed_low_weight`` (lowest-weight
    tenants go first) or ``queue_collapse`` (everyone, at twice the
    watermark)."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class RetryExhausted(RuntimeError):
    """A request burned its dispatch-attempt budget (every attempt
    killed or lost a replica) and is quarantined — HTTP 503 naming the
    reason, never an infinite redispatch of a poison batch."""

    reason = "retry_exhausted"


class TenantQuota:
    """One tenant's admission policy: token bucket + fair-share weight.

    ``rate`` is tokens (requests) per second, ``burst`` the bucket
    capacity; ``rate=None`` means unmetered (the bucket never empties).
    Refill is lazy (computed from elapsed wall time at each take) and
    clamped at ``burst``, so an idle tenant's unused tokens never
    accumulate past one burst.
    """

    __slots__ = ("name", "rate", "burst", "weight", "tokens", "_last",
                 "vft")

    def __init__(self, name: str, rate: Optional[float] = None,
                 burst: Optional[float] = None, weight: float = 1.0):
        self.name = name
        self.rate = float(rate) if rate else None
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"tenant {name!r}: rate must be > 0")
        self.burst = float(burst) if burst is not None else (
            max(self.rate, 1.0) if self.rate is not None else 0.0)
        if self.rate is not None and self.burst < 1.0:
            raise ValueError(f"tenant {name!r}: burst must be >= 1")
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        self.tokens = self.burst
        self._last = time.monotonic()
        self.vft = 0.0                 # fair-queue virtual finish time

    def available(self, now: Optional[float] = None) -> float:
        """Tokens in the bucket right now (refilled, burst-capped)."""
        if self.rate is None:
            return float("inf")
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        return self.tokens

    def try_take(self, now: Optional[float] = None, n: float = 1.0) -> bool:
        if self.rate is None:
            return True
        if self.available(now) < n:
            return False
        self.tokens -= n
        return True


class TenantRegistry:
    """The configured tenants plus a ``"*"`` template for unknown ones.

    Config shape (``--tenant_config`` JSON / ``InferenceServer``
    ``tenants=`` dict)::

        {"A": {"rate": 100, "burst": 20, "weight": 4},
         "B": {"rate": 50, "weight": 1},
         "*": {"rate": 10, "burst": 10}}

    or the compact ``--tenants`` form ``A:100:20:4,B:50::1,*:10:10``
    (``name:rate[:burst[:weight]]``, ``-`` or empty = default).  A
    tenant id never configured inherits the ``"*"`` template (default:
    unmetered, weight 1) — multi-tenancy is opt-in per tenant, not a
    registration wall.
    """

    def __init__(self, config: Optional[Dict[str, dict]] = None):
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantQuota] = {}
        cfg = dict(config or {})
        self._template = cfg.pop("*", {})
        for name, spec in cfg.items():
            self._tenants[name] = TenantQuota(name, **spec)

    @classmethod
    def parse(cls, compact: str) -> "TenantRegistry":
        """``A:100:20:4,B:50``  ->  name:rate[:burst[:weight]]."""
        config: Dict[str, dict] = {}
        for item in compact.split(","):
            item = item.strip()
            if not item:
                continue
            parts = item.split(":")
            name = parts[0]
            if not name:
                raise ValueError(f"tenant spec {item!r} names no tenant")
            spec: dict = {}
            fields = ("rate", "burst", "weight")
            for key, raw in zip(fields, parts[1:]):
                if raw not in ("", "-"):
                    spec[key] = float(raw)
            config[name] = spec
        return cls(config)

    def get(self, name: str) -> TenantQuota:
        with self._lock:
            q = self._tenants.get(name)
            if q is None:
                q = TenantQuota(name, **self._template)
                self._tenants[name] = q
            return q

    def admit(self, name: str) -> TenantQuota:
        """Charge one request to the tenant's bucket; raises
        ``TenantOverQuota`` when it is empty."""
        q = self.get(name)
        with self._lock:
            if not q.try_take():
                raise TenantOverQuota(
                    name, f"tenant {name!r} is over quota "
                    f"(rate={q.rate}/s, burst={q.burst:g})")
        return q

    def max_weight(self) -> float:
        with self._lock:
            if not self._tenants:
                return 1.0
            return max(q.weight for q in self._tenants.values())

    def info(self) -> dict:
        with self._lock:
            return {
                name: {"rate": q.rate, "burst": q.burst,
                       "weight": q.weight,
                       "tokens": (None if q.rate is None
                                  else round(q.available(), 3))}
                for name, q in sorted(self._tenants.items())
            }


def next_bucket(rows: int) -> int:
    """Smallest power-of-two >= rows (the padded batch dim).

    Delegates to the ladder shared with the kernel autotuner
    (pallas/tuning/bucket.py) so serving batch buckets and tuning-DB
    shape buckets can never drift apart.
    """
    return _bucket.bucket_dim(rows)


def bucket_ladder(max_batch: int) -> Tuple[int, ...]:
    """The bucket shapes a server with this cap compiles: 1,2,4..cap."""
    return _bucket.bucket_ladder(max_batch)


def propagate_shapes(program) -> None:
    """Run registered ``infer_shape`` rules over the global block so the
    bucketer sees backfilled var metadata (a program loaded via
    ``Program.from_dict`` skips append-time InferShape).  Rules that
    cannot infer (``SkipInferShape``) or reject are ignored here — the
    bucketer is conservative, not a verifier; ``paddle lint`` is."""
    from paddle_tpu.registry import OpRegistry

    block = program.global_block()
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        info = OpRegistry.get(op.type, none_ok=True)
        if info is None or info.infer_shape is None:
            continue
        try:
            info.infer_shape(op, block)
        except Exception:
            continue
    program.invalidate_cache()


class BatchSpec:
    """Static batchability decision + per-feed row layout."""

    def __init__(self, batchable: bool, reason: str,
                 feed_names: Sequence[str] = (),
                 row_shapes: Optional[Dict[str, tuple]] = None,
                 dtypes: Optional[Dict[str, Any]] = None,
                 code: str = "ok"):
        self.batchable = batchable
        self.reason = reason
        # short slug of the disabled() reason family — the label value
        # for serving_unbatched_total (full prose stays in .reason)
        self.code = code
        self.feed_names = tuple(feed_names)
        self.row_shapes = row_shapes or {}
        self.dtypes = dtypes or {}
        self._feed_set = frozenset(self.feed_names)

    @classmethod
    def disabled(cls, reason: str, code: str = "disabled") -> "BatchSpec":
        return cls(False, reason, code=code)

    @classmethod
    def from_program(cls, program, feed_names: Sequence[str],
                     fetch_names: Sequence[str]) -> "BatchSpec":
        propagate_shapes(program)
        block = program.global_block()
        row_shapes: Dict[str, tuple] = {}
        dtypes: Dict[str, Any] = {}
        for name in feed_names:
            var = block.find_var(name)
            if var is None or var.shape is None:
                return cls.disabled(f"feed {name!r} has no shape metadata",
                                    code="no_shape_metadata")
            if var.lod_level:
                return cls.disabled(f"feed {name!r} is LoD "
                                    f"(lod_level={var.lod_level})",
                                    code="lod_feed")
            if len(var.shape) < 1 or var.shape[0] != -1:
                return cls.disabled(
                    f"feed {name!r} shape {var.shape} is not batch-major",
                    code="not_batch_major")
            if any(d < 0 for d in var.shape[1:]):
                return cls.disabled(
                    f"feed {name!r} shape {var.shape} has dynamic "
                    "non-batch dims", code="dynamic_dims")
            row_shapes[name] = tuple(var.shape[1:])
            from paddle_tpu.ops.common import jnp_dtype

            dtypes[name] = jnp_dtype(var.dtype)
        for name in fetch_names:
            var = block.find_var(name)
            if var is None or var.shape is None:
                return cls.disabled(f"fetch {name!r} has no shape metadata",
                                    code="no_shape_metadata")
            if var.lod_level:
                return cls.disabled(f"fetch {name!r} is LoD "
                                    f"(lod_level={var.lod_level})",
                                    code="lod_fetch")
            if len(var.shape) < 1 or var.shape[0] != -1:
                return cls.disabled(
                    f"fetch {name!r} shape {var.shape} is not batch-major "
                    "(per-request rows cannot be scattered back)",
                    code="not_batch_major")
        return cls(True, "ok", feed_names, row_shapes, dtypes)

    def classify(self, feeds: Dict[str, np.ndarray]):
        """``(rows, cast_feeds)`` when this request can join a coalesced
        batch, else ``None`` (the request executes solo).  Never raises:
        a shape the spec doesn't recognize is a legacy exact-shape
        request, not an error."""
        if not self.batchable or set(feeds) != self._feed_set:
            return None
        rows = None
        cast: Dict[str, np.ndarray] = {}
        for name in self.feed_names:
            arr = feeds[name]
            shape = np.shape(arr)
            if len(shape) != len(self.row_shapes[name]) + 1 or shape[0] < 1:
                return None
            if tuple(shape[1:]) != self.row_shapes[name]:
                return None
            if rows is None:
                rows = shape[0]
            elif shape[0] != rows:
                return None
            if arr.dtype != self.dtypes[name]:
                arr = arr.astype(self.dtypes[name])
            cast[name] = arr
        return rows, cast


class PendingRequest:
    """One in-flight request: feeds + row span + completion event.

    ``tenant`` feeds the fair queue; ``attempts`` counts dispatches —
    the supervised replica pool bumps it each time a replica dies with
    this request in flight, and quarantines the request
    (``RetryExhausted`` -> 503) once the budget is spent.
    """

    __slots__ = ("feeds", "rows", "batchable", "solo_reason", "deadline",
                 "enqueued_at", "abandoned", "outputs", "error", "bucket",
                 "tenant", "attempts", "_vft", "_seq", "_event", "_done")

    def __init__(self, feeds: Dict[str, Any], rows: int = 1,
                 batchable: bool = False, deadline: Optional[float] = None,
                 solo_reason: str = "unbatchable",
                 tenant: str = DEFAULT_TENANT):
        self.feeds = feeds
        self.rows = rows
        self.batchable = batchable
        self.solo_reason = solo_reason    # serving_unbatched_total label
        self.deadline = deadline          # time.monotonic timestamp
        self.tenant = tenant
        self.attempts = 0                 # dispatches consumed so far
        self.enqueued_at = time.monotonic()
        self.abandoned = False            # waiter gave up (timed out)
        self.outputs: Optional[list] = None
        self.error: Optional[BaseException] = None
        self.bucket: Optional[int] = None  # padded rows it dispatched at
        self._vft = 0.0                   # virtual finish time (fair queue)
        self._seq = 0                     # submit order tie-break
        self._event = threading.Event()
        self._done = False

    @property
    def done(self) -> bool:
        return self._done

    def complete(self, outputs: list) -> None:
        if self._done:
            return
        self._done = True
        self.outputs = outputs
        self._event.set()

    def fail(self, exc: BaseException) -> None:
        if self._done:
            return
        self._done = True
        self.error = exc
        self._event.set()

    def wait(self, timeout: Optional[float]) -> bool:
        return self._event.wait(timeout)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class RequestQueue:
    """Coalescing FIFO the replica pool pulls from.

    ``take()`` (worker side) returns a list of requests forming one
    dispatch: either a group of batchable requests totalling at most
    ``max_batch`` rows, or a single unbatchable request.  With
    ``batch_timeout`` > 0 the head request may linger that long waiting
    for peers to fill the bucket; at 0 (default) coalescing is purely
    opportunistic — whatever is queued when a worker frees up rides
    along, so an idle server adds zero latency.

    With a ``TenantRegistry`` the queue is weighted-fair: ``submit``
    charges the tenant's token bucket (``TenantOverQuota`` when empty)
    and stamps a virtual finish time; ``take`` serves in vft order, so
    dispatch share converges to the weight ratio under saturation.
    ``shed_watermark`` arms pressure shedding: past it, tenants below
    the registry's top weight are refused (``QueueShed``
    ``shed_low_weight``); past twice it, everyone is
    (``queue_collapse``) — bounded degradation instead of queue
    collapse.
    """

    def __init__(self, max_batch: int = 8, batch_timeout: float = 0.0,
                 tenants: Optional[TenantRegistry] = None,
                 shed_watermark: Optional[int] = None):
        self.max_batch = max(1, int(max_batch))
        self.batch_timeout = max(0.0, float(batch_timeout))
        self.tenants = tenants if tenants is not None else TenantRegistry()
        self.shed_watermark = (int(shed_watermark)
                               if shed_watermark else None)
        self._cond = threading.Condition()
        self._pending: List[PendingRequest] = []
        self._closed = False
        self._paused = False
        self._vclock = 0.0            # fair-queue virtual time
        self._seq = 0                 # submit counter (vft tie-break)

    def pause(self) -> None:
        """Stop handing out batches (drain/maintenance).  Submissions
        still queue — and expire against their deadlines."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._pending)

    def _shed_check_locked(self, req: PendingRequest) -> None:
        """Pressure shedding (holds the queue lock): lowest-weight
        tenants are refused first, everyone at 2x the watermark."""
        if self.shed_watermark is None:
            return
        depth = len(self._pending)
        if depth >= 2 * self.shed_watermark:
            raise QueueShed(
                "queue_collapse",
                f"serving queue saturated ({depth} pending >= "
                f"{2 * self.shed_watermark}); shedding all tenants")
        if depth >= self.shed_watermark:
            weight = self.tenants.get(req.tenant).weight
            top = self.tenants.max_weight()
            if weight < top:
                raise QueueShed(
                    "shed_low_weight",
                    f"serving queue under pressure ({depth} pending >= "
                    f"{self.shed_watermark}); shedding tenant "
                    f"{req.tenant!r} (weight {weight:g} < {top:g})")

    def submit(self, req: PendingRequest) -> None:
        """Admit one request: charge the tenant's token bucket
        (``TenantOverQuota`` -> 429 when empty), apply pressure
        shedding, stamp the fair-queue virtual finish time, enqueue."""
        quota = self.tenants.admit(req.tenant)
        with self._cond:
            if self._closed:
                raise RuntimeError("serving queue is shut down")
            self._shed_check_locked(req)
            req.enqueued_at = time.monotonic()
            # weighted fair queuing: heavier tenants' requests finish
            # "sooner" in virtual time, so they drain proportionally
            # faster under saturation.  max() with the queue vclock
            # means an idle tenant re-enters at *now* — no banked
            # scheduling credit from its idle spell.
            req._vft = max(quota.vft, self._vclock) + req.rows / quota.weight
            quota.vft = req._vft
            self._seq += 1
            req._seq = self._seq
            self._pending.append(req)
            # notify_all, not notify: a lingering worker (batch_timeout)
            # also waits on this condition and could swallow the single
            # wakeup while an idle replica sleeps through it
            self._cond.notify_all()

    def requeue(self, reqs: Sequence[PendingRequest]) -> None:
        """Put a dead replica's in-flight requests back (supervisor
        path): no fresh quota charge, original vft kept — they return
        to the *front* of the virtual-time order they already earned.
        Requests already completed by a zombie dispatch are skipped."""
        with self._cond:
            for req in reqs:
                if req.done or req.abandoned:
                    continue
                if self._closed:
                    req.fail(RuntimeError("server shutting down"))
                    continue
                self._pending.append(req)
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def degradation(self) -> dict:
        """Pressure snapshot for /health."""
        with self._cond:
            depth = len(self._pending)
        out = {"pending": depth, "shed_watermark": self.shed_watermark,
               "shedding": None}
        if self.shed_watermark is not None:
            if depth >= 2 * self.shed_watermark:
                out["shedding"] = "queue_collapse"
            elif depth >= self.shed_watermark:
                out["shedding"] = "shed_low_weight"
        return out

    def close(self) -> None:
        with self._cond:
            self._closed = True
            for req in self._pending:
                req.fail(RuntimeError("server shutting down"))
            self._pending.clear()
            self._cond.notify_all()

    # -- worker side --------------------------------------------------------

    def _sweep_locked(self) -> None:
        """Drop abandoned/already-completed waiters; expire requests
        whose deadline passed while queued (they 504 without burning a
        dispatch).  Also restores weighted-fair order: the pending list
        is kept sorted by virtual finish time (timsort on a
        nearly-sorted list — requeues are the only out-of-order
        inserts)."""
        now = time.monotonic()
        live = []
        for req in self._pending:
            if req.abandoned or req.done:
                continue
            if req.expired(now):
                req.fail(TimeoutError(
                    "request deadline expired waiting for a serving replica"))
                continue
            live.append(req)
        live.sort(key=lambda r: (r._vft, r._seq))
        self._pending = live
        counts: Dict[str, int] = {}
        for req in live:
            counts[req.tenant] = counts.get(req.tenant, 0) + 1
        seen = {d.get("tenant", "") for d in _M_TENANT_DEPTH.label_sets()}
        for tenant in set(counts) | (seen - {""}):
            _M_TENANT_DEPTH.set(counts.get(tenant, 0), tenant=tenant)

    def take(self) -> Optional[List[PendingRequest]]:
        """Block until a dispatch group is available; None on shutdown."""
        with self._cond:
            head = None
            while head is None:
                while True:
                    self._sweep_locked()
                    if self._closed:
                        return None
                    if self._pending and not self._paused:
                        break
                    self._cond.wait()
                head = self._pending[0]
                if head.batchable and self.batch_timeout > 0:
                    fill_by = head.enqueued_at + self.batch_timeout
                    while True:
                        rows = sum(r.rows for r in self._pending
                                   if r.batchable)
                        remaining = fill_by - time.monotonic()
                        if rows >= self.max_batch or remaining <= 0:
                            break
                        self._cond.wait(remaining)
                        self._sweep_locked()
                        if self._closed:
                            return None
                        if self._paused or not self._pending:
                            # paused mid-linger (pause() must stop
                            # dispatch) or everything expired: start over
                            head = None
                            break
                        head = self._pending[0]
                        if not head.batchable:
                            break
            if not head.batchable:
                batch = [self._pending.pop(0)]
            else:
                batch, rows, keep = [], 0, []
                for req in self._pending:
                    if req.batchable and (
                            not batch or rows + req.rows <= self.max_batch):
                        batch.append(req)
                        rows += req.rows
                    else:
                        keep.append(req)
                self._pending = keep
            now = time.monotonic()
            for req in batch:
                req.attempts += 1
                self._vclock = max(self._vclock, req._vft)
                _M_QUEUE_WAIT.observe(max(0.0, now - req.enqueued_at))
            return batch


def coalesce(batch: Sequence[PendingRequest], spec: BatchSpec):
    """Stack the batch's rows per feed and pad up to the bucket shape.

    Padding replicates each feed's last real row: the padded rows run
    through the same program and are discarded by ``scatter``, and a
    copy of a real row cannot introduce NaN/Inf the way synthetic zeros
    could (e.g. under normalization).
    """
    rows = sum(r.rows for r in batch)
    bucket = next_bucket(rows)
    feeds: Dict[str, np.ndarray] = {}
    for name in spec.feed_names:
        parts = [np.asarray(r.feeds[name]) for r in batch]
        if len(parts) == 1 and bucket == rows:
            feeds[name] = parts[0]
            continue
        if bucket > rows:
            parts.append(np.repeat(parts[-1][-1:], bucket - rows, axis=0))
        feeds[name] = np.concatenate(parts, axis=0)
    return feeds, rows, bucket


def scatter(batch: Sequence[PendingRequest], outs: Sequence[Any],
            bucket: int) -> None:
    """Slice each fetch back to its waiter (de-padding)."""
    for o in outs:
        lead = getattr(o, "shape", (None,))[0] if np.ndim(o) else None
        if lead != bucket:
            raise RuntimeError(
                f"fetch output shape {np.shape(o)} is not batch-aligned to "
                f"the dispatched bucket ({bucket} rows); the program's shape "
                "metadata mis-declared a batch-major fetch")
    start = 0
    for req in batch:
        req.complete([o[start:start + req.rows] for o in outs])
        start += req.rows
