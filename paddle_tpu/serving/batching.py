"""Bucketed request coalescing for the serving engine.

Continuous batching, the way a static-shape compiler wants it: concurrent
single-row (or few-row) ``/predict`` requests are coalesced into one
padded batch at a small set of power-of-two *bucket* shapes, so the
Executor's compile cache holds exactly one XLA program per
(program-fingerprint, bucket) key and steady-state traffic never
re-traces.  The scheduling shape follows the continuous/ragged-batch
ideas in "Ragged Paged Attention" (PAPERS.md): admission, batch
formation, and device dispatch overlap — a worker that frees up takes
whatever compatible requests are queued *right now* (no mandatory
linger), so light traffic keeps single-request latency and heavy
traffic amortizes dispatch across the batch.

Pieces:

- ``BatchSpec`` — the *bucketer's* static decision: does the loaded
  program admit row coalescing at all?  It trusts verifier shape
  metadata (``Variable.shape``/``lod_level``, backfilled by the op
  registry's ``infer_shape`` rules — paddle_tpu/analysis registry
  ratchet): every feed and every fetch must be batch-major
  (leading dim -1, static trailing dims, lod_level 0).  Programs that
  fail the test (ragged feeds, scalar/reduced fetches, LoD outputs)
  still serve — each request just executes solo, exactly as the
  pre-batching server did.
- ``PendingRequest`` — one waiter: converted feeds, row span, deadline,
  and a completion event the HTTP handler blocks on.
- ``RequestQueue`` — the bounded coalescing queue replica workers pull
  from: ``take()`` groups compatible pending requests up to
  ``max_batch`` rows (optionally lingering ``batch_timeout`` seconds to
  fill a bucket) and expires requests whose deadline passed while
  queued.
- ``coalesce``/``scatter`` — pad rows up to the bucket (replicating the
  last real row, so padding can never create NaN/Inf out of thin air)
  and slice each fetch back to the right waiter.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.pallas.tuning import bucket as _bucket

_M_QUEUE_WAIT = _metrics.histogram(
    "serving_queue_wait_seconds",
    "time a request spends queued before a replica takes it")
_M_BATCH_ROWS = _metrics.histogram(
    "serving_batch_size",
    "coalesced request rows per executed batch "
    "(label bucket = padded rows dispatched, 'unbatched' = solo path)",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0))
_M_UNBATCHED = _metrics.counter(
    "serving_unbatched_total",
    "solo-fallback dispatches by reason (the BatchSpec disabled() "
    "family: lod_feed/lod_fetch/not_batch_major/... when the model "
    "cannot batch at all, shape_mismatch when this request's shapes "
    "did not fit an otherwise batchable model)")


def next_bucket(rows: int) -> int:
    """Smallest power-of-two >= rows (the padded batch dim).

    Delegates to the ladder shared with the kernel autotuner
    (pallas/tuning/bucket.py) so serving batch buckets and tuning-DB
    shape buckets can never drift apart.
    """
    return _bucket.bucket_dim(rows)


def bucket_ladder(max_batch: int) -> Tuple[int, ...]:
    """The bucket shapes a server with this cap compiles: 1,2,4..cap."""
    return _bucket.bucket_ladder(max_batch)


def propagate_shapes(program) -> None:
    """Run registered ``infer_shape`` rules over the global block so the
    bucketer sees backfilled var metadata (a program loaded via
    ``Program.from_dict`` skips append-time InferShape).  Rules that
    cannot infer (``SkipInferShape``) or reject are ignored here — the
    bucketer is conservative, not a verifier; ``paddle lint`` is."""
    from paddle_tpu.registry import OpRegistry

    block = program.global_block()
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        info = OpRegistry.get(op.type, none_ok=True)
        if info is None or info.infer_shape is None:
            continue
        try:
            info.infer_shape(op, block)
        except Exception:
            continue
    program.invalidate_cache()


class BatchSpec:
    """Static batchability decision + per-feed row layout."""

    def __init__(self, batchable: bool, reason: str,
                 feed_names: Sequence[str] = (),
                 row_shapes: Optional[Dict[str, tuple]] = None,
                 dtypes: Optional[Dict[str, Any]] = None,
                 code: str = "ok"):
        self.batchable = batchable
        self.reason = reason
        # short slug of the disabled() reason family — the label value
        # for serving_unbatched_total (full prose stays in .reason)
        self.code = code
        self.feed_names = tuple(feed_names)
        self.row_shapes = row_shapes or {}
        self.dtypes = dtypes or {}
        self._feed_set = frozenset(self.feed_names)

    @classmethod
    def disabled(cls, reason: str, code: str = "disabled") -> "BatchSpec":
        return cls(False, reason, code=code)

    @classmethod
    def from_program(cls, program, feed_names: Sequence[str],
                     fetch_names: Sequence[str]) -> "BatchSpec":
        propagate_shapes(program)
        block = program.global_block()
        row_shapes: Dict[str, tuple] = {}
        dtypes: Dict[str, Any] = {}
        for name in feed_names:
            var = block.find_var(name)
            if var is None or var.shape is None:
                return cls.disabled(f"feed {name!r} has no shape metadata",
                                    code="no_shape_metadata")
            if var.lod_level:
                return cls.disabled(f"feed {name!r} is LoD "
                                    f"(lod_level={var.lod_level})",
                                    code="lod_feed")
            if len(var.shape) < 1 or var.shape[0] != -1:
                return cls.disabled(
                    f"feed {name!r} shape {var.shape} is not batch-major",
                    code="not_batch_major")
            if any(d < 0 for d in var.shape[1:]):
                return cls.disabled(
                    f"feed {name!r} shape {var.shape} has dynamic "
                    "non-batch dims", code="dynamic_dims")
            row_shapes[name] = tuple(var.shape[1:])
            from paddle_tpu.ops.common import jnp_dtype

            dtypes[name] = jnp_dtype(var.dtype)
        for name in fetch_names:
            var = block.find_var(name)
            if var is None or var.shape is None:
                return cls.disabled(f"fetch {name!r} has no shape metadata",
                                    code="no_shape_metadata")
            if var.lod_level:
                return cls.disabled(f"fetch {name!r} is LoD "
                                    f"(lod_level={var.lod_level})",
                                    code="lod_fetch")
            if len(var.shape) < 1 or var.shape[0] != -1:
                return cls.disabled(
                    f"fetch {name!r} shape {var.shape} is not batch-major "
                    "(per-request rows cannot be scattered back)",
                    code="not_batch_major")
        return cls(True, "ok", feed_names, row_shapes, dtypes)

    def classify(self, feeds: Dict[str, np.ndarray]):
        """``(rows, cast_feeds)`` when this request can join a coalesced
        batch, else ``None`` (the request executes solo).  Never raises:
        a shape the spec doesn't recognize is a legacy exact-shape
        request, not an error."""
        if not self.batchable or set(feeds) != self._feed_set:
            return None
        rows = None
        cast: Dict[str, np.ndarray] = {}
        for name in self.feed_names:
            arr = feeds[name]
            shape = np.shape(arr)
            if len(shape) != len(self.row_shapes[name]) + 1 or shape[0] < 1:
                return None
            if tuple(shape[1:]) != self.row_shapes[name]:
                return None
            if rows is None:
                rows = shape[0]
            elif shape[0] != rows:
                return None
            if arr.dtype != self.dtypes[name]:
                arr = arr.astype(self.dtypes[name])
            cast[name] = arr
        return rows, cast


class PendingRequest:
    """One in-flight request: feeds + row span + completion event."""

    __slots__ = ("feeds", "rows", "batchable", "solo_reason", "deadline",
                 "enqueued_at", "abandoned", "outputs", "error", "bucket",
                 "_event", "_done")

    def __init__(self, feeds: Dict[str, Any], rows: int = 1,
                 batchable: bool = False, deadline: Optional[float] = None,
                 solo_reason: str = "unbatchable"):
        self.feeds = feeds
        self.rows = rows
        self.batchable = batchable
        self.solo_reason = solo_reason    # serving_unbatched_total label
        self.deadline = deadline          # time.monotonic timestamp
        self.enqueued_at = time.monotonic()
        self.abandoned = False            # waiter gave up (timed out)
        self.outputs: Optional[list] = None
        self.error: Optional[BaseException] = None
        self.bucket: Optional[int] = None  # padded rows it dispatched at
        self._event = threading.Event()
        self._done = False

    def complete(self, outputs: list) -> None:
        if self._done:
            return
        self._done = True
        self.outputs = outputs
        self._event.set()

    def fail(self, exc: BaseException) -> None:
        if self._done:
            return
        self._done = True
        self.error = exc
        self._event.set()

    def wait(self, timeout: Optional[float]) -> bool:
        return self._event.wait(timeout)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class RequestQueue:
    """Coalescing FIFO the replica pool pulls from.

    ``take()`` (worker side) returns a list of requests forming one
    dispatch: either a group of batchable requests totalling at most
    ``max_batch`` rows, or a single unbatchable request.  With
    ``batch_timeout`` > 0 the head request may linger that long waiting
    for peers to fill the bucket; at 0 (default) coalescing is purely
    opportunistic — whatever is queued when a worker frees up rides
    along, so an idle server adds zero latency.
    """

    def __init__(self, max_batch: int = 8, batch_timeout: float = 0.0):
        self.max_batch = max(1, int(max_batch))
        self.batch_timeout = max(0.0, float(batch_timeout))
        self._cond = threading.Condition()
        self._pending: List[PendingRequest] = []
        self._closed = False
        self._paused = False

    def pause(self) -> None:
        """Stop handing out batches (drain/maintenance).  Submissions
        still queue — and expire against their deadlines."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._pending)

    def submit(self, req: PendingRequest) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("serving queue is shut down")
            req.enqueued_at = time.monotonic()
            self._pending.append(req)
            # notify_all, not notify: a lingering worker (batch_timeout)
            # also waits on this condition and could swallow the single
            # wakeup while an idle replica sleeps through it
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            for req in self._pending:
                req.fail(RuntimeError("server shutting down"))
            self._pending.clear()
            self._cond.notify_all()

    # -- worker side --------------------------------------------------------

    def _sweep_locked(self) -> None:
        """Drop abandoned waiters; expire requests whose deadline passed
        while queued (they 504 without burning a dispatch)."""
        now = time.monotonic()
        live = []
        for req in self._pending:
            if req.abandoned:
                continue
            if req.expired(now):
                req.fail(TimeoutError(
                    "request deadline expired waiting for a serving replica"))
                continue
            live.append(req)
        self._pending = live

    def take(self) -> Optional[List[PendingRequest]]:
        """Block until a dispatch group is available; None on shutdown."""
        with self._cond:
            head = None
            while head is None:
                while True:
                    self._sweep_locked()
                    if self._closed:
                        return None
                    if self._pending and not self._paused:
                        break
                    self._cond.wait()
                head = self._pending[0]
                if head.batchable and self.batch_timeout > 0:
                    fill_by = head.enqueued_at + self.batch_timeout
                    while True:
                        rows = sum(r.rows for r in self._pending
                                   if r.batchable)
                        remaining = fill_by - time.monotonic()
                        if rows >= self.max_batch or remaining <= 0:
                            break
                        self._cond.wait(remaining)
                        self._sweep_locked()
                        if self._closed:
                            return None
                        if self._paused or not self._pending:
                            # paused mid-linger (pause() must stop
                            # dispatch) or everything expired: start over
                            head = None
                            break
                        head = self._pending[0]
                        if not head.batchable:
                            break
            if not head.batchable:
                batch = [self._pending.pop(0)]
            else:
                batch, rows, keep = [], 0, []
                for req in self._pending:
                    if req.batchable and (
                            not batch or rows + req.rows <= self.max_batch):
                        batch.append(req)
                        rows += req.rows
                    else:
                        keep.append(req)
                self._pending = keep
            now = time.monotonic()
            for req in batch:
                _M_QUEUE_WAIT.observe(max(0.0, now - req.enqueued_at))
            return batch


def coalesce(batch: Sequence[PendingRequest], spec: BatchSpec):
    """Stack the batch's rows per feed and pad up to the bucket shape.

    Padding replicates each feed's last real row: the padded rows run
    through the same program and are discarded by ``scatter``, and a
    copy of a real row cannot introduce NaN/Inf the way synthetic zeros
    could (e.g. under normalization).
    """
    rows = sum(r.rows for r in batch)
    bucket = next_bucket(rows)
    feeds: Dict[str, np.ndarray] = {}
    for name in spec.feed_names:
        parts = [np.asarray(r.feeds[name]) for r in batch]
        if len(parts) == 1 and bucket == rows:
            feeds[name] = parts[0]
            continue
        if bucket > rows:
            parts.append(np.repeat(parts[-1][-1:], bucket - rows, axis=0))
        feeds[name] = np.concatenate(parts, axis=0)
    return feeds, rows, bucket


def scatter(batch: Sequence[PendingRequest], outs: Sequence[Any],
            bucket: int) -> None:
    """Slice each fetch back to its waiter (de-padding)."""
    for o in outs:
        lead = getattr(o, "shape", (None,))[0] if np.ndim(o) else None
        if lead != bucket:
            raise RuntimeError(
                f"fetch output shape {np.shape(o)} is not batch-aligned to "
                f"the dispatched bucket ({bucket} rows); the program's shape "
                "metadata mis-declared a batch-major fetch")
    start = 0
    for req in batch:
        req.complete([o[start:start + req.rows] for o in outs])
        start += req.rows
