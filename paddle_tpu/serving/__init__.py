"""HTTP model serving over a save_inference_model export — the
continuous-batching serving engine.

The 2017 reference's deployment story was the C API; this serves the
same artifact over JSON/HTTP through a real serving stack instead of a
single executor behind a lock:

- **Bucketed request coalescing** (``paddle_tpu/serving/batching.py``):
  concurrent ``/predict`` requests are merged into padded batches at
  power-of-two bucket shapes — one compiled XLA program per
  (program-fingerprint, bucket) key via the Executor compile cache, so
  steady-state traffic never re-traces.  Results are de-padded and
  scattered back to each waiter.  Models whose feeds/fetches are not
  batch-major (ragged sequences, LoD outputs, reduced fetches — decided
  from verifier shape metadata) still serve; those requests execute
  solo at their exact shape, like the pre-batching server.
- **Replica pool** (``paddle_tpu/serving/replica.py``): ``--replicas=N``
  worker clones, each with its own Scope + Executor and zero shared
  mutable state (the ``pd_machine_clone`` shape), pulling batches from
  one queue so admission, batching, and XLA dispatch overlap.

Endpoints:
  GET  /health           → {"status": "ok", "feeds": [...], "fetches":
                           [...], "batching": {...}, "generation": {...}}
  GET  /metrics          → Prometheus text exposition (0.0.4): request
                           latency histogram, in-flight gauge, status
                           counters, serving_batch_size /
                           serving_queue_wait_seconds, plus the
                           executor's compile/step metrics
  GET  /stats            → the observability registry snapshot as JSON
                           (what `paddle stats --url=...` renders)
  POST /predict          → body {"<feed>": nested-list, ...}
                           → {"outputs": [nested-list per fetch]}
                           Unknown payload keys (other than ``@len``
                           side-feeds) are a 400 naming the key.
  POST /generate         → body {"src": [int ids], "max_new_tokens": N,
                           "stream": bool, "beam": k, "temperature":
                           t, "top_k": k, "seed": s} against a
                           paged-KV decode engine (paddle_tpu/decode).
                           With ``stream`` (default true) the reply is
                           chunked ndjson — one ``{"token": t}`` line
                           per generated token as the continuous-
                           batching session emits it, then a final
                           ``{"done": true, "ids": [...],
                           "finish_reason": ...}`` line; without it,
                           one JSON object after generation finishes.
                           ``beam`` (when the engine allows it) runs
                           beam search over copy-on-write sibling
                           slots and replies non-streamed with the
                           full ``"beams"`` list best-first;
                           ``temperature``/``top_k``/``seed`` switch
                           the slot to seeded sampling (top_k/seed
                           without temperature → 400, never silently
                           greedy).  Page-pool
                           exhaustion / full admission queue
                           → 503 (admission refusal, live sequences
                           unaffected); request deadline → 504.

Graceful degradation (bounded, not unbounded thread pileup):
  - ``max_inflight``: admission cap — requests beyond it are rejected
    immediately with 503 instead of queueing forever;
  - ``request_timeout``: per-request deadline — a request that does not
    complete before it expires returns 504 (and is dropped from the
    queue without burning a dispatch if it expires while queued);
  - clients that disconnect mid-response are counted, not crashed; a
    client that abandons a ``/generate`` stream mid-flight gets its
    decode slot cancelled (pages freed) instead of generating to a
    dead socket.
  All are counted in ``serving_rejected_total{reason=...}`` on
  ``/metrics`` (overload → 503, deadline → 504, client_gone).

Self-healing & multi-tenancy (PR 19):
  - requests carry a tenant id (``X-Tenant`` header or ``"tenant"``
    payload key; absent → ``"default"``); per-tenant token buckets
    turn one tenant's burst into *their* 429 ``tenant_over_quota``
    instead of everyone's 503, and weighted-fair dequeue keeps heavy
    tenants from starving light ones;
  - replicas are supervised: a dispatch that raises or outlives its
    lease marks the replica dead, its in-flight batch is requeued
    (bounded ``attempts``; a poison request is quarantined with 503
    ``retry_exhausted``), and a fresh replica is respawned with
    backoff under a restart-rate limit;
  - sustained pressure past ``shed_watermark`` sheds lowest-weight
    tenants first; ``/health`` flips to ``"degraded"`` with reasons
    while the pool is down replicas or shedding.

Cold start (PR 20): ``--artifacts=DIR`` boots replicas from a
``paddle compile`` export — every bucket-ladder program is
deserialized from the artifact store instead of traced+compiled, with
donation restored (see ``paddle_tpu/aot``).  Warmup wall time lands in
``serving_time_to_ready_seconds{boot=aot|jit|mixed}``.

Launch:  paddle serve --model_dir=DIR [--port=N]
                      [--replicas=N] [--max_batch=N]
                      [--batch_timeout_ms=MS] [--warmup]
                      [--artifacts=DIR]
                      [--request_timeout=SECONDS] [--max_inflight=N]
                      [--tenants=SPEC] [--max_attempts=N]
                      [--replica_heartbeat_ms=MS] [--chaos=KIND@N]
"""

from __future__ import annotations

import json
import queue as queue_mod
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability.events import GLOBAL_EVENTS as _EVENTS
from paddle_tpu.serving.batching import (
    DEFAULT_TENANT,
    BatchSpec,
    PendingRequest,
    QueueShed,
    RequestQueue,
    RetryExhausted,
    TenantOverQuota,
    TenantRegistry,
    bucket_ladder,
    next_bucket,
)
from paddle_tpu.serving.replica import (
    FaultInjector,
    ModelBundle,
    Replica,
    ReplicaPool,
)

__all__ = [
    "BatchSpec", "FaultInjector", "InferenceServer", "ModelBundle",
    "PendingRequest", "QueueShed", "Replica", "ReplicaPool",
    "RequestQueue", "RetryExhausted", "TenantOverQuota",
    "TenantRegistry", "bucket_ladder", "next_bucket",
]

_M_REQ_SEC = _metrics.histogram(
    "serving_request_seconds",
    "wall time per inference request, including executor dispatch")
_M_INFLIGHT = _metrics.gauge(
    "serving_inflight_requests", "requests currently being handled")
_M_RESPONSES = _metrics.counter(
    "serving_responses_total", "HTTP responses by status code")
_M_REJECTED = _metrics.counter(
    "serving_rejected_total",
    "requests shed for graceful degradation, by reason "
    "(overload -> 503, deadline -> 504, client_gone -> disconnect)")


def _jsonable(o):
    """Fetch value → JSON shape; LoD outputs become
    {"data": ..., "lod": [...]} (packed rows + offset tables)."""
    from paddle_tpu.lod import LoDArray

    if isinstance(o, LoDArray):
        return {"data": np.asarray(o.data).tolist(),
                "lod": [np.asarray(l).tolist() for l in o.lod]}
    return np.asarray(o).tolist()


class InferenceServer:
    def __init__(self, model_dir: Optional[str], port: int = 0,
                 request_timeout: float = None, max_inflight: int = None,
                 replicas: int = 1, max_batch: int = 8,
                 batch_timeout_ms: float = 0.0, warmup: bool = False,
                 generator=None, place=None, tenants=None,
                 max_attempts: int = 3,
                 replica_heartbeat_ms: float = 1000.0,
                 dispatch_timeout: float = None, chaos=None,
                 shed_watermark: int = None, artifacts: str = None):
        if model_dir is None and generator is None:
            raise ValueError("need a model_dir to predict from and/or a "
                             "generator (paddle_tpu.decode."
                             "GenerationEngine) to generate with")
        self._generator = generator
        self._bundle = ModelBundle(model_dir) if model_dir else None
        self.feed_names = (self._bundle.feed_names if self._bundle else [])
        self._fetches = (self._bundle.fetch_names if self._bundle else [])
        self._feed_set = frozenset(self.feed_names)
        if self._bundle is None:
            self._spec = BatchSpec.disabled(
                "generation-only server (no --model_dir export loaded)",
                code="generation_only")
        elif max_batch > 1:
            self._spec = self._bundle.batch_spec()
        else:
            self._spec = BatchSpec.disabled(
                "coalescing off (max_batch <= 1): every request runs at "
                "its exact feed shape", code="coalescing_off")
        if isinstance(tenants, str):
            tenants = TenantRegistry.parse(tenants)
        self._tenants = tenants if tenants is not None else TenantRegistry()
        if shed_watermark is None:
            # deep enough that normal bursts never shed, shallow enough
            # that a collapsing pool rejects instead of queueing forever
            shed_watermark = max(64, 8 * max_batch)
        self.fault = (FaultInjector.from_spec(chaos)
                      if isinstance(chaos, str) else chaos)
        self._artifact_store = None
        self._aot_attached = False
        if artifacts:
            # `paddle compile` output: replicas consult the store before
            # tracing; any manifest mismatch is a loud JIT fallback
            # (aot_load_total{result=rejected_*}), never a wrong answer
            from paddle_tpu import aot as _aot

            self._artifact_store = _aot.ArtifactStore(artifacts)
            if generator is not None:
                # the decode engine builds its executors deep inside the
                # model — attach process-globally so they see the store
                _aot.attach(self._artifact_store)
                self._aot_attached = True
        self._queue = RequestQueue(max_batch=max_batch,
                                   batch_timeout=batch_timeout_ms / 1000.0,
                                   tenants=self._tenants,
                                   shed_watermark=shed_watermark)
        self._pool = (ReplicaPool(self._bundle, self._queue, self._spec,
                                  replicas=replicas, place=place,
                                  fault=self.fault,
                                  max_attempts=max_attempts,
                                  heartbeat=replica_heartbeat_ms / 1000.0,
                                  dispatch_timeout=dispatch_timeout,
                                  artifact_store=self._artifact_store)
                      if self._bundle else None)
        self._request_timeout = request_timeout
        self._max_inflight = max_inflight
        self._slots = (threading.BoundedSemaphore(max_inflight)
                       if max_inflight else None)
        if warmup and self._pool is not None:
            self._pool.warmup()
        if isinstance(chaos, str) and self.fault is not None:
            # spec-string chaos is the operator path (--chaos=die@1):
            # nobody else can arm it, so arm now — after warmup, so the
            # nth dispatch counts live traffic, not compile traffic
            self.fault.arm()

        server = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: one TCP connection per load-test client, not
            # one per request (we always send Content-Length)
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code, obj, ctype="application/json",
                       raw=None):
                body = raw if raw is not None else json.dumps(obj).encode()
                _M_RESPONSES.inc(code=str(code))
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # load-test client hung up mid-response: count it,
                    # don't spam stderr or kill the handler thread
                    _M_REJECTED.inc(reason="client_gone")
                    self.close_connection = True

            def do_GET(self):
                if self.path == "/health":
                    reasons = server.degraded_reasons()
                    self._reply(200, {
                        "status": "degraded" if reasons else "ok",
                        "reasons": reasons,
                        "self_healing": server.self_healing_info(),
                        "feeds": server.feed_names,
                        "fetches": [getattr(f, "name", str(f))
                                    for f in server._fetches],
                        "batching": server.batching_info(),
                        "aot": server.aot_info(),
                        "generation": (server._generator.info()
                                       if server._generator else None)})
                elif self.path == "/metrics":
                    self._reply(
                        200, None,
                        ctype="text/plain; version=0.0.4; charset=utf-8",
                        raw=_metrics.render_prometheus().encode())
                elif self.path == "/stats":
                    self._reply(200, _metrics.snapshot())
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                # always consume the body first: on keep-alive
                # (HTTP/1.1) an unread body would be parsed as the
                # next request line, desyncing the connection for
                # every reply sent before rfile.read — 404s and 503s
                # included
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw_body = self.rfile.read(n)
                except (BrokenPipeError, ConnectionResetError):
                    _M_REJECTED.inc(reason="client_gone")
                    self.close_connection = True
                    return
                if self.path == "/generate":
                    self._handle_generate(raw_body)
                    return
                if self.path != "/predict":
                    self._reply(404, {"error": "unknown path"})
                    return
                if server._slots is not None and \
                        not server._slots.acquire(blocking=False):
                    # shed load at admission: a bounded 503 beats an
                    # unbounded request pileup in the batching queue
                    _M_REJECTED.inc(reason="overload")
                    self._reply(503, {"error": "server overloaded "
                                      f"(max_inflight={server._max_inflight})"})
                    return
                _M_INFLIGHT.inc()
                ev_t0 = _EVENTS.now()
                t0 = time.perf_counter()
                tenant = (self.headers.get("X-Tenant")
                          or DEFAULT_TENANT).strip() or DEFAULT_TENANT
                try:
                    payload = json.loads(raw_body or b"{}")
                    if isinstance(payload, dict) and "tenant" in payload:
                        tenant = str(payload.pop("tenant")) or tenant
                    deadline = (time.monotonic() + server._request_timeout
                                if server._request_timeout else None)
                    outs = server.predict(payload, deadline=deadline,
                                          tenant=tenant)
                    self._reply(200, {"outputs": [_jsonable(o)
                                                  for o in outs]})
                except TenantOverQuota as e:
                    _M_REJECTED.inc(reason="tenant_over_quota",
                                    tenant=e.tenant)
                    self._reply(429, {"error": str(e),
                                      "reason": "tenant_over_quota",
                                      "tenant": e.tenant})
                except QueueShed as e:
                    _M_REJECTED.inc(reason=e.reason, tenant=tenant)
                    self._reply(503, {"error": str(e),
                                      "reason": e.reason})
                except RetryExhausted as e:
                    _M_REJECTED.inc(reason="retry_exhausted",
                                    tenant=tenant)
                    self._reply(503, {"error": str(e),
                                      "reason": "retry_exhausted"})
                except TimeoutError as e:
                    _M_REJECTED.inc(reason="deadline")
                    self._reply(504, {"error": str(e)})
                except (BrokenPipeError, ConnectionResetError):
                    _M_REJECTED.inc(reason="client_gone")
                    self.close_connection = True
                except (KeyError, ValueError, TypeError) as e:
                    self._reply(400, {"error": str(e)})
                except Exception as e:  # surface, don't kill the server
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                finally:
                    dt = time.perf_counter() - t0
                    _M_INFLIGHT.dec()
                    if server._slots is not None:
                        server._slots.release()
                    _M_REQ_SEC.observe(dt, endpoint="/predict")
                    _EVENTS.complete("serving.predict", ev_t0, dt,
                                     cat="serving")

            # -- generation (paged-KV decode engine) ---------------------

            def _chunk(self, obj) -> None:
                data = json.dumps(obj).encode() + b"\n"
                self.wfile.write(f"{len(data):X}\r\n".encode()
                                 + data + b"\r\n")

            def _handle_generate(self, raw_body: bytes) -> None:
                from paddle_tpu.decode import AdmissionRefused

                if server._generator is None:
                    self._reply(400, {"error": "no generation engine "
                                      "mounted (serve with --gen_config)"})
                    return
                _M_INFLIGHT.inc()
                ev_t0 = _EVENTS.now()
                t0 = time.perf_counter()
                tenant = (self.headers.get("X-Tenant")
                          or DEFAULT_TENANT).strip() or DEFAULT_TENANT
                try:
                    payload = json.loads(raw_body or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError(
                            "request body must be a JSON object")
                    if "tenant" in payload:
                        tenant = str(payload.pop("tenant")) or tenant
                    src = payload.get("src")
                    if (not isinstance(src, list) or not src
                            or not all(isinstance(t, int) for t in src)):
                        raise ValueError(
                            "'src' must be a non-empty list of int ids")
                    unknown = set(payload) - {"src", "max_new_tokens",
                                              "stream", "beam",
                                              "temperature", "top_k",
                                              "seed"}
                    if unknown:
                        raise ValueError(
                            f"unknown payload key {sorted(unknown)[0]!r}; "
                            "expected src / max_new_tokens / stream / "
                            "beam / temperature / top_k / seed / tenant")
                    # same token buckets as /predict: a generation call
                    # spends one admission token for its tenant
                    server._tenants.admit(tenant)
                    budget = payload.get("max_new_tokens")
                    beam = payload.get("beam")
                    deadline = (time.monotonic() + server._request_timeout
                                if server._request_timeout else None)
                    # grace past the deadline: the session itself
                    # expires the request and reports it
                    timeout = (None if deadline is None else
                               max(0.0, deadline - time.monotonic())
                               + 30.0)
                    if beam is not None:
                        if (not isinstance(beam, int) or beam < 1
                                or isinstance(beam, bool)):
                            raise ValueError(
                                "'beam' must be a positive int")
                        req = server._generator.submit_beam(
                            src, beam_size=beam,
                            max_new_tokens=budget, deadline=deadline)
                        ids = req.result(timeout)
                        self._reply(200, {
                            "ids": ids,
                            "beams": [{"score": s, "ids": t}
                                      for s, t in (req.beams or [])],
                            "finish_reason": req.finish_reason})
                    elif payload.get("stream", True):
                        self._stream_generate(src, budget, deadline,
                                              payload)
                    else:
                        req = server._generator.submit(
                            src, budget, deadline=deadline,
                            temperature=payload.get("temperature"),
                            top_k=payload.get("top_k"),
                            seed=payload.get("seed"))
                        ids = req.result(timeout)
                        self._reply(200, {
                            "ids": ids,
                            "finish_reason": req.finish_reason})
                except TenantOverQuota as e:
                    _M_REJECTED.inc(reason="tenant_over_quota",
                                    tenant=e.tenant)
                    self._reply(429, {"error": str(e),
                                      "reason": "tenant_over_quota",
                                      "tenant": e.tenant})
                except AdmissionRefused as e:
                    _M_REJECTED.inc(reason=e.reason)
                    self._reply(503, {"error": str(e),
                                      "reason": e.reason})
                except TimeoutError as e:
                    _M_REJECTED.inc(reason="deadline")
                    self._reply(504, {"error": str(e)})
                except (BrokenPipeError, ConnectionResetError):
                    _M_REJECTED.inc(reason="client_gone")
                    self.close_connection = True
                except (KeyError, ValueError, TypeError) as e:
                    self._reply(400, {"error": str(e)})
                except Exception as e:
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                finally:
                    dt = time.perf_counter() - t0
                    _M_INFLIGHT.dec()
                    _M_REQ_SEC.observe(dt, endpoint="/generate")
                    _EVENTS.complete("serving.generate", ev_t0, dt,
                                     cat="serving")

            def _stream_generate(self, src, budget, deadline,
                                 payload=None) -> None:
                """Chunked ndjson: one line per token as the decode
                session emits it, then the summary line.  Admission
                refusals (503) and pre-stream deadline expiry (504)
                raise BEFORE any header is written; once tokens are
                flowing, a mid-stream expiry rides the final line as
                ``finish_reason: "deadline"`` (the status is already
                on the wire)."""
                q: queue_mod.Queue = queue_mod.Queue()
                payload = payload or {}
                req = server._generator.submit(
                    src, budget, on_token=q.put, deadline=deadline,
                    temperature=payload.get("temperature"),
                    top_k=payload.get("top_k"),
                    seed=payload.get("seed"))
                if deadline is not None:
                    # hold the 200 until the stream actually starts:
                    # a request that dies of its deadline before its
                    # first token must be the documented 504, not a
                    # 200 that trickles out an error line
                    while (req.first_token_at is None
                           and not req.wait(0.01)):
                        pass
                    if req.first_token_at is None and req.done:
                        if isinstance(req.error, TimeoutError):
                            raise req.error
                        if req.error is not None:
                            raise req.error
                _M_RESPONSES.inc(code="200")
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    while True:
                        try:
                            self._chunk({"token": q.get(timeout=0.05)})
                        except queue_mod.Empty:
                            if req.done and q.empty():
                                break
                    final = {"done": True, "ids": req.tokens,
                             "finish_reason": req.finish_reason}
                    if req.error is not None:
                        final["error"] = str(req.error)
                    self._chunk(final)
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    # the consumer left: cancel the decode slot so its
                    # pages free now instead of generating the rest of
                    # the sequence into a dead socket
                    server._generator.cancel(req)
                    _M_REJECTED.inc(reason="client_gone")
                    self.close_connection = True

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    # -- introspection ------------------------------------------------------

    @property
    def address(self):
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    @property
    def port(self):
        return self._httpd.server_address[1]

    def degraded_reasons(self) -> list:
        """Machine-readable reasons /health is ``degraded`` (empty =
        healthy): dead replicas, exhausted restart budget, active load
        shedding."""
        reasons = []
        if self._pool is not None:
            reasons.extend(self._pool.degraded_reasons())
        deg = self._queue.degradation()
        if deg.get("shedding"):
            reasons.append(f"load_shedding:{deg['shedding']}")
        return reasons

    def self_healing_info(self) -> dict:
        return {
            "pool": self._pool.info() if self._pool else None,
            "tenants": self._tenants.info(),
            "queue": self._queue.degradation(),
        }

    def aot_info(self) -> Optional[dict]:
        """Artifact-store state for /health: root, poison reason, entry
        count, per-outcome lookup results, and the pool's boot source."""
        if self._artifact_store is None:
            return None
        info = self._artifact_store.info()
        info["boot"] = (self._pool.boot_source()
                        if self._pool is not None else None)
        return info

    def batching_info(self) -> dict:
        return {
            "enabled": self._spec.batchable,
            "reason": self._spec.reason,
            "replicas": len(self._pool.replicas) if self._pool else 0,
            "max_batch": self._queue.max_batch,
            "batch_timeout_ms": self._queue.batch_timeout * 1000.0,
            "buckets": (list(bucket_ladder(self._queue.max_batch))
                        if self._spec.batchable else []),
        }

    # -- serving ------------------------------------------------------------

    def _build_feeds(self, payload: dict) -> dict:
        # the executor casts every feed to its declared dtype
        # (_convert_feed), so raw np.asarray is enough here
        feed = {}
        for name in self.feed_names:
            if name not in payload:
                raise KeyError(f"missing feed {name!r}")
        for k, v in payload.items():
            if k in self._feed_set or k.endswith("@len"):
                # lengths side-feeds ride along with declared feeds
                feed[k] = np.asarray(v)
            else:
                # a mis-keyed request must not silently drop data (and
                # must never be coalesced into someone else's bucket)
                raise ValueError(
                    f"unknown payload key {k!r}; expected feeds "
                    f"{sorted(self._feed_set)} (plus optional '@len' "
                    "side-feeds)")
        return feed

    def predict(self, payload: dict, deadline: float = None,
                tenant: str = DEFAULT_TENANT):
        """Run one request through the batching engine.  ``deadline``
        (a ``time.monotonic`` timestamp) bounds the *whole* wait —
        queueing and execution; an expired request raises TimeoutError
        (504 over HTTP) instead of stacking up behind busy replicas.
        ``tenant`` selects the admission token bucket and fair-queue
        weight (429/503 raised here as TenantOverQuota/QueueShed)."""
        if self._bundle is None:
            raise ValueError("this server mounts no inference export "
                             "(generation-only; POST /generate instead)")
        feed = self._build_feeds(payload)
        info = self._spec.classify(feed)
        if info is None:
            # model-level unbatchability carries the BatchSpec code;
            # a batchable model whose request shapes didn't line up is
            # a per-request miss
            reason = (self._spec.code if not self._spec.batchable
                      else "shape_mismatch")
            req = PendingRequest(feed, rows=1, batchable=False,
                                 deadline=deadline, solo_reason=reason,
                                 tenant=tenant)
        else:
            rows, cast = info
            req = PendingRequest(cast, rows=rows, batchable=True,
                                 deadline=deadline, tenant=tenant)
        self._queue.submit(req)
        timeout = None
        if deadline is not None:
            timeout = max(0.0, deadline - time.monotonic())
        if not req.wait(timeout):
            req.abandoned = True
            raise TimeoutError(
                "request deadline expired waiting for a serving replica")
        if req.error is not None:
            raise req.error
        return list(req.outputs)

    # -- lifecycle ----------------------------------------------------------

    def warmup(self):
        """Pre-compile the bucket ladder on every replica."""
        return self._pool.warmup() if self._pool else 0

    def pause(self):
        """Stop replicas taking new batches (drain/maintenance); queued
        requests wait (and expire against their deadlines)."""
        if self._pool:
            self._pool.pause()

    def resume(self):
        if self._pool:
            self._pool.resume()

    def stop(self):
        self._httpd.shutdown()
        if self._pool:
            self._pool.stop()
        if self._generator is not None:
            self._generator.stop()
        if self._aot_attached:
            from paddle_tpu import aot as _aot

            _aot.detach()
            self._aot_attached = False
        self._httpd.server_close()
