"""Implicit-GEMM conv kernels for the MXU (reference analog: the cuDNN
bindings behind paddle/cuda/src/hl_cuda_cudnn.cc and the implicit-GEMM
fallback paddle/function/GemmConvOp.cpp — redone as Pallas row-block
kernels instead of im2col-through-HBM).

Design (stride-1 SAME convs, NHWC, the ResNet-50 3x3 family):

- forward: grid ``(NB, OH, KH)``, KH innermost.  Each step loads one
  padded input row slab ``(bb, 1, Wp, C)`` for a batch block and
  accumulates the KW shifted ``(bb*OW, C) @ (C, O)`` products into an
  f32 VMEM accumulator; the accumulator flushes to the output row when
  kh == KH-1.  M = bb*OW keeps the MXU pipelined even where W alone
  (7..56) could not.
- backward-input: the same forward kernel applied to the padded
  cotangent with the spatially-flipped, channel-transposed filter
  (conv_transpose identity for stride 1).
- backward-filter: grid ``(KH, NB, OH)``, OH innermost.  Each step
  contracts the x row slab against the cotangent row over M = bb*OW
  into a per-kh ``(KW*C, O)`` f32 accumulator (reset at the first
  (batch, row) step, flushed at the last).

Whole-filter blocks use constant index maps so Pallas keeps them
resident in VMEM across grid steps instead of re-copying.  Batch
blocks are sized so the working set (with sub-128 channel dims padded
to full lanes) stays under the ~16 MB scoped-vmem budget.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.pallas import compat as _compat

_VMEM_BUDGET = 9 * 1024 * 1024


def _lanes(c):
    return max(c, 128)


def _fwd_vmem(bb, w, wp, c, o, kh, kw, fold_kw=False):
    vmem = (2 * bb * wp * _lanes(c) * 2      # double-buffered x slab
            + bb * w * _lanes(o) * 4         # f32 accumulator
            + 2 * bb * w * _lanes(o) * 2     # double-buffered out row
            + kh * kw * c * _lanes(o) * 2)   # resident filter
    if fold_kw:
        vmem += bb * w * kw * c * 2          # staged K=KW*C patch
    return vmem


def fwd_block_ok(bb, n, w, wp, c, o, kh, kw, fold_kw=False) -> bool:
    """Validity of an explicit forward batch block at an actual shape
    (the tuning DB's configs are bucket-keyed, so dispatch re-checks)."""
    return (bb >= 8 and n % bb == 0
            and _fwd_vmem(bb, w, wp, c, o, kh, kw, fold_kw)
            <= _VMEM_BUDGET)


def _fwd_batch_block(n, w, wp, c, o, kh, kw, fold_kw=False):
    """Largest divisor-of-n batch block whose fwd working set fits
    (x slab and out row double-buffered, resident filter, f32 acc).
    Returns None when even the smallest block exceeds VMEM — the
    caller must fall back to the XLA emitter."""
    for bb in sorted((d for d in range(8, n + 1) if n % d == 0),
                     reverse=True):
        if _fwd_vmem(bb, w, wp, c, o, kh, kw, fold_kw) <= _VMEM_BUDGET:
            return bb
    return None


def _dw_batch_block(n, ow, wp, c, o, kh, kw):
    for bb in sorted((d for d in range(8, n + 1) if n % d == 0),
                     reverse=True):
        vmem = (2 * bb * wp * _lanes(c) * 2 + 2 * bb * ow * _lanes(o) * 2
                + kw * c * _lanes(o) * 4 + kh * kw * c * _lanes(o) * 4)
        if vmem <= _VMEM_BUDGET:
            return bb
    return None


def fits(n, h, w, c, o, kh, kw, stride, padding) -> bool:
    """Kernel applicability: stride-1 SAME square convs with
    MXU-friendly channel counts and a batch block that fits VMEM in
    every direction (fwd, bwd-input, bwd-filter)."""
    if stride != 1 or kh != kw or kh % 2 == 0:
        return False
    if padding != kh // 2:
        return False
    if c % 64 or o % 64 or n % 8:
        return False
    wp = w + 2 * padding
    return (_fwd_batch_block(n, w, wp, c, o, kh, kw) is not None
            and _fwd_batch_block(n, w, wp, o, c, kh, kw) is not None
            and _dw_batch_block(n, w, wp, c, o, kh, kw) is not None)


def _fwd_kernel(x_ref, w_ref, o_ref, *rest, kh_steps, kw_steps, ow,
                fold_kw, with_stats=False):
    """Forward conv; with ``with_stats`` the per-channel BN sum /
    sum-of-squares accumulate in the flush epilogue while the f32
    output block is still in VMEM (the round-5 epilogue-fusion
    experiment) — stats outputs are revisited every step, so the grid
    must then be fully sequential."""
    if with_stats:
        sum_ref, sq_ref, acc_ref, *scratch = rest
    else:
        sum_ref = sq_ref = None
        acc_ref, *scratch = rest
    kh = pl.program_id(2)

    @pl.when(kh == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if with_stats:
        @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0)
                 & (kh == 0))
        def _init_stats():
            sum_ref[:] = jnp.zeros_like(sum_ref)
            sq_ref[:] = jnp.zeros_like(sq_ref)

    row = x_ref[:, 0]                       # (bb, Wp, C)
    b = row.shape[0]
    c = row.shape[-1]
    if fold_kw:
        (patch_ref,) = scratch
        # one MXU pass with K = KW*C: the kw shifts happen either way,
        # folding them into the contraction amortizes MXU setup.
        # Mosaic cannot concat sublane-shifted vectors, so the shifted
        # slices are staged through a scratch buffer lane-block-wise.
        for kw in range(kw_steps):
            patch_ref[:, :, kw * c:(kw + 1) * c] = row[:, kw:kw + ow]
        patch = patch_ref[:].reshape(b * ow, kw_steps * c)
        wk = w_ref[kh].reshape(kw_steps * c, -1)
        acc_ref[:] += jnp.dot(patch, wk,
                              preferred_element_type=jnp.float32)
    else:
        for kw in range(kw_steps):
            patch = row[:, kw:kw + ow].reshape(b * ow, -1)
            acc_ref[:] += jnp.dot(patch, w_ref[kh, kw],
                                  preferred_element_type=jnp.float32)

    @pl.when(kh == kh_steps - 1)
    def _flush():
        acc = acc_ref[:]
        o_ref[:, 0] = acc.reshape(b, ow, -1).astype(o_ref.dtype)
        if with_stats:
            sum_ref[:] += jnp.sum(acc, axis=0, keepdims=True)
            sq_ref[:] += jnp.sum(acc * acc, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("padding", "interpret",
                                             "fold_kw", "with_stats",
                                             "bb"))
def _conv_fwd_impl(x, w, padding: int, interpret: bool = False,
                   fold_kw: bool = None, with_stats: bool = False,
                   bb: int = None):
    n, h, wd, c = x.shape
    kh, kw, c2, o = w.shape
    assert c == c2, (x.shape, w.shape)
    p = padding
    xp = jnp.pad(x, [(0, 0), (p, p), (p, p), (0, 0)])
    wp = wd + 2 * p
    # tunables (pallas/tuning): the forward batch block bb and the
    # fold_kw layout choice (one K=KW*C MXU pass vs KW shifted passes).
    # Explicit args win (the tuner pins candidates this way); a tuned
    # bb must re-validate against this actual shape before it replaces
    # the divisor heuristic.
    if fold_kw is None or bb is None:
        from paddle_tpu.pallas import tuning

        cfg = tuning.lookup("conv", (n, h, wd, c, o, kh),
                            x.dtype.name) or {}
        if fold_kw is None:
            fold_kw = bool(cfg.get("fold_kw", False))
        if bb is None:
            bb = cfg.get("bb")
    if bb is not None and not fwd_block_ok(bb, n, wd, wp, c, o, kh, kw,
                                           fold_kw):
        bb = None
    if bb is None:
        bb = _fwd_batch_block(n, wd, wp, c, o, kh, kw, fold_kw=fold_kw)
    assert bb is not None, (
        f"conv working set exceeds VMEM at every batch block "
        f"({x.shape} w={w.shape}); gate calls behind fits()")
    scratch = [pltpu.VMEM((bb * wd, o), jnp.float32)]
    if fold_kw:
        scratch.append(pltpu.VMEM((bb, wd, kw * c), x.dtype))
    out_specs = pl.BlockSpec((bb, 1, wd, o), lambda b, oh, k: (b, oh, 0, 0))
    out_shape = jax.ShapeDtypeStruct((n, h, wd, o), x.dtype)
    if with_stats:
        out_specs = [out_specs,
                     pl.BlockSpec((1, o), lambda b, oh, k: (0, 0)),
                     pl.BlockSpec((1, o), lambda b, oh, k: (0, 0))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((1, o), jnp.float32),
                     jax.ShapeDtypeStruct((1, o), jnp.float32)]
    # stats outputs are revisited every grid step -> fully sequential
    semantics = (("arbitrary",) * 3 if with_stats
                 else ("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, kh_steps=kh, kw_steps=kw, ow=wd,
                          fold_kw=fold_kw, with_stats=with_stats),
        grid=(n // bb, h, kh),
        in_specs=[
            pl.BlockSpec((bb, 1, wp, c), lambda b, oh, k: (b, oh + k, 0, 0)),
            pl.BlockSpec((kh, kw, c, o), lambda b, oh, k: (0, 0, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_compat.CompilerParams(
            dimension_semantics=semantics),
        interpret=interpret,
    )(xp, w)


def _dw_kernel(x_ref, g_ref, dw_ref, acc_ref, *, nb_steps, oh_steps,
               kw_steps, ow):
    b_i = pl.program_id(1)
    oh = pl.program_id(2)

    @pl.when(jnp.logical_and(b_i == 0, oh == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    row = x_ref[:, 0]                       # (bb, Wp, C)
    gg = g_ref[:, 0]                        # (bb, OW, O)
    b = row.shape[0]
    c = row.shape[-1]
    gflat = gg.reshape(b * ow, -1)
    for kw in range(kw_steps):
        patch = row[:, kw:kw + ow].reshape(b * ow, c)
        acc_ref[kw * c:(kw + 1) * c] += lax.dot_general(
            patch, gflat, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(b_i == nb_steps - 1, oh == oh_steps - 1))
    def _flush():
        dw_ref[0] = acc_ref[:].reshape(
            kw_steps, c, -1).astype(dw_ref.dtype)


@functools.partial(jax.jit, static_argnames=("kernel", "padding",
                                             "interpret"))
def _conv_dw_impl(x, g, kernel: int, padding: int, interpret: bool = False):
    n, h, wd, c = x.shape
    _, oh, ow, o = g.shape
    kh = kw = kernel
    p = padding
    xp = jnp.pad(x, [(0, 0), (p, p), (p, p), (0, 0)])
    wp = wd + 2 * p
    bb = _dw_batch_block(n, ow, wp, c, o, kh, kw)
    assert bb is not None, (
        f"conv-dw working set exceeds VMEM at every batch block "
        f"({x.shape} g={g.shape}); gate calls behind fits()")
    return pl.pallas_call(
        functools.partial(_dw_kernel, nb_steps=n // bb, oh_steps=oh,
                          kw_steps=kw, ow=ow),
        grid=(kh, n // bb, oh),
        in_specs=[
            pl.BlockSpec((bb, 1, wp, c), lambda k, b, r: (b, r + k, 0, 0)),
            pl.BlockSpec((bb, 1, ow, o), lambda k, b, r: (b, r, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, kw, c, o), lambda k, b, r: (k, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((kh, kw, c, o), jnp.float32),
        scratch_shapes=[pltpu.VMEM((kw * c, o), jnp.float32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(xp, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d_nhwc(x, w, padding: int, interpret: bool = False):
    """Stride-1 SAME NHWC conv, implicit-GEMM Pallas kernels end to end
    (forward + both backwards).  x (N, H, W, C), w (KH, KW, C, O)."""
    return _conv_fwd_impl(x, w, padding, interpret)


@functools.partial(jax.jit, static_argnames=("padding", "interpret"))
def conv2d_bn_stats_nhwc(x, w, padding: int, interpret: bool = False):
    """Fused conv + BN-statistics forward (the epilogue-fusion
    experiment VERDICT r4 names; forward-only — training would pair it
    with the round-4 backward kernels): returns (out, mean, var) with
    the (O,) biased batch statistics over (N, H, W), exactly what
    batch_norm training consumes."""
    n, h, wd, _ = x.shape
    o = w.shape[-1]
    out, s_, sq = _conv_fwd_impl(x, w, padding, interpret,
                                 with_stats=True)
    cnt = jnp.float32(n * h * wd)
    mean = (s_ / cnt).reshape(o)
    var = (sq / cnt).reshape(o) - mean * mean
    return out, mean, var


def _conv_fwd_rule(x, w, padding, interpret):
    return _conv_fwd_impl(x, w, padding, interpret), (x, w)


def _conv_bwd_rule(padding, interpret, res, g):
    x, w = res
    kh = w.shape[0]
    # dx: conv of g with the spatially-flipped, channel-swapped filter
    w_flip = jnp.flip(w, (0, 1)).swapaxes(2, 3)
    dx = _conv_fwd_impl(g, w_flip.astype(g.dtype), kh - 1 - padding,
                        interpret)
    dw = _conv_dw_impl(x, g, kh, padding, interpret)
    return dx.astype(x.dtype), dw.astype(w.dtype)


conv2d_nhwc.defvjp(_conv_fwd_rule, _conv_bwd_rule)
