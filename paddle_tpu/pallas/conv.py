"""Implicit-GEMM conv kernels for the MXU (reference analog: the cuDNN
bindings behind paddle/cuda/src/hl_cuda_cudnn.cc and the implicit-GEMM
fallback paddle/function/GemmConvOp.cpp — redone as Pallas row-block
kernels instead of im2col-through-HBM).

Design (stride-1 SAME convs, NHWC, the ResNet-50 3x3 family):

- forward: grid ``(OH, KH)``, KH innermost.  Each step loads one padded
  input row slab ``(B, 1, Wp, C)`` and accumulates the KW shifted
  ``(B*OW, C) @ (C, O)`` products into an f32 VMEM accumulator; the
  accumulator flushes to the output row when kh == KH-1.  M = B*OW
  (14336 at c2, 1792 at c5) keeps the MXU pipelined even where W alone
  (7..56) could not.
- backward-input: the same forward kernel applied to the padded
  cotangent with the spatially-flipped, channel-transposed filter
  (conv_transpose identity for stride 1).
- backward-filter: grid ``(KH, OH)``, OH innermost.  Each step
  contracts the x row slab against the cotangent row over M = B*OW
  into a per-kh ``(KW*C, O)`` f32 accumulator (reset at oh == 0, flush
  at oh == OH-1).

Whole-filter blocks use constant index maps so Pallas keeps them
resident in VMEM across grid steps instead of re-copying.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def fits(n, h, w, c, o, kh, kw, stride, padding) -> bool:
    """Kernel applicability: stride-1 SAME square convs with
    MXU-friendly channel counts and a VMEM-sized row slab."""
    if stride != 1 or kh != kw or kh % 2 == 0:
        return False
    if padding != kh // 2:
        return False
    if c % 64 or o % 64 or (n * w) % 8:
        return False
    wp = w + 2 * padding
    vmem = (2 * n * wp * c * 2          # double-buffered x slab (bf16)
            + kh * kw * c * o * 2       # resident filter
            + n * w * o * 4             # f32 accumulator
            + n * w * o * 2)            # output row
    return vmem <= 13 * 1024 * 1024


def _fwd_kernel(x_ref, w_ref, o_ref, acc_ref, *, kh_steps, kw_steps, ow):
    kh = pl.program_id(1)

    @pl.when(kh == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    row = x_ref[:, 0]                       # (B, Wp, C)
    b = row.shape[0]
    for kw in range(kw_steps):
        patch = row[:, kw:kw + ow].reshape(b * ow, -1)
        acc_ref[:] += jnp.dot(patch, w_ref[kh, kw],
                              preferred_element_type=jnp.float32)

    @pl.when(kh == kh_steps - 1)
    def _flush():
        o_ref[:, 0] = acc_ref[:].reshape(b, ow, -1).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("padding", "interpret"))
def _conv_fwd_impl(x, w, padding: int, interpret: bool = False):
    n, h, wd, c = x.shape
    kh, kw, c2, o = w.shape
    assert c == c2, (x.shape, w.shape)
    p = padding
    xp = jnp.pad(x, [(0, 0), (p, p), (p, p), (0, 0)])
    wp = wd + 2 * p
    return pl.pallas_call(
        functools.partial(_fwd_kernel, kh_steps=kh, kw_steps=kw, ow=wd),
        grid=(h, kh),
        in_specs=[
            pl.BlockSpec((n, 1, wp, c), lambda oh, k: (0, oh + k, 0, 0)),
            pl.BlockSpec((kh, kw, c, o), lambda oh, k: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((n, 1, wd, o), lambda oh, k: (0, oh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, wd, o), x.dtype),
        scratch_shapes=[pltpu.VMEM((n * wd, o), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(xp, w)


def _dw_kernel(x_ref, g_ref, dw_ref, acc_ref, *, oh_steps, kw_steps, ow):
    oh = pl.program_id(1)

    @pl.when(oh == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    row = x_ref[:, 0]                       # (B, Wp, C)
    gg = g_ref[:, 0]                        # (B, OW, O)
    b = row.shape[0]
    c = row.shape[-1]
    gflat = gg.reshape(b * ow, -1)
    for kw in range(kw_steps):
        patch = row[:, kw:kw + ow].reshape(b * ow, c)
        acc_ref[kw * c:(kw + 1) * c] += lax.dot_general(
            patch, gflat, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(oh == oh_steps - 1)
    def _flush():
        dw_ref[0] = acc_ref[:].reshape(
            kw_steps, c, -1).astype(dw_ref.dtype)


@functools.partial(jax.jit, static_argnames=("kernel", "padding",
                                             "interpret"))
def _conv_dw_impl(x, g, kernel: int, padding: int, interpret: bool = False):
    n, h, wd, c = x.shape
    _, oh, ow, o = g.shape
    kh = kw = kernel
    p = padding
    xp = jnp.pad(x, [(0, 0), (p, p), (p, p), (0, 0)])
    wp = wd + 2 * p
    return pl.pallas_call(
        functools.partial(_dw_kernel, oh_steps=oh, kw_steps=kw, ow=ow),
        grid=(kh, oh),
        in_specs=[
            pl.BlockSpec((n, 1, wp, c), lambda k, r: (0, r + k, 0, 0)),
            pl.BlockSpec((n, 1, ow, o), lambda k, r: (0, r, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, kw, c, o), lambda k, r: (k, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((kh, kw, c, o), jnp.float32),
        scratch_shapes=[pltpu.VMEM((kw * c, o), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(xp, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d_nhwc(x, w, padding: int, interpret: bool = False):
    """Stride-1 SAME NHWC conv, implicit-GEMM Pallas kernels end to end
    (forward + both backwards).  x (N, H, W, C), w (KH, KW, C, O)."""
    return _conv_fwd_impl(x, w, padding, interpret)


def _conv_fwd_rule(x, w, padding, interpret):
    return _conv_fwd_impl(x, w, padding, interpret), (x, w)


def _conv_bwd_rule(padding, interpret, res, g):
    x, w = res
    kh = w.shape[0]
    # dx: conv of g with the spatially-flipped, channel-swapped filter
    w_flip = jnp.flip(w, (0, 1)).swapaxes(2, 3)
    dx = _conv_fwd_impl(g, w_flip.astype(g.dtype), kh - 1 - padding,
                        interpret)
    dw = _conv_dw_impl(x, g, kh, padding, interpret)
    return dx.astype(x.dtype), dw.astype(w.dtype)


conv2d_nhwc.defvjp(_conv_fwd_rule, _conv_bwd_rule)
