"""jax version compat for the pallas kernels (the pltpu analog of
parallel/compat.py): the kernels target the modern
``pltpu.CompilerParams`` name, which jax < 0.4.38 spells
``TPUCompilerParams`` (same dataclass).  Resolved here, in OUR
namespace — monkeypatching the jax module would leak the new-API name
into every other library's feature detection."""

from __future__ import annotations

from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(_pltpu, "CompilerParams",
                         getattr(_pltpu, "TPUCompilerParams", None))
