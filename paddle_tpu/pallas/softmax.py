"""Fused row softmax kernel (reference analog: paddle/operators/math/
softmax.cc + the cudnn softmax path): one pass per row block — max,
exp, sum, divide — entirely in VMEM, single HBM read/write."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _softmax_kernel(x_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


def fits(rows, cols, block_rows=256, itemsize=4) -> bool:
    # VMEM budget: in block + out block + fp32 temps must coexist in
    # ~16MB/core; cap a block's footprint at 2MB so 4-5 live copies fit
    block_bytes = block_rows * cols * max(itemsize, 4)
    return (rows % block_rows == 0 and cols % 128 == 0
            and block_bytes <= 2 * 1024 * 1024)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def softmax(x, block_rows: int = 256, interpret: bool = False):
    return _softmax_impl(x, block_rows, interpret)


def _softmax_fwd(x, block_rows, interpret):
    out = _softmax_impl(x, block_rows, interpret)
    return out, out


def _softmax_bwd(block_rows, interpret, out, g):
    # d/dx softmax: s * (g - sum(g * s))
    inner = jnp.sum(g * out, axis=-1, keepdims=True)
    return (out * (g - inner),)


softmax.defvjp(_softmax_fwd, _softmax_bwd)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _softmax_impl(x, block_rows: int = 256, interpret: bool = False):
    rows, cols = x.shape
    assert fits(rows, cols, block_rows), x.shape
    return pl.pallas_call(
        _softmax_kernel,
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)
