"""Fused row softmax kernel (reference analog: paddle/operators/math/
softmax.cc + the cudnn softmax path): one pass per row block — max,
exp, sum, divide — entirely in VMEM, single HBM read/write."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _softmax_kernel(x_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


# the guessed row block the tuning DB (pallas/tuning) overrides
DEFAULT_CONFIG = {"block_rows": 256}


def fits(rows, cols, block_rows=None, itemsize=4) -> bool:
    # VMEM budget: in block + out block + fp32 temps must coexist in
    # ~16MB/core; cap a block's footprint at 2MB so 4-5 live copies fit
    block_rows = block_rows or DEFAULT_CONFIG["block_rows"]
    block_bytes = block_rows * cols * max(itemsize, 4)
    return (rows % block_rows == 0 and cols % 128 == 0
            and block_bytes <= 2 * 1024 * 1024)


def _resolve_block_rows(rows, cols, dtype, block_rows):
    if block_rows is not None:
        return block_rows
    from paddle_tpu.pallas import tuning

    cfg = tuning.lookup("softmax", (rows, cols), dtype) or {}
    got = cfg.get("block_rows", DEFAULT_CONFIG["block_rows"])
    if cfg and not fits(rows, cols, got):
        got = DEFAULT_CONFIG["block_rows"]  # bucket-valid != shape-valid
    return got


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def softmax(x, block_rows: int = None, interpret: bool = False):
    """Unset ``block_rows`` resolves through the tuning DB, falling
    back to ``DEFAULT_CONFIG`` — an explicit arg always wins."""
    return _softmax_impl(x, block_rows, interpret)


def _softmax_fwd(x, block_rows, interpret):
    out = _softmax_impl(x, block_rows, interpret)
    return out, out


def _softmax_bwd(block_rows, interpret, out, g):
    # d/dx softmax: s * (g - sum(g * s))
    inner = jnp.sum(g * out, axis=-1, keepdims=True)
    return (out * (g - inner),)


softmax.defvjp(_softmax_fwd, _softmax_bwd)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _softmax_impl(x, block_rows: int = None, interpret: bool = False):
    rows, cols = x.shape
    block_rows = _resolve_block_rows(rows, cols, x.dtype.name, block_rows)
    assert fits(rows, cols, block_rows), x.shape
    return pl.pallas_call(
        _softmax_kernel,
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)
