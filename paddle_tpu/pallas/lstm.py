"""Fused whole-sequence LSTM kernel.

Reference analog: paddle/cuda/src/hl_cuda_lstm.cu (hl_lstm.h:42) — the
era's hand-written fused LSTM time step.  The TPU version fuses MORE
than the CUDA one could: a single ``pallas_call`` runs the entire
sequence with the recurrent weight matrix and the (h, c) state resident
in VMEM across all grid steps, so per-step HBM traffic is just the
pre-projected gate block in and the hidden block out.  The XLA
``lax.scan`` lowering re-streams the (H, 4H) weight from HBM every step
and pays per-step kernel overheads — exactly the costs that dominate at
the small (B, H) of the reference's RNN benchmarks.

Forward-only kernel + custom vjp: the forward also writes the activated
gates, so the backward is a reverse ``lax.scan`` of pure elementwise
algebra plus the unavoidable dgates@W^T / h^T@dgates matmuls.

Gate order matches the reference lstm_op.cc: i, f, candidate, o.
Activations fixed to the defaults (sigmoid gates, tanh candidate/cell);
callers with exotic activations fall back to the XLA scan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.pallas import compat as _compat


def _lstm_kernel(xp_ref, w_ref, b_ref, h0_ref, c0_ref,
                 hs_ref, cs_ref, gates_ref, h_s, c_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_s[:] = h0_ref[:].astype(jnp.float32)
        c_s[:] = c0_ref[:].astype(jnp.float32)

    _lstm_step_body(xp_ref, w_ref, b_ref, hs_ref, cs_ref, gates_ref,
                    h_s, c_s)


def _lstm_step_body(xp_ref, w_ref, b_ref, hs_ref, cs_ref, gates_ref,
                    h_s, c_s):
    xt = xp_ref[0].astype(jnp.float32)          # (B, 4H)
    gates = xt + jnp.dot(h_s[:].astype(w_ref.dtype), w_ref[:],
                         preferred_element_type=jnp.float32)
    gates = gates + b_ref[:].astype(jnp.float32)
    d = h_s.shape[-1]
    i = jax.nn.sigmoid(gates[:, 0 * d:1 * d])
    f = jax.nn.sigmoid(gates[:, 1 * d:2 * d])
    g = jnp.tanh(gates[:, 2 * d:3 * d])
    o = jax.nn.sigmoid(gates[:, 3 * d:4 * d])
    c_new = f * c_s[:] + i * g
    h_new = o * jnp.tanh(c_new)
    c_s[:] = c_new
    h_s[:] = h_new
    hs_ref[0] = h_new.astype(hs_ref.dtype)
    cs_ref[0] = c_new.astype(cs_ref.dtype)
    gates_ref[0] = jnp.concatenate([i, f, g, o], axis=-1).astype(gates_ref.dtype)


def fits(b, h, vmem_budget=10 * 1024 * 1024) -> bool:
    if b % 8 != 0 or h % 128 != 0:
        return False
    # resident: W (H,4H) f32-ish + x block + gates + 2 state buffers
    resident = 4 * h * 4 * h + 4 * b * 4 * h * 2 + 4 * b * h * 4
    return resident <= vmem_budget


def block_ok(b: int, h: int, bb: int) -> bool:
    """Validity of an explicit batch block: grid divisibility, sublane
    alignment, and the per-block working set under the VMEM budget."""
    return bb >= 8 and bb % 8 == 0 and b % bb == 0 and fits(bb, h)


def _resolve_block_b(t, b, h, dtype):
    """Tuned batch block from the tuning DB (``None`` = the historical
    whole-batch grid, which stays the default on a miss)."""
    from paddle_tpu.pallas import tuning

    cfg = tuning.lookup("lstm", (t, b, h), dtype) or {}
    bb = cfg.get("block_b")
    if bb and bb != b and block_ok(b, h, bb):
        return bb
    return None


def _lstm_kernel_blocked(xp_ref, w_ref, b_ref, h0_ref, c0_ref,
                         hs_ref, cs_ref, gates_ref, h_s, c_s):
    """The same fused step on a ``(B/bb, T)`` grid: each batch block
    sweeps the whole sequence with its own resident (h, c) scratch.
    With bb == B this is exactly the ``(T,)`` kernel; smaller blocks
    trade x-block residency for state/gates VMEM headroom."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        h_s[:] = h0_ref[:].astype(jnp.float32)
        c_s[:] = c0_ref[:].astype(jnp.float32)

    _lstm_step_body(xp_ref, w_ref, b_ref, hs_ref, cs_ref, gates_ref,
                    h_s, c_s)


@functools.partial(jax.jit, static_argnames=("interpret", "block_b"))
def _lstm_seq_impl(xproj, w, bias, h0, c0, interpret: bool = False,
                   block_b: int = None):
    T, B, H4 = xproj.shape
    H = H4 // 4
    if block_b is None:
        block_b = _resolve_block_b(T, B, H, xproj.dtype.name)
    if block_b is not None and not block_ok(B, H, block_b):
        block_b = None
    if block_b is not None and block_b != B:
        bb = block_b
        return pl.pallas_call(
            _lstm_kernel_blocked,
            grid=(B // bb, T),
            in_specs=[
                pl.BlockSpec((1, bb, H4), lambda i, t: (t, i, 0)),
                pl.BlockSpec((H, H4), lambda i, t: (0, 0)),
                pl.BlockSpec((1, H4), lambda i, t: (0, 0)),
                pl.BlockSpec((bb, H), lambda i, t: (i, 0)),
                pl.BlockSpec((bb, H), lambda i, t: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bb, H), lambda i, t: (t, i, 0)),
                pl.BlockSpec((1, bb, H), lambda i, t: (t, i, 0)),
                pl.BlockSpec((1, bb, H4), lambda i, t: (t, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, H), xproj.dtype),
                jax.ShapeDtypeStruct((T, B, H), xproj.dtype),
                jax.ShapeDtypeStruct((T, B, H4), xproj.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((bb, H), jnp.float32),
                            pltpu.VMEM((bb, H), jnp.float32)],
            compiler_params=_compat.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary")),
            interpret=interpret,
        )(xproj, w, bias.reshape(1, H4), h0, c0)
    return pl.pallas_call(
        _lstm_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((1, H4), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), xproj.dtype),
            jax.ShapeDtypeStruct((T, B, H), xproj.dtype),
            jax.ShapeDtypeStruct((T, B, H4), xproj.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32),
                        pltpu.VMEM((B, H), jnp.float32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(xproj, w, bias.reshape(1, H4), h0, c0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def lstm_seq(xproj, w, bias, h0, c0, interpret: bool = False):
    """(T, B, 4H) pre-projected gates -> ((T, B, H) hidden, (T, B, H) cell).

    Default activations, no peepholes.  Differentiable.
    """
    hs, cs, _ = _lstm_seq_impl(xproj, w, bias, h0, c0, interpret)
    return hs, cs


def _lstm_seq_fwd(xproj, w, bias, h0, c0, interpret):
    hs, cs, gates = _lstm_seq_impl(xproj, w, bias, h0, c0, interpret)
    return (hs, cs), (gates, hs, cs, w, h0, c0, bias)


def _lstm_seq_bwd(interpret, res, cots):
    gates, hs, cs, w, h0, c0, bias = res
    dhs, dcs = cots
    T, B, H = hs.shape
    f32 = jnp.float32

    h_prev = jnp.concatenate([h0[None], hs[:-1]], axis=0)  # (T, B, H)
    c_prev = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def step(carry, inp):
        dh_next, dc_next = carry                  # grads flowing from t+1
        g4, c_t, c_pr, h_pr, dh_out, dc_out = inp
        i = g4[:, 0 * H:1 * H].astype(f32)
        f = g4[:, 1 * H:2 * H].astype(f32)
        g = g4[:, 2 * H:3 * H].astype(f32)
        o = g4[:, 3 * H:4 * H].astype(f32)
        tanh_c = jnp.tanh(c_t.astype(f32))
        dh = dh_next + dh_out.astype(f32)
        dc = dc_next + dc_out.astype(f32) + dh * o * (1 - tanh_c ** 2)
        do = dh * tanh_c
        di = dc * g
        dg = dc * i
        df = dc * c_pr.astype(f32)
        dgates = jnp.concatenate([
            di * i * (1 - i), df * f * (1 - f),
            dg * (1 - g ** 2), do * o * (1 - o)], axis=-1)
        dh_prev = jnp.dot(dgates.astype(w.dtype), w.T,
                          preferred_element_type=f32)
        dw_t = jnp.dot(h_pr.astype(w.dtype).T, dgates.astype(w.dtype),
                       preferred_element_type=f32)
        return (dh_prev, dc * f), (dgates, dw_t)

    (dh0, dc0), (dxproj, dw_t) = lax.scan(
        step, (jnp.zeros((B, H), f32), jnp.zeros((B, H), f32)),
        (gates, cs, c_prev, h_prev, dhs, dcs), reverse=True)
    dw = jnp.sum(dw_t, axis=0)
    dbias = jnp.sum(dxproj, axis=(0, 1)).reshape(bias.shape)
    return (dxproj.astype(hs.dtype), dw.astype(w.dtype),
            dbias.astype(bias.dtype), dh0.astype(hs.dtype),
            dc0.astype(hs.dtype))


lstm_seq.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)
