"""Embedding gather kernel (reference analog: operators/
lookup_table_op.cu LookupTable kernel).

Classic scalar-prefetch gather: ids are prefetched to SMEM, and each
grid step's *index map* uses them to choose which table row block to
DMA — the copy engine does the gather, no VMEM-side indexing."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gather_kernel(ids_ref, w_ref, o_ref):
    o_ref[:] = w_ref[:]


def fits(n, dim) -> bool:
    return dim % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def gather_rows(w, ids, interpret: bool = False):
    return _gather_impl(w, ids, interpret)


def _gather_fwd(w, ids, interpret):
    # residuals must be JAX types (a np.dtype is not): keep ids + the
    # static shape; the cotangent g already has w's dtype (out = w[ids])
    return _gather_impl(w, ids, interpret), (ids, w.shape)


def _gather_bwd(interpret, res, g):
    ids, wshape = res
    gw = jnp.zeros(wshape, g.dtype).at[ids].add(g)
    return gw, None


gather_rows.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_impl(w, ids, interpret: bool = False):
    """w: (V, D), ids: (N,) int32 -> (N, D)."""
    n = ids.shape[0]
    v, d = w.shape
    assert fits(n, d), (n, d)
    # (V, 1, D) rows: a (1, 1, D) block's trailing dims match the array,
    # satisfying the mosaic tiling rule while the index map gathers rows
    w3 = w.reshape(v, 1, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda i, ids_ref: (ids_ref[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, ids_ref: (i, 0, 0)),
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, 1, d), w.dtype),
        interpret=interpret,
    )(ids.astype(jnp.int32), w3)
    return out.reshape(n, d)
