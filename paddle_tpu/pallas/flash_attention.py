"""Blocked online-softmax (flash) attention kernels.

The reference era predates transformer attention entirely (its
attention is seq2seq additive attention built from gserver layers); the
CUDA analog of this file is the hand-written softmax/sequence kernels
(paddle/cuda/src/hl_cuda_sequence.cu) generalized to the modern fused
attention.  TPU design:

- forward: grid ``(B*H, S/blk_q, S/blk_k)``, K/V innermost.  The
  running max ``m``, normalizer ``l`` and output accumulator live in
  VMEM scratch across the K sweep, so the ``S x S`` score matrix never
  exists in HBM — the same VMEM-residency trick as ``pallas/lstm.py``.
  Scores/accumulation in f32 on the MXU regardless of input dtype.
  Causal masking skips the strictly-upper K blocks' FLOPs entirely and
  element-masks the diagonal blocks.
- backward: two kernels (the standard split): ``dq`` accumulates over
  K blocks on a ``(BH, nq, nk)`` grid; ``dk/dv`` accumulate over Q
  blocks on a ``(BH, nk, nq)`` grid.  Both recompute ``p`` from the
  saved per-row logsumexp (no S x S residual).

Used by ``ops/attention_ops.py`` local attention and as the per-shard
chunk kernel of ring attention (parallel/ring_attention.py) via
``flash_attention_with_lse`` — chunks merge in log-sum-exp space, and
the lse cotangent folds into the backward's delta term so the ring
gradient stays exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.pallas import compat as _compat

_F32 = jnp.float32
_NEG_INF = -1e30  # large-but-finite: avoids inf-inf NaNs in corrections


def _pick_block(s: int, pref: int = 512) -> int:
    b = min(pref, s)
    while b > 8 and s % b != 0:
        b //= 2
    return b if s % b == 0 else 0


# the guessed block preference the tuning DB (pallas/tuning) overrides:
# blk_q/blk_k default to _pick_block(S, 512)
DEFAULT_CONFIG = {"blk_pref": 512}


def _blocks_ok(S: int, Sk: int, D: int, blk_q: int, blk_k: int) -> bool:
    """Validity of an explicit (blk_q, blk_k) pair at an actual shape:
    divisibility plus the same VMEM residency model as ``fits``."""
    if blk_q < 128 or blk_k < 128 or S % blk_q or Sk % blk_k:
        return False
    resident = (blk_q + 2 * blk_k) * D * 2 + blk_q * D * 4 \
        + blk_q * blk_k * 4
    return resident <= 12 * 1024 * 1024


def _resolve_blocks(BH, S, Sk, D, dtype, blk_q=None, blk_k=None):
    """Tuned (blk_q, blk_k) from the DB when valid at this shape, else
    the historical ``_pick_block`` preference."""
    if blk_q is None or blk_k is None:
        from paddle_tpu.pallas import tuning

        cfg = tuning.lookup("flash_attention", (BH, S, Sk, D), dtype) or {}
        blk_q = blk_q or cfg.get("blk_q")
        blk_k = blk_k or cfg.get("blk_k")
    blk_q = blk_q or _pick_block(S)
    blk_k = blk_k or _pick_block(Sk)
    if not _blocks_ok(S, Sk, D, blk_q, blk_k):
        blk_q, blk_k = _pick_block(S), _pick_block(Sk)
    return blk_q, blk_k


def fits(B: int, H: int, S: int, D: int) -> bool:
    blk = _pick_block(S)
    if blk < 128 or D > 256 or D % 8 != 0:
        return False
    # VMEM: q,k,v blocks + f32 acc + scores
    resident = blk * D * 2 * 3 + blk * D * 4 + blk * blk * 4
    return resident <= 12 * 1024 * 1024


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, blk_q, blk_k, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        run = ki * blk_k <= qi * blk_q + blk_q - 1

    @pl.when(run)
    def _block():
        q = q_ref[0].astype(_F32)
        k = k_ref[0].astype(_F32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_F32) * scale
        if causal:
            q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32,
                                                      (blk_q, blk_k), 0)
            k_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32,
                                                      (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0:1] = l_scr[:, 0:1] * corr + jnp.sum(p, axis=1,
                                                       keepdims=True)
        m_scr[:, 0:1] = m_new
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=_F32)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0, pl.ds(qi, 1), :] = (
            m_scr[:, 0:1] + jnp.log(l)).reshape(1, -1)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "interpret",
                                             "blk_q", "blk_k"))
def _flash_fwd_impl(q, k, v, causal: bool, scale: float,
                    interpret: bool = False, blk_q: int = None,
                    blk_k: int = None):
    BH, S, D = q.shape
    Sk = k.shape[1]
    blk_q, blk_k = _resolve_blocks(BH, S, Sk, D, q.dtype.name,
                                   blk_q, blk_k)
    nq, nk = S // blk_q, Sk // blk_k
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k, nk=nk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, nq, blk_q), lambda b, i, j: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, nq, blk_q), _F32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, 1), _F32),
            pltpu.VMEM((blk_q, 1), _F32),
            pltpu.VMEM((blk_q, D), _F32),
        ],
        # qi must NOT be "parallel": every qi writes its own row slice
        # of the shared (1, nq, blk_q) lse block, and a megacore split
        # over qi would flush two partially-written private copies of
        # that block (BH carries the core-level parallelism instead)
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse.reshape(BH, S)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_scr, *, scale, causal, blk_q, blk_k, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        run = ki * blk_k <= qi * blk_q + blk_q - 1

    @pl.when(run)
    def _block():
        q = q_ref[0].astype(_F32)
        k = k_ref[0].astype(_F32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_F32) * scale
        if causal:
            q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32,
                                                      (blk_q, blk_k), 0)
            k_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32,
                                                      (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        lse_col = lse_ref[0, pl.ds(qi, 1), :].reshape(-1, 1)
        p = jnp.exp(s - lse_col)
        dp = jax.lax.dot_general(
            do_ref[0].astype(_F32), v_ref[0].astype(_F32),
            (((1,), (1,)), ((), ())), preferred_element_type=_F32)
        ds = p * (dp - delta_ref[0, pl.ds(qi, 1), :].reshape(-1, 1)) * scale
        acc_scr[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=_F32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, blk_q, blk_k, nq):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = ki * blk_k <= qi * blk_q + blk_q - 1

    @pl.when(run)
    def _block():
        q = q_ref[0].astype(_F32)
        k = k_ref[0].astype(_F32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_F32) * scale
        if causal:
            q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32,
                                                      (blk_q, blk_k), 0)
            k_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32,
                                                      (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        lse_col = lse_ref[0, pl.ds(qi, 1), :].reshape(-1, 1)
        p = jnp.exp(s - lse_col)                      # (blk_q, blk_k)
        do = do_ref[0].astype(_F32)
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=_F32)
        dp = jax.lax.dot_general(do, v_ref[0].astype(_F32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=_F32)
        ds = p * (dp - delta_ref[0, pl.ds(qi, 1), :].reshape(-1, 1)) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=_F32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "interpret"))
def _flash_bwd_impl(q, k, v, o, lse, do, causal: bool, scale: float,
                    interpret: bool = False, dlse=None):
    BH, S, D = q.shape
    Sk = k.shape[1]
    # the same resolved blocks as the forward: lse is saved reshaped to
    # (BH, nq, blk_q), so fwd and bwd must agree on blk_q
    blk_q, blk_k = _resolve_blocks(BH, S, Sk, D, q.dtype.name)
    nq, nk = S // blk_q, Sk // blk_k
    delta = jnp.sum(do.astype(_F32) * o.astype(_F32), axis=-1)  # (BH, S)
    if dlse is not None:
        # joint (out, lse) cotangent: d lse/d s = p, so the lse
        # cotangent folds into the delta term of ds = p*(dp - delta)
        delta = delta - dlse.astype(_F32)
    lse3 = lse.reshape(BH, nq, blk_q)
    delta3 = delta.reshape(BH, nq, blk_q)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k, nk=nk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, nq, blk_q), lambda b, i, j: (b, 0, 0)),
            pl.BlockSpec((1, nq, blk_q), lambda b, i, j: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, D), _F32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k, nq=nq),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, nq, blk_q), lambda b, j, i: (b, 0, 0)),
            pl.BlockSpec((1, nq, blk_q), lambda b, j, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((blk_k, D), _F32),
                        pltpu.VMEM((blk_k, D), _F32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse3, delta3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# differentiable entry point
# ---------------------------------------------------------------------------


def flash_attention(q, k, v, causal: bool = False, scale: float = None,
                    interpret: bool = False):
    """q, k, v: (BH, S, D) -> out (BH, S, D).

    Callers with (B, H, S, D) reshape to (B*H, S, D) first (free).
    Thin wrapper over ``flash_attention_with_lse`` (the lse output's
    cotangent is simply zero here).
    """
    out, _lse = flash_attention_with_lse(q, k, v, causal, scale,
                                         interpret)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: float = None, interpret: bool = False):
    """Like ``flash_attention`` but also returns the per-row logsumexp
    (BH, S) — the quantity ring attention needs to merge per-chunk
    results exactly.  Differentiable in BOTH outputs (the lse cotangent
    folds into the backward's delta term)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_fwd_impl(q, k, v, causal, scale, interpret)


def _fa_lse_fwd(q, k, v, causal, scale, interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, lse = _flash_fwd_impl(q, k, v, causal, scale, interpret)
    return (out, lse), (q, k, v, out, lse)


def _fa_lse_bwd(causal, scale, interpret, res, cots):
    q, k, v, out, lse = res
    do, dlse = cots
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # an all-zeros lse cotangent (the flash_attention wrapper's case)
    # folds into delta as a no-op, so no special-casing is needed
    dq, dk, dv = _flash_bwd_impl(q, k, v, out, lse, do, causal, scale,
                                 interpret, dlse=dlse)
    return dq, dk, dv


flash_attention_with_lse.defvjp(_fa_lse_fwd, _fa_lse_bwd)
