"""Hand-written Pallas TPU kernels for hot ops (north star: the
reference's hand-written CUDA kernels — paddle/operators/math/*.cu,
paddle/cuda/src/hl_cuda_lstm.cu etc. — reimplemented for the MXU/VPU).

Policy (PADDLE_TPU_USE_PALLAS, default ``auto``):

- ``auto``: only the kernels with a *measured* win over their XLA
  lowering dispatch (see benchmark/pallas_bench.py, PALLAS_BENCH.md):
  the fused whole-sequence LSTM (1.2-1.6x at the RNN-bench shapes) and
  the row softmax for narrow rows (1.5x at cols<=256).  The blocked
  matmul and scalar-prefetch gather measurably LOSE to XLA on TPU
  (0.6-0.9x) and are never auto-dispatched — they remain as tested
  reference kernels and custom-epilogue scaffolds.
- ``1``/``on``: force every kernel on (benchmarking, tests).
- ``0``/``off``: pure XLA lowerings.

All kernels run under ``interpret=True`` on CPU for numerics tests.
"""

from __future__ import annotations

import os

_MODE_ENV = os.environ.get("PADDLE_TPU_USE_PALLAS", "auto").lower()
_STATE = {
    "mode": {"1": "on", "on": "on", "0": "off", "off": "off"}.get(
        _MODE_ENV, "auto"),
    "interpret": os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1",
}


def enable(flag=True, interpret: bool | None = None):
    """enable(True)='on', enable(False)='off', enable('auto')='auto'.
    Strings follow the env convention: '1'/'on', '0'/'off', 'auto'."""
    if isinstance(flag, str):
        norm = {"1": "on", "on": "on", "true": "on",
                "0": "off", "off": "off", "false": "off",
                "auto": "auto"}.get(flag.lower())
        if norm is None:
            raise ValueError(f"pallas.enable: unknown mode {flag!r}")
        _STATE["mode"] = norm
    else:
        _STATE["mode"] = "on" if flag else "off"
    if interpret is not None:
        _STATE["interpret"] = bool(interpret)


def mode() -> str:
    return _STATE["mode"]


def interpret_mode() -> bool:
    return _STATE["interpret"]


def _tpu_backend() -> bool:
    try:
        import jax

        return jax.default_backend() not in ("cpu", "gpu")
    except Exception:
        return False


def _auto_ok() -> bool:
    # auto mode dispatches real kernels only on a TPU backend; interpret
    # mode works anywhere (CPU numerics tests set it explicitly)
    return _STATE["interpret"] or _tpu_backend()


def use_lstm(b: int, h: int) -> bool:
    from paddle_tpu.pallas import lstm as _l

    if _STATE["mode"] == "off" or not _l.fits(b, h):
        return False
    if _STATE["mode"] == "on":
        return True
    return _auto_ok() and h <= 384  # measured: XLA wins at H>=512


def use_softmax(rows: int, cols: int) -> bool:
    from paddle_tpu.pallas import softmax as _s

    if _STATE["mode"] == "off" or not _s.fits(rows, cols):
        return False
    if _STATE["mode"] == "on":
        return True
    return _auto_ok() and cols <= 256  # measured: XLA wins at 512

def use_flash_attention(bh: int, s_q: int, s_k: int, d: int) -> bool:
    """Blocked online-softmax attention.  Measured (PALLAS_BENCH.md):
    beats the jnp softmax(QK^T)V lowering at S>=1024 where the S x S
    score tensor stops fitting cache-friendly fusions; below that XLA's
    fused unblocked attention wins on kernel-count."""
    from paddle_tpu.pallas import flash_attention as _f

    if _STATE["mode"] == "off" or not _f.fits(1, bh, s_q, d) or s_q != s_k:
        return False
    if _STATE["mode"] == "on":
        return True
    return _auto_ok() and s_q >= 1024


def use_batch_norm(rows: int, cols: int) -> bool:
    """Fused BN stats+normalize / BN-grad kernels.  Measured
    (PALLAS_BENCH.md): XLA's BN lowering runs at a higher fraction of
    HBM bandwidth at ResNet shapes (and fuses the statistics into the
    producing conv's epilogue inside real models), so the kernels are
    never auto-dispatched — they remain as tested reference kernels
    and the building block for fused epilogue variants."""
    from paddle_tpu.pallas import batch_norm as _b

    return _STATE["mode"] == "on" and _b.fits(rows, cols)


def use_conv2d(n: int, h: int, w: int, c: int, o: int, kh: int, kw: int,
               stride: int, padding: int) -> bool:
    """Implicit-GEMM conv kernels (pallas/conv.py).  Measured
    (PALLAS_BENCH.md round 4, R=64 value-chains on the v5e): the XLA
    conv emitter wins at every ResNet-50 hot shape — best kernel ratio
    0.96x (c5 bwd-input), typical 0.83-0.90x, worst 0.37x (c2, where
    C=64 wastes half the MXU lanes) — so the kernels are never
    auto-dispatched; they remain as verified scaffolds for fused
    custom-epilogue experiments."""
    from paddle_tpu.pallas import conv as _c

    return _STATE["mode"] == "on" and _c.fits(n, h, w, c, o, kh, kw,
                                              stride, padding)


def use_matmul() -> bool:
    return _STATE["mode"] == "on"  # measured 0.6-0.9x vs XLA: never auto


def use_gather() -> bool:
    return _STATE["mode"] == "on"  # measured 0.5x vs XLA: never auto


from paddle_tpu.pallas.matmul import matmul as pallas_matmul  # noqa: E402
from paddle_tpu.pallas.softmax import softmax as pallas_softmax  # noqa: E402
from paddle_tpu.pallas.embedding import gather_rows as pallas_gather_rows  # noqa: E402
from paddle_tpu.pallas.lstm import lstm_seq as pallas_lstm_seq  # noqa: E402
from paddle_tpu.pallas.flash_attention import (  # noqa: E402
    flash_attention as pallas_flash_attention)
from paddle_tpu.pallas.batch_norm import (  # noqa: E402
    batch_norm_train as pallas_batch_norm_train)
from paddle_tpu.pallas.conv import conv2d_nhwc as pallas_conv2d_nhwc  # noqa: E402
