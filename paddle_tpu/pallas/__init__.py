"""Hand-written Pallas TPU kernels for hot ops (north star: the
reference's hand-written CUDA kernels — paddle/operators/math/*.cu,
paddle/cuda/src/hl_cuda_lstm.cu etc. — reimplemented for the MXU/VPU).

Kernels are opt-in (``enable()`` or PADDLE_TPU_USE_PALLAS=1): the XLA
lowerings are already fused and fast, so each kernel must earn its
place; they also run under ``interpret=True`` on CPU for numerics
tests.  Op lowerings consult ``use_for(shape)`` and fall back to jnp
whenever a shape doesn't tile cleanly."""

from __future__ import annotations

import os

_STATE = {
    "enabled": os.environ.get("PADDLE_TPU_USE_PALLAS", "0") == "1",
    "interpret": os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1",
}


def enable(flag: bool = True, interpret: bool | None = None):
    _STATE["enabled"] = bool(flag)
    if interpret is not None:
        _STATE["interpret"] = bool(interpret)


def is_enabled() -> bool:
    return _STATE["enabled"]


def interpret_mode() -> bool:
    return _STATE["interpret"]


from paddle_tpu.pallas.matmul import matmul as pallas_matmul  # noqa: E402
from paddle_tpu.pallas.softmax import softmax as pallas_softmax  # noqa: E402
from paddle_tpu.pallas.embedding import gather_rows as pallas_gather_rows  # noqa: E402
