"""Persistent tuning database: measured tile configs, checked in.

The TVM lesson ("TVM: An Automated End-to-End Optimizing Compiler for
Deep Learning", PAPERS.md): search over a schedule space with on-device
measurement, then *persist* the winners so dispatch never searches
again.  The store here is one JSON document:

- schema-versioned (``paddle_tpu.tuning_db.v1``) — a loader rejects
  documents from a different schema instead of misreading them;
- keyed by ``kernel|shape-bucket|dtype|device-kind`` where the shape
  bucket rounds every dimension up the serving engine's power-of-two
  ladder (bucket.py), so one measured config covers a bucket;
- written atomically (tmp file + ``os.replace``) and *merged* rather
  than clobbered on re-tune — tuning one kernel never drops another
  kernel's entries.

Dispatch reads through the process-global accessor (``get_db`` /
``lookup`` in ``tuning/__init__``); kernels fall back to their
hard-coded defaults on a miss, so behavior without a database is
bit-identical to an untuned tree.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

from paddle_tpu.pallas.tuning.bucket import bucket_shape

SCHEMA = "paddle_tpu.tuning_db.v1"

# the checked-in database, shipped next to this module
DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tuning_db.json")


def make_key(kernel: str, shape: Sequence[int], dtype: str,
             device_kind: str) -> str:
    """DB key for a *query* shape: the shape is bucketed here, so every
    shape in a bucket resolves to the same entry."""
    dims = "x".join(str(d) for d in bucket_shape(shape))
    return f"{kernel}|{dims}|{dtype}|{device_kind}"


class TuningDB:
    """In-memory view of the tuning document: {key: record}.

    A record is ``{"config": {...}, "time_ms": float,
    "default_time_ms": float, "speedup": float, "interpret": bool,
    "n_configs": int, "n_infeasible": int, "shape": [...]}`` — only
    ``config`` is consumed by dispatch; the rest is provenance the
    speedup tables and BENCHMARKS.md rows are built from.
    """

    def __init__(self, entries: Optional[Dict[str, dict]] = None,
                 path: Optional[str] = None):
        self.entries: Dict[str, dict] = dict(entries or {})
        self.path = path

    # -- query ----------------------------------------------------------

    def lookup(self, kernel: str, shape: Sequence[int], dtype: str,
               device_kind: str) -> Optional[Dict[str, Any]]:
        rec = self.entries.get(make_key(kernel, shape, dtype, device_kind))
        if rec is None:
            return None
        cfg = rec.get("config")
        return dict(cfg) if isinstance(cfg, dict) else None

    def __len__(self) -> int:
        return len(self.entries)

    def kernels(self) -> Iterable[str]:
        return sorted({k.split("|", 1)[0] for k in self.entries})

    # -- mutation -------------------------------------------------------

    def put(self, kernel: str, shape: Sequence[int], dtype: str,
            device_kind: str, record: dict) -> str:
        key = make_key(kernel, shape, dtype, device_kind)
        self.entries[key] = dict(record)
        return key

    def merge(self, other: "TuningDB") -> "TuningDB":
        """Fold ``other``'s entries over this DB's (other wins on key
        collision — re-tuned entries replace stale ones)."""
        self.entries.update(other.entries)
        return self

    # -- persistence ----------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "TuningDB":
        """Parse a tuning document.  Raises ``ValueError`` on a schema
        mismatch (a future-schema file must not be half-read) and
        propagates IO/JSON errors — callers that want tolerance use
        ``load_or_empty``."""
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") != SCHEMA:
            raise ValueError(
                f"tuning db {path}: schema {doc.get('schema')!r} != "
                f"{SCHEMA!r}; re-run `paddle tune` to regenerate")
        return cls(doc.get("entries", {}), path=path)

    @classmethod
    def load_or_empty(cls, path: str) -> "TuningDB":
        """Dispatch-side loader: a missing/corrupt/foreign-schema file
        degrades to an empty DB (= hard-coded defaults), never a crash."""
        try:
            return cls.load(path)
        except FileNotFoundError:
            return cls(path=path)
        except (ValueError, OSError, json.JSONDecodeError):
            return cls(path=path)

    def save(self, path: Optional[str] = None,
             merge_existing: bool = True) -> str:
        """Atomic write: serialize to a tmp file in the target dir, then
        ``os.replace`` — a reader never sees a torn document.  When the
        target already holds a valid DB, its entries are merged under
        ours first (re-tune updates, never clobbers)."""
        path = path or self.path or DEFAULT_PATH
        entries = self.entries
        if merge_existing and os.path.exists(path):
            try:
                base = TuningDB.load(path)
                entries = dict(base.entries)
                entries.update(self.entries)
            except (ValueError, OSError, json.JSONDecodeError):
                pass  # unreadable target: overwrite with ours
        doc = {"schema": SCHEMA, "entries": dict(sorted(entries.items()))}
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".tuning_db_", suffix=".tmp",
                                   dir=d)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=False)
                f.write("\n")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self.path = path
        return path


def normalize_device_kind(kind: str) -> str:
    """'TPU v5 lite' -> 'tpu_v5_lite' (stable DB-key token)."""
    return "_".join(kind.strip().lower().split())


def current_device_kind() -> str:
    try:
        import jax

        return normalize_device_kind(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"
