"""Shape bucketing shared by the kernel autotuner and the serving
bucketer.

One tuned config should cover a *bucket* of shapes, not a single point,
for the same reason the serving engine coalesces requests into
power-of-two batch buckets (serving/batching.py): a static-shape
compiler wants a small closed set of programs, and a tuning database
wants a small closed set of keys.  Both layers round through THIS
module so their ladders can never drift apart.

Pure python, no jax/numpy imports — serving imports this at module
load.
"""

from __future__ import annotations

from typing import Sequence, Tuple


def bucket_dim(n: int) -> int:
    """Smallest power-of-two >= n (n <= 1 maps to 1).

    This is the serving engine's ``next_bucket`` ladder: 1, 2, 4, 8...
    """
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def bucket_shape(shape: Sequence[int]) -> Tuple[int, ...]:
    """Round every dimension up the power-of-two ladder."""
    return tuple(bucket_dim(int(d)) for d in shape)


def bucket_ladder(max_value: int) -> Tuple[int, ...]:
    """All buckets up to (and including) the one covering max_value:
    1, 2, 4, ..., bucket_dim(max_value)."""
    out = []
    b = 1
    while b < max_value:
        out.append(b)
        b <<= 1
    out.append(bucket_dim(max_value))
    return tuple(out)
