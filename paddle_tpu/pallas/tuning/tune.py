"""`paddle tune`: reproduce the tuning database from one command.

For every selected kernel family and shape, enumerates the valid config
space (space.py), measures the hard-coded default plus up to
``--budget`` candidates (random-sampled beyond the budget, seeded), and
persists the measured winner into the tuning database (db.py, atomic
merge-write).  Prints a tuned-vs-default speedup table and records a
``paddle_tpu.tune.v1`` telemetry artifact through the observability
layer.

Flags (``--k=v`` style, the repo CLI convention):

  --kernel=matmul,softmax   families to tune (default: all)
  --shapes=1024x1024x1024;2048x2048x2048
                            per-family shapes (default: the family's
                            ``default_shapes``; dims are 'x'-joined,
                            shapes ';'-separated)
  --budget=N                max measured candidates per (kernel, shape)
                            (default 32)
  --reps=N                  best-of-N timing repetitions (default 3)
  --dtype=float32           operand dtype
  --output=PATH             database path (default: the checked-in
                            ``tuning_db.json`` next to the package)
  --telemetry=PATH          artifact path (default: ``<output>`` with
                            ``.telemetry.json``)
  --seed=N                  candidate-sampling seed (default 0)
  --smoke                   tiny shapes + budget 2 + interpret-mode on
                            CPU: the enumerate -> measure -> persist ->
                            dispatch-hit path in tier-1 time

On CPU the kernels run in interpret mode and entries are keyed
``device_kind=cpu`` with ``"interpret": true`` provenance — real TPU
runs key separately and never collide with them.
"""

from __future__ import annotations

import json
import random
import sys
from typing import Optional

from paddle_tpu.pallas.tuning import db as _dbmod
from paddle_tpu.pallas.tuning.db import TuningDB, current_device_kind


def _parse_shapes(spec: str):
    shapes = []
    for part in spec.split(";"):
        part = part.strip()
        if part:
            shapes.append(tuple(int(d) for d in part.split("x")))
    return shapes


def _use_interpret() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def tune_one(family, shape, dtype: str, budget: int, reps: int,
             interpret: bool, seed: int = 0, log=print) -> Optional[dict]:
    """Measure one (family, shape) point; returns the DB record (or
    ``None`` when the space is empty) plus prints progress."""
    from paddle_tpu.observability import metrics
    from paddle_tpu.pallas.tuning import measure

    m_measured = metrics.counter(
        "tune_configs_measured_total",
        "autotuner candidate configs actually timed")
    m_infeasible = metrics.counter(
        "tune_configs_infeasible_total",
        "autotuner candidate configs that failed to compile/run")

    cands = family.configs(shape)
    n_space = len(cands)
    if budget and len(cands) > budget:
        cands = random.Random(seed).sample(cands, budget)
    try:
        default_ms = measure.measure_config(family, shape, dtype, None,
                                            interpret, reps)
    except measure.Infeasible as e:
        log(f"  {family.name}{shape}: default infeasible ({e}); skipped")
        return None

    best_cfg, best_ms, n_inf = None, float("inf"), 0
    for cfg in cands:
        try:
            ms = measure.measure_config(family, shape, dtype, cfg,
                                        interpret, reps)
            m_measured.inc(kernel=family.name)
        except measure.Infeasible:
            n_inf += 1
            m_infeasible.inc(kernel=family.name)
            continue
        if ms < best_ms:
            best_cfg, best_ms = cfg, ms
    if best_cfg is None or best_ms >= default_ms:
        # nothing measured beat the default: record the default itself
        # so dispatch stays on the proven-best path and re-tunes skip
        best_cfg, best_ms = None, default_ms
    return {
        "config": best_cfg or {},
        "time_ms": round(best_ms, 6),
        "default_time_ms": round(default_ms, 6),
        "speedup": round(default_ms / best_ms, 4) if best_ms else 1.0,
        "interpret": interpret,
        "n_configs": n_space,
        "n_infeasible": n_inf,
        "shape": list(shape),
    }


def _artifact(path: str, rows, out_path: str, device_kind: str):
    import jax

    from paddle_tpu import observability as obs

    art = {
        "schema": "paddle_tpu.tune.v1",
        "db_path": out_path,
        "device": {
            "backend": jax.default_backend(),
            "kind": jax.devices()[0].device_kind,
            "count": jax.device_count(),
            "db_device_kind": device_kind,
        },
        "results": rows,
        "metrics": obs.snapshot(),
    }
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    return path


def main(argv) -> int:
    from paddle_tpu.pallas import tuning
    from paddle_tpu.pallas.tuning import space

    kv, rest = _cli_kv(argv)
    if rest:
        print(f"tune: unexpected args {rest}", file=sys.stderr)
        return 2
    smoke = "smoke" in kv
    budget = int(kv.get("budget", 2 if smoke else 32))
    reps = int(kv.get("reps", 1 if smoke else 3))
    dtype = kv.get("dtype", "float32")
    seed = int(kv.get("seed", 0))
    out_path = kv.get("output", _dbmod.DEFAULT_PATH)
    names = [n for n in kv.get("kernel", "").split(",") if n]
    if not names:
        names = sorted(space.SPACES)
    unknown = [n for n in names if n not in space.SPACES]
    if unknown:
        print(f"tune: unknown kernel(s) {unknown}; "
              f"one of {sorted(space.SPACES)}", file=sys.stderr)
        return 2
    shapes_flag = _parse_shapes(kv["shapes"]) if "shapes" in kv else None
    interpret = _use_interpret()
    device_kind = current_device_kind()

    # measure against hard-coded defaults, not whatever DB is installed
    tuning.disable()
    new_db = TuningDB()
    rows = []
    mode = "interpret(cpu)" if interpret else "compiled"
    print(f"tune: kernels={names} budget={budget} reps={reps} "
          f"dtype={dtype} mode={mode} -> {out_path}")
    for name in names:
        family = space.SPACES[name]
        shapes = shapes_flag or (family.smoke_shapes if smoke
                                 else family.default_shapes)
        for shape in shapes:
            if len(shape) != len(family.shape_names):
                print(f"tune: {name} wants dims "
                      f"{'x'.join(family.shape_names)}, got {shape}",
                      file=sys.stderr)
                return 2
            rec = tune_one(family, shape, dtype, budget, reps,
                           interpret, seed)
            if rec is None:
                continue
            new_db.put(name, shape, dtype, device_kind, rec)
            rows.append({"kernel": name, "shape": list(shape),
                         "dtype": dtype, **{k: rec[k] for k in
                         ("config", "time_ms", "default_time_ms",
                          "speedup", "n_configs", "n_infeasible")}})
            print(json.dumps({"kernel": name,
                              "shape": "x".join(map(str, shape)),
                              "default_ms": rec["default_time_ms"],
                              "tuned_ms": rec["time_ms"],
                              "speedup": rec["speedup"],
                              "config": rec["config"]}))

    saved = new_db.save(out_path, merge_existing=True)
    print(f"tune: {len(new_db)} entr{'y' if len(new_db) == 1 else 'ies'} "
          f"-> {saved}")

    # prove the round trip: the saved DB must serve dispatch hits
    tuning.set_db(saved)
    hits = sum(1 for r in rows if tuning.lookup(
        r["kernel"], r["shape"], r["dtype"], device_kind) is not None)
    print(f"tune: dispatch round-trip {hits}/{len(rows)} hits")

    telemetry = kv.get("telemetry",
                       out_path.rsplit(".json", 1)[0] + ".telemetry.json")
    try:
        _artifact(telemetry, rows, saved, device_kind)
        print(f"tune: telemetry artifact -> {telemetry}")
    except Exception as e:  # artifact failure must not fail the tune
        print(f"tune: telemetry artifact failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    return 0


def _cli_kv(argv):
    """`--k=v` plus bare `--flag` (stored as empty string) parsing."""
    out, rest = {}, []
    for a in argv:
        if a.startswith("--"):
            k, _, v = a[2:].partition("=")
            out[k] = v
        else:
            rest.append(a)
    return out, rest


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main(sys.argv[1:]))
