"""Per-kernel-family config spaces for the autotuner.

Each family describes, for one kernel entry point:

- ``shape_names``     — what the dims of a tune shape mean (CLI help
  and table headers);
- ``default_shapes``  — the shapes ``paddle tune`` measures when the
  caller gives none (the sizes the repo's benchmarks exercise);
- ``smoke_shapes``    — tiny shapes for ``--smoke`` (CPU interpret
  mode, tier-1 time budget);
- ``configs(shape)``  — every *valid* candidate config at that shape,
  filtered through the kernel's own ``fits()``/``block_ok()``
  predicate so the search space never proposes a config the dispatch
  layer would reject;
- ``build(shape, dtype, cfg, interpret)`` — a zero-arg callable
  running ``CHAIN`` chained applications of the kernel with the config
  pinned as explicit static args (``cfg=None`` = the hard-coded
  default path, the baseline every speedup is measured against).

Configs are pinned explicitly rather than through a temporary DB so
each candidate gets its own jit trace — DB resolution happens at trace
time and would otherwise be frozen into a cached jaxpr.

This module imports the kernel modules, so the tuning package's
``__init__`` must not import it (kernels lazily import the package for
``lookup()`` — importing spaces there would be a cycle).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

CHAIN = 4  # sequential in-jit applications per timed call (bench.py idiom)

_POW2_BLOCKS = (64, 128, 256, 512, 1024)


def _divisors(n: int, lo: int = 8, step: int = 8) -> List[int]:
    return [d for d in range(lo, n + 1, 1) if n % d == 0 and d % step == 0]


class Family:
    """One tunable kernel family: its search space and its harness."""

    def __init__(self, name: str, shape_names: Sequence[str],
                 default_shapes: Sequence[Tuple[int, ...]],
                 smoke_shapes: Sequence[Tuple[int, ...]],
                 configs: Callable[[Tuple[int, ...]], List[Dict[str, Any]]],
                 build: Callable[..., Callable[[], Any]],
                 default_dtype: str = "float32"):
        self.name = name
        self.shape_names = tuple(shape_names)
        self.default_shapes = [tuple(s) for s in default_shapes]
        self.smoke_shapes = [tuple(s) for s in smoke_shapes]
        self.configs = configs
        self.build = build
        self.default_dtype = default_dtype


def _key(i: int):
    return jax.random.key(i)


def _chain_accumulate(apply, out_shape, args):
    """CHAIN applications folded into one jitted callable; every
    application feeds an f32 accumulator so none can be elided."""
    def run(*a):
        acc = jnp.zeros(out_shape, jnp.float32)
        for _ in range(CHAIN):
            out = apply(*a)
            first = jax.tree_util.tree_leaves(out)[0]
            acc = acc + first.astype(jnp.float32)
        return acc

    jitted = jax.jit(run)
    return lambda: jitted(*args)


# ---------------------------------------------------------------------------
# matmul: (m, k, n) -> tile (bm, bk, bn)
# ---------------------------------------------------------------------------


def _matmul_configs(shape):
    from paddle_tpu.pallas import matmul as mm

    m, k, n = shape
    out = []
    for bm, bk, bn in itertools.product(_POW2_BLOCKS, repeat=3):
        if mm.fits(m, k, n, bm, bk, bn):
            out.append({"bm": bm, "bk": bk, "bn": bn})
    return out


def _matmul_build(shape, dtype, cfg, interpret):
    from paddle_tpu.pallas import matmul as mm

    m, k, n = shape
    cfg = cfg or {}
    x = jax.random.normal(_key(0), (m, k), dtype)
    y = jax.random.normal(_key(1), (k, n), dtype)
    return _chain_accumulate(
        lambda a, b: mm._matmul_impl(a, b, cfg.get("bm"), cfg.get("bk"),
                                     cfg.get("bn"), interpret),
        (m, n), (x, y))


# ---------------------------------------------------------------------------
# softmax: (rows, cols) -> block_rows
# ---------------------------------------------------------------------------


def _softmax_configs(shape):
    from paddle_tpu.pallas import softmax as sm

    rows, cols = shape
    return [{"block_rows": br} for br in _POW2_BLOCKS
            if sm.fits(rows, cols, br)]


def _softmax_build(shape, dtype, cfg, interpret):
    from paddle_tpu.pallas import softmax as sm

    rows, cols = shape
    cfg = cfg or {}
    x = jax.random.normal(_key(0), (rows, cols), dtype)
    return _chain_accumulate(
        lambda a: sm._softmax_impl(a, cfg.get("block_rows"), interpret),
        (rows, cols), (x,))


# ---------------------------------------------------------------------------
# flash attention forward: (BH, S, Sk, D) -> (blk_q, blk_k)
# ---------------------------------------------------------------------------


def _flash_configs(shape):
    from paddle_tpu.pallas import flash_attention as fa

    _, s, sk, d = shape
    return [{"blk_q": bq, "blk_k": bk}
            for bq, bk in itertools.product((128, 256, 512, 1024), repeat=2)
            if fa._blocks_ok(s, sk, d, bq, bk)]


def _flash_build(shape, dtype, cfg, interpret):
    from paddle_tpu.pallas import flash_attention as fa

    bh, s, sk, d = shape
    cfg = cfg or {}
    q = jax.random.normal(_key(0), (bh, s, d), dtype)
    k = jax.random.normal(_key(1), (bh, sk, d), dtype)
    v = jax.random.normal(_key(2), (bh, sk, d), dtype)
    scale = d ** -0.5
    return _chain_accumulate(
        lambda a, b, c: fa._flash_fwd_impl(
            a, b, c, False, scale, interpret,
            blk_q=cfg.get("blk_q"), blk_k=cfg.get("blk_k"))[0],
        (bh, s, d), (q, k, v))


# ---------------------------------------------------------------------------
# conv forward: (n, h, w, c, o, k) -> (bb, fold_kw)
# ---------------------------------------------------------------------------


def _conv_configs(shape):
    from paddle_tpu.pallas import conv as cv

    n, h, w, c, o, k = shape
    wp = w + 2 * (k // 2)
    out = []
    for bb in _divisors(n):
        for fold_kw in (False, True):
            if cv.fwd_block_ok(bb, n, w, wp, c, o, k, k, fold_kw):
                out.append({"bb": bb, "fold_kw": fold_kw})
    return out


def _conv_build(shape, dtype, cfg, interpret):
    from paddle_tpu.pallas import conv as cv

    n, h, w, c, o, k = shape
    cfg = cfg or {}
    x = jax.random.normal(_key(0), (n, h, w, c), dtype)
    wts = jax.random.normal(_key(1), (k, k, c, o), dtype) * 0.05
    return _chain_accumulate(
        lambda a, b: cv._conv_fwd_impl(
            a, b, k // 2, interpret, fold_kw=cfg.get("fold_kw"),
            bb=cfg.get("bb")),
        (n, h, w, o), (x, wts))


# ---------------------------------------------------------------------------
# batch norm forward: (rows, cols) -> block_rows
# ---------------------------------------------------------------------------


def _bn_configs(shape):
    from paddle_tpu.pallas import batch_norm as bn

    rows, cols = shape
    return [{"block_rows": rt} for rt in _divisors(rows)
            if bn.block_ok(rows, cols, rt)]


def _bn_build(shape, dtype, cfg, interpret):
    from paddle_tpu.pallas import batch_norm as bn

    rows, cols = shape
    cfg = cfg or {}
    x = jax.random.normal(_key(0), (rows, cols), dtype)
    gamma = jnp.ones((cols,), dtype)
    beta = jnp.zeros((cols,), dtype)
    return _chain_accumulate(
        lambda a, g, b: bn._bn_fwd_impl(
            a, g, b, 1e-5, interpret,
            block_rows=cfg.get("block_rows"))[0],
        (rows, cols), (x, gamma, beta))


# ---------------------------------------------------------------------------
# lstm sequence: (t, b, h) -> block_b (batch blocking)
# ---------------------------------------------------------------------------


def _lstm_configs(shape):
    from paddle_tpu.pallas import lstm as lk

    t, b, h = shape
    # block_b == b is the default whole-batch grid (the baseline)
    return [{"block_b": bb} for bb in _divisors(b)
            if bb != b and lk.block_ok(b, h, bb)]


def _lstm_build(shape, dtype, cfg, interpret):
    from paddle_tpu.pallas import lstm as lk

    t, b, h = shape
    cfg = cfg or {}
    xproj = jax.random.normal(_key(0), (t, b, 4 * h), dtype) * 0.1
    w = jax.random.normal(_key(1), (h, 4 * h), dtype) * 0.1
    bias = jnp.zeros((4 * h,), dtype)
    h0 = jnp.zeros((b, h), dtype)
    c0 = jnp.zeros((b, h), dtype)
    return _chain_accumulate(
        lambda *a: lk._lstm_seq_impl(*a, interpret=interpret,
                                     block_b=cfg.get("block_b"))[0],
        (t, b, h), (xproj, w, bias, h0, c0))


# ---------------------------------------------------------------------------
# ragged paged attention: (S, P, page, H, D) -> (slots_per_block, semantics)
# ---------------------------------------------------------------------------


def _rpa_configs(shape):
    from paddle_tpu.decode import attention as da

    s, p, page, h, d = shape
    out = []
    for sb in (1, 2, 4, 8, 16):
        if not da.block_ok(s, h, d, sb):
            continue
        for sem in ("parallel", "arbitrary"):
            if sb == 1 and sem == "parallel":
                continue  # that IS the default baseline
            out.append({"slots_per_block": sb, "slot_semantics": sem})
    return out


def _rpa_build(shape, dtype, cfg, interpret):
    from paddle_tpu.decode import attention as da

    s, p, page, h, d = shape
    cfg = cfg or {}
    npages = s * p + 1
    q = jax.random.normal(_key(0), (s, h, d), dtype)
    kp = jax.random.normal(_key(1), (npages, page, h, d), dtype)
    vp = jax.random.normal(_key(2), (npages, page, h, d), dtype)
    ptab = jnp.arange(s * p, dtype=jnp.int32).reshape(s, p)
    lens = jnp.full((s,), p * page, jnp.int32)
    return _chain_accumulate(
        lambda *a: da.ragged_paged_attention(
            *a, interpret=interpret,
            slots_per_block=cfg.get("slots_per_block"),
            slot_semantics=cfg.get("slot_semantics")),
        (s, h, d), (q, kp, vp, ptab, lens))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


SPACES: Dict[str, Family] = {
    "matmul": Family(
        "matmul", ("m", "k", "n"),
        default_shapes=[(1024, 1024, 1024), (2048, 2048, 2048)],
        smoke_shapes=[(256, 512, 256)],
        configs=_matmul_configs, build=_matmul_build),
    "softmax": Family(
        "softmax", ("rows", "cols"),
        default_shapes=[(8192, 512), (4096, 1024)],
        smoke_shapes=[(512, 128)],
        configs=_softmax_configs, build=_softmax_build),
    "flash_attention": Family(
        "flash_attention", ("bh", "s", "sk", "d"),
        default_shapes=[(8, 2048, 2048, 128)],
        smoke_shapes=[(2, 256, 256, 8)],
        configs=_flash_configs, build=_flash_build),
    "conv": Family(
        "conv", ("n", "h", "w", "c", "o", "k"),
        default_shapes=[(64, 28, 28, 128, 128, 3)],
        smoke_shapes=[(16, 8, 8, 64, 64, 3)],
        configs=_conv_configs, build=_conv_build),
    "batch_norm": Family(
        "batch_norm", ("rows", "cols"),
        default_shapes=[(16384, 256)],
        smoke_shapes=[(512, 128)],
        configs=_bn_configs, build=_bn_build),
    "lstm": Family(
        "lstm", ("t", "b", "h"),
        default_shapes=[(64, 64, 512)],
        smoke_shapes=[(4, 16, 128)],
        configs=_lstm_configs, build=_lstm_build),
    "ragged_paged_attention": Family(
        "ragged_paged_attention", ("s", "p", "page", "h", "d"),
        default_shapes=[(64, 8, 16, 8, 128)],
        smoke_shapes=[(8, 2, 8, 2, 8)],
        configs=_rpa_configs, build=_rpa_build),
}
