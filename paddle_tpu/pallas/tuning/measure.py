"""On-device measurement for the autotuner.

Borrowed from ``bench.py`` / ``benchmark/pallas_bench.py``: each timed
call runs ``space.CHAIN`` chained kernel applications inside one jit so
the per-dispatch floor amortizes, timings force a host read, and the
reported number is the *best of N* repetitions (min is the standard
autotuner statistic — noise only ever adds time).

Configs that fail to compile or lower are recorded as infeasible
(``Infeasible`` carries the reason), never propagated as a crash: a
search space filtered by ``fits()`` can still hit Mosaic layout limits
the predicates don't model.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from paddle_tpu.pallas.tuning import space as _space


class Infeasible(Exception):
    """The candidate config failed to compile/lower/run."""


def _sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def time_call(fn, reps: int = 3, warmup: int = 1) -> float:
    """Best-of-``reps`` milliseconds per single kernel application.

    ``fn`` is a zero-arg callable running ``space.CHAIN`` chained
    applications (a ``Family.build`` product).
    """
    out = None
    for _ in range(max(warmup, 1)):
        out = fn()
    _sync(out)
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        out = fn()
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3 / _space.CHAIN


def measure_config(family: "_space.Family", shape: Tuple[int, ...],
                   dtype: str, cfg: Optional[Dict[str, Any]],
                   interpret: bool = False, reps: int = 3) -> float:
    """Milliseconds for one (shape, config) point; ``cfg=None`` times
    the hard-coded default path.  Raises ``Infeasible`` on any
    compile/lower/run failure."""
    try:
        fn = family.build(shape, dtype, cfg, interpret)
        return time_call(fn, reps=reps)
    except KeyboardInterrupt:
        raise
    except Exception as e:  # XlaRuntimeError, Mosaic errors, asserts...
        raise Infeasible(f"{family.name}{shape} {cfg}: "
                         f"{type(e).__name__}: {e}") from e
