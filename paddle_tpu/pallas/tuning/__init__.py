"""Pallas kernel autotuning: measured tile configs behind every kernel.

Every kernel in ``paddle_tpu/pallas`` (and the ragged paged-attention
decode kernel) used to ship guessed tile sizes.  This package replaces
the guesses with a TVM-style loop (PAPERS.md):

- ``space.py``    — per-kernel-family config spaces: tunable block/
  tile/grid parameters plus a validity predicate reusing each kernel's
  ``fits()``-style VMEM/divisibility checks;
- ``measure.py``  — on-device measurement: compile + best-of-N
  chain-block timing (the ``bench.py`` idiom), robust to configs that
  fail to lower (recorded infeasible, never a crash);
- ``db.py``       — the persistent, checked-in JSON database keyed by
  ``(kernel, shape-bucket, dtype, device-kind)``;
- ``bucket.py``   — the power-of-two shape ladder shared with the
  serving bucketer, so one tuned config covers a bucket;
- ``tune.py``     — the ``paddle tune`` CLI that reproduces the whole
  database from one command and emits tuned-vs-default speedup tables.

Dispatch contract: every kernel entry point calls ``lookup()`` when the
caller did not pin a config, validates the hit against the *actual*
shape with its own ``fits()`` check, and falls back to its hard-coded
default on a miss — so with no database (or an empty one) behavior is
bit-identical to an untuned tree.

``PADDLE_TPU_TUNING_DB`` overrides the database path (``off``/``0``
disables lookup entirely).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Sequence

from paddle_tpu.pallas.tuning.bucket import (  # noqa: F401
    bucket_dim,
    bucket_ladder,
    bucket_shape,
)
from paddle_tpu.pallas.tuning.db import (  # noqa: F401
    DEFAULT_PATH,
    SCHEMA,
    TuningDB,
    current_device_kind,
    make_key,
    normalize_device_kind,
)

_LOCK = threading.Lock()
# "unset" sentinel: resolve from env/default path on first use
_UNSET = object()
_STATE: Dict[str, Any] = {"db": _UNSET}

_M_LOOKUP = None  # lazy counter handle (observability imports numpy)


def _lookup_metric():
    global _M_LOOKUP
    if _M_LOOKUP is None:
        from paddle_tpu.observability import metrics as _metrics

        _M_LOOKUP = _metrics.counter(
            "tuning_db_lookup_total",
            "kernel-dispatch tuning-database consultations by result "
            "(hit = a tuned config was applied, miss = hard-coded "
            "defaults; counted at trace time, not per device step)")
    return _M_LOOKUP


def _resolve_default() -> TuningDB:
    env = os.environ.get("PADDLE_TPU_TUNING_DB", "")
    if env.lower() in ("off", "0", "none", "disabled"):
        return TuningDB()
    path = env or DEFAULT_PATH
    return TuningDB.load_or_empty(path)


def get_db() -> TuningDB:
    """The process-active tuning database (loaded once, cached)."""
    with _LOCK:
        if _STATE["db"] is _UNSET:
            _STATE["db"] = _resolve_default()
        return _STATE["db"]


def set_db(db: "TuningDB | str | None") -> None:
    """Swap the active database: a ``TuningDB``, a path, or ``None`` to
    re-resolve from the environment on next use (tests/CLI)."""
    with _LOCK:
        if db is None:
            _STATE["db"] = _UNSET
        elif isinstance(db, str):
            _STATE["db"] = TuningDB.load_or_empty(db)
        else:
            _STATE["db"] = db


def disable() -> None:
    """Force empty-DB dispatch (hard-coded defaults) for this process."""
    set_db(TuningDB())


def lookup(kernel: str, shape: Sequence[int], dtype: str,
           device_kind: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Dispatch-side query: the tuned config for this kernel at this
    shape's bucket, or ``None`` (= use the hard-coded default).

    The caller MUST validate the returned config against the actual
    shape (its ``fits()`` predicate): an entry tuned at the bucket shape
    may not divide a smaller in-bucket shape.
    """
    db = get_db()
    if not db.entries:
        return None  # fast path: empty DB never counts a miss
    kind = device_kind or current_device_kind()
    cfg = db.lookup(kernel, shape, dtype, kind)
    try:
        _lookup_metric().inc(kernel=kernel,
                             result="hit" if cfg else "miss")
    except Exception:
        pass  # telemetry must never sink dispatch
    return cfg
