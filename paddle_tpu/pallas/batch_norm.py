"""Fused batch-norm kernels.

Reference analog: paddle/cuda/src/hl_batch_norm.cu and
paddle/operators/batch_norm_op.cu (cuDNN spatial BN) — the era's
hand-written BN statistics + normalize kernels.

TPU redesign: one ``pallas_call`` per direction over a channel-minor
``(R, C)`` view (R = N*H*W), with a *two-phase sequential grid*:

- forward: phase 0 streams row-blocks accumulating per-channel
  ``sum``/``sum(x^2)`` into an f32 VMEM scratch (the only pass over x
  the statistics cost); phase 1 re-streams x and writes the normalized
  output in the same kernel — mean/var never round-trip HBM, and the
  affine (gamma, beta) is folded into one multiply-add per element.
- backward: phase 0 accumulates ``dbeta = sum(dy)`` and
  ``dgamma = sum(dy * xhat)``; phase 1 emits
  ``dx = gamma*inv*(dy - dbeta/R - xhat*dgamma/R)``.

Minimum HBM traffic for exact BN (3 passes fwd, 5 passes bwd) in
exactly 2 kernels.  All f32 accumulation regardless of activation
dtype.  ``interpret=True`` runs the same kernels on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.pallas import compat as _compat

_F32 = jnp.float32


def _pick_row_block(rows: int, cols: int, budget: int = 1 << 19) -> int:
    """Largest divisor of ``rows`` that is a multiple of 8 with
    block elements <= budget (VMEM sizing)."""
    cap = max(8, budget // max(cols, 1))
    best = 0
    d = 8
    while d * d <= rows:
        if rows % d == 0:
            if d % 8 == 0 and d <= cap:
                best = max(best, d)
            q = rows // d
            if q % 8 == 0 and q <= cap:
                best = max(best, q)
        d += 1
    if rows % 8 == 0 and rows <= cap:
        best = max(best, rows)
    return best


def fits(rows: int, cols: int) -> bool:
    return (rows >= 8 and cols <= 8192 and
            _pick_row_block(rows, cols) >= 8)


def block_ok(rows: int, cols: int, rt: int) -> bool:
    """Validity of an explicit row block at an actual shape: the
    divisibility/alignment the kernel grid needs plus a hard VMEM cap
    (x block + y block + f32 temps, ~12MB)."""
    return (rt >= 8 and rt % 8 == 0 and rows % rt == 0
            and rt * cols <= 1 << 20)


def _resolve_row_block(rows, cols, dtype, budget: int = 1 << 19,
                       block_rows: int = None):
    """Explicit block first, then the tuned forward row block from the
    tuning DB when valid at this shape, else the historical divisor
    heuristic."""
    if block_rows is not None and block_ok(rows, cols, block_rows):
        return block_rows
    from paddle_tpu.pallas import tuning

    cfg = tuning.lookup("batch_norm", (rows, cols), dtype) or {}
    rt = cfg.get("block_rows")
    if rt and block_ok(rows, cols, rt):
        return rt
    return _pick_row_block(rows, cols, budget)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _bn_fwd_kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, var_ref,
                   acc_ref, *, rows: int, eps: float):
    p = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when((p == 0) & (i == 0))
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(p == 0)
    def _accumulate():
        xb = x_ref[...].astype(_F32)
        acc_ref[0:1, :] += jnp.sum(xb, axis=0, keepdims=True)
        acc_ref[1:2, :] += jnp.sum(xb * xb, axis=0, keepdims=True)

    @pl.when(p == 1)
    def _normalize():
        inv_r = 1.0 / rows
        m = acc_ref[0:1, :] * inv_r
        v = acc_ref[1:2, :] * inv_r - m * m
        inv = lax.rsqrt(v + eps)
        # fold the affine in f32: y = x*a + b, one mul+add per element
        a = gamma_ref[0:1, :].astype(_F32) * inv
        b = beta_ref[0:1, :].astype(_F32) - m * a
        xb = x_ref[...].astype(_F32)
        y_ref[...] = (xb * a + b).astype(y_ref.dtype)
        mean_ref[0:1, :] = m
        var_ref[0:1, :] = v


@functools.partial(jax.jit, static_argnames=("eps", "interpret",
                                             "block_rows"))
def _bn_fwd_impl(x2d, gamma, beta, eps: float, interpret: bool = False,
                 block_rows: int = None):
    R, C = x2d.shape
    Rt = _resolve_row_block(R, C, x2d.dtype.name, block_rows=block_rows)
    grid = (2, R // Rt)
    y, mean, var = pl.pallas_call(
        functools.partial(_bn_fwd_kernel, rows=R, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((Rt, C), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((Rt, C), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2d.dtype),
            jax.ShapeDtypeStruct((1, C), _F32),
            jax.ShapeDtypeStruct((1, C), _F32),
        ],
        scratch_shapes=[pltpu.VMEM((2, C), _F32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(x2d, gamma.reshape(1, C), beta.reshape(1, C))
    return y, mean.reshape(C), var.reshape(C)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bn_bwd_kernel(x_ref, dy_ref, gamma_ref, mean_ref, inv_ref,
                   dx_ref, dgamma_ref, dbeta_ref, acc_ref, *, rows: int):
    p = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when((p == 0) & (i == 0))
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    m = mean_ref[0:1, :]
    inv = inv_ref[0:1, :]

    @pl.when(p == 0)
    def _accumulate():
        xb = x_ref[...].astype(_F32)
        dyb = dy_ref[...].astype(_F32)
        xhat = (xb - m) * inv
        acc_ref[0:1, :] += jnp.sum(dyb, axis=0, keepdims=True)
        acc_ref[1:2, :] += jnp.sum(dyb * xhat, axis=0, keepdims=True)

    @pl.when(p == 1)
    def _dx():
        inv_r = 1.0 / rows
        dbeta = acc_ref[0:1, :]
        dgamma = acc_ref[1:2, :]
        g = gamma_ref[0:1, :].astype(_F32)
        xb = x_ref[...].astype(_F32)
        dyb = dy_ref[...].astype(_F32)
        xhat = (xb - m) * inv
        dx = (g * inv) * (
            dyb - (dbeta * inv_r) - xhat * (dgamma * inv_r))
        dx_ref[...] = dx.astype(dx_ref.dtype)
        dgamma_ref[0:1, :] = dgamma
        dbeta_ref[0:1, :] = dbeta


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bn_bwd_impl(x2d, dy2d, gamma, mean, inv, interpret: bool = False):
    R, C = x2d.shape
    Rt = _pick_row_block(R, C, budget=1 << 18)  # two streams resident
    grid = (2, R // Rt)
    dx, dgamma, dbeta = pl.pallas_call(
        functools.partial(_bn_bwd_kernel, rows=R),
        grid=grid,
        in_specs=[
            pl.BlockSpec((Rt, C), lambda p, i: (i, 0)),
            pl.BlockSpec((Rt, C), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((Rt, C), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), dy2d.dtype),
            jax.ShapeDtypeStruct((1, C), _F32),
            jax.ShapeDtypeStruct((1, C), _F32),
        ],
        scratch_shapes=[pltpu.VMEM((2, C), _F32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(x2d, dy2d, gamma.reshape(1, C), mean.reshape(1, C), inv.reshape(1, C))
    return dx, dgamma.reshape(C), dbeta.reshape(C)


# ---------------------------------------------------------------------------
# differentiable entry point
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def batch_norm_train(x2d, gamma, beta, eps: float = 1e-5,
                     interpret: bool = False):
    """Training-mode BN over a channel-minor ``(R, C)`` view.

    Returns ``(y, batch_mean, batch_var)`` with f32 statistics.
    Differentiable w.r.t. ``x2d``, ``gamma``, ``beta``.
    """
    y, mean, var = _bn_fwd_impl(x2d, gamma, beta, eps, interpret)
    return y, mean, var


def _bn_train_fwd(x2d, gamma, beta, eps, interpret):
    y, mean, var = _bn_fwd_impl(x2d, gamma, beta, eps, interpret)
    inv = lax.rsqrt(var + eps)
    return (y, mean, var), (x2d, gamma, mean, inv)


def _bn_train_bwd(eps, interpret, res, cots):
    x2d, gamma, mean, inv = res
    dy, dmean, dvar = cots
    # batch statistics are consumed as aux outputs (running averages),
    # treated as non-differentiable targets like the reference's
    # MeanOut/VarianceOut slots
    del dmean, dvar
    dx, dgamma, dbeta = _bn_bwd_impl(x2d, dy, gamma, mean, inv, interpret)
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


batch_norm_train.defvjp(_bn_train_fwd, _bn_train_bwd)
