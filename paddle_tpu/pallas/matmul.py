"""Blocked MXU matmul kernel (reference analog: the cuBLAS path behind
paddle/operators/math/math_function.cc gemm).

Grid (M/bm, N/bn, K/bk); fp32 accumulation in VMEM scratch; bf16 or
f32 operands.  K is innermost so the accumulator lives across the K
steps of one (i, j) tile."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.pallas import compat as _compat


def _mm_kernel(x_ref, y_ref, o_ref, acc_ref, *, k_steps):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        x_ref[:], y_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _flush():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def fits(m, k, n, bm=256, bk=512, bn=256) -> bool:
    return m % bm == 0 and k % bk == 0 and n % bn == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def matmul(x, y, bm: int = 256, bk: int = 512, bn: int = 256,
           interpret: bool = False):
    return _matmul_impl(x, y, bm, bk, bn, interpret)


def _matmul_fwd(x, y, bm, bk, bn, interpret):
    return _matmul_impl(x, y, bm, bk, bn, interpret), (x, y)


def _matmul_bwd(bm, bk, bn, interpret, res, g):
    x, y = res
    # dX = g @ Y^T, dY = X^T @ g — via XLA (transposed tilings differ)
    gx = jnp.dot(g, y.T, preferred_element_type=jnp.float32).astype(x.dtype)
    gy = jnp.dot(x.T, g, preferred_element_type=jnp.float32).astype(y.dtype)
    return gx, gy


matmul.defvjp(_matmul_fwd, _matmul_bwd)


@functools.partial(jax.jit, static_argnames=("bm", "bk", "bn", "interpret"))
def _matmul_impl(x, y, bm: int = 256, bk: int = 512, bn: int = 256,
                 interpret: bool = False):
    m, k = x.shape
    k2, n = y.shape
    assert k == k2 and fits(m, k, n, bm, bk, bn), (x.shape, y.shape)
    k_steps = k // bk
    return pl.pallas_call(
        functools.partial(_mm_kernel, k_steps=k_steps),
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, y)
