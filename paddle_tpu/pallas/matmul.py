"""Blocked MXU matmul kernel (reference analog: the cuBLAS path behind
paddle/operators/math/math_function.cc gemm).

Grid (M/bm, N/bn, K/bk); fp32 accumulation in VMEM scratch; bf16 or
f32 operands.  K is innermost so the accumulator lives across the K
steps of one (i, j) tile."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.pallas import compat as _compat

# The one place the guessed tile lives (ISSUE 16: `fits` and `matmul`
# used to repeat bm=256, bk=512, bn=256 independently — a tuned default
# could desync the fits check from dispatch).  The tuning database
# (pallas/tuning) overrides these per (shape-bucket, dtype, device).
DEFAULT_CONFIG = {"bm": 256, "bk": 512, "bn": 256}


def _resolve_blocks(m, k, n, dtype, bm, bk, bn):
    """Fill unset block dims from the tuning DB, else the defaults.

    A tuned config is validated against the ACTUAL shape (the DB keys
    by bucket, so a bucket-valid config may not divide this shape) and
    dropped back to the defaults when it doesn't fit.
    """
    if bm is not None and bk is not None and bn is not None:
        return bm, bk, bn
    from paddle_tpu.pallas import tuning

    cfg = tuning.lookup("matmul", (m, k, n), dtype) or {}
    got = (bm or cfg.get("bm", DEFAULT_CONFIG["bm"]),
           bk or cfg.get("bk", DEFAULT_CONFIG["bk"]),
           bn or cfg.get("bn", DEFAULT_CONFIG["bn"]))
    if cfg and not fits(m, k, n, *got):
        got = (bm or DEFAULT_CONFIG["bm"], bk or DEFAULT_CONFIG["bk"],
               bn or DEFAULT_CONFIG["bn"])
    return got


def _mm_kernel(x_ref, y_ref, o_ref, acc_ref, *, k_steps):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        x_ref[:], y_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _flush():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def fits(m, k, n, bm=None, bk=None, bn=None) -> bool:
    bm = bm or DEFAULT_CONFIG["bm"]
    bk = bk or DEFAULT_CONFIG["bk"]
    bn = bn or DEFAULT_CONFIG["bn"]
    return m % bm == 0 and k % bk == 0 and n % bn == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def matmul(x, y, bm: int = None, bk: int = None, bn: int = None,
           interpret: bool = False):
    """Unset block dims resolve through the tuning DB (pallas/tuning),
    falling back to ``DEFAULT_CONFIG`` — explicit args always win."""
    return _matmul_impl(x, y, bm, bk, bn, interpret)


def _matmul_fwd(x, y, bm, bk, bn, interpret):
    return _matmul_impl(x, y, bm, bk, bn, interpret), (x, y)


def _matmul_bwd(bm, bk, bn, interpret, res, g):
    x, y = res
    # dX = g @ Y^T, dY = X^T @ g — via XLA (transposed tilings differ)
    gx = jnp.dot(g, y.T, preferred_element_type=jnp.float32).astype(x.dtype)
    gy = jnp.dot(x.T, g, preferred_element_type=jnp.float32).astype(y.dtype)
    return gx, gy


matmul.defvjp(_matmul_fwd, _matmul_bwd)


@functools.partial(jax.jit, static_argnames=("bm", "bk", "bn", "interpret"))
def _matmul_impl(x, y, bm: int = None, bk: int = None, bn: int = None,
                 interpret: bool = False):
    m, k = x.shape
    k2, n = y.shape
    bm, bk, bn = _resolve_blocks(m, k, n, x.dtype.name, bm, bk, bn)
    assert k == k2 and fits(m, k, n, bm, bk, bn), (x.shape, y.shape)
    k_steps = k // bk
    return pl.pallas_call(
        functools.partial(_mm_kernel, k_steps=k_steps),
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_compat.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, y)
